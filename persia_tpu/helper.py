"""ServiceCtx: single-machine fake cluster for tests and quick starts.

Parity target: `persia/helper.py:125-331` — spawns nats-server + embedding
workers + parameter servers as local subprocesses with random ports so
integration tests exercise the real multi-process topology without a
cluster; includes a crash watchdog (helper.py:296-315).

Here: an in-process Coordinator + N parameter-server subprocesses + M
embedding-worker subprocesses; `worker_clients()` hands back RPC clients
with the EmbeddingWorker surface for TrainCtx/DataLoader.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time
from typing import List, Optional

from persia_tpu.config import EmbeddingConfig
from persia_tpu.logger import get_default_logger
from persia_tpu.service.clients import StoreClient, WorkerClient
from persia_tpu.service.discovery import Coordinator, CoordinatorClient

logger = get_default_logger("persia_tpu.helper")


class ServiceCtx:
    def __init__(
        self,
        num_parameter_servers: int = 1,
        num_embedding_workers: int = 1,
        embedding_config_path: Optional[str] = None,
        global_config_path: Optional[str] = None,
        capacity: int = 1 << 18,
        num_internal_shards: int = 4,
        backend: str = "auto",
        seed: int = 0,
        startup_timeout_s: float = 60.0,
    ):
        self.n_ps = num_parameter_servers
        self.n_workers = num_embedding_workers
        self.embedding_config_path = embedding_config_path
        self.global_config_path = global_config_path
        self.capacity = capacity
        self.num_internal_shards = num_internal_shards
        self.backend = backend
        self.seed = seed
        self.startup_timeout_s = startup_timeout_s
        self.procs: List[subprocess.Popen] = []
        self.coordinator: Optional[Coordinator] = None
        self._watchdog_stop = threading.Event()
        self._crashed: Optional[str] = None
        self._expected_dead: set = set()
        # failover state: last dump_shard snapshot per PS index (fed by
        # snapshot_ps / the snapshot guard; replayed by restart_ps /
        # promote_standby), and any spawned-but-unregistered standbys
        self._ps_snapshots: dict = {}
        self._standbys: List[tuple] = []  # (addr, Popen)
        self._guard_stop = threading.Event()
        self._guard_thread: Optional[threading.Thread] = None
        # elastic tier: the PS ring currently in force (None = the legacy
        # modulo topology every fresh cluster starts with); set by
        # reshard_ps / resume_reshard and published to the coordinator KV
        # as "ps_ring" so late joiners route by the live ring
        self.ps_ring = None

    # ------------------------------------------------------------ lifecycle

    def __enter__(self) -> "ServiceCtx":
        try:
            return self._enter_impl()
        except BaseException:
            # __exit__ never runs if __enter__ raises: reap spawned services
            self._teardown()
            raise

    def _enter_impl(self) -> "ServiceCtx":
        self.coordinator = Coordinator(port=0).start()
        coord_addr = f"127.0.0.1:{self.coordinator.port}"
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            + os.pathsep
            + env.get("PYTHONPATH", "")
        )
        # services never need a TPU; keep them off the chip
        env["JAX_PLATFORMS"] = "cpu"

        self._env = env
        self._coord_addr = coord_addr
        self._ps_procs: List[subprocess.Popen] = []
        for i in range(self.n_ps):
            p = subprocess.Popen(self._ps_cmd(i), env=env)
            self._ps_procs.append(p)
            self.procs.append(p)

        for i in range(self.n_workers):
            cmd = [
                sys.executable, "-m", "persia_tpu.service.worker_server",
                "--replica-index", str(i), "--replica-size", str(self.n_workers),
                "--coordinator", coord_addr,
                "--num-parameter-servers", str(self.n_ps),
            ]
            if self.embedding_config_path:
                cmd += ["--embedding-config", self.embedding_config_path]
            if self.global_config_path:
                cmd += ["--global-config", self.global_config_path]
            self.procs.append(subprocess.Popen(cmd, env=env))

        self.coord_client = CoordinatorClient(coord_addr)
        # wait for BOTH roles: a worker-less cluster (e.g. the cached tier's
        # trainer-direct-to-PS shape) must still see its PS replicas
        # registered before ps_clients() is usable
        self.coord_client.wait_for(
            "parameter_server", self.n_ps, timeout_s=self.startup_timeout_s
        )
        self.coord_client.wait_for(
            "embedding_worker", self.n_workers, timeout_s=self.startup_timeout_s
        )
        self._watchdog = threading.Thread(target=self._watch, daemon=True)
        self._watchdog.start()
        return self

    def _ps_cmd(self, i: int, port: int = 0) -> List[str]:
        cmd = [
            sys.executable, "-m", "persia_tpu.service.ps_server",
            "--replica-index", str(i), "--replica-size", str(self.n_ps),
            "--coordinator", self._coord_addr,
            "--capacity", str(self.capacity),
            "--num-internal-shards", str(self.num_internal_shards),
            "--backend", self.backend, "--seed", str(self.seed),
        ]
        if port:
            cmd += ["--port", str(port)]
        if self.global_config_path:
            cmd += ["--global-config", self.global_config_path]
        return cmd

    # ---------------------------------------------------- failure injection

    def kill_ps(self, i: int) -> None:
        """SIGKILL parameter server ``i`` (fault injection for recovery
        tests; the watchdog ignores PSs killed through this API)."""
        p = self._ps_procs[i]
        self._expected_dead.add(p.pid)
        p.kill()
        p.wait(timeout=10)

    def snapshot_ps(self, i: int, job_state=None) -> int:
        """Record PS ``i``'s full state (every internal shard's
        ``dump_shard`` bytes, plus the registered optimizer config — a
        restored shard serving lookups without its optimizer would
        re-initialize every restored entry on entry-width mismatch) for a
        later replaying restart/promotion. Returns the snapshot's total
        byte size.

        ``job_state`` (a directory or :class:`~persia_tpu.jobstate.
        JobStateManager`) additionally commits the snapshot as a DURABLE
        manifest epoch, so the failover state survives the ServiceCtx
        process itself: a fresh process calls
        :meth:`restore_ps_snapshots` and can ``restart_ps(restore=True)``
        replicas it never snapshotted in-memory."""
        c = StoreClient(self.ps_addrs()[i])
        shards = [
            c.dump_shard(s) for s in range(c.num_internal_shards)
        ]
        opt = c.get_optimizer()
        opt_dict = opt.to_dict() if opt else None
        self._ps_snapshots[i] = (shards, opt_dict)
        if job_state is not None:
            from persia_tpu import jobstate

            writer = jobstate.coerce_manager(job_state).begin_epoch()
            for si, blob in enumerate(shards):
                writer.add_blob(f"ps/replica_{i}_shard_{si}.emb", blob)
            writer.commit({
                "kind": "ps_failover",
                "replica_index": i,
                "n_shards": len(shards),
                "optimizer": opt_dict,
            })
        return sum(len(s) for s in shards)

    def restore_ps_snapshots(self, job_state) -> List[int]:
        """Rebuild the in-memory failover snapshot cache from durable
        ``snapshot_ps(..., job_state=)`` manifests — the path a REPLACEMENT
        ServiceCtx process takes after the original host died. Newest
        manifest per replica wins; replicas already cached in memory are
        left alone. Returns the replica indices restored."""
        from persia_tpu import jobstate

        mgr = jobstate.coerce_manager(job_state)
        found: List[int] = []
        for _e, d in reversed(mgr._epoch_dirs()):
            m = mgr._load_manifest(d)
            if m is None or m.meta.get("kind") != "ps_failover":
                continue
            ri = int(m.meta["replica_index"])
            if ri in self._ps_snapshots or ri in found:
                continue
            shards = [
                m.read_blob(f"ps/replica_{ri}_shard_{si}.emb")
                for si in range(int(m.meta["n_shards"]))
            ]
            self._ps_snapshots[ri] = (shards, m.meta.get("optimizer"))
            found.append(ri)
        return found

    def start_snapshot_guard(
        self, interval_s: float = 5.0, job_state=None
    ) -> None:
        """Background snapshot loop over every PS — the failover state
        source when a shard dies without warning. Snapshot staleness is
        bounded by ``interval_s`` (the accepted loss window, exactly like
        a periodic checkpoint). ``job_state`` makes every guard snapshot
        durable (see :meth:`snapshot_ps`)."""
        if self._guard_thread is not None:
            return

        def loop():
            while not self._guard_stop.wait(interval_s):
                for i in range(self.n_ps):
                    try:
                        self.snapshot_ps(i, job_state=job_state)
                    except Exception as e:  # noqa: BLE001 — shard may be down
                        logger.warning("snapshot guard: ps %d failed: %s", i, e)

        self._guard_thread = threading.Thread(
            target=loop, daemon=True, name="ps-snapshot-guard"
        )
        self._guard_thread.start()

    def restart_ps(self, i: int, restore: bool = False) -> None:
        """Respawn parameter server ``i`` on its ORIGINAL port so existing
        clients reconnect transparently. ``restore=False``: fresh store
        (k8s pod restart without a boot checkpoint). ``restore=True``:
        replay the last ``snapshot_ps`` state as a BOOT load
        (``--load-shards``) — the new process only answers its first probe
        after the replay, so a reconnecting client can never observe the
        un-restored store and mistake trained signs for cold ones (loss
        stays bounded by snapshot staleness)."""
        import json
        import tempfile

        addr = self.ps_addrs()[i]
        port = int(addr.rsplit(":", 1)[1])
        cmd = self._ps_cmd(i, port=port)
        snap = self._ps_snapshots.get(i) if restore else None
        tmp_files = []
        if snap:
            shards, opt_dict = snap
            fd, snap_file = tempfile.mkstemp(prefix=f"ps{i}_boot_", suffix=".shards")
            tmp_files.append(snap_file)
            with os.fdopen(fd, "wb") as f:
                for raw in shards:
                    f.write(len(raw).to_bytes(8, "little"))
                    f.write(raw)
            cmd += ["--load-shards", snap_file]
            if opt_dict:
                fd, opt_file = tempfile.mkstemp(prefix=f"ps{i}_opt_", suffix=".json")
                tmp_files.append(opt_file)
                with os.fdopen(fd, "w") as f:
                    json.dump(opt_dict, f)
                cmd += ["--boot-optimizer", opt_file]
        p = subprocess.Popen(cmd, env=self._env)
        self.procs.append(p)
        self._ps_procs[i] = p
        try:
            StoreClient(addr).wait_ready(timeout_s=self.startup_timeout_s)
        finally:
            for path in tmp_files:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def _replay_snapshot(self, i: int, client: StoreClient) -> int:
        snap = self._ps_snapshots.get(i)
        if not snap:
            return 0
        shards, opt_dict = snap
        if opt_dict:
            # optimizer FIRST: a store without it re-initializes restored
            # entries on the first train lookup (entry-width mismatch)
            from persia_tpu.embedding.optim import OptimizerConfig

            client.register_optimizer(OptimizerConfig.from_dict(opt_dict))
        return sum(client.load_shard_bytes(raw) for raw in shards)

    # ---------------------------------------------------- standby failover

    def spawn_standby_ps(self) -> str:
        """Start a spare, UNREGISTERED parameter server (same config) and
        return its address. It idles until ``promote_standby`` loads a dead
        shard's snapshot into it and re-points the coordinator entry."""
        # reserve a port (races are theoretically possible but this is a
        # single-machine test/bench topology)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        cmd = [
            sys.executable, "-m", "persia_tpu.service.ps_server",
            "--port", str(port),
            "--replica-index", "0", "--replica-size", str(self.n_ps),
            "--capacity", str(self.capacity),
            "--num-internal-shards", str(self.num_internal_shards),
            "--backend", self.backend, "--seed", str(self.seed),
        ]
        if self.global_config_path:
            cmd += ["--global-config", self.global_config_path]
        p = subprocess.Popen(cmd, env=self._env)
        self.procs.append(p)
        addr = f"127.0.0.1:{port}"
        StoreClient(addr).wait_ready(timeout_s=self.startup_timeout_s)
        self._standbys.append((addr, p))
        return addr

    def promote_standby(self, i: int, standby_addr: Optional[str] = None,
                        batch_advances: Optional[dict] = None) -> str:
        """Fail shard ``i`` over onto a standby: replay the last snapshot
        into it and upsert the coordinator registration so new clients
        resolve the standby's address. Callers holding an in-process
        router should also swap the replica handle
        (``router.replace_replica(i, StoreClient(new_addr))``).

        ``batch_advances`` (``{group: count}``) re-advances the standby's
        per-group optimizer batch counters to the fleet's fence — a parked
        standby never saw a batch, so its Adam beta powers sit at t=0 while
        the survivors advanced; shard snapshots carry entries, NOT the
        batch-state clock (same contract as the elastic joiner path).
        Returns the promoted address."""
        from persia_tpu import elastic

        proc = None
        if standby_addr is None:
            if not self._standbys:
                raise RuntimeError("no standby spawned (spawn_standby_ps first)")
            standby_addr, proc = self._standbys.pop(0)
        else:
            for j, (a, p) in enumerate(self._standbys):
                if a == standby_addr:
                    proc = self._standbys.pop(j)[1]
                    break
        c = StoreClient(standby_addr)
        c.wait_ready(timeout_s=self.startup_timeout_s)
        self._replay_snapshot(i, c)
        # optimizer came from the snapshot replay; only the batch-state
        # clock is left to catch up
        elastic.prime_joiner(c, None, batch_advances)
        self.coord_client.register("parameter_server", i, standby_addr)
        if proc is not None:
            while len(self._ps_procs) <= i:
                self._ps_procs.append(proc)
            self._ps_procs[i] = proc
        return standby_addr

    # ------------------------------------------------------- self-heal hooks

    def heal_promote(self, i: int, *, router=None,
                     batch_advances: Optional[dict] = None,
                     fault_hook=None) -> str:
        """Autonomous failover of a DEAD shard ``i``: promote a warm
        standby (spawning one when none is parked), then swap the live
        router handle so in-flight callers migrate without an operator.

        Idempotent end to end — snapshot replay into a fresh standby is
        deterministic, batch re-advance is replayed from the same counts,
        and the coordinator registration is an upsert — so the healer's
        two-phase journal may re-drive this after a mid-heal SIGKILL and
        converge on a bit-identical fleet (a half-promoted orphan standby
        is re-pointed away from and reaped at teardown). ``fault_hook``
        (stage names ``"promoted"``/``"swapped"``) is the chaos plane's
        mid-heal crash injection point."""
        if not self._standbys:
            self.spawn_standby_ps()
        addr = self.promote_standby(i, batch_advances=batch_advances)
        if fault_hook is not None:
            fault_hook("promoted")
        if router is not None:
            router.replace_replica(i, StoreClient(addr))
        if fault_hook is not None:
            fault_hook("swapped")
        logger.info("heal: promoted standby %s for dead ps %d", addr, i)
        return addr

    def heal_drain_gray(self, i: int, *, router=None,
                        batch_advances: Optional[dict] = None,
                        fault_hook=None) -> str:
        """Replace a limping (GRAY) replica without dropping in-flight
        requests: live-snapshot it (it still answers — that is what makes
        it gray rather than dead), promote a standby from that fresh
        snapshot, swap the router so NEW calls route to the standby while
        calls already in flight finish on the old handle, then drain the
        old process with a graceful shutdown RPC."""
        old_addr = self.ps_addrs()[i]
        old_proc = self._ps_procs[i] if i < len(self._ps_procs) else None
        self.snapshot_ps(i)
        if fault_hook is not None:
            fault_hook("snapshotted")
        if not self._standbys:
            self.spawn_standby_ps()
        addr = self.promote_standby(i, batch_advances=batch_advances)
        if fault_hook is not None:
            fault_hook("promoted")
        if router is not None:
            router.replace_replica(i, StoreClient(addr))
        if fault_hook is not None:
            fault_hook("swapped")
        # drain, don't SIGKILL: the shutdown RPC lets handlers already on
        # the old socket complete before the process exits
        if old_proc is not None and old_proc.poll() is None:
            self._expected_dead.add(old_proc.pid)
            StoreClient(old_addr).shutdown()
        logger.info("heal: drained gray ps %d (%s -> %s)", i, old_addr, addr)
        return addr

    def ps_probes(self, timeout_s: float = 1.0) -> dict:
        """Per-replica one-attempt healthz probes for a FailureDetector."""
        from persia_tpu.service.failure_detector import ps_fleet_probes

        return ps_fleet_probes(self.ps_addrs(), timeout_s=timeout_s)

    def ps_lease_reader(self):
        """Lease scan over the PS fleet's coordinator kv leases."""
        from persia_tpu.service.failure_detector import coordinator_lease_reader

        return coordinator_lease_reader(self.coord_client, "parameter_server")

    # ------------------------------------------------------ elastic reshard

    def _publish_ring(self, splits) -> None:
        import numpy as np

        self.ps_ring = np.asarray(splits, dtype=np.uint64)
        self.coord_client.kv_put(
            "ps_ring", self.ps_ring.astype("<u8").tobytes()
        )

    def _grow_ps(self, i: int) -> str:
        """Bring replica ``i`` (>= current fleet) online: reuse an idle
        standby if one was pre-spawned (warm add — no process startup on
        the critical path), else spawn one, then claim the coordinator
        slot. Extends the per-index process table so restart_ps/kill_ps
        address the new replica like any other."""
        if not self._standbys:
            self.spawn_standby_ps()
        addr, p = self._standbys.pop(0)
        self.coord_client.register("parameter_server", i, addr)
        while len(self._ps_procs) <= i:
            self._ps_procs.append(p)
        self._ps_procs[i] = p
        return addr

    def reshard_ps(
        self,
        n_new: int,
        job_state,
        *,
        step: int = 0,
        splits=None,
        planner=None,
        profiler=None,
        router=None,
        fault_hook=None,
        batch_advances=None,
        abort_check=None,
    ) -> dict:
        """Live-reshard the PS tier to ``n_new`` replicas at a drained
        stream fence (the caller guarantees nothing is in flight). The new
        ring comes from ``splits`` if given, else a sparsity-aware
        ``planner.plan(n_new, profiler=...)`` (load-weighted boundaries
        from the tiering access sketch), else hash-uniform. Handoffs run
        under the exactly-once journal discipline of
        :mod:`persia_tpu.elastic`; a crash at ANY point (ours or a PS's)
        resumes via :meth:`resume_reshard` to a state bit-identical to an
        uninterrupted reshard. ``router`` (a ``ShardedLookup``) is swapped
        to the new ring at the imported boundary; ``fault_hook`` is the
        chaos plane's injection point. ``abort_check`` (the arbiter's
        preemption flag) lets a higher-priority intent roll the reshard
        back at a phase boundary: the engine raises
        ``elastic.ReshardAborted`` after the journaled rollback, and the
        topology bookkeeping (grown joiners, replica count) is restored
        to the old ring before the exception propagates."""
        from persia_tpu import elastic, jobstate
        from persia_tpu.embedding.hashing import uniform_splits

        mgr = jobstate.coerce_manager(job_state)
        old_n = self.n_ps
        old_addrs = self.ps_addrs()
        if splits is None:
            if planner is not None:
                splits = planner.plan(n_new, profiler=profiler).splits
            else:
                splits = uniform_splits(n_new)
        old_splits = None if self.ps_ring is None else [int(x) for x in self.ps_ring]
        plan = elastic.plan_reshard(
            old_n, n_new, old_splits, splits,
            elastic.reshard_base_id(mgr, step),
        )

        sources = [StoreClient(a) for a in old_addrs]
        opt = sources[0].get_optimizer()
        opt_dict = opt.to_dict() if opt else None
        dest_addrs = list(old_addrs[:min(old_n, n_new)])
        for i in range(old_n, n_new):
            dest_addrs.append(self._grow_ps(i))
        dests = [
            sources[i] if i < old_n else StoreClient(dest_addrs[i])
            for i in range(n_new)
        ]
        # joiners need the optimizer BEFORE the first import: a store
        # without it re-initializes imported entries on entry-width
        # mismatch at the first train lookup (see _replay_snapshot), and
        # Adam joiners additionally re-advance beta powers to the fence
        for i in range(old_n, n_new):
            elastic.prime_joiner(dests[i], opt, batch_advances)
        self.n_ps = max(old_n, n_new)

        try:
            stats = elastic.execute_reshard(
                plan, sources, dests, mgr,
                fault_hook=fault_hook,
                on_imported=self._ring_swapper(router, dests, splits),
                extra_meta={"optimizer": opt_dict,
                            "batch_advances": {str(k): int(v) for k, v in
                                               (batch_advances or {}).items()}},
                abort_check=abort_check,
            )
        except elastic.ReshardAborted:
            self._finalize_abort(plan)
            raise
        self._finalize_reshard(plan, splits)
        stats["skew_splits"] = [int(x) for x in splits]
        return stats

    def _ring_swapper(self, router, dests, splits):
        if router is None:
            return None

        def swap():
            import numpy as np

            router.swap_topology(list(dests), ring=np.asarray(splits, np.uint64))

        return swap

    def _finalize_reshard(self, plan, splits) -> None:
        """Post-``done`` topology bookkeeping: drop drained replicas from
        the registry and the process table, publish the new ring."""
        for i in range(plan.new_n, plan.old_n):
            self.coord_client.deregister("parameter_server", i)
            self.kill_ps(i)
        self._ps_procs = self._ps_procs[: plan.new_n]
        self.n_ps = plan.new_n
        self._publish_ring(splits)

    def _finalize_abort(self, plan) -> None:
        """Post-``aborted`` topology bookkeeping: the fleet is back on the
        OLD ring — joiners grown for the preempted plan are drained (their
        imported arcs were released by the abort arm) and the replica
        count restored. The ring was never republished, so there is
        nothing to swap back."""
        for i in range(plan.old_n, plan.new_n):
            self.coord_client.deregister("parameter_server", i)
            self.kill_ps(i)
        self._ps_procs = self._ps_procs[: plan.old_n]
        self.n_ps = plan.old_n

    def resume_reshard(self, job_state, *, router=None, fault_hook=None,
                       abort_check=None):
        """Re-enter a reshard interrupted by a SIGKILL — of a source PS, a
        dest PS, or the coordinating process itself. Restores dead replicas
        per the crash matrix (fence snapshot for sources mid-handoff, fresh
        + re-import for dests mid-handoff, post-import snapshot for dests
        mid-delete), then replays the recorded plan; every op the crashed
        run already applied dedupes against the PS apply-journal. Returns
        the run stats, or None when there is nothing to resume. A plan
        recorded mid-abort (phase ``aborting``) re-enters the rollback arm
        instead: dead survivors restore from the ``handoff`` manifest's
        fence snapshots, the remaining arc releases replay (dedupe), and
        the OLD topology is finalized."""
        from persia_tpu import elastic, jobstate
        from persia_tpu.embedding.optim import OptimizerConfig

        mgr = jobstate.coerce_manager(job_state)
        man = elastic.find_reshard_manifest(mgr)
        if man is None or man.meta.get("phase") in ("done", "aborted"):
            return None
        plan = elastic.ReshardPlan.from_meta(man.meta)
        phase = man.meta["phase"]
        opt_dict = man.meta.get("optimizer")
        self.n_ps = max(plan.old_n, plan.new_n)
        addrs = self.ps_addrs()

        def dead(i: int) -> bool:
            return i >= len(self._ps_procs) or self._ps_procs[i].poll() is not None

        if phase == "aborting":
            # mid-rollback: survivors restore to their fence snapshot (the
            # ``handoff`` manifest holds it); the replayed arc releases
            # then apply as no-ops or dedupe either way. Joiners restart
            # fresh only so the release RPCs land — _finalize_abort drains
            # them right after.
            hman = elastic.find_phase_manifest(mgr, "handoff", plan.base_id)
            for i in range(plan.new_n):
                if not dead(i):
                    continue
                if i < plan.old_n and hman is not None:
                    self._ps_snapshots[i] = (
                        elastic.source_snapshot(hman, i), opt_dict,
                    )
                    self.restart_ps(i, restore=True)
                else:
                    self.restart_ps(i, restore=False)
        elif phase == "handoff":
            for i in range(plan.old_n):
                if dead(i):
                    self._ps_snapshots[i] = (
                        elastic.source_snapshot(man, i), opt_dict,
                    )
                    self.restart_ps(i, restore=True)
            for i in range(plan.old_n, plan.new_n):
                if dead(i):
                    # a joiner's journal died with it: restart FRESH, the
                    # replayed imports re-apply the identical blobs
                    self.restart_ps(i, restore=False)
                    elastic.prime_joiner(
                        StoreClient(addrs[i]),
                        OptimizerConfig.from_dict(opt_dict) if opt_dict else None,
                        man.meta.get("batch_advances"),
                    )
        else:  # "imported": only surviving replicas matter for the deletes
            for i in range(plan.new_n):
                if dead(i):
                    self._ps_snapshots[i] = (
                        elastic.dest_snapshot(man, i), opt_dict,
                    )
                    self.restart_ps(i, restore=True)

        sources = [StoreClient(a) for a in addrs[: plan.old_n]]
        dests = [
            sources[i] if i < plan.old_n else StoreClient(addrs[i])
            for i in range(plan.new_n)
        ]
        splits = plan.new_splits
        try:
            stats = elastic.resume_reshard(
                mgr, sources, dests, fault_hook=fault_hook,
                on_imported=self._ring_swapper(router, dests, splits),
                abort_check=abort_check,
            )
        except elastic.ReshardAborted:
            self._finalize_abort(plan)
            raise
        if stats is not None:
            if stats.get("aborted"):
                self._finalize_abort(plan)
            else:
                self._finalize_reshard(plan, splits)
        return stats

    def _watch(self):
        """Crash watchdog (ref: helper.py:296-315): if any service process
        dies, record it so clients fail fast instead of hanging."""
        while not self._watchdog_stop.wait(0.5):
            for p in self.procs:
                rc = p.poll()
                if rc is not None and rc != 0 and p.pid not in self._expected_dead:
                    self._crashed = f"service pid {p.pid} exited with {rc}"
                    logger.error(self._crashed)
                    return

    def check_healthy(self):
        if self._crashed:
            raise RuntimeError(self._crashed)

    def __exit__(self, *exc):
        self._watchdog_stop.set()
        self._guard_stop.set()
        try:
            for client in self.worker_clients():
                try:
                    client.shutdown(shutdown_servers=True)
                except Exception:
                    pass
        except Exception:
            pass
        self._teardown()
        return False

    def _teardown(self):
        deadline = time.time() + 5
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.terminate()
        for p in self.procs:
            if p.poll() is None:
                p.kill()
        for p in self.procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        if self.coordinator:
            self.coordinator.stop()

    # -------------------------------------------------------------- clients

    def worker_addrs(self) -> List[str]:
        return self.coord_client.list("embedding_worker")

    def ps_addrs(self) -> List[str]:
        return self.coord_client.list("parameter_server")

    def worker_clients(self) -> List[WorkerClient]:
        return [WorkerClient(a) for a in self.worker_addrs()]

    def ps_clients(self) -> List[StoreClient]:
        return [StoreClient(a) for a in self.ps_addrs()]

"""ServiceCtx: single-machine fake cluster for tests and quick starts.

Parity target: `persia/helper.py:125-331` — spawns nats-server + embedding
workers + parameter servers as local subprocesses with random ports so
integration tests exercise the real multi-process topology without a
cluster; includes a crash watchdog (helper.py:296-315).

Here: an in-process Coordinator + N parameter-server subprocesses + M
embedding-worker subprocesses; `worker_clients()` hands back RPC clients
with the EmbeddingWorker surface for TrainCtx/DataLoader.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import List, Optional

from persia_tpu.config import EmbeddingConfig
from persia_tpu.logger import get_default_logger
from persia_tpu.service.clients import StoreClient, WorkerClient
from persia_tpu.service.discovery import Coordinator, CoordinatorClient

logger = get_default_logger("persia_tpu.helper")


class ServiceCtx:
    def __init__(
        self,
        num_parameter_servers: int = 1,
        num_embedding_workers: int = 1,
        embedding_config_path: Optional[str] = None,
        global_config_path: Optional[str] = None,
        capacity: int = 1 << 18,
        num_internal_shards: int = 4,
        backend: str = "auto",
        seed: int = 0,
        startup_timeout_s: float = 60.0,
    ):
        self.n_ps = num_parameter_servers
        self.n_workers = num_embedding_workers
        self.embedding_config_path = embedding_config_path
        self.global_config_path = global_config_path
        self.capacity = capacity
        self.num_internal_shards = num_internal_shards
        self.backend = backend
        self.seed = seed
        self.startup_timeout_s = startup_timeout_s
        self.procs: List[subprocess.Popen] = []
        self.coordinator: Optional[Coordinator] = None
        self._watchdog_stop = threading.Event()
        self._crashed: Optional[str] = None
        self._expected_dead: set = set()

    # ------------------------------------------------------------ lifecycle

    def __enter__(self) -> "ServiceCtx":
        try:
            return self._enter_impl()
        except BaseException:
            # __exit__ never runs if __enter__ raises: reap spawned services
            self._teardown()
            raise

    def _enter_impl(self) -> "ServiceCtx":
        self.coordinator = Coordinator(port=0).start()
        coord_addr = f"127.0.0.1:{self.coordinator.port}"
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            + os.pathsep
            + env.get("PYTHONPATH", "")
        )
        # services never need a TPU; keep them off the chip
        env["JAX_PLATFORMS"] = "cpu"

        self._env = env
        self._coord_addr = coord_addr
        self._ps_procs: List[subprocess.Popen] = []
        for i in range(self.n_ps):
            p = subprocess.Popen(self._ps_cmd(i), env=env)
            self._ps_procs.append(p)
            self.procs.append(p)

        for i in range(self.n_workers):
            cmd = [
                sys.executable, "-m", "persia_tpu.service.worker_server",
                "--replica-index", str(i), "--replica-size", str(self.n_workers),
                "--coordinator", coord_addr,
                "--num-parameter-servers", str(self.n_ps),
            ]
            if self.embedding_config_path:
                cmd += ["--embedding-config", self.embedding_config_path]
            if self.global_config_path:
                cmd += ["--global-config", self.global_config_path]
            self.procs.append(subprocess.Popen(cmd, env=env))

        self.coord_client = CoordinatorClient(coord_addr)
        # wait for BOTH roles: a worker-less cluster (e.g. the cached tier's
        # trainer-direct-to-PS shape) must still see its PS replicas
        # registered before ps_clients() is usable
        self.coord_client.wait_for(
            "parameter_server", self.n_ps, timeout_s=self.startup_timeout_s
        )
        self.coord_client.wait_for(
            "embedding_worker", self.n_workers, timeout_s=self.startup_timeout_s
        )
        self._watchdog = threading.Thread(target=self._watch, daemon=True)
        self._watchdog.start()
        return self

    def _ps_cmd(self, i: int, port: int = 0) -> List[str]:
        cmd = [
            sys.executable, "-m", "persia_tpu.service.ps_server",
            "--replica-index", str(i), "--replica-size", str(self.n_ps),
            "--coordinator", self._coord_addr,
            "--capacity", str(self.capacity),
            "--num-internal-shards", str(self.num_internal_shards),
            "--backend", self.backend, "--seed", str(self.seed),
        ]
        if port:
            cmd += ["--port", str(port)]
        if self.global_config_path:
            cmd += ["--global-config", self.global_config_path]
        return cmd

    # ---------------------------------------------------- failure injection

    def kill_ps(self, i: int) -> None:
        """SIGKILL parameter server ``i`` (fault injection for recovery
        tests; the watchdog ignores PSs killed through this API)."""
        p = self._ps_procs[i]
        self._expected_dead.add(p.pid)
        p.kill()
        p.wait(timeout=10)

    def restart_ps(self, i: int) -> None:
        """Respawn parameter server ``i`` on its ORIGINAL port so existing
        clients reconnect transparently (fresh store, like a k8s pod
        restart without a boot checkpoint)."""
        addr = self.ps_addrs()[i]
        port = int(addr.rsplit(":", 1)[1])
        p = subprocess.Popen(self._ps_cmd(i, port=port), env=self._env)
        self.procs.append(p)
        self._ps_procs[i] = p
        StoreClient(addr).wait_ready(timeout_s=self.startup_timeout_s)

    def _watch(self):
        """Crash watchdog (ref: helper.py:296-315): if any service process
        dies, record it so clients fail fast instead of hanging."""
        while not self._watchdog_stop.wait(0.5):
            for p in self.procs:
                rc = p.poll()
                if rc is not None and rc != 0 and p.pid not in self._expected_dead:
                    self._crashed = f"service pid {p.pid} exited with {rc}"
                    logger.error(self._crashed)
                    return

    def check_healthy(self):
        if self._crashed:
            raise RuntimeError(self._crashed)

    def __exit__(self, *exc):
        self._watchdog_stop.set()
        try:
            for client in self.worker_clients():
                try:
                    client.shutdown(shutdown_servers=True)
                except Exception:
                    pass
        except Exception:
            pass
        self._teardown()
        return False

    def _teardown(self):
        deadline = time.time() + 5
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.terminate()
        for p in self.procs:
            if p.poll() is None:
                p.kill()
        for p in self.procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        if self.coordinator:
            self.coordinator.stop()

    # -------------------------------------------------------------- clients

    def worker_addrs(self) -> List[str]:
        return self.coord_client.list("embedding_worker")

    def ps_addrs(self) -> List[str]:
        return self.coord_client.list("parameter_server")

    def worker_clients(self) -> List[WorkerClient]:
        return [WorkerClient(a) for a in self.worker_addrs()]

    def ps_clients(self) -> List[StoreClient]:
        return [StoreClient(a) for a in self.ps_addrs()]

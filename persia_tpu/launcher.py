"""`persia-tpu-launcher` CLI.

Parity target: `persia/launcher.py` (click CLI with subcommands nn-worker /
data-loader / embedding-worker / embedding-parameter-server, env-var entry
fallbacks `PERSIA_NN_WORKER_ENTRY` etc). Here argparse (no click dependency);
server roles exec this package's service modules; trainer/data-loader roles
exec user scripts.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional


def _user_entry(args_entry: Optional[str], env_key: str, default: str) -> str:
    return args_entry or os.environ.get(env_key, default)


def _run(cmd: List[str], extra_env: dict) -> int:
    env = dict(os.environ)
    env.update({k: str(v) for k, v in extra_env.items() if v is not None})
    return subprocess.call(cmd, env=env)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser("persia-tpu-launcher")
    sub = ap.add_subparsers(dest="role", required=True)

    nn = sub.add_parser("nn-worker", help="launch the TPU training script")
    nn.add_argument("entry", nargs="?", default=None)
    nn.add_argument("--nproc-per-node", type=int, default=1)
    nn.add_argument("--node-rank", type=int, default=0)
    nn.add_argument("--nnodes", type=int, default=1)
    nn.add_argument("--job-state-dir", type=str,
                    default=os.environ.get("PERSIA_JOB_STATE_DIR"),
                    help="step-fenced snapshot directory (persia_tpu.jobstate); "
                         "exported to the entry as PERSIA_JOB_STATE_DIR")
    nn.add_argument("--auto-resume", action="store_true",
                    help="restart the entry after a crash (any nonzero exit, "
                         "incl. SIGKILL); the entry resumes from the newest "
                         "manifest in --job-state-dir")
    nn.add_argument("--max-restarts", type=int, default=3,
                    help="auto-resume restart budget per launcher invocation")
    nn.add_argument("--auto-tier", action="store_true",
                    help="export PERSIA_AUTO_TIER=1: the entry enables "
                         "sparsity-aware auto-tiering (embedding.tiering) — "
                         "slots migrate between sparse tiers at snapshot "
                         "fences based on profiled access skew")

    dl = sub.add_parser("data-loader", help="launch the data-loader script")
    dl.add_argument("entry", nargs="?", default=None)
    dl.add_argument("--replica-index", type=int, default=0)
    dl.add_argument("--replica-size", type=int, default=1)

    for name in ("embedding-worker", "embedding-parameter-server"):
        p = sub.add_parser(name, help=f"launch the {name} service")
        p.add_argument("--port", type=int, default=0)
        p.add_argument("--replica-index", type=int, default=0)
        p.add_argument("--replica-size", type=int, default=1)
        p.add_argument("--coordinator", type=str, default=os.environ.get("PERSIA_COORDINATOR_ADDR"))
        p.add_argument("--global-config", type=str, default=None)
        p.add_argument("--embedding-config", type=str, default=None)
        if name == "embedding-worker":
            p.add_argument("--num-parameter-servers", type=int, required=False,
                           default=int(os.environ.get("PERSIA_NUM_PS", "1")))

    coord = sub.add_parser("coordinator", help="run the discovery/control service")
    coord.add_argument("--port", type=int, default=int(os.environ.get("PERSIA_COORDINATOR_PORT", "7799")))

    # serving replica: exec the user's serve script (it builds the model +
    # InferCtx — app-specific) with the serving-plane knobs in env; the
    # script wires them into persia_tpu.serving.ServingServer
    srv = sub.add_parser("serve", help="launch a model-serving replica")
    srv.add_argument("entry", nargs="?", default=None)
    srv.add_argument("--port", type=int, default=int(os.environ.get("PERSIA_SERVE_PORT", "8501")))
    srv.add_argument("--replica-index", type=int, default=0)
    srv.add_argument("--checkpoint-dir", type=str, default=None,
                     help="watch this dir's done-marker for live rollover")
    srv.add_argument("--incremental-dir", type=str, default=None,
                     help="scan this dir for .inc delta packets")
    srv.add_argument("--coordinator", type=str,
                     default=os.environ.get("PERSIA_COORDINATOR_ADDR"))
    srv.add_argument("--max-batch", type=int, default=256,
                     help="micro-batcher: max coalesced rows per forward")
    srv.add_argument("--max-wait-ms", type=float, default=2.0,
                     help="micro-batcher: coalescing window")
    srv.add_argument("--queue-depth", type=int, default=256,
                     help="admission queue bound (full = 429)")
    srv.add_argument("--cache-rows", type=int, default=0,
                     help="hot-embedding LRU capacity (0 = no cache)")
    srv.add_argument("--store", type=str,
                     default=os.environ.get("PERSIA_STORE_BACKEND", "auto"),
                     choices=["auto", "native", "numpy"],
                     help="embedding store backend for replica-local "
                          "lookups; auto resolves to native whenever the "
                          "C++ core builds")

    # one-command local train-to-serve topology (persia_tpu/topology.py):
    # K demo trainers streaming incremental deltas + R serving replicas
    # consuming them live behind a staleness-aware gateway, with optional
    # PS/worker services as the discovery fabric
    loc = sub.add_parser("local", help="one-command local train-to-serve topology")
    loc.add_argument("--ps", type=int, default=0,
                     help="parameter-server replicas (0 = no service tier)")
    loc.add_argument("--workers", type=int, default=0,
                     help="embedding-worker replicas (needs --ps > 0)")
    loc.add_argument("--trainers", type=int, default=1)
    loc.add_argument("--replicas", type=int, default=2)
    loc.add_argument("--steps", type=int, default=2000,
                     help="train steps per trainer before it finishes")
    loc.add_argument("--duration-s", type=float, default=0.0,
                     help="stop after this long (0 = run until trainers finish)")
    loc.add_argument("--vocab", type=int, default=100_000)
    loc.add_argument("--rows", type=int, default=32)
    loc.add_argument("--step-ms", type=float, default=5.0)
    loc.add_argument("--ckpt-every", type=int, default=200)
    loc.add_argument("--flush-every", type=int, default=5)
    loc.add_argument("--cache-rows", type=int, default=1 << 15)
    loc.add_argument("--max-staleness-steps", type=int, default=None,
                     help="quarantine replicas lagging past this many steps")
    loc.add_argument("--base-dir", type=str, default=None,
                     help="working directory (default: a fresh tempdir)")
    loc.add_argument("--reshard-ps", type=int, default=0,
                     help="live-reshard the PS tier to this many replicas "
                          "once the fleet is up (needs --ps > 0): exercises "
                          "the exactly-once elastic handoff "
                          "(persia_tpu/elastic.py) on a real topology")
    loc.add_argument("--autopilot", action="store_true",
                     help="arm the closed-loop fleet controller "
                          "(persia_tpu/autopilot): a parent-side thread "
                          "senses gateway QPS/quarantine pressure and "
                          "scales the serving replica set (decisions "
                          "two-phase-journaled, hysteresis+dwell guarded); "
                          "exports PERSIA_AUTOPILOT=1 so trainer entries "
                          "can arm the fence-driven PS side too")
    loc.add_argument("--autopilot-interval-s", type=float, default=2.0,
                     help="serving autopilot sense/decide cadence")
    loc.add_argument("--self-heal", action="store_true",
                     help="arm the self-healing PS control plane (needs "
                          "--ps > 0): lease+probe failure detector feeding "
                          "an autonomous standby-promotion healer "
                          "(persia_tpu/autopilot/heal.py)")
    loc.add_argument("--self-heal-interval-s", type=float, default=0.5,
                     help="failure-detector poll cadence")
    loc.add_argument("--seed", type=int, default=7)
    loc.add_argument("--trace-dir", type=str, default=None,
                     help="arm fleet tracing: every role serves /metrics + "
                          "/spans + /flight and a merged Perfetto timeline "
                          "(merged_trace.json) lands here on shutdown")

    # k8s sub-CLI (ref: persia/k8s_utils.py gencrd/operator/server)
    k8s = sub.add_parser("k8s", help="generate/apply k8s manifests + operator")
    k8s.add_argument("action",
                     choices=["gen", "gencrd", "apply", "delete", "operator", "e2e"])
    k8s.add_argument("--timeout-s", type=float, default=600.0,
                     help="e2e: deadline for trainer pods to succeed")
    k8s.add_argument("--image", type=str, default="persia-tpu:latest",
                     help="e2e: job image")
    k8s.add_argument("--interval-s", type=float, default=2.0,
                     help="operator reconcile interval")
    k8s.add_argument("--rest-port", type=int, default=0,
                     help="operator: also serve the REST scheduler (0 = off)")
    k8s.add_argument("--job-yaml", type=str, default=None,
                     help="PersiaTpuJob CR or bare spec yaml file")
    k8s.add_argument("--name", type=str, default=None, help="job name (delete)")
    k8s.add_argument("--namespace", type=str, default=None,
                     help="override the spec/CR namespace")

    args = ap.parse_args(argv)
    py = sys.executable

    if args.role == "nn-worker":
        entry = _user_entry(args.entry, "PERSIA_NN_WORKER_ENTRY", "train.py")
        # one TPU process per host: JAX owns all local chips (no
        # torch.distributed.launch equivalent needed; multi-host uses
        # jax.distributed.initialize via env)
        env = {"WORLD_SIZE": args.nnodes * args.nproc_per_node,
               "RANK": args.node_rank, "LOCAL_RANK": 0}
        if args.job_state_dir:
            env["PERSIA_JOB_STATE_DIR"] = args.job_state_dir
        if args.auto_tier:
            env["PERSIA_AUTO_TIER"] = 1  # tiering.auto_tier_enabled()
        if not args.auto_resume:
            return _run([py, entry], env)
        if not args.job_state_dir:
            print("--auto-resume requires --job-state-dir "
                  "(or PERSIA_JOB_STATE_DIR)", file=sys.stderr)
            return 2
        # auto-resume loop: a crashed trainer (any nonzero exit — SIGKILL,
        # OOM, preemption) restarts and resumes from the newest manifest
        # (entry scripts call ctx.resume(os.environ["PERSIA_JOB_STATE_DIR"]));
        # PERSIA_RESUME_ATTEMPT lets the entry log which life it is on
        attempt = 0
        while True:
            env["PERSIA_RESUME_ATTEMPT"] = attempt
            rc = _run([py, entry], env)
            if rc == 0:
                return 0
            attempt += 1
            if attempt > args.max_restarts:
                print(f"nn-worker failed with rc={rc}; restart budget "
                      f"({args.max_restarts}) exhausted", file=sys.stderr)
                return rc
            print(f"nn-worker exited rc={rc}; auto-resume attempt "
                  f"{attempt}/{args.max_restarts}", file=sys.stderr)

    if args.role == "data-loader":
        entry = _user_entry(args.entry, "PERSIA_DATALOADER_ENTRY", "data_loader.py")
        return _run([py, entry], {"REPLICA_INDEX": args.replica_index,
                                  "REPLICA_SIZE": args.replica_size})

    if args.role == "embedding-worker":
        cmd = [py, "-m", "persia_tpu.service.worker_server",
               "--port", str(args.port),
               "--replica-index", str(args.replica_index),
               "--replica-size", str(args.replica_size),
               "--coordinator", args.coordinator or "127.0.0.1:7799",
               "--num-parameter-servers", str(args.num_parameter_servers)]
        if args.global_config:
            cmd += ["--global-config", args.global_config]
        if args.embedding_config:
            cmd += ["--embedding-config", args.embedding_config]
        return subprocess.call(cmd)

    if args.role == "embedding-parameter-server":
        cmd = [py, "-m", "persia_tpu.service.ps_server",
               "--port", str(args.port),
               "--replica-index", str(args.replica_index),
               "--replica-size", str(args.replica_size)]
        if args.coordinator:
            cmd += ["--coordinator", args.coordinator]
        if args.global_config:
            cmd += ["--global-config", args.global_config]
        return subprocess.call(cmd)

    if args.role == "serve":
        entry = _user_entry(args.entry, "PERSIA_SERVE_ENTRY", "serve.py")
        return _run([py, entry], {
            "PERSIA_SERVE_PORT": args.port,
            "REPLICA_INDEX": args.replica_index,
            "PERSIA_CHECKPOINT_DIR": args.checkpoint_dir,
            "PERSIA_INC_DIR": args.incremental_dir,
            "PERSIA_COORDINATOR_ADDR": args.coordinator,
            "PERSIA_SERVE_MAX_BATCH": args.max_batch,
            "PERSIA_SERVE_MAX_WAIT_MS": args.max_wait_ms,
            "PERSIA_SERVE_QUEUE_DEPTH": args.queue_depth,
            "PERSIA_SERVE_CACHE_ROWS": args.cache_rows,
            "PERSIA_STORE_BACKEND": args.store,
        })

    if args.role == "local":
        import json as _json
        import time as _time

        from persia_tpu.topology import LocalTopology

        if args.autopilot:
            # children inherit the opt-in (autopilot.autopilot_enabled())
            os.environ["PERSIA_AUTOPILOT"] = "1"
        topo = LocalTopology(
            ps=args.ps, workers=args.workers, trainers=args.trainers,
            replicas=args.replicas, base_dir=args.base_dir, steps=args.steps,
            rows=args.rows, vocab=args.vocab, step_ms=args.step_ms,
            ckpt_every=args.ckpt_every, flush_every=args.flush_every,
            cache_rows=args.cache_rows,
            max_staleness_steps=args.max_staleness_steps, seed=args.seed,
            trace_dir=args.trace_dir,
        )
        with topo:
            ports = " ".join(f"127.0.0.1:{p}" for p in topo.replica_ports)
            print(f"local topology up: {args.trainers} trainer(s), "
                  f"{args.replicas} replica(s) [{ports}]", flush=True)
            print(f"workdir: {topo.base_dir}", flush=True)
            if args.autopilot:
                topo.start_autopilot(interval_s=args.autopilot_interval_s)
                print("autopilot armed (serving plane)", flush=True)
            if args.self_heal:
                if args.ps <= 0:
                    print("--self-heal needs --ps > 0", file=sys.stderr)
                    return 2
                topo.start_self_heal(interval_s=args.self_heal_interval_s)
                print("self-heal armed (PS plane)", flush=True)
            if args.reshard_ps > 0:
                if args.ps <= 0:
                    print("--reshard-ps needs --ps > 0", file=sys.stderr)
                    return 2
                # operator CLI at job setup: the stream has not started, so
                # the whole fleet is trivially drained here and no other
                # control loop is live to contend for the arbiter lease
                stats = topo.reshard_ps(args.reshard_ps)  # persia-lint: disable=PROTO005,CTRL002
                print(f"PS tier resharded {args.ps} -> {args.reshard_ps}: "
                      f"{_json.dumps({k: v for k, v in stats.items() if k != 'skew_splits'})}",
                      flush=True)
            t_end = (_time.monotonic() + args.duration_s
                     if args.duration_s > 0 else None)
            try:
                while topo.trainer_running():
                    if t_end is not None and _time.monotonic() >= t_end:
                        break
                    _time.sleep(2.0)
                    s = topo.stats()
                    gw = s.get("gateway", {})
                    print(
                        f"steps={s['trainer_steps']} head={gw.get('head_step')} "
                        f"live={len(gw.get('live', []))} "
                        f"quarantined={gw.get('quarantined', [])}",
                        flush=True,
                    )
            except KeyboardInterrupt:
                pass
            print(_json.dumps(topo.stats(), default=str), flush=True)
            if args.trace_dir:
                # merge while the roles are still up: live /spans beats the
                # dead-role fallback files
                merged = topo.merge_traces()
                if merged:
                    print(f"merged trace: {merged}", flush=True)
        return 0

    if args.role == "coordinator":
        from persia_tpu.service.discovery import Coordinator

        c = Coordinator(port=args.port).start()
        print(f"coordinator on port {c.port}", flush=True)
        c.server._thread.join()
        return 0

    if args.role == "k8s":
        from persia_tpu import k8s as k8s_mod
        from persia_tpu.utils import dump_yaml_str

        if args.action == "gencrd":
            print(dump_yaml_str(k8s_mod.generate_crd()))
            return 0
        if args.action == "operator":
            # reconcile loop (ref: k8s/src/bin/operator.rs) + optional REST
            # scheduler (ref: k8s/src/bin/server.rs)
            from persia_tpu.k8s_operator import main as operator_main

            op_args = ["--interval-s", str(args.interval_s)]
            if args.namespace:
                op_args += ["--namespace", args.namespace]
            if args.rest_port:
                op_args += ["--rest-port", str(args.rest_port)]
            operator_main(op_args)
            return 0
        if args.action == "e2e":
            # cluster system test (ref: k8s/src/bin/e2e.rs)
            from persia_tpu.k8s_e2e import main as e2e_main

            e2e_args = ["--timeout-s", str(args.timeout_s), "--image", args.image]
            if args.name:
                e2e_args += ["--name", args.name]
            if args.namespace:
                e2e_args += ["--namespace", args.namespace]
            return e2e_main(e2e_args)
        if args.action == "delete":
            if not args.name:
                print("k8s delete requires --name", file=sys.stderr)
                return 2
            return k8s_mod.delete(args.name, args.namespace or "default")
        if not args.job_yaml:
            print(f"k8s {args.action} requires --job-yaml", file=sys.stderr)
            return 2
        with open(args.job_yaml) as f:
            spec = k8s_mod.load_job_yaml(f.read())
        if args.namespace:
            spec.namespace = args.namespace
        if args.action == "gen":
            print(k8s_mod.manifests_yaml(spec))
            return 0
        return k8s_mod.apply(spec)

    return 2


def _cli() -> None:
    try:
        rc = main()
    except BrokenPipeError:  # e.g. `... k8s gen | head`
        # Redirect stdout to devnull so the interpreter-shutdown flush of the
        # closed pipe can't raise again (python docs SIGPIPE recipe).
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        rc = 0
    sys.exit(rc)


if __name__ == "__main__":
    _cli()

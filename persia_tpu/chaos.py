"""Deterministic chaos / fault injection for the training-side service plane.

Nothing in a resilience stack is real until a fault can be injected on
demand and the recovery asserted. This module provides the two fault
surfaces the training plane has:

- **transport faults** via :class:`ChaosProxy` — a frame-aware TCP proxy
  slotted between an RPC client and a real server. Per forwarded frame it
  can, driven by a SEEDED RNG (same seed → same fault sequence per
  connection): refuse new connections, cut the stream mid-frame
  (``reset``), delay delivery (``slow``), flip a payload byte
  (``corrupt`` — detected end-to-end when the RPC layer's negotiated
  crc32 trailer is on, ``PERSIA_RPC_CRC=1``), or truncate a frame and
  close. A ``blackhole`` switch emulates a network partition (every new
  and existing connection dies) independent of process liveness.

- **process faults** via :class:`ChaosPlane` — wraps a
  :class:`~persia_tpu.helper.ServiceCtx` local topology: every PS replica
  gets a proxy, and a scripted :class:`ChaosSchedule` (fired from the
  training loop through ``on_step``/``wrap_batches``) can SIGKILL a PS
  shard, restart it (optionally replaying the last snapshot through
  ``dump_shard``/``load_shard_bytes``), or open/heal a partition at a
  chosen step — the same schedule file shape ``bench.py --chaos`` takes
  for soak runs.

Everything is usable both from tests (tests/test_chaos.py) and from
``bench.py --chaos``.
"""

from __future__ import annotations

import os
import random
import socket
import struct
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics
from persia_tpu.tracing import record_event

logger = get_default_logger("persia_tpu.chaos")


@dataclass
class ChaosConfig:
    """Per-frame fault probabilities (all default 0 = transparent proxy).

    ``seed`` drives every decision: per accepted connection the proxy
    derives ``Random((seed, conn_id))`` and draws once per forwarded
    frame, so a schedule replays identically run to run (connection
    ARRIVAL order is the only nondeterminism left, and each connection's
    own fault stream is fixed)."""

    seed: int = 0
    refuse_prob: float = 0.0    # close a brand-new connection at accept
    reset_prob: float = 0.0     # cut the stream mid-frame
    slow_prob: float = 0.0      # delay a frame by slow_ms
    slow_ms: float = 50.0
    corrupt_prob: float = 0.0   # flip one byte inside the frame body
    truncate_prob: float = 0.0  # ship a partial frame, then close

    def to_dict(self) -> Dict:
        return asdict(self)


def parse_chaos_spec(spec: str) -> ChaosConfig:
    """Parse a ``bench.py --chaos`` spec string like
    ``"seed=7,reset=0.02,slow=0.01,slow_ms=40,corrupt=0.005"``.
    Keys: seed, refuse, reset, slow, slow_ms, corrupt, truncate."""
    cfg = ChaosConfig()
    if not spec:
        return cfg
    alias = {
        "refuse": "refuse_prob", "reset": "reset_prob", "slow": "slow_prob",
        "corrupt": "corrupt_prob", "truncate": "truncate_prob",
        "seed": "seed", "slow_ms": "slow_ms",
    }
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        attr = alias.get(key.strip())
        if attr is None:
            raise ValueError(f"unknown chaos knob {key!r} in {spec!r}")
        setattr(cfg, attr, int(val) if attr == "seed" else float(val))
    return cfg


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    while n:
        try:
            chunk = sock.recv(min(n, 1 << 20))
        except OSError:
            return None
        if not chunk:
            return None
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class ChaosProxy:
    """Frame-aware TCP proxy injecting transport faults.

    Understands the RPC framing (``u32 length | body`` in BOTH
    directions), so faults land on frame boundaries the way real network
    damage presents to the framing layer: a ``reset`` delivers a partial
    frame then EOF, a ``corrupt`` flips a byte inside the body (never the
    length prefix — the point is payload damage the framing alone cannot
    see), a ``truncate`` ships a prefix and closes.
    """

    def __init__(self, backend_addr: str, cfg: Optional[ChaosConfig] = None,
                 name: str = ""):
        host, port = backend_addr.rsplit(":", 1)
        self.backend = (host, int(port))
        self.cfg = cfg or ChaosConfig()
        self.name = name or backend_addr
        self.blackhole = threading.Event()
        # forced per-frame latency floor (gray-failure injection): unlike
        # the probabilistic ``slow_prob`` this delays EVERY frame, turning
        # the backend into a replica that still answers — just at p99 far
        # above its peers. Float so tests can set sub-ms floors.
        self._forced_latency_s = 0.0
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self.addr = f"127.0.0.1:{self.port}"
        self._stop = threading.Event()
        self._conn_id = 0
        self._live_socks: List[socket.socket] = []
        self._lock = threading.Lock()
        # injected-fault accounting (tests assert the schedule actually
        # fired; bench records it in the artifact)
        self.counts: Dict[str, int] = {
            "frames": 0, "refused": 0, "reset": 0, "slow": 0,
            "corrupt": 0, "truncated": 0, "grayed": 0,
        }
        m = get_metrics()
        self._m_injected = m.counter(
            "persia_tpu_chaos_faults_injected", "faults injected by ChaosProxy"
        )
        self._accept_t = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"chaos-accept-{self.name}",
        )
        self._accept_t.start()

    def _note_fault(self, kind: str) -> None:
        """ONE ledger per injected fault: the counts dict (tests), the
        metric (scrapes), and the flight recorder (post-mortem
        correlation against the breaker/quarantine events it caused)."""
        self.counts[kind] += 1
        self._m_injected.inc(kind=kind)
        record_event(f"chaos.{kind}", proxy=self.name)

    # ----------------------------------------------------------- lifecycle

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self._kill_live()

    def _kill_live(self) -> None:
        # shutdown (not close) wakes pump threads blocked in recv without
        # freeing the fd under them (close here would race a concurrent
        # recv with fd reuse — observed as 5 s client hangs); each pump
        # closes its own read-side socket on exit
        with self._lock:
            socks, self._live_socks = self._live_socks, []
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def set_blackhole(self, on: bool) -> None:
        """Partition emulation: while on, new connections are refused and
        every existing one is cut."""
        if on:
            self.blackhole.set()
            self._kill_live()
        else:
            self.blackhole.clear()

    def set_latency(self, ms: float) -> None:
        """Gray-failure injection: force a latency floor of ``ms`` onto
        EVERY forwarded frame (0 restores transparency). The backend keeps
        answering correctly — it just becomes a sustained latency outlier
        against its peers, which is exactly the failure class a liveness
        probe alone cannot see."""
        self._forced_latency_s = max(float(ms), 0.0) / 1e3
        record_event("chaos.set_latency", proxy=self.name, ms=float(ms))

    # ------------------------------------------------------------- pumping

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                self._conn_id += 1
                cid = self._conn_id
            # int-mixed seeds (tuple seeding is deprecated): one stream
            # per connection per direction, stable across runs
            rng = random.Random(self.cfg.seed * 1_000_003 + cid * 2)
            if self.blackhole.is_set() or (
                self.cfg.refuse_prob and rng.random() < self.cfg.refuse_prob
            ):
                self._note_fault("refused")
                try:
                    client.close()
                except OSError:
                    pass
                continue
            try:
                upstream = socket.create_connection(self.backend, timeout=10)
            except OSError:
                try:
                    client.close()
                except OSError:
                    pass
                continue
            for s in (client, upstream):
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._live_socks += [client, upstream]
            # each direction gets its own deterministic fault stream
            threading.Thread(
                target=self._pump, args=(client, upstream, rng),
                daemon=True, name=f"chaos-c2s-{self.name}-{cid}",
            ).start()
            threading.Thread(
                target=self._pump,
                args=(upstream, client,
                      random.Random(self.cfg.seed * 1_000_003 + cid * 2 + 1)),
                daemon=True, name=f"chaos-s2c-{self.name}-{cid}",
            ).start()

    def _close_pair(self, a: socket.socket, b: socket.socket) -> None:
        """Terminate a proxied connection: SHUTDOWN both sockets — this
        sends FIN to both peers immediately AND wakes the sibling pump
        thread blocked in recv — but do NOT close fds here: the sibling
        may still be inside recv() on one of them, and closing would free
        the fd under it (fd-reuse hands it someone else's bytes). Each
        pump closes its own read-side socket when it exits."""
        for s in (a, b):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def _pump(self, src: socket.socket, dst: socket.socket,
              rng: random.Random) -> None:
        try:
            self._pump_loop(src, dst, rng)
        finally:
            # this thread is the only reader of ``src`` — safe to close it
            # now that our recv loop has exited
            try:
                src.close()
            except OSError:
                pass

    def _pump_loop(self, src: socket.socket, dst: socket.socket,
                   rng: random.Random) -> None:
        cfg = self.cfg
        while not self._stop.is_set():
            header = _recv_exact(src, 4)
            if header is None:
                self._close_pair(src, dst)
                return
            (total,) = struct.unpack("<I", header)
            frame = _recv_exact(src, total) if total else b""
            if frame is None:
                self._close_pair(src, dst)
                return
            self.counts["frames"] += 1
            if self.blackhole.is_set():
                self._close_pair(src, dst)
                return
            try:
                forced = self._forced_latency_s
                if forced > 0.0:
                    self._note_fault("grayed")
                    time.sleep(forced)
                r = rng.random()
                if cfg.reset_prob and r < cfg.reset_prob:
                    # mid-frame cut: the peer sees a partial frame + EOF
                    self._note_fault("reset")
                    dst.sendall(header + frame[: len(frame) // 2])
                    self._close_pair(src, dst)
                    return
                if cfg.truncate_prob and r < cfg.reset_prob + cfg.truncate_prob:
                    self._note_fault("truncated")
                    dst.sendall(header + frame[: max(len(frame) - 3, 0)])
                    self._close_pair(src, dst)
                    return
                if cfg.slow_prob and rng.random() < cfg.slow_prob:
                    self._note_fault("slow")
                    time.sleep(cfg.slow_ms / 1e3)
                if (
                    cfg.corrupt_prob and len(frame) > 1
                    and rng.random() < cfg.corrupt_prob
                ):
                    # flip one byte INSIDE the body (never byte 0: damaging
                    # the flags/status byte changes protocol dispatch rather
                    # than payload content, which is a different fault class)
                    self._note_fault("corrupt")
                    pos = 1 + rng.randrange(len(frame) - 1)
                    frame = bytearray(frame)
                    frame[pos] ^= 0xFF
                    frame = bytes(frame)
                dst.sendall(header + frame)
            except OSError:
                self._close_pair(src, dst)
                return


# ------------------------------------------------------- delta-channel chaos


class DeltaChannelChaos:
    """Fault-injecting relay for the incremental delta channel.

    The train-to-serve delta stream (persia_tpu/incremental.py) is a
    storage directory, not a TCP stream, so :class:`ChaosProxy` cannot
    damage it. This relay gives each serving replica its OWN delivery
    directory and copies packets + done-markers from the trainer's source
    dir into it — with per-delivery faults decided by a SEEDED hash of
    ``(seed, replica, name)``, so a schedule replays identically:

    - ``corrupt_prob`` — flip one byte inside the packet body (caught by
      the v2 crc32 frame);
    - ``truncate_prob`` — deliver a torn prefix (caught by the crc/framing
      check);
    - ``drop_prob`` — never deliver the packet (a seq gap at the consumer);
    - ``set_blackhole(i)`` — stop delivering ANYTHING to replica ``i``
      (partition: its freshness head freezes and its lag grows until the
      gateway quarantines it);
    - :meth:`redeliver` — recopy every retained source file fresh (the
      consumer's resync path re-fetches from durable storage).

    Damaged deliveries stay damaged until redelivered — exactly how object
    storage presents a torn upload.
    """

    def __init__(self, src_dir, base_dir, n_replicas: int,
                 cfg: Optional[ChaosConfig] = None, seed: int = 0):
        from persia_tpu.storage import storage_path

        self.src = storage_path(str(src_dir))
        self.cfg = cfg or ChaosConfig()
        self.seed = seed if not (cfg and cfg.seed) else cfg.seed
        self.replica_dirs = [
            storage_path(str(base_dir)).join(f"replica_{i}")
            for i in range(n_replicas)
        ]
        for d in self.replica_dirs:
            d.makedirs()
        self._delivered: List[set] = [set() for _ in range(n_replicas)]
        self._blackholed: List[bool] = [False] * n_replicas
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.counts: Dict[str, int] = {
            "delivered": 0, "corrupt": 0, "truncated": 0, "dropped": 0,
            "blackholed": 0, "redelivered": 0,
        }

    def inc_dir(self, i: int) -> str:
        """The delivery directory replica ``i``'s IncrementalLoader scans."""
        return str(self.replica_dirs[i])

    def set_blackhole(self, i: int, on: bool) -> None:
        with self._lock:
            self._blackholed[i] = on
        record_event("chaos.blackhole" if on else "chaos.heal", replica=i)

    def _fault_for(self, replica: int, name: str) -> str:
        """Deterministic per-(replica, delivery) fault draw."""
        rng = random.Random(f"{self.seed}:{replica}:{name}")
        r = rng.random()
        cfg = self.cfg
        if cfg.corrupt_prob and r < cfg.corrupt_prob:
            return "corrupt"
        r -= cfg.corrupt_prob
        if cfg.truncate_prob and r < cfg.truncate_prob:
            return "truncated"
        r -= cfg.truncate_prob
        # reuse refuse_prob as the drop knob (a refused delivery = a gap)
        if cfg.refuse_prob and r < cfg.refuse_prob:
            return "dropped"
        return "ok"

    def _damage(self, blob: bytes, fault: str, replica: int, name: str) -> bytes:
        rng = random.Random(f"{self.seed}:damage:{replica}:{name}")
        if fault == "corrupt" and len(blob) > 40:
            # flip a byte INSIDE the body (past the 36-byte v2 header): the
            # point is payload damage only the crc frame can see
            pos = 40 + rng.randrange(len(blob) - 40)
            out = bytearray(blob)
            out[pos] ^= 0xFF
            return bytes(out)
        if fault == "truncated":
            return blob[: max(len(blob) - max(4, len(blob) // 3), 1)]
        return blob

    def _src_names(self) -> List[str]:
        """Published packet + done-marker names only — never a publisher's
        in-flight ``.tmp_*`` file (temp + atomic-rename means those vanish
        under a concurrent read)."""
        from persia_tpu.incremental import _MARKER_RE, _PACKET_RE
        from persia_tpu.storage import StorageError

        try:
            names = sorted(self.src.list()) if self.src.exists() else []
        except StorageError:
            return []
        return [n for n in names if _PACKET_RE.match(n) or _MARKER_RE.match(n)]

    def pump_once(self) -> int:
        """Relay every undelivered source file to every non-blackholed
        replica. Returns deliveries made."""
        names = self._src_names()
        made = 0
        for i, dst in enumerate(self.replica_dirs):
            with self._lock:
                if self._blackholed[i]:
                    self.counts["blackholed"] += 1  # pumps withheld
                    continue
                todo = [n for n in names if n not in self._delivered[i]]
            for name in todo:
                made += self._deliver(i, dst, name)
        return made

    def _deliver(self, i: int, dst, name: str, force_clean: bool = False) -> int:
        from persia_tpu.storage import StorageError

        try:
            blob = self.src.join(name).read_bytes()
        except StorageError:
            return 0  # pruned mid-pump; next scan settles
        fault = "ok" if force_clean else self._fault_for(i, name)
        with self._lock:
            self._delivered[i].add(name)
            if fault == "dropped":
                self.counts["dropped"] += 1
                record_event("chaos.dropped", replica=i, packet=name)
                return 0
            if fault != "ok":
                self.counts[fault] += 1
                record_event(f"chaos.{fault}", replica=i, packet=name)
            self.counts["delivered"] += 1
        try:
            dst.join(name).write_bytes(self._damage(blob, fault, i, name))
        except StorageError:
            with self._lock:
                self._delivered[i].discard(name)  # retry next pump
            return 0
        return 1

    def redeliver(self, i: int) -> int:
        """Resync support: recopy every retained source file to replica
        ``i`` fresh (clean — the durable source is intact; the damage
        happened in delivery). Clears the delivery memory first so future
        pumps stay consistent."""
        names = self._src_names()
        with self._lock:
            self._delivered[i].clear()
        n = 0
        for name in names:
            n += self._deliver(i, self.replica_dirs[i], name, force_clean=True)
        with self._lock:
            self.counts["redelivered"] += n
        return n

    def start(self, interval_s: float = 0.2) -> "DeltaChannelChaos":
        if self._thread is None:
            def loop():
                while not self._stop.wait(interval_s):
                    try:
                        self.pump_once()
                    except Exception:  # noqa: BLE001 — relay must survive
                        logger.exception("delta-channel pump failed")

            self._thread = threading.Thread(
                target=loop, daemon=True, name="chaos-delta-relay"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


@dataclass
class DataPlaneChaosConfig:
    """Per-batch data-corruption probabilities for :class:`DataPlaneChaos`
    (all default 0 = transparent). One fault class fires per batch at
    most — draws share a single uniform sample so probabilities compose
    the same way as :class:`ChaosConfig`."""

    seed: int = 0
    nan_prob: float = 0.0          # NaN/Inf written into a dense feature
    label_flip_prob: float = 0.0   # binary labels inverted
    sign_corrupt_prob: float = 0.0 # high bits set on id-feature signs
    spike_prob: float = 0.0        # dense features scaled by spike_scale
    spike_scale: float = 1e6       # finite, but large enough to spike grads

    def to_dict(self) -> Dict:
        return asdict(self)


def parse_data_chaos_spec(spec: str) -> DataPlaneChaosConfig:
    """Parse a ``bench.py --chaos`` data-plane spec string like
    ``"seed=7,nan=0.01,label_flip=0.02,sign=0.01,spike=0.01"``.
    Keys: seed, nan, label_flip, sign, spike, spike_scale."""
    cfg = DataPlaneChaosConfig()
    if not spec:
        return cfg
    alias = {
        "nan": "nan_prob", "label_flip": "label_flip_prob",
        "sign": "sign_corrupt_prob", "spike": "spike_prob",
        "spike_scale": "spike_scale", "seed": "seed",
    }
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        attr = alias.get(key.strip())
        if attr is None:
            raise ValueError(f"unknown data-chaos knob {key!r} in {spec!r}")
        setattr(cfg, attr, int(val) if attr == "seed" else float(val))
    return cfg


class DataPlaneChaos:
    """Seeded batch-level fault injector for the training data plane.

    The transport fault classes above damage bytes in flight; this one
    damages batch CONTENT — the poisons the health layer
    (persia_tpu/health) exists to catch: non-finite dense features and
    labels (validator reject), out-of-domain signs (validator reject),
    flipped labels and finite gradient spikes (on-device sentinel /
    host z-score). The fault draw hashes ``(seed, batch_index)`` so a
    schedule replays identically — the same property that makes the
    bit-parity rollback test deterministic.

    Mutated arrays are COPIES: the source batch stays clean, so a
    clean-vs-poisoned parity run can share one dataset object.
    """

    def __init__(self, cfg: Optional[DataPlaneChaosConfig] = None):
        self.cfg = cfg or DataPlaneChaosConfig()
        self.counts: Dict[str, int] = {
            "batches": 0, "nan": 0, "label_flip": 0, "sign_corrupt": 0,
            "spike": 0,
        }

    def _fault_for(self, index: int) -> str:
        rng = random.Random(f"{self.cfg.seed}:batch:{index}")
        r = rng.random()
        cfg = self.cfg
        for name, prob in (("nan", cfg.nan_prob),
                           ("label_flip", cfg.label_flip_prob),
                           ("sign_corrupt", cfg.sign_corrupt_prob),
                           ("spike", cfg.spike_prob)):
            if prob and r < prob:
                return name
            r -= prob
        return "ok"

    def _poison(self, batch, fault: str, index: int):
        from persia_tpu.data import (IDTypeFeature, Label, NonIDTypeFeature,
                                     PersiaBatch)

        rng = random.Random(f"{self.cfg.seed}:poison:{index}")
        id_feats = batch.id_type_features
        dense = list(batch.non_id_type_features)
        labels = list(batch.labels)
        if fault == "nan" and dense:
            fi = rng.randrange(len(dense))
            arr = dense[fi].data.astype(np.float32, copy=True)
            flat = arr.reshape(-1)
            flat[rng.randrange(flat.size)] = (
                np.nan if rng.random() < 0.5 else np.inf
            )
            dense[fi] = NonIDTypeFeature(arr, name=dense[fi].name)
        elif fault == "label_flip" and labels:
            li = rng.randrange(len(labels))
            arr = labels[li].data.astype(np.float32, copy=True)
            labels[li] = Label(1.0 - arr, name=labels[li].name)
        elif fault == "sign_corrupt" and id_feats:
            fi = rng.randrange(len(id_feats))
            feat = id_feats[fi]
            flat, cnts = feat.flat_counts()
            if flat.size:
                flat = flat.copy()
                flat[rng.randrange(flat.size)] |= np.uint64(1) << np.uint64(63)
                id_feats = list(id_feats)
                id_feats[fi] = IDTypeFeature.from_flat(feat.name, flat, cnts)
        elif fault == "spike" and dense:
            fi = rng.randrange(len(dense))
            arr = dense[fi].data.astype(np.float32, copy=True)
            dense[fi] = NonIDTypeFeature(
                arr * np.float32(self.cfg.spike_scale), name=dense[fi].name
            )
        return PersiaBatch(
            id_type_features=id_feats,
            non_id_type_features=dense,
            labels=labels,
            requires_grad=batch.requires_grad,
            batch_id=batch.batch_id,
            meta=batch.meta,
        )

    def wrap(self, batches):
        """Yield each batch, poisoned per the seeded schedule."""
        for index, batch in enumerate(batches):
            self.counts["batches"] += 1
            fault = self._fault_for(index)
            if fault != "ok":
                self.counts[fault] += 1
                record_event("chaos.data_fault", fault=fault, batch=index)
                batch = self._poison(batch, fault, index)
            yield batch


# ----------------------------------------------------------- trainer kills


def write_progress(path: str, step: int) -> None:
    """Trainer-side step beacon for an external killer/watchdog: atomic
    replace so a reader never sees a torn value. Called once per step by a
    trainer under chaos test (tests/jobstate_trainer_main.py)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(str(int(step)))
    os.replace(tmp, path)


def read_progress(path: str) -> int:
    """-1 until the trainer has published its first step."""
    try:
        with open(path) as f:
            return int(f.read().strip() or -1)
    except (OSError, ValueError):
        return -1


class TrainerKiller:
    """SIGKILL a trainer subprocess when its progress beacon reaches a
    target step — the process-fault half of the trainer-crash story (the
    PS-side kills live in :class:`ChaosPlane`). The kill is a real
    ``SIGKILL`` mid-step: no atexit, no flush, exactly the failure a TPU
    preemption or OOM-kill presents."""

    def __init__(self, proc, progress_path: str, kill_at_step: int,
                 poll_s: float = 0.02):
        self.proc = proc
        self.progress_path = progress_path
        self.kill_at_step = int(kill_at_step)
        self.poll_s = poll_s
        self.killed_at: Optional[int] = None
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._watch, daemon=True, name="chaos-trainer-killer"
        )

    def start(self) -> "TrainerKiller":
        self._thread.start()
        return self

    def _watch(self) -> None:
        try:
            while self.proc.poll() is None:
                step = read_progress(self.progress_path)
                if step >= self.kill_at_step:
                    self.proc.kill()
                    self.proc.wait(timeout=30)
                    self.killed_at = step
                    logger.info(
                        "chaos: SIGKILLed trainer pid %d at step %d",
                        self.proc.pid, step,
                    )
                    return
                time.sleep(self.poll_s)
        finally:
            self._done.set()

    def wait(self, timeout_s: float = 120.0) -> bool:
        """True once the watcher finished (kill fired, or the trainer
        exited on its own first — ``killed_at`` distinguishes)."""
        return self._done.wait(timeout_s)


# ---------------------------------------------------------- load schedules


@dataclass
class LoadShapeConfig:
    """Seeded TRAFFIC-shape schedule — the workload half of the chaos
    plane. The fault machinery above perturbs the transport; this perturbs
    the LOAD so a closed-loop controller (persia_tpu/autopilot) has
    something real to react to. Three composable shapes, all driven by the
    step ordinal and ``seed`` alone (bit-reproducible run to run):

    - **zipf exponent ramp**: the sign distribution's zipf exponent
      interpolates ``zipf_a0 → zipf_a1`` over steps
      ``[ramp_start, ramp_end]`` — skew concentrates (or relaxes) under
      the fleet, moving the per-shard load balance the sketch measures;
    - **step traffic spike**: modeled request rate multiplies by
      ``spike_x`` inside ``[spike_start, spike_end)`` — the serving-plane
      scale-up/scale-down trigger;
    - **hot-set rotation**: every ``rotate_every`` steps the IDENTITY of
      the hot head shifts by ``rotate_stride`` sign positions (the
      distribution's shape is unchanged, its support moves) — yesterday's
      heavy hitters go cold, invalidating any placement pinned to them.

    Used by both ``benchmarks/autopilot_bench.py`` and ``bench.py
    --chaos`` (``BENCH_CHAOS_LOAD`` spec, :func:`parse_load_spec`)."""

    seed: int = 7
    vocab: int = 1 << 17
    zipf_a0: float = 1.2
    zipf_a1: float = 1.2
    ramp_start: int = 0
    ramp_end: int = 0
    base_qps: float = 100.0
    spike_x: float = 1.0
    spike_start: int = 0
    spike_end: int = 0
    rotate_every: int = 0  # 0 = no rotation
    rotate_stride: int = 7919  # prime stride keeps rotations disjoint

    def to_dict(self) -> Dict:
        return asdict(self)


def parse_load_spec(spec: str) -> LoadShapeConfig:
    """Parse a ``BENCH_CHAOS_LOAD`` spec like
    ``"a0=1.1,a1=1.7,ramp=10:50,spike=4x20:30,rotate=16,seed=7"``.
    Keys: seed, vocab, a0, a1, ramp=START:END, qps, spike=Xx|spike=XxS:E,
    rotate (= rotate_every), stride."""
    cfg = LoadShapeConfig()
    if not spec:
        return cfg
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        if key == "seed":
            cfg.seed = int(val)
        elif key == "vocab":
            cfg.vocab = int(val)
        elif key == "a0":
            cfg.zipf_a0 = float(val)
        elif key == "a1":
            cfg.zipf_a1 = float(val)
        elif key == "ramp":
            s, _, e = val.partition(":")
            cfg.ramp_start, cfg.ramp_end = int(s), int(e)
        elif key == "qps":
            cfg.base_qps = float(val)
        elif key == "spike":
            mult, _, window = val.partition("x")
            cfg.spike_x = float(mult)
            if window:
                s, _, e = window.partition(":")
                cfg.spike_start, cfg.spike_end = int(s), int(e)
        elif key == "rotate":
            cfg.rotate_every = int(val)
        elif key == "stride":
            cfg.rotate_stride = int(val)
        else:
            raise ValueError(f"unknown load knob {key!r} in {spec!r}")
    return cfg


class LoadSchedule:
    """Materializes a :class:`LoadShapeConfig`: per-step zipf exponent,
    modeled request rate, and seeded sign batches. Every draw derives its
    generator from ``(seed, step, slot)`` so any step is reproducible in
    isolation — a resumed soak replays the exact traffic of the run it
    resumes (the same discipline the fault proxies keep per connection)."""

    def __init__(self, cfg: Optional[LoadShapeConfig] = None):
        self.cfg = cfg or LoadShapeConfig()

    def zipf_a(self, step: int) -> float:
        c = self.cfg
        if c.ramp_end <= c.ramp_start:
            return c.zipf_a0
        t = min(max((step - c.ramp_start) / (c.ramp_end - c.ramp_start), 0.0),
                1.0)
        return c.zipf_a0 + t * (c.zipf_a1 - c.zipf_a0)

    def qps(self, step: int) -> float:
        c = self.cfg
        if c.spike_end > c.spike_start and c.spike_start <= step < c.spike_end:
            return c.base_qps * c.spike_x
        return c.base_qps

    def rotation(self, step: int) -> int:
        c = self.cfg
        return 0 if c.rotate_every <= 0 else step // c.rotate_every

    def signs(self, step: int, n: int, slot: int = 0) -> np.ndarray:
        """One seeded sign batch: zipf(``zipf_a(step)``) ranks, rotated by
        the step's hot-set rotation, offset into the slot's sign space
        (u64, never 0 — sign 0 is the stores' reserved empty key)."""
        c = self.cfg
        rng = np.random.default_rng(
            (c.seed * 1_000_003 + step) * 1_000_033 + slot
        )
        ranks = rng.zipf(max(self.zipf_a(step), 1.001), n).astype(np.uint64)
        rot = np.uint64((self.rotation(step) * c.rotate_stride) % c.vocab)
        ids = (ranks + rot) % np.uint64(c.vocab)
        return ids + np.uint64(slot * c.vocab + 1)


# --------------------------------------------------------------- schedules


@dataclass
class ChaosAction:
    """One scripted process/topology fault, fired when the driving loop
    reaches ``step``. ``op``: ``kill_ps`` | ``restart_ps`` |
    ``kill_restart_ps`` (kill + immediate same-port restart) |
    ``kill_ps_autoheal`` (snapshot then SIGKILL, and deliberately NO
    restart — the self-healing autopilot is expected to detect the death
    and promote a standby on its own; the schedule just makes the hole) |
    ``blackhole`` / ``heal`` (partition one shard's proxy) |
    ``gray_ps`` / ``ungray_ps`` (force/clear a per-frame latency floor of
    ``latency_ms`` on one shard's proxy — the replica still answers, at
    p99 far above its peers: the gray-failure injector) |
    ``heartbeat_ghost`` (SIGKILL the shard but keep publishing its
    heartbeat lease from this process — heartbeat-only death: the lease
    plane says alive while the data plane is gone) |
    ``snapshot`` (record the shard's state for a later replaying
    restart).

    ``after_s > 0`` executes the op in a BACKGROUND thread after the
    delay — the idiom for a real outage window: fire ``kill_ps`` inline
    at step N and a delayed ``restart_ps`` in the same step, so the
    training loop keeps issuing (and failing, and breaker-tripping)
    lookups while the shard is genuinely gone."""

    step: int
    op: str
    idx: int = 0
    restore: bool = False  # restart replays the last snapshot
    after_s: float = 0.0   # 0 = synchronous at fire time
    fired: bool = False
    # ``op="kill_trainer"`` SIGKILLs the subprocess registered via
    # ChaosPlane.attach_trainer — only meaningful when the schedule is
    # driven from OUTSIDE the trainer process (a parent harness walking
    # the trainer's progress beacon), since a trainer cannot outlive
    # firing its own SIGKILL.
    #
    # ``op="kill_during_reshard"`` does not kill at fire time: it ARMS the
    # plane so the NEXT reshard driven with ``reshard_fault_hook()``
    # SIGKILLs PS ``idx`` when the handoff reaches (handoff_op, op_index)
    # — the reshard engine's own injection point, so the kill lands
    # between two journaled ops rather than between two steps.
    # ``op_index < 0`` draws the target op ordinal from the plane's seed
    # instead (same seed → same kill point, run to run).
    handoff_op: str = "import"  # "import" | "delete"
    op_index: int = 0
    latency_ms: float = 250.0  # gray_ps forced per-frame latency floor


class ChaosPlane:
    """Chaos harness over a :class:`~persia_tpu.helper.ServiceCtx`.

    Every PS replica is fronted by a :class:`ChaosProxy`; ``ps_clients``
    hands back StoreClients wired through the proxies, so transport
    faults hit the same code paths production traffic uses. Process
    faults run from a scripted schedule driven by the training loop
    (``on_step`` / ``wrap_batches``) — deterministic by construction.
    """

    def __init__(
        self,
        svc,
        cfg: Optional[ChaosConfig] = None,
        schedule: Optional[Sequence[ChaosAction]] = None,
    ):
        self.svc = svc
        self.cfg = cfg or ChaosConfig()
        self.schedule: List[ChaosAction] = sorted(
            (schedule or []), key=lambda a: a.step
        )
        self.proxies: List[ChaosProxy] = [
            ChaosProxy(addr, self.cfg, name=f"ps{i}")
            for i, addr in enumerate(svc.ps_addrs())
        ]
        self._step = -1
        self._trainer_proc = None
        # kill_during_reshard arms land here; reshard_fault_hook consumes
        self._reshard_arms: List[ChaosAction] = []
        self._reshard_counts: Dict[str, int] = {"reshard_kills": 0}
        # heartbeat_ghost publishers keep a dead shard's lease fresh until
        # stop() exorcises them
        self._ghosts: List["HeartbeatGhost"] = []

    def attach_trainer(self, proc) -> None:
        """Register the trainer subprocess the ``kill_trainer`` op targets
        (the watchdogging parent harness owns the Popen)."""
        self._trainer_proc = proc

    def ps_addrs(self) -> List[str]:
        return [p.addr for p in self.proxies]

    def ps_clients(self, **kwargs) -> List:
        from persia_tpu.service.clients import StoreClient

        return [StoreClient(p.addr, **kwargs) for p in self.proxies]

    def fault_counts(self) -> Dict[str, int]:
        total: Dict[str, int] = dict(self._reshard_counts)
        for p in self.proxies:
            for k, v in p.counts.items():
                total[k] = total.get(k, 0) + v
        return total

    def reshard_fault_hook(self):
        """The ``fault_hook`` to pass into ``ServiceCtx.reshard_ps`` /
        ``resume_reshard``: fires every armed ``kill_during_reshard``
        action whose (handoff_op, op_index) the engine reaches. A seeded
        arm (``op_index < 0``) resolves its target ordinal from the
        plane's chaos seed, counting hook invocations of its op kind."""
        import random as _random

        for a in self._reshard_arms:
            if a.op_index < 0:
                a.op_index = _random.Random(
                    self.cfg.seed * 1_000_003 + a.idx * 2 + a.step
                ).randrange(0, 4)
        def hook(kind: str, idx: int, mv) -> None:
            for a in list(self._reshard_arms):
                if a.handoff_op == kind and a.op_index == idx:
                    self._reshard_arms.remove(a)
                    self._reshard_counts["reshard_kills"] += 1
                    record_event(
                        "chaos.kill_during_reshard", idx=a.idx,
                        handoff_op=kind, op_index=idx,
                    )
                    logger.info(
                        "chaos: SIGKILL ps%d during reshard at %s[%d]",
                        a.idx, kind, idx,
                    )
                    self.svc.kill_ps(a.idx)

        return hook

    # ------------------------------------------------------------ schedule

    def on_step(self, step: int) -> None:
        """Fire every not-yet-fired action with ``action.step <= step``."""
        self._step = step
        for a in self.schedule:
            if a.fired or a.step > step:
                continue
            a.fired = True
            logger.info(
                "chaos: firing %s(idx=%d) at step %d%s", a.op, a.idx, step,
                f" after {a.after_s}s" if a.after_s else "",
            )
            if a.after_s > 0:
                threading.Thread(
                    target=self._fire_delayed, args=(a,), daemon=True,
                    name=f"chaos-delayed-{a.op}",
                ).start()
            else:
                self._execute(a)

    def _fire_delayed(self, a: ChaosAction) -> None:
        time.sleep(a.after_s)
        try:
            self._execute(a)
        except Exception:  # noqa: BLE001 — must not die silently
            logger.exception("chaos: delayed %s(idx=%d) failed", a.op, a.idx)

    def _execute(self, a: ChaosAction) -> None:
        record_event(f"chaos.{a.op}", idx=a.idx, step=a.step)
        if a.op == "snapshot":
            self.svc.snapshot_ps(a.idx)
        elif a.op == "kill_ps":
            self.svc.kill_ps(a.idx)
        elif a.op == "restart_ps":
            self.svc.restart_ps(a.idx, restore=a.restore)
        elif a.op == "kill_restart_ps":
            if a.restore:
                self.svc.snapshot_ps(a.idx)
            self.svc.kill_ps(a.idx)
            self.svc.restart_ps(a.idx, restore=a.restore)
        elif a.op == "kill_ps_autoheal":
            # snapshot first so the healer's standby promotion has a fresh
            # fence to boot-load from; then make the hole and WALK AWAY —
            # recovery is the autopilot's job, not the schedule's
            self.svc.snapshot_ps(a.idx)
            self.svc.kill_ps(a.idx)
        elif a.op == "blackhole":
            self.proxies[a.idx].set_blackhole(True)
        elif a.op == "heal":
            self.proxies[a.idx].set_blackhole(False)
        elif a.op == "gray_ps":
            self.proxies[a.idx].set_latency(a.latency_ms)
        elif a.op == "ungray_ps":
            self.proxies[a.idx].set_latency(0.0)
        elif a.op == "heartbeat_ghost":
            self._ghosts.append(HeartbeatGhost.haunt(self.svc, a.idx))
            self.svc.kill_ps(a.idx)
        elif a.op == "kill_during_reshard":
            self._reshard_arms.append(a)
        elif a.op == "kill_trainer":
            if self._trainer_proc is None:
                raise RuntimeError(
                    "kill_trainer scheduled but no trainer attached "
                    "(ChaosPlane.attach_trainer)"
                )
            self._trainer_proc.kill()
            self._trainer_proc.wait(timeout=30)
        else:
            raise ValueError(f"unknown chaos op {a.op!r}")

    def wrap_batches(self, batches):
        """Drive the schedule from a batch stream: yields each batch after
        firing the actions scheduled for its ordinal."""
        for i, b in enumerate(batches):
            self.on_step(i)
            yield b

    def stop(self) -> None:
        for g in self._ghosts:
            g.stop()
        self._ghosts = []
        for p in self.proxies:
            p.stop()


# -------------------------------------------------- detector-facing chaos


class HeartbeatGhost:
    """Heartbeat-only death: keeps publishing a DEAD replica's lease.

    Wraps a :class:`~persia_tpu.service.failure_detector.LeasePublisher`
    bound to the victim's (role, index, addr) identity, run from the
    chaos harness's own process. To the lease plane the replica looks
    perfectly alive (seq keeps advancing); to the data plane it is gone.
    A detector that trusts heartbeats over probes never evicts it — the
    exact failure mode the verdict matrix's "fresh lease does not rescue
    failing probes" rule exists for.
    """

    def __init__(self, coord, role: str, index: int, addr: str,
                 interval_s: float = 0.2):
        from persia_tpu.service.failure_detector import LeasePublisher

        self._pub = LeasePublisher(
            coord, role, index, addr, interval_s=interval_s
        )
        self._pub.start()
        record_event("chaos.heartbeat_ghost", role=role, index=index)
        logger.info("chaos: heartbeat ghost haunting %s/%d (%s)",
                    role, index, addr)

    @classmethod
    def haunt(cls, svc, idx: int, interval_s: float = 0.2) -> "HeartbeatGhost":
        """Possess PS ``idx`` of a ServiceCtx: publish its lease identity
        from here. Call BEFORE (or right after) killing the process."""
        return cls(svc.coord_client, "parameter_server", idx,
                   svc.ps_addrs()[idx], interval_s=interval_s)

    def stop(self) -> None:
        self._pub.stop()


def partition_view(probes: Dict[int, "object"], cut: Sequence[int]) -> Dict:
    """Observer-side partial partition: wrap a detector probe dict so the
    probes for replicas in ``cut`` raise (this OBSERVER cannot reach them;
    the replicas themselves are fine and other observers still can). Feed
    the wrapped dict to a FailureDetector to exercise the
    majority-of-peers witness rule: an observer cut off from most of the
    fleet must suspect ITSELF (withhold DEAD) rather than evict everyone
    it cannot see."""
    cut_set = set(int(i) for i in cut)

    def _severed(idx: int, inner):
        def probe() -> None:
            raise OSError(f"chaos: partitioned from replica {idx}")

        probe.addr = getattr(inner, "addr", "")  # type: ignore[attr-defined]
        probe.close = getattr(inner, "close", lambda: None)  # type: ignore[attr-defined]
        return probe

    return {
        idx: (_severed(idx, p) if idx in cut_set else p)
        for idx, p in probes.items()
    }

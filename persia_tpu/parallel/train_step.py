"""Sharded train/eval steps with embedding-gradient return.

This is the TPU-native heart of the hybrid trainer. The reference's NN worker
runs torch forward/backward with DDP allreduce and scatters gradients back to
sparse tensors with ``index_add_`` (`persia/ctx.py:893-1005`). Here the whole
step — dense forward, loss, backward, dense-optimizer update, and the
embedding-input gradients — is ONE jitted XLA program:

- batch leaves are sharded over the mesh ``data`` axis; parameters are
  replicated, so XLA inserts the ICI psum for dense grads (replacing NCCL).
- raw (sequence) slots enter as (distinct_rows, index, mask); the gather
  ``distinct[index]`` happens inside the differentiated function, so autodiff
  produces the scatter-add back onto distinct rows (replacing torch
  index_add_, ref ctx.py:968-982) as an XLA scatter that is itself psum'd
  across the mesh.
- the returned per-slot embedding gradients go back to the embedding-worker
  tier (`EmbeddingWorker.update_gradient_batched`).

Batch pytree convention (built by ``persia_tpu.ctx.EmbeddingCtx.prepare_features``):

    batch = {
      "dense":  [ (B, F) f32/bf16 ... ],
      "labels": [ (B, 1) f32 ... ],
      "emb":    [ {"pooled": (B, D)}                                  # sum slot
                  | {"distinct": (P, D), "index": (B,L) i32,
                     "mask": (B,L) bool} ... ],                       # raw slot
    }
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

from persia_tpu.parallel.mesh import batch_sharding, replicated


@flax.struct.dataclass
class LossScaleState:
    """Dynamic mixed-precision loss scaling (ref: the GradScaler management
    in persia/ctx.py:926-1005 — finite checks, skip-step on overflow, scale
    backoff/growth). On TPU the finite check is a fused on-device reduction,
    so it runs every step instead of every Nth."""

    scale: jnp.ndarray  # f32 scalar
    good_steps: jnp.ndarray  # i32 scalar


@flax.struct.dataclass
class TrainState:
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jnp.ndarray
    loss_scale: Optional[LossScaleState] = None


def _embedding_model_inputs(emb_diff: List, emb_static: List) -> List:
    """Rebuild per-slot model inputs from (differentiable, static) halves."""
    out = []
    for diff, static in zip(emb_diff, emb_static):
        if static is None:  # pooled slot: diff IS the (B, dim) array
            out.append(diff)
        elif len(static) == 3:  # ("pool", index, counts) — raw statics are
            # 2-tuples; don't compare static[0] to a string (it may be a
            # numpy index array, where == broadcasts)
            # device-pooled sum slot: gather + sum (+ sqrt scaling) inside
            # the diff'ed function, so autodiff returns per-DISTINCT
            # gradients — the TPU-side replacement for worker sum pooling
            # (mod.rs:486-629); index pads point at zero rows past D
            _, index, pool_counts = static
            if index.dtype != jnp.int32:  # uint16 wire → device-side cast
                index = index.astype(jnp.int32)
            # accumulate in f32 even on a bf16 wire (the host pool summed
            # in f32 too); (B, L, dim) → (B, dim)
            pooled = diff[index].astype(jnp.float32).sum(axis=1)
            if pool_counts is not None:
                scale = jax.lax.rsqrt(
                    jnp.maximum(pool_counts[:, 0], 1).astype(jnp.float32)
                )
                pooled = pooled * scale[:, None]
            out.append(pooled)
        else:  # raw slot: gather inside the diff'ed function → autodiff scatter
            index, mask = static
            gathered = diff[index]  # (B, L, dim)
            out.append((gathered, mask))
    return out


def _split_emb(emb: List[Dict]) -> Tuple[List, List]:
    diff, static = [], []
    for e in emb:
        if "pooled" in e:
            diff.append(e["pooled"])
            static.append(None)
        elif "pool_index" in e:
            diff.append(e["distinct"])
            static.append(("pool", e["pool_index"], e.get("pool_counts")))
        else:
            diff.append(e["distinct"])
            static.append((e["index"], e["mask"]))
    return diff, static


def default_loss_fn(logits, labels):
    """Binary cross-entropy with logits (the reference example's BCELoss +
    in-model sigmoid, done the numerically stable way)."""
    return optax.sigmoid_binary_cross_entropy(logits, labels).mean()


def init_train_state(
    model,
    rng,
    sample_batch: Dict,
    optimizer: optax.GradientTransformation,
    loss_scale_init: Optional[float] = None,
) -> TrainState:
    emb_diff, emb_static = _split_emb(sample_batch["emb"])
    model_emb = _embedding_model_inputs(emb_diff, emb_static)
    variables = model.init(rng, sample_batch["dense"], model_emb, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        params=params,
        batch_stats=batch_stats,
        opt_state=optimizer.init(params),
        step=jnp.zeros((), dtype=jnp.int32),
        loss_scale=(
            None
            if loss_scale_init is None
            else LossScaleState(
                scale=jnp.asarray(loss_scale_init, dtype=jnp.float32),
                good_steps=jnp.zeros((), dtype=jnp.int32),
            )
        ),
    )


def build_train_step(
    model,
    optimizer: optax.GradientTransformation,
    loss_fn: Callable = default_loss_fn,
    dynamic_loss_scale: bool = False,
    growth_interval: int = 2000,
    growth_factor: float = 2.0,
    backoff_factor: float = 0.5,
    max_scale: float = float(2 ** 24),
):
    """Returns jitted ``step(state, batch) -> (state, (header, gpacked))``.

    ``header`` is a small f32 array [loss | preds] — the cheap synchronous
    fetch (with ``dynamic_loss_scale``: [loss | scale_used | finite |
    preds]). ``gpacked`` is ONE flat array [emb_grad_0 | ...] in the
    embedding wire dtype (bf16 halves device→host bytes, matching the
    reference's f16 gradient wire) — the bulk transfer, fetched
    asynchronously by the BackwardEngine so it overlaps the next step
    (per-array fetches pay a full round-trip each; on a remote-attached TPU
    that latency dominated the step). ``unpack_step_output`` splits them
    using shapes derived from the batch. Emb grads align with
    ``batch['emb']``: (B, dim) for pooled slots, (P, dim) for raw slots
    (rows past the true distinct count are zero — the host slices them off
    before shipping to the worker).

    ``dynamic_loss_scale`` (ref: GradScaler management, persia/ctx.py:926-
    1005): the loss is multiplied by the running scale before backward; an
    on-device finite check over ALL gradients decides whether the dense
    update applies (overflow → skip step, scale *= backoff) and the scale
    grows by ``growth_factor`` after ``growth_interval`` consecutive finite
    steps. Embedding gradients ship SCALED; the header carries the scale so
    the worker's ``scale_factor`` division unscales them (non-finite slots
    are NaN-skipped there, mod.rs:716-744).
    """

    def step(state: TrainState, batch: Dict):
        emb_diff, emb_static = _split_emb(batch["emb"])
        scale = (
            state.loss_scale.scale
            if dynamic_loss_scale
            else jnp.asarray(1.0, jnp.float32)
        )

        def loss_wrapper(params, emb_diff):
            model_emb = _embedding_model_inputs(emb_diff, emb_static)
            variables = {"params": params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
                logits, updates = model.apply(
                    variables, batch["dense"], model_emb, train=True,
                    mutable=["batch_stats"],
                )
                new_stats = updates["batch_stats"]
            else:
                logits = model.apply(variables, batch["dense"], model_emb, train=True)
                new_stats = state.batch_stats
            loss = loss_fn(logits, batch["labels"][0])
            return loss * scale.astype(loss.dtype), (loss, logits, new_stats)

        (_, (loss, logits, new_stats)), (param_grads, emb_grads) = jax.value_and_grad(
            loss_wrapper, argnums=(0, 1), has_aux=True
        )(state.params, emb_diff)

        if dynamic_loss_scale:
            leaves = jax.tree.leaves(param_grads) + jax.tree.leaves(emb_grads)
            finite = jnp.all(
                jnp.stack([jnp.all(jnp.isfinite(g)) for g in leaves])
            )
            inv = jnp.where(finite, 1.0 / scale, 0.0).astype(jnp.float32)
            # unscale for the dense update; overflow zeros the grads and the
            # select below keeps params/opt_state untouched (skip-step)
            param_grads = jax.tree.map(
                lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), param_grads
            )
        else:
            finite = jnp.asarray(True)

        updates, opt_state_candidate = optimizer.update(
            param_grads, state.opt_state, state.params
        )
        params_candidate = optax.apply_updates(state.params, updates)
        if dynamic_loss_scale:
            new_params = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old),
                params_candidate, state.params,
            )
            new_opt_state = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old),
                opt_state_candidate, state.opt_state,
            )
            good = jnp.where(finite, state.loss_scale.good_steps + 1, 0)
            grown = good >= growth_interval
            new_scale = jnp.where(
                finite,
                jnp.where(grown, scale * growth_factor, scale),
                scale * backoff_factor,
            )
            new_scale = jnp.clip(new_scale, 1.0, max_scale)
            new_ls = LossScaleState(
                scale=new_scale, good_steps=jnp.where(grown, 0, good)
            )
        else:
            new_params, new_opt_state, new_ls = (
                params_candidate, opt_state_candidate, state.loss_scale,
            )
        new_state = TrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            step=state.step + 1,
            loss_scale=new_ls,
        )
        preds = jax.nn.sigmoid(logits)
        # Header (loss|preds) stays exact f32 — the cheap sync fetch; emb
        # grads ride the wire dtype in their own buffer so the bulk transfer
        # can be fetched asynchronously off the critical path.
        head = [jnp.reshape(loss, (1,)).astype(jnp.float32)]
        if dynamic_loss_scale:
            head.append(jnp.reshape(scale, (1,)).astype(jnp.float32))
            head.append(jnp.reshape(finite, (1,)).astype(jnp.float32))
        head.append(jnp.reshape(preds, (-1,)).astype(jnp.float32))
        header = jnp.concatenate(head)
        gflat = [jnp.reshape(g, (-1,)) for g in emb_grads]
        gpacked = jnp.concatenate(gflat) if gflat else jnp.zeros((0,), jnp.float32)
        return new_state, (header, gpacked)

    return jax.jit(step)


def _note_nonfinite_loss(loss: float) -> float:
    """Finite-guard on every host loss consumption: a NaN/Inf loss bumps
    the health counter + flight recorder instead of flowing silently into
    metrics/telemetry consumers."""
    if not np.isfinite(loss):
        from persia_tpu.metrics import get_metrics
        from persia_tpu.tracing import record_event

        get_metrics().counter(
            "persia_tpu_health_nonfinite_loss",
            "non-finite loss scalars observed at header decode",
        ).inc()
        record_event("health.anomaly", cause="nonfinite_loss", loss=repr(loss))
    return loss


def unpack_step_header(header: np.ndarray, batch: Dict):
    """Host view of the step's small output: (loss, preds). A sentinel
    probe tail (if any) rides after the preds and is ignored here — use
    :func:`unpack_step_probe` for it."""
    labels = batch["labels"][0]
    loss = _note_nonfinite_loss(float(header[0]))
    n = int(np.prod(labels.shape))
    preds = header[1:1 + n].reshape(labels.shape)
    return loss, preds


def unpack_step_header_dynamic(header: np.ndarray, batch: Dict):
    """Header view for a ``dynamic_loss_scale`` step:
    (loss, preds, scale_used, grads_finite)."""
    labels = batch["labels"][0]
    loss = _note_nonfinite_loss(float(header[0]))
    scale = float(header[1])
    finite = bool(header[2] > 0.5)
    n = int(np.prod(labels.shape))
    preds = header[3:3 + n].reshape(labels.shape)
    return loss, preds, scale, finite


def probe_tail_len(n_groups: int) -> int:
    """Floats appended to the header by ``sentinel_probe=True``:
    [dense_gnorm, group_gnorm x n_groups, ps_gnorm, finite, clipped]."""
    return n_groups + 4


def unpack_step_probe(
    header: np.ndarray, n_labels: int, n_groups: int, dynamic: bool = False
) -> Dict:
    """Decode the sentinel probe tail from a step header.

    All norms are unscaled (loss-scale divided out on device) and
    pre-clip; ``finite`` is the device-side skip gate, ``clipped``
    whether ``guard_clip_norm`` rescaled the update.
    """
    base = (3 if dynamic else 1) + int(n_labels)
    tail = np.asarray(header[base:base + probe_tail_len(n_groups)], np.float32)
    if tail.shape[0] != probe_tail_len(n_groups):
        raise ValueError(
            f"header carries no probe tail (got {tail.shape[0]} floats, "
            f"want {probe_tail_len(n_groups)}) — was the step built with "
            "sentinel_probe=True?"
        )
    dense = float(tail[0])
    groups = [float(v) for v in tail[1:1 + n_groups]]
    ps = float(tail[1 + n_groups])
    total = float(np.sqrt(dense * dense + ps * ps + sum(g * g for g in groups)))
    return {
        "dense_gnorm": dense,
        "group_gnorms": groups,
        "ps_gnorm": ps,
        "total_gnorm": total,
        "finite": float(tail[1 + n_groups + 1]),
        "clipped": float(tail[1 + n_groups + 2]),
    }


def unpack_step_grads(gpacked: np.ndarray, batch: Dict) -> List[np.ndarray]:
    """Split the bulk gradient buffer into per-slot arrays (shapes come from
    the same ``batch`` the step consumed; ``gpacked`` must already be host
    memory)."""
    grads = []
    off = 0
    for e in batch["emb"]:
        shape = e["pooled"].shape if "pooled" in e else e["distinct"].shape
        k = int(np.prod(shape))
        grads.append(np.ascontiguousarray(gpacked[off:off + k]).reshape(shape))
        off += k
    return grads


def unpack_step_output(header: np.ndarray, gpacked: np.ndarray, batch: Dict):
    """(loss, preds, emb_grads) from the step's two output buffers."""
    loss, preds = unpack_step_header(header, batch)
    return loss, preds, unpack_step_grads(gpacked, batch)


def build_eval_step(model):
    """Returns jitted ``eval_step(state, batch) -> preds`` (running-average
    batch norm, no mutation)."""

    def eval_step(state: TrainState, batch: Dict):
        emb_diff, emb_static = _split_emb(batch["emb"])
        model_emb = _embedding_model_inputs(emb_diff, emb_static)
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, batch["dense"], model_emb, train=False)
        return jax.nn.sigmoid(logits)

    return jax.jit(eval_step)


def _packed_put(batch: Dict) -> Dict:
    """Single-chip fast path: ship every float embedding leaf in ONE
    device_put (host-side concat, device-side lazy slices). Per-leaf puts pay
    a full host→device round-trip each — on a remote-attached chip that
    latency dominated staging."""
    out: Dict = {
        "dense": [jnp.asarray(x) for x in batch["dense"]],
        "labels": [jnp.asarray(x) for x in batch["labels"]],
        "emb": [],
    }
    def _is_float(a) -> bool:
        d = np.asarray(a).dtype
        return np.issubdtype(d, np.floating) or d.name == "bfloat16"

    float_leaves = []  # (entry_idx, key, shape, size)
    entries: List[Dict] = [dict() for _ in batch["emb"]]
    for i, e in enumerate(batch["emb"]):
        for key, val in e.items():
            if _is_float(val):
                float_leaves.append((i, key, val.shape, val.size))
            else:
                entries[i][key] = jnp.asarray(val)
    if float_leaves:
        dt = batch["emb"][float_leaves[0][0]][float_leaves[0][1]].dtype
        flat = np.concatenate(
            [np.ascontiguousarray(batch["emb"][i][k]).reshape(-1)
             for i, k, _, _ in float_leaves]
        ).astype(dt, copy=False)
        dev = jax.device_put(flat)
        off = 0
        for i, k, shape, size in float_leaves:
            entries[i][k] = jax.lax.slice(dev, (off,), (off + size,)).reshape(shape)
            off += size
    out["emb"] = entries
    return out


def shard_device_batch(batch: Dict, mesh=None) -> Dict:
    """device_put the batch with DP shardings: batch-dim leaves over ``data``,
    raw-slot distinct rows replicated. Computation follows data: the jitted
    step picks these shardings up without explicit in_shardings.

    Mesh staging is PACKED like the single-chip path (round-1 Weak #8: the
    per-leaf device_put round-trips return on pods, where they matter most):
    one transfer per (sharding, dtype) group — batch-dim floats concat along
    axis 1 into (B, F_total), raw distinct rows concat along axis 0
    (replicated), int32 index matrices concat along axis 1 — then sliced
    back on device. Raw-slot masks are derived on device (``index != P-1``,
    the pad row) instead of shipping a bool matrix."""
    if mesh is None:
        return _packed_put(batch)
    bsh = batch_sharding(mesh)
    rep = replicated(mesh)

    # ---- group host leaves
    bdim_float: List[Tuple[str, int, np.ndarray]] = []  # ("dense"/"labels"/i, …)
    for j, x in enumerate(batch["dense"]):
        bdim_float.append(("dense", j, np.asarray(x)))
    for j, x in enumerate(batch["labels"]):
        bdim_float.append(("labels", j, np.asarray(x)))
    raw_distinct: List[Tuple[int, np.ndarray]] = []
    index_mats: List[Tuple[Tuple[str, int], np.ndarray]] = []
    for i, e in enumerate(batch["emb"]):
        if "pooled" in e:
            bdim_float.append(("emb", i, np.asarray(e["pooled"])))
        elif "pool_index" in e:
            raw_distinct.append((i, np.asarray(e["distinct"])))
            index_mats.append(
                (("idx", i), np.ascontiguousarray(e["pool_index"]))
            )
            if "pool_counts" in e:
                index_mats.append(
                    (("cnt", i), np.ascontiguousarray(e["pool_counts"], dtype=np.int32))
                )
        else:
            raw_distinct.append((i, np.asarray(e["distinct"])))
            index_mats.append(
                (("idx", i), np.ascontiguousarray(e["index"], dtype=np.int32))
            )

    def _packed_groups(leaves, axis, sharding):
        """One device_put per (dtype, off-axis width) group of 2-D leaves;
        other ranks ship individually (packing along one axis requires the
        other to match — NdarrayDataBase allows any ndim >= 1, and raw
        slots may carry different embedding dims)."""
        views: Dict = {}
        by_dtype: Dict = {}
        for key, arr in leaves:
            if arr.ndim != 2:
                views[key] = jax.device_put(arr, sharding)
                continue
            gk = (arr.dtype.name, arr.shape[1 - axis])
            by_dtype.setdefault(gk, []).append((key, arr))
        for group in by_dtype.values():
            packed = np.concatenate([a for _, a in group], axis=axis)
            dev = jax.device_put(packed, sharding)
            off = 0
            for key, a in group:
                w = a.shape[axis]
                if axis == 1:
                    views[key] = dev[:, off:off + w]
                else:
                    views[key] = dev[off:off + w]
                off += w
        return views

    fviews = _packed_groups([((k, j), a) for k, j, a in bdim_float], 1, bsh)
    dviews = _packed_groups(raw_distinct, 0, rep)
    iviews = _packed_groups(index_mats, 1, bsh)

    out: Dict = {
        "dense": [fviews[("dense", j)] for j in range(len(batch["dense"]))],
        "labels": [fviews[("labels", j)] for j in range(len(batch["labels"]))],
        "emb": [],
    }
    for i, e in enumerate(batch["emb"]):
        if "pooled" in e:
            out["emb"].append({"pooled": fviews[("emb", i)]})
        elif "pool_index" in e:
            entry = {"distinct": dviews[i], "pool_index": iviews[("idx", i)]}
            if "pool_counts" in e:
                entry["pool_counts"] = iviews[("cnt", i)]
            out["emb"].append(entry)
        else:
            idx = iviews[("idx", i)]
            p = e["distinct"].shape[0]
            out["emb"].append(
                {
                    "distinct": dviews[i],
                    "index": idx,
                    "mask": idx != (p - 1),  # pad row = P-1 (stage_embeddings)
                }
            )
    return out


def replicate_state(state: TrainState, mesh) -> TrainState:
    rep = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, rep), state)

"""Sequence/context parallelism: ring attention and all-to-all (Ulysses).

The reference has no sequence parallelism (SURVEY.md §2.6 / §5 — its
"sequences" are variable-length ID lists). A TPU-native framework treats
long-context as first-class: the sequence axis of attention is sharded over a
mesh axis and the KV blocks ride ICI.

Two strategies, both built on ``jax.shard_map`` so XLA sees a static SPMD
program:

- **Ring attention** (`ring_attention`): each device holds a [B, L/n, H, D]
  shard of Q/K/V. K/V blocks rotate around the ring with ``lax.ppermute``
  while each device accumulates its queries' attention with the
  online-softmax (flash) recurrence — peak memory O(L/n), full overlap of
  compute with ICI transfer. Supports causal masking via global position ids.
- **Ulysses / all-to-all** (`ulysses_attention`): two ``lax.all_to_all``
  collectives re-shard [B, L/n, H, D] → [B, L, H/n, D] so every device runs
  dense attention over the full sequence for a head subset, then shards back.
  Requires num_heads % n == 0; cheaper collectives for moderate L.

Both return bit-identical results to single-device attention (see
tests/test_sequence_parallel.py) and compose with the ``data`` axis of the
training mesh (mesh axes ("data", "sp")).
"""

from __future__ import annotations

import functools

from persia_tpu.parallel.mesh import shard_map_compat
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_BIG = -1e30


def _attn_block(q, k, v, mask, m_prev, l_prev, o_prev, scale):
    """One online-softmax (flash) accumulation step.

    q: [B, Lq, H, D]; k, v: [B, Lk, H, D]; mask: [Lq, Lk] bool or None.
    m, l: [B, Lq, H]; o: [B, Lq, H, D].
    """
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale
    if mask is not None:
        ms = jnp.where(mask[None, :, None, :], s, _NEG_BIG)
    else:
        ms = s
    m_cur = jnp.max(ms, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(ms - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask[None, :, None, :], p, 0.0)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=-1)
    o_new = o_prev * corr[..., None] + jnp.einsum("bqhk,bkhd->bqhd", p, v)
    return m_new, l_new, o_new


def _ring_attention_local(q, k, v, axis_name: str, causal: bool, scale: float):
    """Per-shard body: rotate K/V around the ring, accumulate online softmax."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, l_loc, h, d = q.shape
    q_pos = idx * l_loc + jnp.arange(l_loc)

    m0 = jnp.full((b, l_loc, h), _NEG_BIG, dtype=jnp.float32)
    l0 = jnp.zeros((b, l_loc, h), dtype=jnp.float32)
    o0 = jnp.zeros((b, l_loc, h, d), dtype=jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(step, carry):
        m, l, o, k_cur, v_cur = carry
        src = (idx - step) % n  # which global block this device holds now
        if causal:
            k_pos = src * l_loc + jnp.arange(l_loc)
            mask = k_pos[None, :] <= q_pos[:, None]
        else:
            mask = None
        m, l, o = _attn_block(
            q.astype(jnp.float32), k_cur.astype(jnp.float32),
            v_cur.astype(jnp.float32), mask, m, l, o, scale,
        )
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return m, l, o, k_nxt, v_nxt

    m, l, o, _, _ = lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ring attention over sequence shards.

    q, k, v: [B, L, H, D] with L sharded over ``axis_name`` of ``mesh``.
    Returns [B, L, H, D] sharded the same way. Peak per-device memory is
    O(L/n); the K/V ring rides ICI via ``ppermute``.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(None, axis_name, None, None)
    fn = shard_map_compat(
        functools.partial(
            _ring_attention_local, axis_name=axis_name, causal=causal, scale=scale
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def _dense_attention(q, k, v, causal: bool, scale: float):
    """Plain softmax attention: q,k,v [B, L, H, D] (fp32 accumulation)."""
    s = jnp.einsum("bqhd,bkhd->bqhk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    if causal:
        lq, lk = s.shape[1], s.shape[3]
        mask = jnp.arange(lk)[None, :] <= jnp.arange(lq)[:, None]
        s = jnp.where(mask[None, :, None, :], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def _ulysses_local(q, k, v, axis_name: str, causal: bool, scale: float):
    # [B, L/n, H, D] → all-to-all → [B, L, H/n, D]
    def gather_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def scatter_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qg, kg, vg = gather_seq(q), gather_seq(k), gather_seq(v)
    out = _dense_attention(qg, kg, vg, causal, scale)
    return scatter_seq(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses-style) sequence-parallel attention.

    Re-shards sequence↔heads with two ``all_to_all`` collectives and runs
    dense attention per head subset. Requires H % mesh.shape[axis_name] == 0.
    """
    n = mesh.shape[axis_name]
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"num_heads={h} not divisible by mesh axis {axis_name}={n}")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(None, axis_name, None, None)
    fn = shard_map_compat(
        functools.partial(
            _ulysses_local, axis_name=axis_name, causal=causal, scale=scale
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """Single-device oracle used by tests and by models off-mesh."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _dense_attention(q, k, v, causal, scale)

"""Fully-fused hybrid train step: embedding tables resident in HBM.

The reference's hot loop crosses process boundaries four times per step
(lookup RPC → h2d → step → d2h → gradient RPC, §3.2/3.3 of SURVEY.md)
because GPU memory cannot hold the tables. On TPU, Criteo-class tables fit
in (pooled) HBM, so the idiomatic fast path keeps them on device and fuses
the ENTIRE hybrid step into one XLA program:

    ids → gather → dense fwd/bwd → optax dense update → duplicate-safe
    sparse optimizer update (persia_tpu.ops.sparse_update)

Host↔device traffic per step collapses to the raw batch (int32 ids + dense
features + labels) in, one scalar loss out — no embedding or gradient ever
crosses the PCIe/tunnel boundary. The host C++ PS tier
(`persia_tpu.embedding.native_store`) remains the capacity tier for vocab
that exceeds HBM; `persia_tpu.interop` moves rows between the two tiers.

Sharding: tables are row-sharded over the mesh "data" axis (GSPMD turns the
gathers/scatters into ICI collectives); batch leaves are sharded over "data";
dense params replicated (psum grads). Single-device jit needs no mesh.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

from persia_tpu.embedding.optim import OptimizerConfig
from persia_tpu.ops.sparse_update import (
    init_sparse_state,
    masked_flat_ids_grads,
    sparse_update,
)
from persia_tpu.parallel.train_step import default_loss_fn


@dataclass(frozen=True)
class FusedSlotSpec:
    """One HBM-resident slot (ref: SlotConfig,
    `persia-embedding-config/src/lib.rs:528-560`; LRU/eviction is the host
    tier's job — HBM slots are dense [0, vocab) keyed).

    ``init_method`` (a ``config.InitializationMethod``) selects the init
    distribution (uniform/gamma/poisson/normal/inverse_sqrt — the
    reference's InitializationMethod enum, lib.rs:79-98); ``None`` falls
    back to uniform over ``init_bounds``. HBM tables are dense-keyed and
    seeded from a PRNGKey, so parity with the host tiers' seeded-by-sign
    init is STATISTICAL, not bitwise (the key spaces differ by design)."""

    vocab: int
    dim: int
    pooled: bool = True  # embedding_summation; False → raw (B, L, D) + mask
    sqrt_scaling: bool = False
    init_bounds: Tuple[float, float] = (-0.01, 0.01)
    init_method: "object | None" = None


def _sample_init(key, shape, spec: "FusedSlotSpec", dtype):
    """Draw a table block from the slot's init distribution (traceable)."""
    m = spec.init_method
    if m is None:
        lo, hi = spec.init_bounds
        return jax.random.uniform(key, shape, dtype=dtype, minval=lo, maxval=hi)
    kind = m.kind
    if kind == "uniform":
        return jax.random.uniform(key, shape, dtype=dtype, minval=m.p0, maxval=m.p1)
    if kind == "inverse_sqrt":
        b = 1.0 / float(np.sqrt(shape[-1]))
        return jax.random.uniform(key, shape, dtype=dtype, minval=-b, maxval=b)
    if kind == "normal":
        return (m.p0 + m.p1 * jax.random.normal(key, shape)).astype(dtype)
    if kind == "gamma":
        return (jax.random.gamma(key, m.p0, shape) * m.p1).astype(dtype)
    if kind == "poisson":
        return jax.random.poisson(key, m.p0, shape).astype(dtype)
    raise ValueError(f"unknown init kind: {kind!r}")


@flax.struct.dataclass
class FusedTrainState:
    params: Any
    batch_stats: Any
    opt_state: Any
    tables: Dict[str, jnp.ndarray]
    emb_state: Dict[str, Dict[str, jnp.ndarray]]
    emb_batch_state: jnp.ndarray  # (beta1^t, beta2^t) for Adam
    step: jnp.ndarray


def create_fused_tables(
    rng,
    specs: Dict[str, FusedSlotSpec],
    sparse_cfg: OptimizerConfig,
    dtype=jnp.float32,
):
    """Seeded uniform tables + optimizer state (ref init:
    `emb_entry.rs:28-60` uniform from EmbeddingConfig.emb_initialization)."""
    tables, emb_state = {}, {}
    names = sorted(specs)
    keys = jax.random.split(rng, max(len(names), 1))
    for key, name in zip(keys, names):
        s = specs[name]
        tables[name] = _sample_init(key, (s.vocab, s.dim), s, dtype)
        emb_state[name] = init_sparse_state(sparse_cfg, s.vocab, s.dim)
    return tables, emb_state


def _model_inputs(
    specs: Dict[str, FusedSlotSpec],
    slot_order: Sequence[str],
    gathered: Dict[str, jnp.ndarray],
    ids: Dict[str, jnp.ndarray],
) -> List:
    """Build the per-slot model input list from gathered embeddings —
    pooling happens INSIDE the differentiated function so autodiff routes
    grads back to per-position rows."""
    out = []
    for name in slot_order:
        g = gathered[name]
        if g.ndim == 2:  # single-id slot; -1 padding → zero embedding
            i = ids[name]
            out.append(g * (i >= 0)[..., None].astype(g.dtype))
            continue
        i = ids[name]
        mask = i >= 0
        if specs[name].pooled:
            m = mask[..., None].astype(g.dtype)
            pooled = (g * m).sum(axis=1)
            if specs[name].sqrt_scaling:
                cnt = jnp.maximum(mask.sum(axis=1), 1).astype(pooled.dtype)
                pooled = pooled / jnp.sqrt(cnt)[..., None]
            out.append(pooled)
        else:
            out.append((g, mask))
    return out


def _gather_all(
    tables: Dict[str, jnp.ndarray], ids: Dict[str, jnp.ndarray]
) -> Dict[str, jnp.ndarray]:
    out = {}
    for name, i in ids.items():
        safe = jnp.where(i >= 0, i, 0).astype(jnp.int32)
        out[name] = jnp.take(tables[name], safe, axis=0)
    return out


# ---------------------------------------------------------------------------
# Stacked tables: all same-dim slots share one physical (sum(vocab), dim)
# table with per-slot row offsets, so the step issues ONE gather and ONE
# sparse-update scatter per dim-group instead of one per slot. This is the
# HBM analogue of the reference's single global key space partitioned by
# per-slot index prefixes (`embedding_worker_service/mod.rs:403-429`,
# `persia-embedding-config/src/lib.rs:600-650`) — offsets play the role of
# index prefixes.
# ---------------------------------------------------------------------------

_INT32_MAX = np.iinfo(np.int32).max


@dataclass(frozen=True)
class StackGroup:
    """One physical stacked table covering several same-dim slots."""

    name: str
    slots: Tuple[str, ...]
    offsets: Tuple[int, ...]  # row offset of each slot, aligned with ``slots``
    vocab: int
    dim: int


def group_stacked_specs(
    specs: Dict[str, FusedSlotSpec], slot_order: Sequence[str]
) -> List[StackGroup]:
    """Deterministically group slots by dim into stacked tables (splitting a
    group if its total rows would overflow int32 ids)."""
    by_dim: Dict[int, List[str]] = {}
    for name in slot_order:
        by_dim.setdefault(specs[name].dim, []).append(name)
    groups = []
    for dim in sorted(by_dim):
        names, offsets, total = [], [], 0
        part = 0
        for name in by_dim[dim]:
            v = specs[name].vocab
            if total + v > _INT32_MAX and names:
                groups.append(
                    StackGroup(f"__stack_d{dim}_{part}", tuple(names), tuple(offsets), total, dim)
                )
                names, offsets, total = [], [], 0
                part += 1
            names.append(name)
            offsets.append(total)
            total += v
        groups.append(
            StackGroup(f"__stack_d{dim}_{part}", tuple(names), tuple(offsets), total, dim)
        )
    return groups


def create_stacked_tables(
    rng,
    specs: Dict[str, FusedSlotSpec],
    groups: Sequence[StackGroup],
    sparse_cfg: OptimizerConfig,
    dtype=jnp.float32,
):
    """Stacked tables with each slot's row range drawing from its own
    init_bounds (ref init: `emb_entry.rs:28-60`).

    Filled one slot at a time into a donated group table (peak HBM = full
    table + one slot's rows, not 2x the table as a concat of parts would be
    — stacking exists precisely for the multi-GB case)."""
    tables, emb_state = {}, {}
    # key assignment matches create_fused_tables (sorted slot name) so a
    # given slot's seeded init is layout-independent
    all_names = sorted(n for g in groups for n in g.slots)
    keys = dict(zip(all_names, jax.random.split(rng, max(len(all_names), 1))))

    @partial(jax.jit, static_argnames=("shape", "spec"), donate_argnums=(0,))
    def _fill(tbl, key, off, shape, spec):
        part = _sample_init(key, shape, spec, tbl.dtype)
        return jax.lax.dynamic_update_slice(tbl, part, (off, 0))

    for g in groups:
        tbl = jnp.zeros((g.vocab, g.dim), dtype=dtype)
        for name, off in zip(g.slots, g.offsets):
            s = specs[name]
            tbl = _fill(tbl, keys[name], jnp.int32(off), (s.vocab, s.dim), s)
        tables[g.name] = tbl
        emb_state[g.name] = init_sparse_state(sparse_cfg, g.vocab, g.dim)
    return tables, emb_state


def _gather_all_stacked(
    tables: Dict[str, jnp.ndarray],
    ids: Dict[str, jnp.ndarray],
    groups: Sequence[StackGroup],
) -> Dict[str, jnp.ndarray]:
    """One ``take`` per dim-group; per-slot views are cheap slices.

    Ids are clamped to the slot's own [0, vocab) range before the offset is
    applied, matching the unstacked path's XLA gather-clamp semantics — an
    out-of-range id must never read a neighboring slot's rows."""
    out = {}
    for g in groups:
        parts = []
        ends = list(g.offsets[1:]) + [g.vocab]
        for name, off, end in zip(g.slots, g.offsets, ends):
            i = ids[name]
            clamped = jnp.minimum(i, end - off - 1)
            parts.append(jnp.where(i >= 0, clamped + off, 0).reshape(-1).astype(jnp.int32))
        flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        rows = jnp.take(tables[g.name], flat, axis=0)  # (sum(B·L), dim)
        pos = 0
        for name in g.slots:
            shape = ids[name].shape
            k = int(np.prod(shape))
            out[name] = jax.lax.slice(rows, (pos, 0), (pos + k, g.dim)).reshape(
                shape + (g.dim,)
            )
            pos += k
    return out


def stacked_slot_table(
    tables: Dict[str, jnp.ndarray], groups: Sequence[StackGroup], name: str
) -> jnp.ndarray:
    """Per-slot (vocab, dim) view of a stacked table (for checkpoints/tests)."""
    for g in groups:
        if name in g.slots:
            i = g.slots.index(name)
            end = g.offsets[i + 1] if i + 1 < len(g.slots) else g.vocab
            return tables[g.name][g.offsets[i]:end]
    raise KeyError(name)


def init_fused_state(
    model,
    rng,
    specs: Dict[str, FusedSlotSpec],
    sample_batch: Dict,
    dense_optimizer: optax.GradientTransformation,
    sparse_cfg: OptimizerConfig,
    slot_order: Optional[Sequence[str]] = None,
    stack: bool = False,
    table_dtype=jnp.float32,
) -> FusedTrainState:
    slot_order = list(slot_order or sorted(specs))
    rng_tbl, rng_model = jax.random.split(rng)
    if stack:
        groups = group_stacked_specs(specs, slot_order)
        tables, emb_state = create_stacked_tables(
            rng_tbl, specs, groups, sparse_cfg, dtype=table_dtype
        )
        gathered = _gather_all_stacked(tables, sample_batch["ids"], groups)
    else:
        tables, emb_state = create_fused_tables(rng_tbl, specs, sparse_cfg, dtype=table_dtype)
        gathered = _gather_all(tables, sample_batch["ids"])
    ids = sample_batch["ids"]
    model_emb = _model_inputs(specs, slot_order, gathered, ids)
    del gathered
    variables = model.init(rng_model, sample_batch["dense"], model_emb, train=False)
    params = variables["params"]
    return FusedTrainState(
        params=params,
        batch_stats=variables.get("batch_stats", {}),
        opt_state=dense_optimizer.init(params),
        tables=tables,
        emb_state=emb_state,
        emb_batch_state=jnp.ones((2,), dtype=jnp.float32),
        step=jnp.zeros((), dtype=jnp.int32),
    )


def build_fused_train_step(
    model,
    dense_optimizer: optax.GradientTransformation,
    sparse_cfg: OptimizerConfig,
    specs: Dict[str, FusedSlotSpec],
    slot_order: Optional[Sequence[str]] = None,
    loss_fn=default_loss_fn,
    donate: bool = True,
    jit: bool = True,
    stack: bool = False,
):
    """Returns jitted ``step(state, batch) -> (state, (loss, preds))``.

    batch = {"dense": [(B,F) f32...], "labels": [(B,1) f32...],
             "ids": {slot: (B,) or (B,L) int32, -1 = padding}}.
    ``donate=True`` donates the state buffers so multi-GB tables update
    in place instead of being copied each step. ``jit=False`` returns the
    raw traceable step for callers that wrap it (packed-I/O benches,
    shard_map composition). ``stack=True`` expects state built with
    ``init_fused_state(stack=True)``: same-dim slots share one physical
    table, so the step runs one gather + one sparse-update per dim-group
    instead of one per slot.
    """
    slot_order = list(slot_order or sorted(specs))
    groups = group_stacked_specs(specs, slot_order) if stack else None

    def step(state: FusedTrainState, batch: Dict):
        ids = batch["ids"]
        gathered = (
            _gather_all_stacked(state.tables, ids, groups)
            if stack
            else _gather_all(state.tables, ids)
        )

        def loss_wrapper(params, gathered):
            model_emb = _model_inputs(specs, slot_order, gathered, ids)
            variables = {"params": params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
                logits, updates = model.apply(
                    variables, batch["dense"], model_emb, train=True,
                    mutable=["batch_stats"],
                )
                new_stats = updates["batch_stats"]
            else:
                logits = model.apply(variables, batch["dense"], model_emb, train=True)
                new_stats = state.batch_stats
            loss = loss_fn(logits, batch["labels"][0])
            return loss, (logits, new_stats)

        (loss, (logits, new_stats)), (param_grads, emb_grads) = jax.value_and_grad(
            loss_wrapper, argnums=(0, 1), has_aux=True
        )(state.params, gathered)

        updates, new_opt_state = dense_optimizer.update(
            param_grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)

        batch_state = state.emb_batch_state * jnp.array(
            [sparse_cfg.beta1, sparse_cfg.beta2], dtype=jnp.float32
        )
        new_tables, new_emb_state = {}, {}
        if stack:
            for grp in groups:
                idp, gp, mp = [], [], []
                for name, off in zip(grp.slots, grp.offsets):
                    i = ids[name]
                    # ids outside the slot's own [0, vocab) are masked out,
                    # matching the unstacked scatter's mode="drop" — they
                    # must not write a neighboring slot's rows
                    in_range = (i >= 0) & (i < specs[name].vocab)
                    fi, fg, fm = masked_flat_ids_grads(
                        jnp.where(in_range, i + off, -1),
                        emb_grads[name].astype(jnp.float32),
                    )
                    idp.append(fi)
                    gp.append(fg)
                    mp.append(fm)
                new_tables[grp.name], new_emb_state[grp.name] = sparse_update(
                    sparse_cfg,
                    state.tables[grp.name],
                    state.emb_state[grp.name],
                    jnp.concatenate(idp) if len(idp) > 1 else idp[0],
                    jnp.concatenate(gp) if len(gp) > 1 else gp[0],
                    batch_state,
                    mask=jnp.concatenate(mp) if len(mp) > 1 else mp[0],
                )
        else:
            for name in slot_order:
                g = emb_grads[name].astype(jnp.float32)
                flat_ids, flat_g, flat_mask = masked_flat_ids_grads(ids[name], g)
                new_tables[name], new_emb_state[name] = sparse_update(
                    sparse_cfg,
                    state.tables[name],
                    state.emb_state[name],
                    flat_ids,
                    flat_g,
                    batch_state,
                    mask=flat_mask,
                )

        new_state = FusedTrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            tables=new_tables,
            emb_state=new_emb_state,
            emb_batch_state=batch_state,
            step=state.step + 1,
        )
        return new_state, (loss, jax.nn.sigmoid(logits))

    if not jit:
        return step
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def build_fused_multi_step(
    model,
    dense_optimizer: optax.GradientTransformation,
    sparse_cfg: OptimizerConfig,
    specs: Dict[str, FusedSlotSpec],
    k: int,
    slot_order: Optional[Sequence[str]] = None,
    loss_fn=default_loss_fn,
    stack: bool = False,
):
    """K-step fused dispatch for the all-in-HBM path: ONE jitted program
    advances ``k`` consecutive batches — ``multi(state, batches) -> (state,
    (losses, preds_list))`` with ``batches`` a length-``k`` tuple of the
    single-step batch dict. The per-dispatch Python/header overhead that
    bounds small-step-time loops (and dominates on a remote-attached chip,
    where every dispatch pays tunnel latency) is paid once per K steps; the
    math is the single-step program iterated, so parity with
    ``build_fused_train_step`` is exact in program terms — but NOT bitwise:
    XLA compiles the step subgraph differently inside the larger program
    (cross-step/cluster fusion reorders float ops at the ~1 ulp level, and
    ``optimization_barrier`` between steps does not recover the standalone
    bits). Callers needing bit parity with the single-step loop must use
    k=1. The cached tier's stream applies the same idea to its hazard-free
    windows (hbm_cache/stream.py ``dispatch_k``) — there the K program IS
    bit-exact (pinned by test_stream_kstep_packing_bitwise_parity)."""
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    raw = build_fused_train_step(
        model, dense_optimizer, sparse_cfg, specs, slot_order,
        loss_fn=loss_fn, jit=False, stack=stack,
    )

    def multi(state: FusedTrainState, batches):
        losses, preds = [], []
        for b in batches:
            state, (loss, p) = raw(state, b)
            losses.append(loss)
            preds.append(p)
        return state, (jnp.stack(losses), preds)

    return jax.jit(multi, donate_argnums=(0,))


def build_fused_eval_step(model, specs, slot_order=None, stack: bool = False):
    slot_order = list(slot_order or sorted(specs))
    groups = group_stacked_specs(specs, slot_order) if stack else None

    def eval_step(state: FusedTrainState, batch: Dict):
        ids = batch["ids"]
        gathered = (
            _gather_all_stacked(state.tables, ids, groups)
            if stack
            else _gather_all(state.tables, ids)
        )
        model_emb = _model_inputs(specs, slot_order, gathered, ids)
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, batch["dense"], model_emb, train=False)
        return jax.nn.sigmoid(logits)

    return jax.jit(eval_step)


def shard_fused_state(state: FusedTrainState, mesh, table_axis: str = "data"):
    """Place tables row-sharded over ``table_axis`` and everything else
    replicated; GSPMD then partitions the step's gathers/scatters into ICI
    collectives (the TPU analogue of the reference's farmhash row sharding
    across PS replicas, `embedding_worker_service/mod.rs:342-345`)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P(table_axis, None))

    def place_tbl(x):
        return jax.device_put(x, row if x.shape[0] % mesh.shape[table_axis] == 0 else rep)

    return FusedTrainState(
        params=jax.tree.map(lambda x: jax.device_put(x, rep), state.params),
        batch_stats=jax.tree.map(lambda x: jax.device_put(x, rep), state.batch_stats),
        opt_state=jax.tree.map(lambda x: jax.device_put(x, rep), state.opt_state),
        tables={k: place_tbl(v) for k, v in state.tables.items()},
        emb_state={
            k: {sk: place_tbl(sv) for sk, sv in st.items()}
            for k, st in state.emb_state.items()
        },
        emb_batch_state=jax.device_put(state.emb_batch_state, rep),
        step=jax.device_put(state.step, rep),
    )


def pack_ids(ids_np: Dict[str, np.ndarray], slot_order: Sequence[str]):
    """Host-side helper: one contiguous int32 buffer for all slots' ids so
    staging is a single host→device transfer (per-leaf puts pay a full
    round-trip each on a remote-attached chip)."""
    flat = np.concatenate(
        [np.ascontiguousarray(ids_np[n], dtype=np.int32).reshape(-1) for n in slot_order]
    )
    shapes = [ids_np[n].shape for n in slot_order]
    return flat, shapes


def unpack_ids(flat_dev: jnp.ndarray, slot_order: Sequence[str], shapes) -> Dict[str, jnp.ndarray]:
    out = {}
    off = 0
    for name, shape in zip(slot_order, shapes):
        k = int(np.prod(shape))
        out[name] = jax.lax.slice(flat_dev, (off,), (off + k,)).reshape(shape)
        off += k
    return out


class FusedPipeline:
    """Stage-pipelined driver for the fused tier: a feeder thread runs the
    FEED stage (host batch conversion + h2d staging, double-buffered up to
    ``depth`` in flight) while the caller's thread runs the DENSE stage
    (the jitted single- or K-step program). Every table row is HBM-resident
    and the sparse update is fused INTO the dense program, so there are no
    feed/gradient hazards to ledger — the stage graph's window only bounds
    how many staged batches (and therefore how much staging HBM) ride
    ahead of the dense stage. Batches enter the program in stream order,
    so with ``k == 1`` the result is the sequential ``step`` loop's bit
    for bit (pinned by test_stage_graph.py); ``k > 1`` packs the dense
    stage via ``build_fused_multi_step``, whose parity is numerical, not
    bitwise (see its docstring) — same trade as calling that program
    directly.

    ``run`` drains the window before returning — callers may checkpoint
    (``FusedTrainCtx.dump_checkpoint``) immediately after with fence
    semantics. The cached tier's ``train_stream(pipeline_depth=...)``
    applies the same stage graph WITH the hazard ledger (rows there are
    cache slots that feeds mutate); see parallel/stage_graph.py.
    """

    def __init__(self, step, multi=None, depth: int = 2, k: int = 1):
        from persia_tpu.parallel.stage_graph import StageGraph

        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if k > 1 and multi is None:
            raise ValueError("k > 1 needs the multi-step program")
        self._step = step
        self._multi = multi
        self.depth = int(depth)
        # a full pack must fit in the window or feed and dense deadlock
        # waiting on each other
        self.k = max(1, min(int(k), self.depth))
        self.graph = StageGraph(self.depth)

    def run(self, state, batches, stage=None):
        """Drive ``batches`` (iterable of fused batch dicts — or anything
        ``stage`` maps to one) through the pipeline. The iterable is
        consumed by the FEED thread, so host-side conversion inside a
        generator rides the feed lane too. Returns ``(state, losses)``
        with ``losses`` the per-step device scalars in stream order;
        :meth:`stats` reports overlap after the run."""
        import queue as _queue
        import threading
        import time as _time

        stage = jax.device_put if stage is None else stage
        graph = self.graph
        q: "_queue.Queue" = _queue.Queue(maxsize=self.depth)
        errors: List[BaseException] = []
        SENTINEL = object()

        def feeder():
            try:
                for seq, b in enumerate(batches):
                    if errors:
                        break
                    # no hazard rows: empty feed/trained sets, the window
                    # acts purely as the staging-buffer bound
                    if not graph.reserve_feed(
                        seq, {}, {}, should_abort=lambda: bool(errors)
                    ):
                        break
                    with graph.lane("feed"):
                        staged = stage(b)
                    q.put((seq, staged))
            except BaseException as e:  # noqa: BLE001 — reraised on the caller
                errors.append(e)
            finally:
                q.put(SENTINEL)

        t0 = _time.perf_counter()
        th = threading.Thread(target=feeder, name="fused-pipe-feeder", daemon=True)
        th.start()
        losses: List[jnp.ndarray] = []
        pack: List[Tuple[int, Dict]] = []
        n_seen = 0
        try:
            def flush():
                nonlocal state
                if not pack:
                    return
                if len(pack) > 1:
                    with self.graph.lane("dense", k=len(pack)):
                        state, (ls, _preds) = self._multi(
                            state, tuple(b for _, b in pack)
                        )
                    losses.extend(ls[i] for i in range(len(pack)))
                else:
                    with self.graph.lane("dense"):
                        state, (loss, _preds) = self._step(state, pack[0][1])
                    losses.append(loss)
                graph.note_dense(pack[-1][0])
                pack.clear()

            while True:
                item = q.get()
                if item is SENTINEL:
                    break
                pack.append(item)
                n_seen += 1
                if len(pack) >= self.k:
                    flush()
            flush()
            if errors:
                raise errors[0]
            graph.drain_for_fence(n_seen, reason="end")
        finally:
            graph.abort()
            th.join(timeout=5.0)
        self._wall_s = _time.perf_counter() - t0
        return state, losses

    def stats(self) -> Dict:
        """Pipeline stats of the last :meth:`run` (stage_graph stats dict
        plus the run's wall seconds)."""
        out = self.graph.stats(getattr(self, "_wall_s", 0.0))
        out["wall_s"] = round(getattr(self, "_wall_s", 0.0), 6)
        return out


def build_fused_pipeline(
    model,
    dense_optimizer: optax.GradientTransformation,
    sparse_cfg: OptimizerConfig,
    specs: Dict[str, FusedSlotSpec],
    slot_order: Optional[Sequence[str]] = None,
    loss_fn=default_loss_fn,
    stack: bool = False,
    depth: int = 2,
    k: int = 1,
) -> FusedPipeline:
    """Convenience factory: builds the jitted single-step (and, when
    ``k > 1``, the K-step) program and wraps them in a
    :class:`FusedPipeline`. Reuse the returned pipeline across runs — each
    factory call retraces."""
    step = build_fused_train_step(
        model, dense_optimizer, sparse_cfg, specs, slot_order,
        loss_fn=loss_fn, stack=stack,
    )
    multi = None
    if k > 1:
        multi = build_fused_multi_step(
            model, dense_optimizer, sparse_cfg, specs, min(k, depth),
            slot_order, loss_fn=loss_fn, stack=stack,
        )
    return FusedPipeline(step, multi, depth=depth, k=k)

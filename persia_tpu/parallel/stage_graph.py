"""Explicit MPMD stage graph for the pipelined hybrid step.

The hybrid step decomposes into three device-program stages — ``feed``
(embedding lookup/feed: the fused aux scatters that admit missed rows and
read eviction payloads), ``dense`` (model fwd/bwd + dense/sparse updates;
a packed K-step window is ONE dense stage), and ``psgrad`` (the gradient
return + eviction write-back d2h lane). The source paper's core win is
bounded-staleness *overlap* between the sparse plane and the dense tower;
this module expresses that overlap as MPMD pipeline stages in the dispatch
layer (PAPERS.md: "Scaling Deep Learning Training with MPMD Pipeline
Parallelism", arxiv 2412.14374) instead of host threads alone: batch
N+k's feed dispatches from the stream's stager thread and rides under
batch N's dense compute, with the pipeline depth as the staleness knob.

Bit-parity contract (the reason the overlap is SOUND, not just fast):
feed(t)'s program touches exactly the cache rows newly assigned at
prepare(t) (evict-payload reads + warm/cold scatter targets); dense(j)'s
program touches exactly the rows step j trains (gathers + gradient
scatters). Scatter/gather chains over DISJOINT rows of the same pool
commute bitwise — each row's final value depends only on the ops that
touch that row — so hoisting feed(t) above dense(j < t) changes no bit
as long as the row sets are disjoint. :func:`feed_hazard_info` computes
both sets host-side at prepare time; :meth:`StageGraph.reserve_feed`
stalls the feed (``pipeline.stall`` flight event +
``persia_tpu_pipeline_stalls``) until the conflicting dense stages
retire. Everything the hazard ledger already forbids (in-flight-eviction
restores, PS-tier forwards) enters the window as a *barrier* entry that
no later feed may hoist across.

Fences drain the window (``pipeline.drain`` + the drains counter): the
feeder parks first, so by the time the dispatcher reaches the fence
marker every feed AND dense has dispatched and
:meth:`StageGraph.drain_for_fence` merely asserts the invariant — jobstate
bit-parity needs no new machinery. :meth:`StageGraph.rebuild` is the
fence-point hook that fires after a tier migration re-registers groups:
the clean place for the tiering follow-on of promoting a migrated group
into ``FusedTrainCtx`` proper (a step-graph rebuild at the fence).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from persia_tpu.metrics import get_metrics
from persia_tpu.tracing import record_event, stage_span

#: stage lanes of the hybrid step, in dataflow order
STAGES = ("feed", "dense", "psgrad")


def _rows_intersect(sorted_rows: np.ndarray, probe: np.ndarray) -> bool:
    """True when any value of ``probe`` occurs in ``sorted_rows``."""
    if sorted_rows.size == 0 or probe.size == 0:
        return False
    idx = np.searchsorted(sorted_rows, probe)
    np.minimum(idx, sorted_rows.size - 1, out=idx)
    return bool(np.any(sorted_rows[idx] == probe))


def feed_hazard_info(
    device_inputs: Dict,
    miss_aux: Dict,
    cold_aux: Dict,
    evict_aux: Dict,
    slot_group: Dict[str, str],
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """Host-side hazard sets of one prepared step, computed BEFORE the h2d
    staging turns the arrays into device buffers.

    Returns ``(feed_rows, trained_rows)`` keyed by group name: the cache
    rows the step's FEED stage writes/reads (evict-payload reads + warm
    miss scatters + cold scatters) and the *sorted* rows its DENSE stage
    gathers and gradient-scatters (stacked + raw lookup rows; the pad row
    rides along harmlessly — a feed never targets it). Disjointness of a
    later step's ``feed_rows`` against every in-flight step's
    ``trained_rows`` is the bit-parity license for hoisting the feed
    (module docstring); ``slot_group`` maps raw-slot names to their group.
    """
    feed: Dict[str, np.ndarray] = {}
    for gname in set(miss_aux) | set(cold_aux) | set(evict_aux):
        parts: List[np.ndarray] = []
        ev = evict_aux.get(gname)
        if ev is not None and np.size(ev):
            parts.append(np.asarray(ev, dtype=np.int64).ravel())
        m = miss_aux.get(gname)
        if m is not None and np.size(m[0]):
            parts.append(np.asarray(m[0], dtype=np.int64).ravel())
        c = cold_aux.get(gname)
        if c is not None and np.size(c[0]):
            parts.append(np.asarray(c[0], dtype=np.int64).ravel())
        if parts:
            feed[gname] = np.concatenate(parts)
    by_group: Dict[str, List[np.ndarray]] = {}
    for gname, rows in device_inputs["stacked_rows"].items():
        by_group.setdefault(gname, []).append(
            np.asarray(rows, dtype=np.int64).ravel()
        )
    for slot, rows in device_inputs.get("raw_rows", {}).items():
        by_group.setdefault(slot_group[slot], []).append(
            np.asarray(rows, dtype=np.int64).ravel()
        )
    trained = {
        gname: np.sort(np.concatenate(parts) if len(parts) > 1 else parts[0])
        for gname, parts in by_group.items()
    }
    return feed, trained


class StageGraph:
    """In-flight window + hazard accounting of the pipelined stream.

    The window holds one entry per step whose FEED stage has dispatched
    (or, for barrier steps, been forwarded) but whose DENSE stage has not;
    its length is bounded by ``depth``, which is therefore the staleness
    knob — a feed dispatches at most ``depth - 1`` steps ahead of its own
    dense stage, and ``depth == 1`` degenerates to the fully in-order
    pipeline. The stager thread appends via :meth:`reserve_feed` /
    barrier entries; the dispatch thread pops via :meth:`note_dense` after
    each dense dispatch. Per-lane busy seconds (:meth:`lane`) feed the
    ``stage.*`` span histograms and the ``stage_overlap_frac`` stat the
    bench artifact records.
    """

    def __init__(self, depth: int, clock=time.perf_counter):
        self.depth = max(1, int(depth))
        self._clock = clock
        # guards the window, the lane accounting, and the abort flag; a
        # leaf-ish condition — nothing ranked is ever taken under it
        # (analysis/lock_order.py rank 1)
        self._pipe_cv = threading.Condition()
        self._window: "deque[Tuple[int, Optional[Dict[str, np.ndarray]]]]" = deque()
        self._aborted = False
        self.stalls = 0
        self.drains = 0
        self._lane_busy: Dict[str, float] = {s: 0.0 for s in STAGES}
        self._rebuild_hooks: List[Callable[[int], None]] = []
        m = get_metrics()
        self._m_stalls = m.counter(
            "persia_tpu_pipeline_stalls",
            "feed stages stalled on a row hazard against an in-flight dense stage",
        )
        self._m_drains = m.counter(
            "persia_tpu_pipeline_drains",
            "pipeline windows drained at a fence or stream end",
        )
        m.gauge(
            "persia_tpu_pipeline_depth",
            "stage-pipeline depth of the most recent stream",
        ).set(self.depth)

    # ----------------------------------------------------------- window

    def reserve_feed(
        self,
        seq: int,
        feed_rows: Optional[Dict[str, np.ndarray]],
        trained_rows: Optional[Dict[str, np.ndarray]],
        should_abort: Optional[Callable[[], bool]] = None,
        barrier: bool = False,
    ) -> bool:
        """Block until step ``seq`` may enter the in-flight window, then
        append it. Feed entries (``barrier=False``) additionally wait
        until ``feed_rows`` is disjoint from every in-flight entry's
        trained rows; barrier entries (restore / PS-forward / pre-init
        steps, which dispatch through the full in-order path) only wait
        for window capacity and then conflict with EVERY later feed, so
        nothing hoists across them. Returns False when aborted — the
        caller unwinds without dispatching."""
        stalled = False
        with self._pipe_cv:
            while True:
                if self._aborted or (should_abort is not None and should_abort()):
                    return False
                if len(self._window) < self.depth:
                    conflict = None if barrier else self._conflict(feed_rows)
                    if conflict is None:
                        self._window.append(
                            (seq, None if barrier else trained_rows)
                        )
                        return True
                    if not stalled:
                        # counted once per stalled feed, not per retry
                        stalled = True
                        self.stalls += 1
                        self._m_stalls.inc()
                        record_event("pipeline.stall", step=seq, group=conflict)
                self._pipe_cv.wait(timeout=0.05)

    def _conflict(self, feed_rows) -> Optional[str]:
        for _seq, trained in self._window:
            if trained is None:
                return "barrier"
            if not feed_rows:
                continue
            for gname, probe in feed_rows.items():
                srt = trained.get(gname)
                if srt is not None and _rows_intersect(srt, probe):
                    return gname
        return None

    def note_dense(self, seq: int) -> None:
        """Retire every window entry up to and including ``seq`` — its
        dense stage (single or packed) has dispatched."""
        with self._pipe_cv:
            while self._window and self._window[0][0] <= seq:
                self._window.popleft()
            self._pipe_cv.notify_all()

    def abort(self) -> None:
        with self._pipe_cv:
            self._aborted = True
            self._pipe_cv.notify_all()

    # ----------------------------------------------------- fences/rebuild

    def drain_for_fence(self, step: int, reason: str = "fence") -> None:
        """Assert the window empty (feeder parked + FIFO ordering make it
        so by the time the dispatcher reaches a fence marker) and record
        the drain. Raises when a feed is still in flight — that would
        break the fence's jobstate bit-parity."""
        with self._pipe_cv:
            n = len(self._window)
        if n:
            raise RuntimeError(
                f"pipeline drain at step {step}: {n} feed stage(s) still "
                "in flight ahead of their dense stages"
            )
        self.drains += 1
        self._m_drains.inc()
        record_event("pipeline.drain", step=step, reason=reason)

    def on_rebuild(self, fn: Callable[[int], None]) -> None:
        self._rebuild_hooks.append(fn)

    def rebuild(self, step: int) -> None:
        """Fence-point stage-graph rebuild: fired with the window drained
        and the feeder parked, right after a tier migration re-registered
        the groups (the step programs' shapes changed underneath the
        stages). Registered hooks run here — the extension point for
        promoting a migrated group into ``FusedTrainCtx`` proper, per
        ROADMAP direction 1."""
        record_event("pipeline.rebuild", step=step)
        for fn in list(self._rebuild_hooks):
            fn(step)

    # ------------------------------------------------------------- lanes

    @contextmanager
    def lane(self, stage: str, **attrs):
        """Time a stage-lane occupancy: feeds the always-on ``stage.*``
        histogram (tracing.stage_span) and the per-lane busy accounting
        behind ``stage_overlap_frac``."""
        t0 = self._clock()
        try:
            with stage_span(f"stage.{stage}", **attrs):
                yield
        finally:
            dt = self._clock() - t0
            with self._pipe_cv:
                self._lane_busy[stage] = self._lane_busy.get(stage, 0.0) + dt

    def stats(self, wall_s: float) -> Dict:
        """Pipeline stats for the stream's stats dict / bench record.
        ``stage_overlap_frac`` is the fraction of lane-busy time hidden
        under other lanes: ``max(0, (sum(busy) - wall) / sum(busy))`` —
        0 when the lanes ran strictly serially, approaching 1 - 1/n_lanes
        at perfect overlap."""
        with self._pipe_cv:
            busy = dict(self._lane_busy)
        total = sum(busy.values())
        overlap = max(0.0, (total - wall_s) / total) if total > 0.0 else 0.0
        return {
            "pipeline_depth": self.depth,
            "pipeline_stalls": self.stalls,
            "pipeline_drains": self.drains,
            "stage_wall_s": {k: round(v, 6) for k, v in busy.items()},
            "stage_overlap_frac": round(overlap, 6),
        }

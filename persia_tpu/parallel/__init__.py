"""Parallelism layer: device meshes, sharded train/eval steps.

Replaces the reference's dense-distributed stack (`persia/distributed.py`
DDP/Bagua + NCCL, `rust/persia-core/src/nats.rs:22-100` master discovery)
with JAX-native SPMD: a `jax.sharding.Mesh` + `jax.jit` with
`NamedSharding`s; XLA inserts the ICI collectives (psum of dense grads)
that DDP performed explicitly.
"""

from persia_tpu.parallel.mesh import data_parallel_mesh, batch_sharding, replicated  # noqa: F401
from persia_tpu.parallel.train_step import (  # noqa: F401
    TrainState,
    build_eval_step,
    build_train_step,
    init_train_state,
)
from persia_tpu.parallel.grad_sync import (  # noqa: F401
    ByteGradAllReduce,
    Decentralized,
    GradientAllReduce,
    LocalSGD,
    build_sync_train_step,
)
from persia_tpu.parallel.fused_ctx import FusedTrainCtx, batch_to_fused  # noqa: F401

"""TrainCtx-shaped wrapper around the fused all-in-HBM tier.

The fused tier (``parallel/fused_step.py``) is the idiomatic TPU answer to
the reference's async CPU-PS pipeline when the tables fit in HBM: gather →
model fwd/bwd → dense update → duplicate-safe sparse update, all ONE jitted
XLA program, host↔device traffic per step = the raw batch. Until now only
bench/test code drove it, wiring ``init_fused_state``/``build_fused_*`` by
hand; this module packages the same machinery behind the ``TrainCtx`` API
(train_step / eval_batch / dump_checkpoint / load_checkpoint, ref:
`persia/ctx.py` TrainCtx surface) so the example CLIs and user code can
switch tiers with one flag.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from persia_tpu.data import PersiaBatch
from persia_tpu.logger import get_default_logger
from persia_tpu.parallel.fused_step import (
    FusedSlotSpec,
    FusedTrainState,
    build_fused_eval_step,
    build_fused_train_step,
    init_fused_state,
)
from persia_tpu.parallel.train_step import _note_nonfinite_loss

logger = get_default_logger("persia_tpu.fused_ctx")


def batch_to_fused(
    batch: PersiaBatch,
    specs: Optional[Dict[str, FusedSlotSpec]] = None,
    fold_ids: bool = False,
) -> Dict:
    """PersiaBatch → the fused step's dict batch.

    Single-id slots (every sample carries exactly one id) become (B,)
    int32; list-of-list slots become (B, Lmax) int32 padded with -1 (the
    step's pad sentinel). Static shapes matter on TPU: Lmax is the batch's
    own max, so callers with ragged streams should bucket batch shapes
    upstream.

    Fused tables are dense [0, vocab) while the rest of the framework
    passes open u64 hash signs, so when ``specs`` is given every slot's
    ids are range-checked against its vocab BEFORE the int32 cast (an
    id >= 2^31 would wrap negative and collide with the pad sentinel; an
    id in [vocab, 2^31) would alias XLA's clamped last row — both silent
    corruption). ``fold_ids=True`` folds by modulo instead of raising.
    """
    def _ranged(name: str, flat: np.ndarray) -> np.ndarray:
        if specs is None or not len(flat):
            return flat
        vocab = np.uint64(specs[name].vocab)
        if fold_ids:
            return flat % vocab
        bad = flat >= vocab
        if bad.any():
            raise ValueError(
                f"slot {name!r}: {int(bad.sum())} id(s) outside "
                f"[0, {int(vocab)}) (max {int(flat.max())}); hash-sign ids "
                f"must be folded first — pass fold_ids=True or fold upstream"
            )
        return flat

    ids = {}
    for f in batch.id_type_features:
        flat, counts = f.flat_counts()
        flat = _ranged(f.name, np.asarray(flat, dtype=np.uint64))
        if len(counts) and (counts == 1).all():  # one id per sample
            ids[f.name] = flat.astype(np.int32)
        else:
            b = len(counts)
            lmax = max(int(counts.max()), 1) if b else 1
            padded = np.full((b, lmax), -1, dtype=np.int32)
            off = 0
            for i, c in enumerate(counts):
                padded[i, :c] = flat[off:off + c]
                off += c
            ids[f.name] = padded
    out = {
        "dense": [np.asarray(d.data, np.float32) for d in batch.non_id_type_features],
        "ids": ids,
    }
    if batch.labels:
        out["labels"] = [np.asarray(l.data, np.float32) for l in batch.labels]
    return out


class FusedTrainCtx:
    """All-in-HBM training context (the bench's "fused" tier as an API).

    State initializes lazily from the first batch (the model needs a sample
    to trace). ``train_step`` fetches the loss (one d2h per step — fine for
    examples; throughput loops should use the raw ``build_fused_train_step``
    the way bench.py does, or ``fetch_metrics=False``).
    """

    def __init__(
        self,
        model,
        dense_optimizer: optax.GradientTransformation,
        embedding_optimizer,
        specs: Dict[str, FusedSlotSpec],
        loss_fn=None,
        stack: bool = True,
        table_dtype=jnp.float32,
        seed: int = 0,
        fold_ids: bool = False,
    ):
        self.model = model
        self.dense_optimizer = dense_optimizer
        self.sparse_cfg = embedding_optimizer.config
        self.specs = dict(specs)
        self.slot_order = sorted(self.specs)
        self.stack = stack
        self.table_dtype = table_dtype
        self.seed = seed
        self.fold_ids = fold_ids
        kw = {} if loss_fn is None else {"loss_fn": loss_fn}
        self._loss_kw = kw
        self._pipelines: Dict = {}
        self._pipe_stats: Optional[Dict] = None
        self._step = build_fused_train_step(
            model, dense_optimizer, self.sparse_cfg, self.specs,
            self.slot_order, stack=stack, **kw
        )
        self._eval = build_fused_eval_step(
            model, self.specs, self.slot_order, stack=stack
        )
        self.state: Optional[FusedTrainState] = None

    # lifecycle ------------------------------------------------------------

    def __enter__(self) -> "FusedTrainCtx":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def _ensure_state(self, fused_batch: Dict) -> None:
        if self.state is None:
            self.state = init_fused_state(
                self.model, jax.random.PRNGKey(self.seed), self.specs,
                fused_batch, self.dense_optimizer, self.sparse_cfg,
                slot_order=self.slot_order, stack=self.stack,
                table_dtype=self.table_dtype,
            )

    # training -------------------------------------------------------------

    def train_step(self, batch: PersiaBatch, fetch_metrics: bool = True) -> Dict:
        fb = batch_to_fused(batch, self.specs, self.fold_ids)
        self._ensure_state(fb)
        self.state, (loss, preds) = self._step(self.state, fb)
        self._last = (loss, preds)
        if not fetch_metrics:
            return {}
        return {"loss": _note_nonfinite_loss(float(loss)),
                "preds": np.asarray(preds)}

    def train_pipelined(
        self,
        batches,
        pipeline_depth: int = 2,
        dispatch_k: int = 1,
        fetch_metrics: bool = True,
    ) -> Dict:
        """Stage-pipelined drive of a ``PersiaBatch`` iterable: host
        conversion + h2d staging (FEED) overlap the jitted step (DENSE)
        via :class:`~persia_tpu.parallel.fused_step.FusedPipeline`, with
        ``pipeline_depth`` bounding the staged buffers in flight and
        ``dispatch_k`` packing the dense stage into K-step windows. With
        ``dispatch_k=1`` the math is the sequential ``train_step`` loop's
        bit for bit (all rows are HBM-resident — no feed hazards);
        ``dispatch_k>1`` inherits ``build_fused_multi_step``'s numerical
        (~1 ulp) parity. The pipeline drains before this
        returns, so ``dump_checkpoint`` right after has fence semantics;
        pipeline overlap stats land in :meth:`pipeline_stats`. Programs
        are cached per ``(pipeline_depth, dispatch_k)``."""
        from persia_tpu.parallel.fused_step import build_fused_pipeline

        it = iter(batches)
        try:
            first = next(it)
        except StopIteration:
            return {}
        fb0 = batch_to_fused(first, self.specs, self.fold_ids)
        self._ensure_state(fb0)
        key = (int(pipeline_depth), int(dispatch_k))
        pipe = self._pipelines.get(key)
        if pipe is None:
            pipe = build_fused_pipeline(
                self.model, self.dense_optimizer, self.sparse_cfg,
                self.specs, self.slot_order, stack=self.stack,
                depth=pipeline_depth, k=dispatch_k, **self._loss_kw,
            )
            self._pipelines[key] = pipe

        def fused_stream():
            # consumed by the pipeline's feed thread: conversion rides
            # the feed lane
            yield fb0
            for b in it:
                yield batch_to_fused(b, self.specs, self.fold_ids)

        self.state, losses = pipe.run(self.state, fused_stream())
        self._pipe_stats = pipe.stats()
        self._last = None
        if not fetch_metrics or not losses:
            return {}
        return {"loss": _note_nonfinite_loss(float(losses[-1])),
                "losses": np.asarray([float(l) for l in losses])}

    def pipeline_stats(self) -> Optional[Dict]:
        """Stage/overlap stats of the last :meth:`train_pipelined` run."""
        return self._pipe_stats

    @property
    def sync_mode(self) -> str:
        """Dense-plane sync label for bench records: the fused tier is one
        device, one program — no dense collective crosses any wire. Shares
        the grad_sync mode vocabulary so fused/stream/hybrid rows compare."""
        return "local"

    def dense_wire_bytes_per_step(self) -> int:
        """Per-replica dense collective bytes/step: 0 by construction (the
        whole hybrid step is one single-device XLA program)."""
        return 0

    def last_metrics(self) -> Optional[Dict]:
        if getattr(self, "_last", None) is None:
            return None
        loss, preds = self._last
        return {"loss": _note_nonfinite_loss(float(loss)),
                "preds": np.asarray(preds)}

    def eval_batch(self, batch: PersiaBatch) -> np.ndarray:
        fb = batch_to_fused(batch, self.specs, self.fold_ids)
        self._ensure_state(fb)
        return np.asarray(self._eval(self.state, fb))

    # checkpoint -----------------------------------------------------------
    # One .npz of every state leaf keyed by its tree path + a JSON manifest
    # (ref capability: full-state dump/load, persia-model-manager). The
    # host tiers' directory checkpoints (checkpoint.py) cover the PS side;
    # fused state is pure device arrays so an archive is the natural form.

    def dump_checkpoint(self, path: str) -> None:
        assert self.state is not None, "no state to dump (train first)"
        import io

        from persia_tpu.jobstate import fsync_write_bytes

        os.makedirs(path, exist_ok=True)
        leaves = jax.tree_util.tree_leaves_with_path(self.state)
        arrays = {}
        manifest = []
        for i, (kp, leaf) in enumerate(leaves):
            arrays[f"a{i}"] = np.asarray(leaf)
            manifest.append(jax.tree_util.keystr(kp))
        # atomic + fsync'd publish (persia-lint DUR001): a crash mid-dump
        # must never leave a torn archive under the final name
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        fsync_write_bytes(os.path.join(path, "fused_state.npz"), buf.getvalue())
        fsync_write_bytes(
            os.path.join(path, "fused_state.json"), json.dumps(manifest).encode()
        )
        logger.info("fused checkpoint written to %s (%d leaves)", path, len(manifest))

    def load_checkpoint(self, path: str) -> None:
        assert self.state is not None, (
            "load_checkpoint needs an initialized state shape — run one "
            "train_step/eval_batch first (the model traces from a sample)"
        )
        with open(os.path.join(path, "fused_state.json")) as f:
            manifest = json.load(f)
        data = np.load(os.path.join(path, "fused_state.npz"))
        leaves_now = jax.tree_util.tree_leaves_with_path(self.state)
        if [jax.tree_util.keystr(kp) for kp, _ in leaves_now] != manifest:
            raise ValueError(
                "checkpoint layout mismatch: model/spec/optimizer changed "
                "since the dump"
            )
        treedef = jax.tree_util.tree_structure(self.state)
        self.state = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(data[f"a{i}"]) for i in range(len(manifest))]
        )

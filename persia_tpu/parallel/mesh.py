"""Mesh + sharding helpers.

The dense half trains synchronously data-parallel over the ``data`` mesh axis
(ref capability: `persia/distributed.py:74-202` DDP / Bagua allreduce).
Gradient averaging is implicit: with batch inputs sharded over ``data`` and
parameters replicated, XLA lowers the grad reduction to a psum over ICI.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions. The top-level alias and its
    ``check_vma`` kwarg are newer than 0.4.x; older jax exposes
    ``jax.experimental.shard_map.shard_map`` whose equivalent kwarg is
    ``check_rep``. Every shard_map call site in the repo goes through here
    so a version bump in either direction is a one-line change."""
    if hasattr(jax, "shard_map"):  # deprecation __getattr__ => False on old jax
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


def data_parallel_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D ``data`` mesh over the first ``n_devices`` devices (default all)."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(f"requested {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=("data",))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard leading (batch) axis over ``data``."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

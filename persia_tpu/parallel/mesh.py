"""Mesh + sharding helpers.

The dense half trains synchronously data-parallel over the ``data`` mesh axis
(ref capability: `persia/distributed.py:74-202` DDP / Bagua allreduce).
Gradient averaging is implicit: with batch inputs sharded over ``data`` and
parameters replicated, XLA lowers the grad reduction to a psum over ICI.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def data_parallel_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D ``data`` mesh over the first ``n_devices`` devices (default all)."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(f"requested {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=("data",))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard leading (batch) axis over ``data``."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

"""Dense gradient/model synchronization algorithms over the ``data`` axis.

Parity target: the reference's Bagua-class dense distributed options
(`persia/distributed.py:204-411` — gradient_allreduce, bytegrad,
low_precision_decentralized, decentralized, async model averaging; DDP covers
plain allreduce, `persia/distributed.py:74-202`). On TPU the default DP path
needs none of this — params replicated + batch sharded makes XLA insert the
exact ICI psum (persia_tpu/parallel/train_step.py). What survives translation
is the *algorithm* choice: trading gradient fidelity or synchrony for
bandwidth, which matters once the dense half rides DCN (multi-pod) or the
model head grows past what ICI hides.

Implemented as explicit collectives under ``jax.shard_map`` (XLA cannot be
asked to quantize its own psum):

- :class:`GradientAllReduce` — exact mean-psum; ``dtype="bfloat16"`` casts
  gradients to bf16 before the wire (2x bytes saved, the TPU-native
  low-precision analogue).
- :class:`ByteGradAllReduce` — the bytegrad analogue: per-leaf absmax int8
  quantization (pmax-shared scale) with an error-feedback residual so the
  quantization error is re-injected next step instead of lost.
- :class:`Decentralized` — no allreduce at all: each replica updates with its
  LOCAL gradients, then averages parameters with one ring neighbor per step
  (alternating left/right), the decentralized SGD analogue.
- :class:`LocalSGD` — async-model-averaging analogue: local updates, full
  parameter pmean every ``period`` steps.
- :class:`QAdam` — the qadam analogue (1-bit Adam): full-precision allreduce
  Adam during warmup, then ``v`` freezes and only int8-quantized momentum
  crosses the wire, with error feedback.
- :class:`LowPrecisionDecentralized` — ring averaging over int8-compressed
  parameter *differences* with error compensation; both-neighbor exchange at
  half the bytes of one f32 copy.
- :class:`BlockInt8Ring` — byte-optimal ring allreduce in the EQuARX style
  (arxiv 2506.17615): an explicit reduce-scatter + all-gather ring where the
  payload of EVERY hop is block-scaled int8 (per-block absmax scales), not
  just the endpoints. ByteGrad's psum ships int32 summands — 4 bytes/elem on
  the wire, same as f32 — whereas this ring really moves ~1 byte/elem
  (+4/block_size for scales). Per-hop rounding error lands in an on-device
  error-feedback residual carried inside ``state.opt_state``.

With all of these, the reference's Bagua algorithm menu
(`persia/distributed.py:204-411`) is covered end to end — plus the
TPU-native byte-optimal ring the reference never had.

Orthogonally, ``build_sync_train_step(..., sharded_update=True)`` shards the
dense optimizer state and the weight update across the data axis (ZeRO /
"Automatic Cross-Replica Sharding of Weight Update", arxiv 2004.13336): each
replica reduce-scatters gradients, updates its 1/n parameter shard with 1/n
of the optimizer moments, and all-gathers fresh params. Composes with
:class:`GradientAllReduce` (f32/bf16 reduce-scatter) and
:class:`BlockInt8Ring` (the ring's reduce-scatter half IS the grad shard, so
the quantized all-gather of gradients is skipped entirely). Requires an
elementwise optimizer (adam/adagrad/sgd/rmsprop-class: state leaves are
scalars or param-shaped) — the shard update must equal the corresponding
slice of the full update.

``GradientAllReduce``/``ByteGradAllReduce`` keep parameters bit-identical
across replicas (the update consumes identical synced grads); the other two
hold genuinely divergent per-replica params, carried as a leading
``(dp, ...)`` axis sharded over ``data`` (build the state with
:func:`replicate_for_local`).

Embedding-input gradients are NEVER quantized or desynchronized here — they
ship to the sparse tier (worker NaN-skip/scale path) exactly as the default
path produces them: pooled slots stay batch-sharded, raw-slot distinct rows
are exact-psum'd.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from persia_tpu.parallel.train_step import (
    TrainState,
    _embedding_model_inputs,
    _split_emb,
    default_loss_fn,
)

from persia_tpu.parallel.mesh import shard_map_compat as shard_map


# --------------------------------------------------------------- algorithms


@dataclass(frozen=True)
class GradientAllReduce:
    """Exact (f32) or bf16-compressed gradient mean over ``data``.

    ``dtype="bfloat16"`` halves the wire bytes (the TPU-native analogue of the
    reference's low-precision options); the mean itself is computed in f32
    after an exact psum of bf16 summands.
    """

    dtype: str = "float32"  # "float32" | "bfloat16"


@dataclass(frozen=True)
class ByteGradAllReduce:
    """Int8 absmax-quantized gradient mean with error feedback (bytegrad
    analogue, persia/distributed.py BaguaAlgorithm.bytegrad).

    Each leaf is scaled by its global absmax (pmax), rounded to int8, psum'd
    in int32, and de-scaled. The per-replica rounding error is carried in a
    residual pytree and added back into the next step's gradients, so the
    *accumulated* update stays unbiased (plain truncation stalls training).
    """

    error_feedback: bool = True


@dataclass(frozen=True)
class Decentralized:
    """Ring neighbor parameter averaging; no gradient collective at all.

    Step t averages with the neighbor at offset +1 or -1 (alternating), so
    information diffuses around the ring while each sync only moves one
    param-sized message per replica (the reference's decentralized
    peer-to-peer averaging).
    """

    period: int = 1  # average every Nth step


@dataclass(frozen=True)
class LocalSGD:
    """Local updates with a full parameter pmean every ``period`` steps (the
    async-model-averaging analogue — synchrony decoupled from the step)."""

    period: int = 4


@dataclass(frozen=True)
class QAdam:
    """Quantized-momentum Adam (the reference's ``qadam`` Bagua option,
    `persia/distributed.py:238-244`; algorithm after 1-bit Adam, Tang et al.).

    The algorithm **is** the optimizer (exactly like Bagua, which swaps the
    user's optimizer for ``QAdamOptimizer``): ``build_sync_train_step``
    ignores the ``optimizer`` argument for this algorithm and runs Adam
    itself, carrying ``(m, v, residual)`` in the threaded algo state.

    - **warmup** (``step <= warmup_steps``): exact f32 gradient allreduce,
      standard Adam ``m``/``v`` updates — identical to GradientAllReduce+Adam.
    - **after warmup**: ``v`` freezes; each replica folds its LOCAL gradient
      into the momentum, and only the **momentum** crosses the wire, int8
      absmax-quantized with an error-feedback residual (4x fewer bytes, and
      the quantity quantized is the smooth momentum, not the noisy gradient —
      that is the whole point of the algorithm).
    """

    lr: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    warmup_steps: int = 100

    def __post_init__(self):
        # warmup_steps == 0 would freeze v at its all-zero init AND make the
        # frozen bias correction 1 - beta2^0 = 0, so the first update divides
        # 0/0 and every parameter goes NaN immediately.
        if self.warmup_steps < 1:
            raise ValueError(
                f"QAdam requires warmup_steps >= 1 (got {self.warmup_steps}): "
                "v freezes at warmup end, so at least one warmup step must "
                "populate it"
            )


@dataclass(frozen=True)
class LowPrecisionDecentralized:
    """Decentralized neighbor averaging with an int8 **difference** wire (the
    reference's ``low_precision_decentralized`` Bagua option,
    `persia/distributed.py:232-236`).

    Each replica keeps reconstruction shadows of itself and both ring
    neighbors. On a sync step it quantizes ``(params - shadow_self +
    residual)`` to int8 (error compensation: what int8 loses re-enters next
    sync), ships the int8 delta + one f32 scale to BOTH neighbors, advances
    all three shadows by the dequantized deltas (so ``shadow_left_i`` tracks
    ``shadow_self_{i-1}`` exactly), and averages ``(params + shadow_left +
    shadow_right) / 3``. Wire cost per sync: two int8 param-sized messages —
    half of ONE f32 exchange — while plain :class:`Decentralized` ships one
    full f32 copy.
    """

    period: int = 1


@dataclass(frozen=True)
class BlockInt8Ring:
    """Block-scaled int8 ring allreduce with per-hop quantization (EQuARX
    style, arxiv 2506.17615).

    The gradient pytree is flattened to one vector, padded to ``n * chunk``
    (``chunk`` a multiple of ``block_size``), and reduced around the ring:

    - **reduce-scatter** (n-1 hops): each hop quantizes the outgoing chunk to
      int8 with one f32 absmax scale per ``block_size`` elements, ships
      ``(int8[chunk], f32[chunk/block_size])`` via ppermute, and the receiver
      accumulates the dequantized payload. The sender's rounding error lands
      in the error-feedback residual at that chunk's position — each chunk
      position is sent exactly once per step, so the residual is exact
      bookkeeping, and the ring accumulates SUMS (divide by n only at the
      end) so residual units match gradient units.
    - **all-gather**: the owned chunk-sum is quantized once more (error →
      residual at the owner's position) and all-gathered as int8+scales;
      every replica — including the owner — consumes the DEQUANTIZED values,
      so parameters stay bit-identical across replicas.

    Wire cost per replica per step: ``2·(n-1)/n · P · (1 + 4/block_size)``
    bytes vs ``2·(n-1)/n · P · 4`` for the f32 ring — ~3.94x fewer at
    ``block_size=256``. The residual rides ``state.opt_state["ef"]`` (built
    by :func:`init_sync_opt_state`), so the 2-arg ``step(state, batch)``
    contract and jobstate snapshot/resume hold unchanged.
    """

    block_size: int = 256
    error_feedback: bool = True

    def __post_init__(self):
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1 (got {self.block_size})")


Algorithm = Any  # one of the dataclasses above


# --------------------------------------------------------- sync primitives


def _tree_cast(tree, dtype):
    return jax.tree.map(lambda g: g.astype(dtype), tree)


def quantize_int8_ef(g, residual, scale=None):
    """Absmax int8 quantization with error feedback — the shared core of
    :func:`bytegrad_allreduce`, :func:`lp_ring_sync`, and the cached tier's
    int8 ps-gradient-return wire (hbm_cache/step.py).

    ``g`` (f32) is summed with the carried ``residual``, scaled by absmax
    (or the caller's ``scale``, e.g. a pmax-shared one), rounded to int8,
    and the rounding error becomes the new residual — what int8 could not
    represent is re-sent later instead of lost. Returns
    ``(q int8, scale f32 scalar, dequantized f32, new_residual f32)``.
    Traceable; use inside jit/shard_map."""
    v = g.astype(jnp.float32) + residual
    if scale is None:
        scale = jnp.maximum(jnp.max(jnp.abs(v)), 1e-30)
    q = jnp.clip(jnp.round(v / scale * 127.0), -127, 127).astype(jnp.int8)
    deq = q.astype(jnp.float32) * (scale / 127.0)
    return q, scale, deq, v - deq


def dequantize_int8_np(q: np.ndarray, scale: float) -> np.ndarray:
    """Host-side inverse of :func:`quantize_int8_ef` for wire consumers
    (the stream's write-back thread dequantizes fetched int8 grads with
    numpy, off the device)."""
    return q.astype(np.float32) * (np.float32(scale) / np.float32(127.0))


def allreduce_mean(grads, axis: str, dtype: str = "float32"):
    """Mean over ``axis``; optionally bf16 on the wire. Use inside shard_map."""
    n = jax.lax.psum(1, axis)
    if dtype == "bfloat16":
        grads = _tree_cast(grads, jnp.bfloat16)
    summed = jax.lax.psum(grads, axis)
    return jax.tree.map(lambda g: g.astype(jnp.float32) / n, summed)


def bytegrad_allreduce(grads, residual, axis: str):
    """Int8-quantized mean over ``axis`` with error feedback.

    Returns ``(mean_grads, new_residual)``. ``residual`` must be a pytree of
    f32 zeros_like(grads) on the first call (see :func:`init_residual`).
    Use inside shard_map.
    """
    n = jax.lax.psum(1, axis)

    def one(g, r):
        # pmax-shared scale so every replica's int8 lattice matches
        scale = jax.lax.pmax(
            jnp.max(jnp.abs(g.astype(jnp.float32) + r)), axis
        )
        scale = jnp.maximum(scale, 1e-30)
        q, _, _deq, new_r = quantize_int8_ef(g, r, scale=scale)
        summed = jax.lax.psum(q.astype(jnp.int32), axis)
        mean = summed.astype(jnp.float32) * (scale / 127.0) / n
        return mean, new_r

    flat, treedef = jax.tree.flatten(grads)
    rflat = jax.tree.leaves(residual)
    out = [one(g, r) for g, r in zip(flat, rflat)]
    means = treedef.unflatten([m for m, _ in out])
    new_res = treedef.unflatten([r for _, r in out])
    return means, new_res


def init_residual(params):
    """Zero error-feedback residual shaped like the dense gradients."""
    return jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)


def block_quantize_int8(v, block_size: int):
    """Per-block absmax int8 quantization of a flat f32 vector whose length
    is a multiple of ``block_size``. Returns ``(q int8[P], scales
    f32[P/block_size], deq f32[P])``. The block granularity is the whole
    point vs :func:`quantize_int8_ef`'s single tensor scale: one outlier
    only poisons its own 256 elements, not the entire message."""
    blocks = v.reshape(-1, block_size)
    scales = jnp.maximum(jnp.max(jnp.abs(blocks), axis=1), 1e-30)
    q = jnp.clip(
        jnp.round(blocks / scales[:, None] * 127.0), -127, 127
    ).astype(jnp.int8)
    deq = q.astype(jnp.float32) * (scales[:, None] / 127.0)
    return q.reshape(-1), scales, deq.reshape(-1)


def block_dequantize_int8(q, scales, block_size: int):
    """Inverse of :func:`block_quantize_int8` (without the clip loss)."""
    blocks = q.reshape(-1, block_size).astype(jnp.float32)
    return (blocks * (scales[:, None] / 127.0)).reshape(-1)


def _flat_chunk(p_total: int, n: int, block_size: int) -> Tuple[int, int]:
    """Static ring geometry: per-device chunk length (a block_size multiple)
    and the padded flat length ``n * chunk``."""
    chunk = -(-p_total // n)
    chunk = -(-chunk // block_size) * block_size
    return chunk, n * chunk


def _ravel_f32(tree):
    """Flatten a pytree to one f32 vector; returns ``(flat, unravel)``."""
    from jax.flatten_util import ravel_pytree

    return ravel_pytree(jax.tree.map(lambda x: x.astype(jnp.float32), tree))


def _unravel_like(unravel, flat, ref):
    out = unravel(flat)
    return jax.tree.map(lambda o, r: o.astype(r.dtype), out, ref)


def ring_reduce_scatter_block_int8(v, axis: str, n: int, block_size: int):
    """EQuARX-style quantized ring reduce-scatter (use inside shard_map).

    ``v`` is the local ``(n * chunk,)`` f32 vector (gradient + residual).
    Runs n-1 hops; hop s sends chunk ``(me - s) % n`` (quantized per block)
    to ring-right and accumulates the dequantized chunk ``(me - s - 1) % n``
    arriving from ring-left, so after the loop device ``me`` holds the full
    SUM of chunk ``(me + 1) % n``.

    Returns ``(own_sum f32[chunk], err f32[n, chunk], own_idx)`` where
    ``err`` carries this device's quantization error at each sent chunk's
    position (the own chunk's row stays zero — it was never quantized here).
    """
    chunk = v.shape[0] // n
    acc = v.reshape(n, chunk)
    err = jnp.zeros_like(acc)
    me = jax.lax.axis_index(axis)
    fwd = [(i, (i + 1) % n) for i in range(n)]  # receive from ring-left
    for s in range(n - 1):
        send_idx = jnp.mod(me - s, n)
        payload = jax.lax.dynamic_slice(acc, (send_idx, 0), (1, chunk))[0]
        q, scales, deq = block_quantize_int8(payload, block_size)
        err = jax.lax.dynamic_update_slice(
            err, (payload - deq)[None], (send_idx, 0)
        )
        q_in = jax.lax.ppermute(q, axis, fwd)
        sc_in = jax.lax.ppermute(scales, axis, fwd)
        recv_idx = jnp.mod(me - s - 1, n)
        cur = jax.lax.dynamic_slice(acc, (recv_idx, 0), (1, chunk))[0]
        acc = jax.lax.dynamic_update_slice(
            acc,
            (cur + block_dequantize_int8(q_in, sc_in, block_size))[None],
            (recv_idx, 0),
        )
    own_idx = jnp.mod(me + 1, n)
    own_sum = jax.lax.dynamic_slice(acc, (own_idx, 0), (1, chunk))[0]
    return own_sum, err, own_idx


def ring_allgather_block_int8(own_sum, axis: str, n: int, block_size: int):
    """All-gather phase of the quantized ring: quantize the owned chunk-sum
    once, gather int8 + scales (byte-equal to a ring all-gather), and let
    EVERY replica — owner included — consume the dequantized values, so the
    downstream update keeps parameters bit-identical across replicas.

    Returns ``(flat_sum f32[n*chunk] in chunk order, err_own f32[chunk])``.
    """
    q, scales, deq = block_quantize_int8(own_sum, block_size)
    err_own = own_sum - deq
    rows_q = jax.lax.all_gather(q, axis)  # (n, chunk) int8
    rows_s = jax.lax.all_gather(scales, axis)  # (n, chunk/bs) f32
    rows = (
        rows_q.reshape(n, -1, block_size).astype(jnp.float32)
        * (rows_s[:, :, None] / 127.0)
    ).reshape(n, -1)
    # row j is device j's owned chunk (j+1) % n → roll by one restores
    # chunk order 0..n-1
    flat_sum = jnp.roll(rows, 1, axis=0).reshape(-1)
    return flat_sum, err_own


def _block_ring_allreduce_flat(flat_g, ef, algorithm: "BlockInt8Ring", n: int,
                               axis: str = "data"):
    """Full quantized-ring allreduce of a flat gradient: reduce-scatter +
    all-gather, SUM units throughout (caller divides by n). Returns
    ``(flat_sum f32[Ppad] in chunk order, new_ef f32[Ppad])``."""
    bs = algorithm.block_size
    p_total = flat_g.shape[0]
    chunk, p_pad = _flat_chunk(p_total, n, bs)
    gpad = jnp.pad(flat_g, (0, p_pad - p_total))
    v = gpad + ef if algorithm.error_feedback else gpad
    own_sum, err, own_idx = ring_reduce_scatter_block_int8(v, axis, n, bs)
    flat_sum, err_own = ring_allgather_block_int8(own_sum, axis, n, bs)
    err = jax.lax.dynamic_update_slice(err, err_own[None], (own_idx, 0))
    new_ef = (
        err.reshape(-1) if algorithm.error_feedback
        else jnp.zeros((p_pad,), jnp.float32)
    )
    return flat_sum, new_ef


def lp_ring_sync(params, shadows, axis: str, n: int):
    """One low-precision decentralized sync (see
    :class:`LowPrecisionDecentralized`). ``shadows`` is the algo-state dict of
    per-leaf trees; everything here is the LOCAL shard (use inside shard_map).
    Returns ``(new_params, new_shadows)``. The ppermute payload is the int8
    tensor + a scalar scale — XLA ships the int8 buffer as-is, so the wire
    really is quarter-width."""
    fwd = [(i, (i + 1) % n) for i in range(n)]  # receive from ring-left
    bwd = [(i, (i - 1) % n) for i in range(n)]  # receive from ring-right

    def one(x, ss, sl, sr, r):
        q, scale, deq, new_r = quantize_int8_ef(x - ss, r)
        new_ss = ss + deq
        ql = jax.lax.ppermute(q, axis, fwd)
        scl = jax.lax.ppermute(scale, axis, fwd)
        qr = jax.lax.ppermute(q, axis, bwd)
        scr = jax.lax.ppermute(scale, axis, bwd)
        new_sl = sl + ql.astype(jnp.float32) * (scl / 127.0)
        new_sr = sr + qr.astype(jnp.float32) * (scr / 127.0)
        new_x = (x + new_sl + new_sr) / 3.0
        return new_x, new_ss, new_sl, new_sr, new_r

    flat_x, treedef = jax.tree.flatten(params)
    out = [
        one(x, ss, sl, sr, r)
        for x, ss, sl, sr, r in zip(
            flat_x,
            jax.tree.leaves(shadows["shadow_self"]),
            jax.tree.leaves(shadows["shadow_left"]),
            jax.tree.leaves(shadows["shadow_right"]),
            jax.tree.leaves(shadows["residual"]),
        )
    ]
    unf = lambda i: treedef.unflatten([o[i] for o in out])
    return unf(0), {
        "shadow_self": unf(1),
        "shadow_left": unf(2),
        "shadow_right": unf(3),
        "residual": unf(4),
    }


def init_qadam_state(params, mesh: Mesh):
    """(m, v, residual) for :class:`QAdam`: moments replicated (the synced
    momentum is identical on every replica), residual per-replica with a
    leading ``dp`` axis (each replica's own quantization error)."""
    dp = mesh.shape["data"]
    rep = NamedSharding(mesh, P())
    lead = NamedSharding(mesh, P("data"))
    zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
    return {
        "m": jax.tree.map(lambda p: jax.device_put(zeros(p), rep), params),
        "v": jax.tree.map(lambda p: jax.device_put(zeros(p), rep), params),
        "residual": jax.tree.map(
            lambda p: jax.device_put(
                jnp.zeros((dp,) + p.shape, jnp.float32), lead
            ),
            params,
        ),
    }


def init_lp_decentralized_state(state: TrainState, mesh: Mesh):
    """Shadow/residual algo state for :class:`LowPrecisionDecentralized`.
    ``state`` must already carry the per-replica leading axis (from
    :func:`replicate_for_local`); every replica starts from identical params,
    so all three shadows start as that copy."""
    copy = lambda: jax.tree.map(lambda p: jnp.array(p), state.params)
    return {
        "shadow_self": copy(),
        "shadow_left": copy(),
        "shadow_right": copy(),
        "residual": jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state.params
        ),
    }


def ring_neighbor_average(params, sync_idx, axis: str, n: int):
    """Average with the ring neighbor at offset +1 (even ``sync_idx``) / -1
    (odd) — pass the per-sync ordinal, not the raw step, so alternation
    survives any sync period.

    The direction is a ``lax.cond`` on the replicated ordinal, so exactly
    ONE param-sized ppermute executes per sync — the message decentralized
    SGD pays, not both directions.
    """
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    use_fwd = (sync_idx % 2) == 0

    def one(p):
        peer = jax.lax.cond(
            use_fwd,
            lambda q: jax.lax.ppermute(q, axis, fwd),
            lambda q: jax.lax.ppermute(q, axis, bwd),
            p,
        )
        return (p + peer) * 0.5

    return jax.tree.map(one, params)


# ----------------------------------------------- dense sync modes / wiring
#
# The mode-string registry is the single vocabulary shared by TrainCtx's
# ``dense_sync=`` knob, bench.py's records, WIRE_BENCH rows, and the README
# mode table. "implicit-psum" / "local" are accounting-only labels for the
# default XLA path and single-device tiers.


DENSE_SYNC_MODES = (
    "f32",
    "bf16",
    "bytegrad",
    "block-int8-ring",
    "f32-sharded",
    "block-int8-ring-sharded",
)


def sync_mode_algorithm(mode: str, block_size: int = 256):
    """Mode string → ``(algorithm, sharded_update)`` for
    :func:`build_sync_train_step`."""
    if mode == "f32":
        return GradientAllReduce(), False
    if mode == "bf16":
        return GradientAllReduce(dtype="bfloat16"), False
    if mode == "bytegrad":
        return ByteGradAllReduce(), False
    if mode == "block-int8-ring":
        return BlockInt8Ring(block_size=block_size), False
    if mode == "f32-sharded":
        return GradientAllReduce(), True
    if mode == "block-int8-ring-sharded":
        return BlockInt8Ring(block_size=block_size), True
    raise ValueError(
        f"unknown dense sync mode {mode!r}; expected one of {DENSE_SYNC_MODES}"
    )


def dense_param_count(params) -> int:
    """Total dense parameter element count (the P in the wire model)."""
    return int(sum(int(np.prod(jnp.shape(l))) for l in jax.tree.leaves(params)))


def dense_sync_wire_bytes(
    mode: str, param_count: int, n: int, block_size: int = 256
) -> int:
    """Modeled per-replica per-step dense collective bytes for ``mode``.

    Ring model: an allreduce of P elements moves ``2·(n-1)/n·P`` element
    transfers per replica (reduce-scatter + all-gather halves). Honest
    footnotes: "bytegrad" psums int8 summands AS INT32 (XLA's psum has no
    sub-word accumulator), so its wire is f32-width despite the int8 math —
    that asymmetry is the motivation for the explicit block-int8 ring, whose
    hops really carry 1 byte/elem + 4/block_size scale overhead. Sharded
    modes replace the gradient all-gather half with an f32 parameter
    all-gather (f32-sharded therefore matches f32; the quantized ring keeps
    its reduce-scatter half at int8 width).
    """
    if n <= 1:
        return 0
    ring = (n - 1) / n
    blk = 1.0 + 4.0 / block_size
    if mode in ("f32", "implicit-psum", "f32-sharded"):
        return int(2 * ring * param_count * 4)
    if mode == "bf16":
        return int(2 * ring * param_count * 2)
    if mode == "bytegrad":
        return int(2 * ring * param_count * 4)
    if mode == "block-int8-ring":
        return int(2 * ring * param_count * blk)
    if mode == "block-int8-ring-sharded":
        return int(ring * param_count * (blk + 4.0))
    if mode == "local":
        return 0
    raise ValueError(f"unknown dense sync mode {mode!r}")


def init_sync_opt_state(
    params,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    algorithm: Algorithm,
    sharded_update: bool = False,
):
    """Build ``state.opt_state`` for :func:`build_sync_train_step`'s
    BlockInt8Ring / sharded-update modes (plain ``optimizer.init(params)``
    otherwise).

    Wrapper layout — chosen so the 2-arg step contract and
    ``flax.serialization`` jobstate snapshots hold with no new plumbing:

    - ``{"opt": ...}``: replicated optimizer tree (non-sharded), or the
      optimizer tree over a ``(chunk,)`` shard carried with a leading
      ``(n, ...)`` axis sharded ``P("data")`` — row i is replica i's owned
      shard (chunk ``i`` for reduce-scatter modes, chunk ``(i+1) % n`` for
      the ring). Scalar leaves (optax's count) stay replicated.
    - ``{"ef": f32[n, Ppad]}`` (BlockInt8Ring only): per-replica
      error-feedback residual, sharded ``P("data")``.
    """
    ring = isinstance(algorithm, BlockInt8Ring)
    if not (ring or sharded_update):
        return optimizer.init(params)
    n = mesh.shape["data"]
    bs = algorithm.block_size if ring else 1
    chunk, p_pad = _flat_chunk(dense_param_count(params), n, bs)
    rep = NamedSharding(mesh, P())
    lead = NamedSharding(mesh, P("data"))
    if sharded_update:
        def place(x):
            x = jnp.asarray(x)
            if x.ndim >= 1:
                return jax.device_put(
                    jnp.broadcast_to(x[None], (n,) + x.shape), lead
                )
            return jax.device_put(x, rep)

        inner = jax.tree.map(
            place, optimizer.init(jnp.zeros((chunk,), jnp.float32))
        )
    else:
        inner = jax.tree.map(
            lambda x: jax.device_put(jnp.asarray(x), rep),
            optimizer.init(params),
        )
    out = {"opt": inner}
    if ring:
        out["ef"] = jax.device_put(jnp.zeros((n, p_pad), jnp.float32), lead)
    return out


def place_sync_state(
    state: TrainState,
    mesh: Mesh,
    algorithm: Algorithm,
    sharded_update: bool = False,
) -> TrainState:
    """Device placement for a (possibly host-resident, e.g. jobstate-restored)
    TrainState whose ``opt_state`` is the :func:`init_sync_opt_state`
    wrapper: params/stats/step replicated, leading-axis wrapper leaves
    sharded over ``data``. The sharded-vs-replicated rule mirrors
    ``build_sync_train_step``'s spec rule (sharded optimizer leaves are the
    1-D shard plus the lead axis → ndim >= 2)."""
    ring = isinstance(algorithm, BlockInt8Ring)
    rep = NamedSharding(mesh, P())
    lead = NamedSharding(mesh, P("data"))
    put_rep = lambda t: jax.tree.map(
        lambda x: jax.device_put(jnp.asarray(x), rep), t
    )
    if not (ring or sharded_update):
        return TrainState(
            params=put_rep(state.params),
            batch_stats=put_rep(state.batch_stats),
            opt_state=put_rep(state.opt_state),
            step=jax.device_put(jnp.asarray(state.step), rep),
            loss_scale=state.loss_scale,
        )

    def put_opt(x):
        x = jnp.asarray(x)
        if sharded_update and x.ndim >= 2:
            return jax.device_put(x, lead)
        return jax.device_put(x, rep)

    wrap = {"opt": jax.tree.map(put_opt, state.opt_state["opt"])}
    if ring:
        wrap["ef"] = jax.device_put(jnp.asarray(state.opt_state["ef"]), lead)
    return TrainState(
        params=put_rep(state.params),
        batch_stats=put_rep(state.batch_stats),
        opt_state=wrap,
        step=jax.device_put(jnp.asarray(state.step), rep),
        loss_scale=state.loss_scale,
    )


def per_replica_opt_state_bytes(opt_state) -> int:
    """MEASURED optimizer-state bytes held by one device: replicated leaves
    count in full, mesh-sharded leaves count one addressable shard. This is
    the 1/n number the sharded-update artifact records."""
    total = 0
    for leaf in jax.tree.leaves(opt_state):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            total += int(shards[0].data.nbytes)
        elif hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total


def _sharded_flat_update(
    params, opt_lead, ef_loc, grads, algorithm, n: int,
    optimizer: optax.GradientTransformation, axis: str = "data",
):
    """ZeRO-style cross-replica weight update (use inside shard_map):
    reduce-scatter grads (f32/bf16 psum_scatter, or the quantized ring's
    reduce-scatter half), update this replica's 1/n parameter shard with its
    1/n optimizer-moment shard, all-gather fresh f32 params. Returns
    ``(new_params, new_opt_lead, new_ef | None)``."""
    ring = isinstance(algorithm, BlockInt8Ring)
    bs = algorithm.block_size if ring else 1
    flat_g, _ = _ravel_f32(grads)
    flat_p, unravel = _ravel_f32(params)
    p_total = flat_p.shape[0]
    chunk, p_pad = _flat_chunk(p_total, n, bs)
    gpad = jnp.pad(flat_g, (0, p_pad - p_total))
    ppad = jnp.pad(flat_p, (0, p_pad - p_total))
    new_ef = None
    if ring:
        v = gpad + ef_loc if algorithm.error_feedback else gpad
        own_sum, err, own_idx = ring_reduce_scatter_block_int8(v, axis, n, bs)
        # the owned chunk is never quantized in sharded mode: it feeds the
        # optimizer in f32 and fresh params all-gather in f32, so the grad
        # all-gather (and its quantization error) disappears entirely
        g_shard = own_sum / n
        new_ef = (
            err.reshape(-1) if algorithm.error_feedback
            else jnp.zeros((p_pad,), jnp.float32)
        )
    else:
        x = gpad
        if algorithm.dtype == "bfloat16":
            x = x.astype(jnp.bfloat16)
        g_shard = jax.lax.psum_scatter(
            x, axis, scatter_dimension=0, tiled=True
        ).astype(jnp.float32) / n
        own_idx = jax.lax.axis_index(axis)
    p_shard = jax.lax.dynamic_slice(ppad, (own_idx * chunk,), (chunk,))
    squeeze = lambda t: t[0] if getattr(t, "ndim", 0) >= 2 else t
    opt_shard = jax.tree.map(squeeze, opt_lead)
    updates, new_opt_shard = optimizer.update(g_shard, opt_shard, p_shard)
    new_p_shard = optax.apply_updates(p_shard, updates)
    rows = jax.lax.all_gather(new_p_shard, axis)  # (n, chunk) f32
    if ring:
        # row j is device j's owned chunk (j+1) % n → restore chunk order
        rows = jnp.roll(rows, 1, axis=0)
    new_params = _unravel_like(unravel, rows.reshape(-1)[:p_total], params)
    relead = lambda t: t[None] if getattr(t, "ndim", 0) >= 1 else t
    return new_params, jax.tree.map(relead, new_opt_shard), new_ef


# ----------------------------------------------------------- state helpers


def replicate_for_local(state: TrainState, mesh: Mesh) -> TrainState:
    """Broadcast a TrainState to per-replica copies with a leading ``dp``
    axis sharded over ``data`` (the carrier for genuinely divergent params in
    Decentralized/LocalSGD). batch_stats/step stay replicated (batch norm in
    a divergent-params run is per-replica too, so it also gets the axis)."""
    dp = mesh.shape["data"]
    lead = NamedSharding(mesh, P("data"))

    def bcast(x):
        arr = jnp.broadcast_to(x[None], (dp,) + jnp.shape(x))
        return jax.device_put(arr, lead)

    return TrainState(
        params=jax.tree.map(bcast, state.params),
        batch_stats=jax.tree.map(bcast, state.batch_stats),
        opt_state=jax.tree.map(bcast, state.opt_state),
        step=jax.device_put(state.step, NamedSharding(mesh, P())),
        loss_scale=state.loss_scale,
    )


def collapse_local(state: TrainState) -> TrainState:
    """Mean the per-replica leading axis away — the deployable model of a
    Decentralized/LocalSGD run (replicas are consensus-close by design).
    Integer leaves (e.g. optax's step count) can't be meaningfully averaged:
    they keep replica 0's value and their dtype."""

    def mean0(x):
        arr = np.asarray(x)
        if np.issubdtype(arr.dtype, np.integer) or arr.dtype == np.bool_:
            return arr[0]
        return arr.astype(np.float32).mean(axis=0).astype(arr.dtype)

    return TrainState(
        params=jax.tree.map(mean0, state.params),
        batch_stats=jax.tree.map(mean0, state.batch_stats),
        opt_state=jax.tree.map(mean0, state.opt_state),
        step=state.step,
        loss_scale=state.loss_scale,
    )


# ------------------------------------------------------------ step builder


def build_sync_train_step(
    model,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    algorithm: Algorithm,
    loss_fn: Callable = default_loss_fn,
    sharded_update: bool = False,
):
    """Jitted DP ``step(state, batch[, residual]) -> (state, (header,
    gpacked)[, residual])`` with an explicit gradient/model sync algorithm.

    Mirrors ``build_train_step``'s contract (header = [loss | preds], gpacked
    = flat embedding grads in wire dtype; use the same unpack helpers) but
    runs the whole step under shard_map over ``data`` so the dense-grad
    collective is OURS, not XLA's:

    - GradientAllReduce / ByteGradAllReduce: ``state`` is replicated (P());
      ByteGrad threads an extra ``residual`` pytree through the call.
    - BlockInt8Ring: ``state.opt_state`` is the :func:`init_sync_opt_state`
      wrapper (``{"opt", "ef"}``); the quantized ring keeps the 2-arg step
      contract because the residual rides the state.
    - Decentralized / LocalSGD: ``state`` carries a leading per-replica axis
      (from :func:`replicate_for_local`); loss in the header is the
      cross-replica mean.

    ``sharded_update=True`` (GradientAllReduce or BlockInt8Ring only) shards
    the dense optimizer state and weight update over ``data`` (ZeRO-style;
    see module docstring). Build the state's ``opt_state`` with
    :func:`init_sync_opt_state` and place restored states with
    :func:`place_sync_state`. Requires an elementwise optimizer.

    Embedding grads: pooled cotangents stay batch-sharded (out P("data")),
    raw distinct-row cotangents are exact-psum'd (out P()) — identical
    numbers to the default implicit-psum path.
    """
    n = mesh.shape["data"]
    local_params = isinstance(
        algorithm, (Decentralized, LocalSGD, LowPrecisionDecentralized)
    )
    bytegrad = isinstance(algorithm, ByteGradAllReduce)
    qadam = isinstance(algorithm, QAdam)
    lp_dec = isinstance(algorithm, LowPrecisionDecentralized)
    ring = isinstance(algorithm, BlockInt8Ring)
    if sharded_update and not isinstance(
        algorithm, (GradientAllReduce, BlockInt8Ring)
    ):
        raise ValueError(
            "sharded_update composes with GradientAllReduce or BlockInt8Ring "
            f"only (got {type(algorithm).__name__}): the other algorithms "
            "own their update or hold divergent per-replica params"
        )
    wrapped = ring or sharded_update  # opt_state is the {"opt"[, "ef"]} dict
    has_algo_state = bytegrad or qadam or lp_dec

    def core(state: TrainState, batch: Dict, residual):
        # under shard_map leaves arrive as the LOCAL shard; per-replica state
        # carries a leading axis of size 1 here — drop it for the model
        if local_params:
            squeeze = lambda t: jax.tree.map(lambda x: x[0], t)
            params = squeeze(state.params)
            batch_stats = squeeze(state.batch_stats)
            opt_state = squeeze(state.opt_state)
        else:
            params, batch_stats, opt_state = (
                state.params, state.batch_stats, state.opt_state,
            )
        ef_loc = None
        if wrapped:
            # init_sync_opt_state wrapper: inner optimizer tree + (ring only)
            # the per-replica EF residual, arriving as the (1, Ppad) local
            # shard of the P("data") lead axis
            if ring:
                ef_loc = opt_state["ef"][0]
            opt_state = opt_state["opt"]
        # per-replica algo-state leaves arrive with a leading axis of 1
        if lp_dec:
            shadows = jax.tree.map(lambda x: x[0], residual)
        elif qadam:
            q_m, q_v = residual["m"], residual["v"]
            q_res = jax.tree.map(lambda x: x[0], residual["residual"])
        emb_diff, emb_static = _split_emb(batch["emb"])

        def loss_wrapper(params, emb_diff):
            model_emb = _embedding_model_inputs(emb_diff, emb_static)
            variables = {"params": params}
            if batch_stats:
                variables["batch_stats"] = batch_stats
                logits, updates = model.apply(
                    variables, batch["dense"], model_emb, train=True,
                    mutable=["batch_stats"],
                )
                new_stats = updates["batch_stats"]
            else:
                logits = model.apply(variables, batch["dense"], model_emb, train=True)
                new_stats = batch_stats
            loss = loss_fn(logits, batch["labels"][0])
            return loss, (logits, new_stats)

        (loss, (logits, new_stats)), (param_grads, emb_grads) = jax.value_and_grad(
            loss_wrapper, argnums=(0, 1), has_aux=True
        )(params, emb_diff)

        new_residual = residual
        new_ef = None
        if ring and not sharded_update:
            flat_g, unravel_g = _ravel_f32(param_grads)
            flat_sum, new_ef = _block_ring_allreduce_flat(
                flat_g, ef_loc, algorithm, n
            )
            param_grads = _unravel_like(
                unravel_g, flat_sum[: flat_g.shape[0]] / n, param_grads
            )
        elif isinstance(algorithm, GradientAllReduce) and not sharded_update:
            param_grads = allreduce_mean(param_grads, "data", algorithm.dtype)
        elif bytegrad:
            if algorithm.error_feedback:
                param_grads, new_residual = bytegrad_allreduce(
                    param_grads, residual, "data"
                )
            else:
                param_grads, _ = bytegrad_allreduce(
                    param_grads, init_residual(param_grads), "data"
                )
        # Decentralized/LocalSGD/LowPrecisionDecentralized: LOCAL grads
        # drive the update as-is

        step_no = state.step + 1
        if qadam:
            # the algorithm IS the optimizer (Bagua swaps in QAdamOptimizer,
            # persia/distributed.py:238-244): warmup = exact-allreduce Adam;
            # after warmup v freezes and only int8 momentum crosses the wire
            b1, b2 = algorithm.beta1, algorithm.beta2
            in_warmup = step_no <= algorithm.warmup_steps

            def warm(args):
                m, v, r = args
                g = allreduce_mean(param_grads, "data")
                m2 = jax.tree.map(lambda mm, gg: b1 * mm + (1 - b1) * gg, m, g)
                v2 = jax.tree.map(
                    lambda vv, gg: b2 * vv + (1 - b2) * gg * gg, v, g
                )
                return m2, v2, r

            def post(args):
                m, v, r = args
                m_loc = jax.tree.map(
                    lambda mm, gg: b1 * mm + (1 - b1) * gg, m, param_grads
                )
                m2, r2 = bytegrad_allreduce(m_loc, r, "data")
                return m2, v, r2

            m2, v2, r2 = jax.lax.cond(in_warmup, warm, post, (q_m, q_v, q_res))
            t = step_no.astype(jnp.float32)
            bc1 = 1.0 - jnp.power(b1, t)
            # v froze at warmup end → its bias correction freezes with it
            bc2 = 1.0 - jnp.power(
                b2, jnp.minimum(t, float(algorithm.warmup_steps))
            )
            new_params = jax.tree.map(
                lambda p, mm, vv: p
                - algorithm.lr * (mm / bc1) / (jnp.sqrt(vv / bc2) + algorithm.eps),
                params, m2, v2,
            )
            new_opt_state = opt_state
            new_residual = {
                "m": m2,
                "v": v2,
                "residual": jax.tree.map(lambda x: x[None], r2),
            }
        elif sharded_update:
            new_params, new_opt_state, ef_out = _sharded_flat_update(
                params, opt_state, ef_loc, param_grads, algorithm, n, optimizer
            )
            if ring:
                new_ef = ef_out
        else:
            updates, new_opt_state = optimizer.update(
                param_grads, opt_state, params
            )
            new_params = optax.apply_updates(params, updates)

        # collectives are gated by lax.cond on the (replicated) step counter,
        # NOT computed-then-jnp.where-discarded: the whole point of these
        # algorithms is paying the parameter-sized message only on sync
        # steps, and every replica agrees on the predicate so conditional
        # collectives are SPMD-safe
        if isinstance(algorithm, Decentralized):
            sync_now = (step_no % algorithm.period) == 0
            # direction alternates per SYNC (not per raw step): with an even
            # period a raw-step parity would pick the same neighbor forever
            sync_idx = step_no // algorithm.period
            new_params = jax.lax.cond(
                sync_now,
                lambda p: ring_neighbor_average(p, sync_idx, "data", n),
                lambda p: p,
                new_params,
            )
        elif isinstance(algorithm, LocalSGD):
            sync_now = (step_no % algorithm.period) == 0
            new_params = jax.lax.cond(
                sync_now,
                lambda p: jax.tree.map(
                    lambda x: jax.lax.pmean(x, "data"), p
                ),
                lambda p: p,
                new_params,
            )
        elif lp_dec:
            sync_now = (step_no % algorithm.period) == 0
            new_params, new_shadows = jax.lax.cond(
                sync_now,
                lambda a: lp_ring_sync(a[0], a[1], "data", n),
                lambda a: a,
                (new_params, shadows),
            )
            new_residual = jax.tree.map(lambda x: x[None], new_shadows)

        if local_params:
            lead = lambda t: jax.tree.map(lambda x: x[None], t)
            new_params = lead(new_params)
            new_stats = lead(new_stats)
            new_opt_state = lead(new_opt_state)
            loss = jax.lax.pmean(loss, "data")
        if wrapped:
            rewrap = {"opt": new_opt_state}
            if ring:
                rewrap["ef"] = new_ef[None]
            new_opt_state = rewrap

        new_state = TrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            step=step_no,
            loss_scale=state.loss_scale,
        )
        # emb grads ship in the GLOBAL-mean-loss convention the sparse tier
        # expects (the implicit-psum path's numbers): the local loss is a
        # mean over B/n samples, so pooled cotangents scale by 1/n; raw
        # distinct-row cotangents (gathered identically on every replica
        # from replicated inputs) psum-then-scale — together exactly the
        # gradient of the global-batch mean, for every algorithm including
        # the locally-updating ones
        synced_emb = tuple(
            (g / n) if static is None else (jax.lax.psum(g, "data") / n)
            for g, static in zip(emb_grads, emb_static)
        )
        preds = jax.nn.sigmoid(logits)
        loss_out = jnp.reshape(
            jax.lax.pmean(loss, "data"), (1,)
        ).astype(jnp.float32)
        preds_out = jnp.reshape(preds, (-1,)).astype(jnp.float32)
        return new_state, (loss_out, preds_out, synced_emb), new_residual

    # ---- shard_map specs

    def state_specs_of(state: TrainState):
        if wrapped:
            # init_sync_opt_state wrapper: sharded optimizer leaves are the
            # 1-D shard + lead axis (ndim >= 2) → P("data"); scalars (optax
            # count) and the non-sharded inner tree stay replicated; the EF
            # residual is per-replica
            def opt_spec(x):
                if sharded_update and getattr(x, "ndim", 0) >= 2:
                    return P("data")
                return P()

            wrap_spec = {"opt": jax.tree.map(opt_spec, state.opt_state["opt"])}
            if ring:
                wrap_spec["ef"] = P("data")
            return TrainState(
                params=jax.tree.map(lambda _: P(), state.params),
                batch_stats=jax.tree.map(lambda _: P(), state.batch_stats),
                opt_state=wrap_spec,
                step=P(),
                loss_scale=None,
            )
        if not local_params:
            return jax.tree.map(lambda _: P(), state)
        lead = lambda t: jax.tree.map(lambda _: P("data"), t)
        return TrainState(
            params=lead(state.params),
            batch_stats=lead(state.batch_stats),
            opt_state=lead(state.opt_state),
            step=P(),
            loss_scale=None,
        )

    def batch_specs(batch):
        emb_specs = []
        for e in batch["emb"]:
            if "pooled" in e:
                emb_specs.append({"pooled": P("data")})
            else:
                emb_specs.append(
                    {"distinct": P(), "index": P("data"), "mask": P("data")}
                )
        return {
            "dense": [P("data")] * len(batch["dense"]),
            "labels": [P("data")] * len(batch["labels"]),
            "emb": emb_specs,
        }

    # One compiled executable per batch STRUCTURE (slot kinds + leaf counts;
    # shapes are handled by jit's own cache). Building shard_map + a fresh
    # jit wrapper per call would retrace every step.
    compiled: Dict[Any, Any] = {}

    def _build(state: TrainState, batch: Dict, res_example):
        state_specs = state_specs_of(state)
        if bytegrad:
            res_spec = jax.tree.map(lambda _: P(), res_example)
        elif qadam:
            res_spec = {
                "m": jax.tree.map(lambda _: P(), res_example["m"]),
                "v": jax.tree.map(lambda _: P(), res_example["v"]),
                "residual": jax.tree.map(
                    lambda _: P("data"), res_example["residual"]
                ),
            }
        elif lp_dec:
            res_spec = jax.tree.map(lambda _: P("data"), res_example)
        else:
            res_spec = P()
        # per-slot emb-grad out specs: pooled cotangents reassemble over the
        # batch axis, raw distinct-row cotangents are psum'd → replicated
        emb_out_specs = tuple(
            P("data") if "pooled" in e else P() for e in batch["emb"]
        )
        mapped = shard_map(
            core,
            mesh=mesh,
            in_specs=(state_specs, batch_specs(batch), res_spec),
            out_specs=(
                state_specs,
                (P(), P("data"), emb_out_specs),
                res_spec,
            ),
            check_vma=False,
        )

        @jax.jit
        def full(state, batch, residual):
            new_state, (loss, preds, emb_g), new_res = mapped(
                state, batch, residual
            )
            header = jnp.concatenate([loss, preds])
            gflat = [jnp.reshape(g, (-1,)) for g in emb_g]
            gpacked = (
                jnp.concatenate(gflat) if gflat else jnp.zeros((0,), jnp.float32)
            )
            return new_state, (header, gpacked), new_res

        return full

    def step(state: TrainState, batch: Dict, residual=None):
        res_in = residual if has_algo_state else 0
        key = (
            len(batch["dense"]),
            len(batch["labels"]),
            tuple("pooled" in e for e in batch["emb"]),
        )
        full = compiled.get(key)
        if full is None:
            full = compiled[key] = _build(state, batch, res_in)
        new_state, (header, gpacked), new_res = full(state, batch, res_in)
        if has_algo_state:
            return new_state, (header, gpacked), new_res
        return new_state, (header, gpacked)

    return step

"""Batch validator + quarantine directory.

The validator sits at the loader boundary (``DataLoader(validator=...)``
or ``validator.wrap(batches)``) and applies cheap, vectorized checks to
every :class:`~persia_tpu.data.PersiaBatch` before it can reach the
train plane:

- ``schema``       — labels present when ``requires_grad``, consistent
                     batch sizes across id/dense/label parts.
- ``nonfinite``    — NaN/Inf anywhere in a float dense feature or label.
- ``label_range``  — labels outside ``[label_min, label_max]``.
- ``sign_domain``  — raw ids touching the per-group salt prefix
                     (``id >= 2**(64 - prefix_bit)``), which would alias
                     across embedding groups after salting.

A rejected batch is never trained on: it is persisted to the quarantine
directory (full ``PersiaBatch.to_bytes()`` wire plus a JSON sidecar with
rule / reason / trace_id / step ordinal) so the poisoned payload can be
reloaded for postmortem, then counted and dropped.
"""
from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Iterable, Iterator, List, Optional, Tuple

import numpy as np

from persia_tpu.data import PersiaBatch
from persia_tpu.metrics import get_metrics
from persia_tpu.tracing import current_trace_id, record_event


@dataclass(frozen=True)
class ValidatorConfig:
    label_min: float = 0.0
    label_max: float = 1.0
    # Bits reserved at the top of the u64 sign space for group salting;
    # 0 disables the sign-domain rule.
    sign_prefix_bit: int = 0
    check_nonfinite: bool = True
    check_label_range: bool = True


class Quarantine:
    """Append-only quarantine directory with postmortem round-trip.

    Each rejected batch lands as ``<name>.batch`` (the exact
    ``PersiaBatch.to_bytes()`` wire) next to ``<name>.json``
    (rule, reason, step, trace_id, batch_id).
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._lock = threading.Lock()
        self._seq = 0

    def put(
        self,
        batch: PersiaBatch,
        rule: str,
        reason: str,
        step: Optional[int] = None,
    ) -> str:
        with self._lock:
            ordinal = self._seq
            self._seq += 1
        name = f"q{ordinal:06d}"
        sidecar = {
            "rule": rule,
            "reason": reason,
            "step": step,
            "trace_id": current_trace_id(),
            "batch_id": batch.batch_id,
        }
        blob = batch.to_bytes()
        tmp = os.path.join(self.path, f".{name}.tmp")
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, os.path.join(self.path, f"{name}.batch"))
        with open(os.path.join(self.path, f"{name}.json"), "w") as f:
            json.dump(sidecar, f, sort_keys=True)
        return name

    def names(self) -> List[str]:
        out = []
        for fn in os.listdir(self.path):
            if fn.endswith(".batch") and not fn.startswith("."):
                out.append(fn[: -len(".batch")])
        return sorted(out)

    def load(self, name: str) -> Tuple[PersiaBatch, dict]:
        with open(os.path.join(self.path, f"{name}.batch"), "rb") as f:
            batch = PersiaBatch.from_bytes(f.read())
        with open(os.path.join(self.path, f"{name}.json")) as f:
            sidecar = json.load(f)
        return batch, sidecar

    def __len__(self) -> int:
        return len(self.names())


class BatchValidator:
    """Schema / finiteness / label / sign-domain checks for PersiaBatch."""

    def __init__(
        self,
        config: Optional[ValidatorConfig] = None,
        quarantine: Optional[Quarantine] = None,
    ):
        self.config = config or ValidatorConfig()
        self.quarantine = quarantine
        m = get_metrics()
        self._m_checked = m.counter(
            "persia_tpu_health_batches_validated",
            "batches inspected by the health validator",
        )
        self._m_rejected = m.counter(
            "persia_tpu_health_batches_rejected",
            "batches rejected and quarantined by the health validator",
        )
        self.rejected_by_rule: dict = {}

    # -- rules ---------------------------------------------------------
    def check(self, batch: PersiaBatch) -> Optional[Tuple[str, str]]:
        """Return (rule, reason) for the first violated rule, else None."""
        cfg = self.config
        bs = batch.batch_size
        if batch.requires_grad and not batch.labels:
            return "schema", "requires_grad batch has no labels"
        for lab in batch.labels:
            if lab.batch_size != bs:
                return "schema", (
                    f"label {lab.name!r} rows {lab.batch_size} != batch {bs}"
                )
        for dense in batch.non_id_type_features:
            if dense.batch_size != bs:
                return "schema", (
                    f"dense {dense.name!r} rows {dense.batch_size} != batch {bs}"
                )
        if cfg.check_nonfinite:
            for dense in batch.non_id_type_features:
                if np.issubdtype(dense.data.dtype, np.floating) and not bool(
                    np.isfinite(dense.data).all()
                ):
                    return "nonfinite", f"non-finite value in dense {dense.name!r}"
            for lab in batch.labels:
                if np.issubdtype(lab.data.dtype, np.floating) and not bool(
                    np.isfinite(lab.data).all()
                ):
                    return "nonfinite", f"non-finite value in label {lab.name!r}"
        if cfg.check_label_range:
            for lab in batch.labels:
                if lab.data.size == 0:
                    continue
                lo = float(np.min(lab.data))
                hi = float(np.max(lab.data))
                if lo < cfg.label_min or hi > cfg.label_max:
                    return "label_range", (
                        f"label {lab.name!r} range [{lo:g}, {hi:g}] outside "
                        f"[{cfg.label_min:g}, {cfg.label_max:g}]"
                    )
        if cfg.sign_prefix_bit > 0:
            bound = np.uint64(1) << np.uint64(64 - cfg.sign_prefix_bit)
            for feat in batch.id_type_features:
                flat, _ = feat.flat_counts()
                if flat.size and bool(np.any(flat >= bound)):
                    return "sign_domain", (
                        f"id feature {feat.name!r} has signs touching the "
                        f"{cfg.sign_prefix_bit}-bit salt prefix"
                    )
        return None

    # -- admission -----------------------------------------------------
    def admit(self, batch: PersiaBatch, step: Optional[int] = None) -> bool:
        """Check one batch; quarantine + count on rejection."""
        self._m_checked.inc()
        verdict = self.check(batch)
        if verdict is None:
            return True
        rule, reason = verdict
        self.rejected_by_rule[rule] = self.rejected_by_rule.get(rule, 0) + 1
        self._m_rejected.inc(rule=rule)
        name = None
        if self.quarantine is not None:
            name = self.quarantine.put(batch, rule, reason, step=step)
        record_event(
            "health.anomaly",
            cause="batch_rejected",
            rule=rule,
            reason=reason,
            step=step,
            quarantined=name,
        )
        return False

    def wrap(self, batches: Iterable[PersiaBatch]) -> Iterator[PersiaBatch]:
        """Yield only admitted batches (rejected ones are quarantined)."""
        for i, batch in enumerate(batches):
            if self.admit(batch, step=i):
                yield batch

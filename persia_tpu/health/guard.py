"""Fence-point auto-rollback driver for the cached train stream.

``ctx.resume()`` rewinds PS shards and dense state but a live ctx's cache
directory / pools are NOT rewound — the proven bit-identical recovery
path (tests/test_jobstate.py) is a FRESH ctx + ``resume()``. The guard
therefore owns the ctx lifecycle: the caller hands it a ``ctx_factory``
and a ``batches_fn(start_step)`` that can re-open the stream at any
global step, and the guard loops

    fresh ctx -> resume(LAST_GOOD fence) -> train_stream(minus skips)

until the stream finishes, adding each :class:`SentinelRollback` step to
the quarantined skip set before replaying. ``SentinelAbort`` (anomaly
fraction / rollback budget) propagates to the caller.
"""
from __future__ import annotations

from typing import Callable, Iterable, Optional, Set

from persia_tpu.health.sentinel import (
    SentinelConfig,
    SentinelRollback,
    StreamSentinel,
)
from persia_tpu.tracing import record_event


def run_guarded_stream(
    ctx_factory: Callable[[], object],
    batches_fn: Callable[[int], Iterable],
    job_state,
    sentinel,
    snapshot_every: int,
    skip_steps: Optional[Iterable[int]] = None,
    **stream_kwargs,
):
    """Run ``train_stream`` under sentinel guard with fence auto-rollback.

    ``sentinel`` is a :class:`StreamSentinel`, or a :class:`SentinelConfig`
    to have the guard build one from the first ctx's ``sentinel_spec()``
    (the probe-tail shape is a property of the ctx, not the caller).

    Returns ``(metrics, ctx, skipped)`` — the final stream metrics, the
    ctx that finished the stream (for state inspection / further use),
    and the full set of quarantined global steps.
    """
    from persia_tpu import jobstate

    skipped: Set[int] = set(skip_steps or ())
    while True:
        ctx = ctx_factory()
        if isinstance(sentinel, SentinelConfig):
            sentinel = StreamSentinel.from_ctx(ctx, sentinel)
        manifest = ctx.resume(job_state)
        start = manifest.step if manifest is not None else 0
        try:
            metrics = ctx.train_stream(
                batches_fn(start),
                start_step=start,
                snapshot_every=snapshot_every,
                job_state=job_state,
                sentinel=sentinel,
                skip_steps=skipped,
                **stream_kwargs,
            )
        except SentinelRollback as rb:
            skipped.add(rb.step)
            mgr = jobstate.coerce_manager(job_state)
            last_good = mgr.latest()
            fence = last_good.step if last_good is not None else 0
            record_event(
                "health.rollback",
                anomaly_step=rb.step,
                fence_step=fence,
                cause=rb.kind,
                metric=rb.metric,
                z=rb.z,
            )
            # Raises SentinelAbort once the rollback budget is spent.
            sentinel.note_rollback(rb.step, fence)
            continue
        return metrics, ctx, skipped

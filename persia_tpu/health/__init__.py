"""Data-plane integrity layer: validator -> sentinel -> scrubber.

persia_tpu.health spans the whole train-to-serve loop:

- :mod:`~persia_tpu.health.validator` rejects malformed batches at the
  loader boundary and persists them to a quarantine directory for
  postmortem (schema, finiteness, label-range, sign-domain rules).
- :mod:`~persia_tpu.health.sentinel` watches the per-step probe tail the
  cached train step emits (finite flag, dense/per-group/ps grad norms)
  and drives the escalation ladder: on-device skip-batch -> clip ->
  auto-rollback to the LAST_GOOD jobstate fence -> abort at
  ``max_anomaly_frac``.
- :mod:`~persia_tpu.health.scrub` repairs non-finite PS rows to the
  deterministic seeded init at snapshot fences, journaled exactly-once.
- :func:`~persia_tpu.health.guard.run_guarded_stream` is the rollback
  driver: it owns the ctx lifecycle so a sentinel trip can rebuild a
  fresh ctx, ``resume()`` from the last fence, and replay the stream
  minus the quarantined steps.

Arming: pass explicit flags, or set ``PERSIA_HEALTH=1`` to arm the
on-device probe + fence scrub by default (off by default — the disabled
path costs one ``is None`` check on the stream hot path).
"""
from __future__ import annotations

import os


def health_enabled() -> bool:
    """True when PERSIA_HEALTH=1 arms the data-plane health layer."""
    return os.environ.get("PERSIA_HEALTH", "0") in ("1", "true")


from persia_tpu.health.validator import (  # noqa: E402
    BatchValidator,
    Quarantine,
    ValidatorConfig,
)
from persia_tpu.health.sentinel import (  # noqa: E402
    SentinelAbort,
    SentinelConfig,
    SentinelRollback,
    StreamSentinel,
    sentinel_drain,
    sentinel_note,
)
from persia_tpu.health.scrub import (  # noqa: E402
    SCRUB_CRC,
    scrub_journal_id,
    scrub_router,
    scrub_store,
)
from persia_tpu.health.guard import run_guarded_stream  # noqa: E402

__all__ = [
    "BatchValidator",
    "Quarantine",
    "ValidatorConfig",
    "SentinelAbort",
    "SentinelConfig",
    "SentinelRollback",
    "StreamSentinel",
    "sentinel_drain",
    "sentinel_note",
    "SCRUB_CRC",
    "scrub_journal_id",
    "scrub_router",
    "scrub_store",
    "run_guarded_stream",
    "health_enabled",
]

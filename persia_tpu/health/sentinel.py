"""On-device numerical sentinel for the cached train stream.

The cached train step (``build_cached_train_step(sentinel_probe=True)``)
appends a fixed-length probe tail to the step header it already emits:

    [dense_gnorm, group_gnorm..., ps_gnorm, finite_flag, clipped_flag]

Everything in the tail is computed on device inside the jitted step —
when the sentinel is disabled the stream hot path pays exactly one
``is None`` check (pinned by ``tests/test_health.py``); when armed, the
host reads headers one dispatch behind the newest in-flight step, so
detection lands within one dispatch window without stalling dispatch.

Escalation ladder:

1. **skip-batch** — non-finite grads zero the update on device (the
   step's ``finite`` gate); the sentinel only counts the skip.
2. **clip** — ``guard_clip_norm`` rescales the update on device; the
   sentinel counts the clip.
3. **rollback** — a grad global-norm z-score blowout vs the decayed EMA
   raises :class:`SentinelRollback`; ``run_guarded_stream`` parks the
   feeder, rebuilds a fresh ctx, resumes from the LAST_GOOD jobstate
   fence and replays the stream minus the quarantined step.
4. **abort** — anomaly fraction above ``max_anomaly_frac`` (or rollback
   budget exhausted) raises :class:`SentinelAbort`.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from persia_tpu.metrics import get_metrics
from persia_tpu.tracing import record_event


class SentinelRollback(RuntimeError):
    """Raised when the sentinel demands a rollback to the last fence."""

    def __init__(self, step: int, kind: str = "grad_z", metric: float = 0.0, z: float = 0.0):
        super().__init__(
            f"sentinel anomaly at step {step}: {kind} metric={metric:g} z={z:g}"
        )
        self.step = step
        self.kind = kind
        self.metric = metric
        self.z = z


class SentinelAbort(RuntimeError):
    """Raised when the anomaly fraction exceeds ``max_anomaly_frac``."""


@dataclass(frozen=True)
class SentinelConfig:
    z_threshold: float = 6.0
    warmup_steps: int = 8
    decay: float = 0.9
    # Relative floor added to the EMA stddev so near-constant norm
    # streams do not turn numeric jitter into huge z-scores.
    rel_floor: float = 0.05
    max_anomaly_frac: float = 0.5
    # Anomaly-fraction abort only applies once this many steps observed.
    min_anomaly_steps: int = 8
    max_rollbacks: int = 4


class StreamSentinel:
    """Decayed-EMA z-score watchdog over the on-device probe tail."""

    def __init__(
        self,
        config: Optional[SentinelConfig] = None,
        n_groups: int = 0,
        dynamic_loss_scale: bool = False,
    ):
        self.config = config or SentinelConfig()
        self.n_groups = int(n_groups)
        self.dynamic_loss_scale = bool(dynamic_loss_scale)
        self._mean = 0.0
        self._var = 0.0
        self._warm = 0
        self._max_seen = -1
        self.stats = {
            "observed": 0,
            "replayed": 0,
            "nonfinite_skips": 0,
            "clips": 0,
            "z_anomalies": 0,
            "anomalies": 0,
            "rollbacks": 0,
        }
        m = get_metrics()
        self._m_anomaly = m.counter(
            "persia_tpu_health_anomalies",
            "sentinel anomalies by kind",
        )
        self._m_rollback = m.counter(
            "persia_tpu_health_rollbacks",
            "sentinel-driven fence rollbacks",
        )
        self._m_observed = m.counter(
            "persia_tpu_health_steps_observed",
            "train steps observed by the sentinel",
        )

    @classmethod
    def from_ctx(cls, ctx, config: Optional[SentinelConfig] = None) -> "StreamSentinel":
        spec = ctx.sentinel_spec()
        return cls(
            config,
            n_groups=spec["n_groups"],
            dynamic_loss_scale=spec["dynamic_loss_scale"],
        )

    # -- internals -----------------------------------------------------
    def _anomaly(self, kind: str, step: int, **attrs) -> None:
        self.stats["anomalies"] += 1
        self._m_anomaly.inc(kind=kind)
        record_event("health.anomaly", cause=kind, step=step, **attrs)
        cfg = self.config
        obs = self.stats["observed"]
        if obs >= cfg.min_anomaly_steps:
            frac = self.stats["anomalies"] / max(obs, 1)
            if frac > cfg.max_anomaly_frac:
                raise SentinelAbort(
                    f"anomaly fraction {frac:.3f} > max_anomaly_frac "
                    f"{cfg.max_anomaly_frac:.3f} after {obs} steps"
                )

    def note_rollback(self, anomaly_step: int, fence_step: int) -> None:
        self.stats["rollbacks"] += 1
        self._m_rollback.inc()
        if self.stats["rollbacks"] > self.config.max_rollbacks:
            raise SentinelAbort(
                f"rollback budget exhausted ({self.config.max_rollbacks}); "
                f"last anomaly at step {anomaly_step} (fence {fence_step})"
            )

    # -- observation ---------------------------------------------------
    def observe(self, gstep: int, header: np.ndarray, n_labels: int) -> None:
        """Digest one completed step header; raise on escalation.

        Steps at or below the replay high-water mark are counted but not
        re-folded into the EMA, so a post-rollback replay cannot double
        count or re-trip on history it already digested.
        """
        if gstep <= self._max_seen:
            self.stats["replayed"] += 1
            return
        self._max_seen = gstep
        self.stats["observed"] += 1
        self._m_observed.inc()
        from persia_tpu.parallel.train_step import unpack_step_probe

        probe = unpack_step_probe(
            header, n_labels, self.n_groups, dynamic=self.dynamic_loss_scale
        )
        if probe["finite"] < 0.5:
            # Rung 1: update already zeroed on device — state is clean.
            self.stats["nonfinite_skips"] += 1
            self._anomaly("nonfinite_grad", gstep, device_skipped=True)
            return
        if probe["clipped"] >= 0.5:
            # Rung 2: update rescaled on device — contained, but counted.
            self.stats["clips"] += 1
            self._anomaly(
                "grad_clipped", gstep, grad_norm=float(probe["total_gnorm"])
            )
        x = float(probe["total_gnorm"])
        if not math.isfinite(x):
            self.stats["nonfinite_skips"] += 1
            self._anomaly("nonfinite_probe", gstep, device_skipped=False)
            return
        cfg = self.config
        if self._warm >= cfg.warmup_steps:
            sd = math.sqrt(max(self._var, 0.0)) + cfg.rel_floor * abs(self._mean) + 1e-12
            z = (x - self._mean) / sd
            if z > cfg.z_threshold:
                # Rung 3: the update already landed — demand a rollback.
                self.stats["z_anomalies"] += 1
                self._anomaly("grad_norm_z", gstep, grad_norm=x, z=z)
                raise SentinelRollback(gstep, kind="grad_norm_z", metric=x, z=z)
        d = cfg.decay
        delta = x - self._mean
        self._mean = d * self._mean + (1.0 - d) * x
        self._var = d * self._var + (1.0 - d) * delta * delta
        self._warm += 1


# -- stream hot-path hooks ---------------------------------------------
# The stream calls these unconditionally; the disabled cost is the
# ``sentinel is None`` check (overhead pinned tracer-style in tests).

def sentinel_note(
    sentinel: Optional[StreamSentinel],
    pending: List[Tuple[int, object, int]],
    gstep: int,
    header,
    n_labels: int,
) -> None:
    """Queue a just-dispatched step header; digest all-but-newest.

    Only headers strictly older than the newest in-flight dispatch are
    materialized, so the host never blocks on work it just issued —
    detection trails dispatch by at most one window.
    """
    if sentinel is None:
        return
    pending.append((gstep, header, n_labels))
    while len(pending) > 1:
        g, h, n = pending.pop(0)
        sentinel.observe(g, np.asarray(h), n)


def sentinel_drain(
    sentinel: Optional[StreamSentinel],
    pending: List[Tuple[int, object, int]],
) -> None:
    """Digest every pending header (end-of-stream / fence barrier)."""
    if sentinel is None:
        return
    while pending:
        g, h, n = pending.pop(0)
        sentinel.observe(g, np.asarray(h), n)

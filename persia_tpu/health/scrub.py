"""PS row scrubber: repair non-finite embedding rows at snapshot fences.

``scan_nonfinite`` (numpy store, native store via the ``ps_scan_nonfinite``
export, RPC client, and the ShardedLookup fan-out) walks every live entry
and re-initializes any row whose embedding or optimizer-state floats are
NaN/Inf back to the deterministic seeded init — the SAME contract the
degraded-mode lookups use, so a scrubbed row is indistinguishable from a
freshly admitted one.

Repairs are recorded in the PS apply-journal under a scrub-reserved id
(the top half of the per-replica low byte of :func:`make_journal_id`), so
a retried fence — e.g. a trainer killed between scan and capture — probes
the journal first and becomes a no-op: exactly-once per (epoch, step,
replica).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from persia_tpu.analysis.crashcheck import reach
from persia_tpu.jobstate import make_journal_id, payload_crc
from persia_tpu.metrics import get_metrics
from persia_tpu.tracing import record_event

# Constant crc tag for scrub journal records: a probe hit with a
# DIFFERENT crc under a scrub id means the id space collided with a
# non-scrub record — loud error, never silent skip.
SCRUB_CRC = payload_crc(np.frombuffer(b"health.scrub", dtype=np.uint8))

# Scrub ids claim the 0x80 half of the low byte (like handoff ids) plus
# step bit 30 as the scrub subspace tag: gradient ids keep low byte
# < 0x80, handoff ids have step bit 30 = 0 and bit 31 = 0, replication
# ids have step bit 31 = 1 — so all four id families are pairwise
# disjoint by a fixed bit, and the namespace prover in
# analysis/protocol.py certifies it. Fence/train steps stay < 2^30 by
# the same contract that kept them < 2^31 for replication ids.
_SCRUB_SUBID = 0x80
_SCRUB_STEP_BIT = 1 << 30


def scrub_journal_id(job_epoch: int, step: int, replica_index: int = 0) -> int:
    return (
        make_journal_id(job_epoch, (step & 0x3FFFFFFF) | _SCRUB_STEP_BIT)
        | _SCRUB_SUBID
        | (replica_index & 0x7F)
    )


def scrub_store(store, journal_id: Optional[int] = None, cap: int = 65536) -> dict:
    """Scan one store-like for non-finite rows and repair them.

    With ``journal_id``, the scrub is exactly-once: an already-recorded
    id skips the scan entirely (retry after a crash between scan and
    fence capture), and a successful scan records the id before
    returning.
    """
    if journal_id is not None:
        probe = store.journal_probe(journal_id, SCRUB_CRC)
        if probe == 1:
            return {"repaired": 0, "signs": [], "skipped": True}
        if probe == -1:
            raise RuntimeError(
                f"scrub journal id {journal_id:#x} collides with a "
                "non-scrub record (crc mismatch)"
            )
    repaired, signs = store.scan_nonfinite(cap=cap)
    if journal_id is not None:
        reach("scrub.record")
        store.journal_record(journal_id, SCRUB_CRC)
    return {"repaired": int(repaired), "signs": list(signs), "skipped": False}


def scrub_router(
    router,
    job_epoch: int = 0,
    step: int = 0,
    journaled: bool = True,
    cap: int = 65536,
) -> dict:
    """Scrub every PS replica behind a router (or a bare store).

    Emits one ``health.scrub`` flight-recorder event per replica and
    bumps ``persia_tpu_health_rows_scrubbed``. Returns the aggregate
    ``{"repaired": n, "replicas": [...]}``.
    """
    replicas = getattr(router, "replicas", None)
    if replicas is None:
        replicas = [router]
    m_scrubbed = get_metrics().counter(
        "persia_tpu_health_rows_scrubbed",
        "non-finite PS rows repaired to seeded init by the fence scrubber",
    )
    total = 0
    per_replica = []
    for i, replica in enumerate(replicas):
        jid = scrub_journal_id(job_epoch, step, i) if journaled else None
        res = scrub_store(replica, journal_id=jid, cap=cap)
        if res["repaired"]:
            m_scrubbed.inc(res["repaired"])
        record_event(
            "health.scrub",
            step=step,
            replica=i,
            repaired=res["repaired"],
            skipped=res["skipped"],
        )
        total += res["repaired"]
        per_replica.append(res)
    return {"repaired": total, "replicas": per_replica}

"""Embedding parameter-server process.

Parity target: `rust/persia-embedding-server/src/bin/
persia-embedding-parameter-server.rs` (structopt CLI {port, replica_index,
replica_size, configs}, hyper server with graceful shutdown, Infer mode loads
a checkpoint at boot) and the RPC surface of
`embedding_parameter_service/mod.rs:492-646`: ready_for_serving,
model_manager_status, set_embedding, lookup, update_gradient, configure,
register_optimizer, dump, load, size, clear, shutdown."""

from __future__ import annotations

import argparse
import os
import struct
import threading
from typing import Optional

import numpy as np

from persia_tpu.checkpoint import ModelManagerStatus, dump_store, load_store
from persia_tpu.config import HyperParameters
from persia_tpu.embedding.optim import OptimizerConfig
from persia_tpu.logger import get_default_logger
from persia_tpu.service import proto
from persia_tpu.service.discovery import CoordinatorClient
from persia_tpu.service.rpc import RpcServer

logger = get_default_logger("persia_tpu.ps_server")


class ParameterServerService:
    def __init__(
        self,
        store,
        replica_index: int = 0,
        replica_size: int = 1,
        port: int = 0,
        native_server: Optional[bool] = None,
        status: Optional[ModelManagerStatus] = None,
    ):
        self.store = store
        self.replica_index = replica_index
        self.replica_size = replica_size
        # which store implementation actually backs this replica — the
        # native core carries a ctypes handle, the numpy golden model does
        # not. Recorded in the flight ring and surfaced on healthz /
        # replica_info so a mixed-backend fleet is diagnosable from the
        # outside (the wire bytes are identical either way).
        self.store_backend = "native" if getattr(store, "_h", None) else "numpy"
        from persia_tpu.tracing import record_event

        record_event(
            "ps.store_backend", backend=self.store_backend,
            replica_index=replica_index, replica_size=replica_size,
        )
        # boot loads happen BEFORE this service exists (their status is
        # threaded in) — the native server's accept loop starts at
        # construction, so any load after this point races live probes
        self.status = status or ModelManagerStatus()
        # data plane: the C++ listener serves the hot methods off the GIL
        # when the store is native (ref: the reference's entire remote path
        # is compiled, persia-rpc/src/lib.rs:68-145); Python socketserver
        # remains the portable fallback and the control plane either way
        if native_server is None:
            native_server = os.environ.get("PERSIA_NATIVE_SERVER", "1") != "0"
        self.server = None
        if native_server and getattr(store, "_h", None):
            try:
                from persia_tpu.service.native_rpc import NativeRpcServer

                self.server = NativeRpcServer(store, port=port)
            except Exception as e:  # noqa: BLE001 — fall back to Python
                logger.warning("native rpc server unavailable (%r)", e)
        if self.server is None:
            self.server = RpcServer(port=port)
        s = self.server
        s.register("lookup", self._lookup)
        s.register("lookup_batched", self._lookup_batched)
        s.register("update_batched", self._update_batched)
        s.register("update_journaled", self._update_journaled)
        s.register("journal_probe", self._journal_probe)
        s.register("journal_len", self._journal_len)
        s.register("journal_clear", self._journal_clear)
        s.register("scan_nonfinite", self._scan_nonfinite)
        s.register("checkout_entries", self._checkout)
        s.register("probe_entries", self._probe_entries)
        s.register("update_gradients", self._update)
        s.register("advance_batch_state", self._advance)
        s.register("register_optimizer", self._register_optimizer)
        s.register("configure", self._configure)
        s.register("set_embedding", self._set_embedding)
        s.register("set_embedding_v2", self._set_embedding_v2)
        s.register("get_entry", self._get_entry)
        s.register("size", lambda p: struct.pack("<q", self.store.size()))
        s.register("clear", lambda p: (self.store.clear(), b"ok")[1])
        s.register("num_shards", lambda p: struct.pack("<I", self.store.num_internal_shards))
        s.register("get_optimizer", self._get_optimizer)
        s.register("dump_shard", self._dump_shard)
        s.register("load_shard", self._load_shard)
        # elastic handoff (live resharding, persia_tpu.elastic): range
        # export is read-only; import/delete ride the SAME bounded
        # apply-journal as gradient batches (handoff ids live in the
        # jobstate.handoff_journal_id 0x80 low-byte namespace, so they
        # never collide with per-replica gradient ids)
        s.register("export_range", self._export_range)
        s.register("import_range_journaled", self._import_range_journaled)
        s.register("delete_range_journaled", self._delete_range_journaled)
        s.register("dump_to_dir", self._dump_to_dir)
        s.register("load_from_dir", self._load_from_dir)
        s.register("model_manager_status", lambda p: proto.pack_json(self.status.get()))
        s.register("replica_info", lambda p: proto.pack_json(
            {"replica_index": self.replica_index,
             "replica_size": self.replica_size,
             "store_backend": self.store_backend}
        ))
        s.register("healthz", lambda p: proto.pack_json(
            {"status": "ok", "store_backend": self.store_backend,
             "replica_index": self.replica_index,
             "replica_size": self.replica_size}
        ))
        self.port = s.port

    # handlers -------------------------------------------------------------

    def _lookup(self, payload: bytes) -> bytes:
        signs, dim, train = proto.unpack_lookup_request(payload)
        return self.store.lookup(signs, dim, train).tobytes()

    def _lookup_batched(self, payload: bytes):
        """ONE frame per training batch: all slots' keys in, one flat
        (optionally f16/bf16) row buffer out — the hot lookup wire
        (ref: lookup_batched_all_slots + f16 postprocess,
        embedding_worker_service/mod.rs:874-942,486-629). Falls back to
        per-group store calls when the store lacks the batched surface."""
        signs, key_ofs, dims, train, dtype_code = (
            proto.unpack_lookup_batched_request(payload)
        )
        if hasattr(self.store, "lookup_batched"):
            flat = self.store.lookup_batched(signs, key_ofs, dims, train)
        else:
            parts = [
                self.store.lookup(
                    signs[key_ofs[g]:key_ofs[g + 1]], int(dims[g]), train
                ).reshape(-1)
                for g in range(len(dims))
            ]
            flat = (
                np.concatenate(parts) if parts else np.empty(0, np.float32)
            )
        return proto.pack_lookup_batched_reply(flat, dtype_code)

    def _update_batched(self, payload: bytes) -> bytes:
        signs, key_ofs, dims, grads, opt_groups = (
            proto.unpack_update_batched_request(payload)
        )
        if hasattr(self.store, "update_batched"):
            self.store.update_batched(signs, key_ofs, dims, grads, opt_groups)
        else:
            off = 0
            for g in range(len(dims)):
                d = int(dims[g])
                ks = signs[key_ofs[g]:key_ofs[g + 1]]
                size = len(ks) * d
                self.store.update_gradients(
                    ks, grads[off:off + size].reshape(len(ks), d),
                    int(opt_groups[g]),
                )
                off += size
        return b"ok"

    def _update_journaled(self, payload: bytes) -> bytes:
        """Exactly-once gradient apply through the store's bounded
        apply-journal (persia_tpu.jobstate): ``b"\\x01"`` applied,
        ``b"\\x00"`` duplicate skipped. Retry-safe by construction — a
        dropped reply re-sent lands on the journal record."""
        (jid, crc, signs, key_ofs, dims, grads, opt_groups) = (
            proto.unpack_update_journaled_request(payload)
        )
        if hasattr(self.store, "update_batched_journaled"):
            applied = self.store.update_batched_journaled(
                jid, crc, signs, key_ofs, dims, grads, opt_groups
            )
            return b"\x01" if applied else b"\x00"
        # store without a journal (should not happen for the shipped
        # backends): fall back to a plain apply — at-least-once
        self.store.update_batched(signs, key_ofs, dims, grads, opt_groups)
        return b"\x01"

    def _journal_probe(self, payload: bytes) -> bytes:
        jid, crc = struct.unpack("<QI", payload)
        return struct.pack("<b", self.store.journal_probe(jid, crc))

    def _journal_len(self, payload: bytes) -> bytes:
        return struct.pack("<q", self.store.journal_len())

    def _journal_clear(self, payload: bytes) -> bytes:
        self.store.journal_clear()
        return b"ok"

    def _scan_nonfinite(self, payload: bytes) -> bytes:
        """Health scrub (persia_tpu/health): repair NaN/Inf rows to the
        seeded init. Reply = [repaired i64 | reported signs u64...]."""
        (cap,) = struct.unpack("<q", payload)
        repaired, signs = self.store.scan_nonfinite(cap=cap)
        return struct.pack("<q", repaired) + np.asarray(
            signs, dtype=np.uint64
        ).tobytes()

    def _checkout(self, payload: bytes) -> bytes:
        signs, dim, _ = proto.unpack_lookup_request(payload)
        return self.store.checkout_entries(signs, dim).tobytes()

    def _probe_entries(self, payload: bytes) -> bytes:
        signs, dim, _ = proto.unpack_lookup_request(payload)
        warm, vals = self.store.probe_entries(signs, dim)
        return warm.astype(np.uint8).tobytes() + vals.tobytes()

    def _update(self, payload: bytes) -> bytes:
        signs, grads, group = proto.unpack_update_request(payload)
        self.store.update_gradients(signs, grads, group)
        return b"ok"

    def _advance(self, payload: bytes) -> bytes:
        (group,) = struct.unpack("<i", payload)
        self.store.advance_batch_state(group)
        return b"ok"

    def _get_optimizer(self, payload: bytes) -> bytes:
        """The registered sparse-optimizer config (empty dict when none):
        lets a worker recovering a RESTARTED replica source the config from
        a healthy sibling even when it never registered the optimizer
        itself (multi-worker topologies register through one worker)."""
        opt = getattr(self.store, "optimizer", None)
        return proto.pack_json(opt.to_dict() if opt is not None else {})

    def _register_optimizer(self, payload: bytes) -> bytes:
        self.store.register_optimizer(OptimizerConfig.from_dict(proto.unpack_json(payload)))
        return b"ok"

    def _configure(self, payload: bytes) -> bytes:
        self.store.configure(HyperParameters.from_dict(proto.unpack_json(payload)))
        return b"ok"

    def _set_embedding(self, payload: bytes) -> bytes:
        # legacy v1 (no flags): plain insert, never commits incrementals
        signs, values, dim = proto.unpack_set_embedding(payload)
        self.store.set_embedding(signs, values, dim)
        return b"ok"

    def _set_embedding_v2(self, payload: bytes) -> bytes:
        signs, values, dim, commit_inc = proto.unpack_set_embedding_v2(payload)
        self.store.set_embedding(
            signs, values, dim, commit_incremental=commit_inc
        )
        return b"ok"

    def _get_entry(self, payload: bytes) -> bytes:
        (sign,) = struct.unpack("<Q", payload)
        entry = self.store.get_embedding_entry(sign)
        return b"" if entry is None else entry.astype(np.float32).tobytes()

    def _dump_shard(self, payload: bytes) -> bytes:
        (idx,) = struct.unpack("<I", payload)
        return self.store.dump_shard(idx)

    def _load_shard(self, payload: bytes) -> bytes:
        return struct.pack("<q", self.store.load_shard_bytes(payload))

    # elastic handoff --------------------------------------------------------

    def _export_range(self, payload: bytes) -> bytes:
        """Serialize the hash range [lo, hi) (hi == 0 = 2^64), sorted by
        sign — deterministic bytes, so a resumed handoff's re-export
        carries the same crc and the journal dedups it."""
        lo, hi = struct.unpack("<QQ", payload)
        return self.store.export_range(lo, hi)

    def _import_range_journaled(self, payload: bytes) -> bytes:
        """Exactly-once range import: ``b"\\x01"`` applied, ``b"\\x00"``
        skipped (journal dedup — see ``EmbeddingStore.import_range_journaled``
        for the resume semantics)."""
        jid, crc = struct.unpack_from("<QI", payload)
        applied = self.store.import_range_journaled(jid, crc, payload[12:])
        return b"\x01" if applied else b"\x00"

    def _delete_range_journaled(self, payload: bytes) -> bytes:
        """Exactly-once source-side range release; reply = applied flag +
        removed count."""
        jid, crc, lo, hi = struct.unpack("<QIQQ", payload)
        applied, removed = self.store.delete_range_journaled(jid, crc, lo, hi)
        return struct.pack("<bq", int(applied), removed)

    def _dump_to_dir(self, payload: bytes) -> bytes:
        req = proto.unpack_json(payload)
        kwargs = {"status": self.status, "session": req.get("session")}
        if req.get("blocking", True):
            dump_store(
                self.store, req["path"], self.replica_index, self.replica_size, **kwargs
            )
        else:
            threading.Thread(
                target=dump_store,
                args=(self.store, req["path"], self.replica_index, self.replica_size),
                kwargs=kwargs,
                daemon=True,
            ).start()
        return b"ok"

    def _load_from_dir(self, payload: bytes) -> bytes:
        n = load_store(
            self.store, payload.decode(), self.replica_index, self.replica_size,
            status=self.status,
        )
        return struct.pack("<q", n)

    # lifecycle ------------------------------------------------------------

    def start(self) -> "ParameterServerService":
        self.server.start()
        return self

    def serve_forever(self) -> None:
        self.server.serve_forever()


def main(argv: Optional[list] = None) -> None:
    ap = argparse.ArgumentParser("persia-tpu-embedding-parameter-server")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--replica-index", type=int, default=None)
    ap.add_argument("--replica-size", type=int, default=None)
    ap.add_argument("--coordinator", type=str, default=None, help="host:port")
    ap.add_argument("--advertise-host", type=str,
                    default=os.environ.get("PERSIA_ADVERTISE_HOST", "127.0.0.1"),
                    help="address other hosts use to reach this service")
    ap.add_argument("--capacity", type=int, default=1 << 20)
    ap.add_argument("--num-internal-shards", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--backend", "--store", dest="backend", type=str,
                    default="auto", choices=["auto", "native", "numpy"],
                    help="embedding store implementation; auto resolves to "
                         "native whenever the C++ core builds")
    ap.add_argument("--global-config", type=str, default=None)
    ap.add_argument("--load-checkpoint", type=str, default=None,
                    help="Infer-mode boot checkpoint (ref: ps bin :109-117)")
    ap.add_argument("--load-shards", type=str, default=None,
                    help="boot shard-bytes file (failover restart replay: "
                         "length-prefixed dump_shard blobs, loaded BEFORE "
                         "the server answers its first probe)")
    ap.add_argument("--boot-optimizer", type=str, default=None,
                    help="optimizer-config JSON file registered BEFORE "
                         "serving (a restored shard answering lookups "
                         "without its optimizer re-initializes — destroys — "
                         "every restored entry on width mismatch)")
    args = ap.parse_args(argv)

    from persia_tpu import env
    from persia_tpu.embedding.native_store import create_store

    replica_index = (
        args.replica_index if args.replica_index is not None else env.get_replica_index()
    )
    replica_size = (
        args.replica_size if args.replica_size is not None else env.get_replica_size()
    )

    capacity, shards = args.capacity, args.num_internal_shards
    g = None
    if args.global_config:
        from persia_tpu.config import load_global_config

        g = load_global_config(args.global_config)
        capacity = g.parameter_server.capacity
        shards = g.parameter_server.num_hashmap_internal_shards

    store = create_store(
        args.backend, capacity=capacity, num_internal_shards=shards, seed=args.seed
    )
    inc_mgr = None
    inc_infer = False
    if g is not None and g.parameter_server.enable_incremental_update:
        # train side ships deltas; infer side consumes them
        # (ref: persia-incremental-update-manager/src/lib.rs:178-364)
        from persia_tpu.config import JobType
        from persia_tpu.incremental import attach_incremental

        psc = g.parameter_server
        if g.common.job_type == JobType.INFER:
            inc_infer = True  # loader starts after the boot checkpoint below
        else:
            inc_mgr = attach_incremental(
                store, psc.incremental_dir, replica_index, psc.incremental_buffer_size
            )
    # every boot load runs BEFORE the service binds and serves: a same-port
    # restart answering probes from a not-yet-restored store would make
    # clients mistake trained signs for cold ones and fork their rows
    status = ModelManagerStatus()
    skip_before_us = 0
    if args.boot_optimizer:
        import json as _json

        with open(args.boot_optimizer) as f:
            store.register_optimizer(OptimizerConfig.from_dict(_json.load(f)))
    if args.load_shards:
        with open(args.load_shards, "rb") as f:
            raw = f.read()
        off = 0
        n_loaded = 0
        while off < len(raw):
            (ln,) = struct.unpack_from("<Q", raw, off)
            off += 8
            n_loaded += store.load_shard_bytes(raw[off:off + ln])
            off += ln
        logger.info("boot shard replay: %d entries restored", n_loaded)
    if args.load_checkpoint:
        load_store(store, args.load_checkpoint, replica_index, replica_size,
                   status=status)
        try:
            from persia_tpu.checkpoint import checkpoint_info

            skip_before_us = int(checkpoint_info(args.load_checkpoint).get("time_us", 0))
        except Exception:
            pass  # markerless/legacy checkpoint — apply all retained packets
    svc = ParameterServerService(
        store, replica_index, replica_size, port=args.port, status=status
    )
    svc.start()
    logger.info(
        "parameter server %d/%d on port %d (store backend: %s)",
        replica_index, replica_size, svc.port, svc.store_backend,
    )
    from persia_tpu.diagnostics import maybe_start_from_env

    maybe_start_from_env()  # opt-in deadlock/stall detector (ref: lib.rs:494)
    if inc_infer:
        # started only after the boot checkpoint: applies only packets newer
        # than it, so stale retained deltas can't regress loaded entries
        from persia_tpu.incremental import IncrementalLoader

        IncrementalLoader(
            store, g.parameter_server.incremental_dir, skip_before_us=skip_before_us
        ).start()
    lease = None
    if args.coordinator:
        coord = CoordinatorClient(args.coordinator)
        addr = f"{args.advertise_host}:{svc.port}"
        coord.register("parameter_server", replica_index, addr)
        # heartbeat lease for the failure detector (monotone seq through
        # the coordinator kv; each beat also feeds the in-process stall
        # detector). Default on; PERSIA_LEASE=0 opts out (the chaos
        # suite's heartbeat-only-death injector wants manual control).
        from persia_tpu.service.failure_detector import (
            maybe_start_lease_publisher,
        )

        lease = maybe_start_lease_publisher(
            coord, "parameter_server", replica_index, addr
        )
    # server runs in its background thread; park until the 'shutdown' RPC
    svc.server._thread.join()
    if lease is not None:
        lease.stop()
    if inc_mgr is not None:
        # ship the final flush window before exit (the reference flushes on
        # drop); without this the last seconds of updates never reach serving
        inc_mgr.stop(final_flush=True)


if __name__ == "__main__":
    main()

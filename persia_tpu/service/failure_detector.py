"""Lease-based failure detection for the PS fleet.

The reference assumes an operator notices a dead embedding parameter
server; every recovery *mechanism* here (standby promotion, degraded-mode
lookups, journaled replay) existed without *detection*. This module closes
the sensing half of the self-healing loop:

- **Leases** — every fleet process publishes a monotone-sequence heartbeat
  lease through the coordinator kv (``lease/<role>/<index>``). A lease that
  stops advancing is a *control-plane* signal only: the data plane stays
  authoritative, so a replica whose heartbeat thread died but which still
  answers probes is SUSPECT, never evicted (and the inverse — a ghost
  heartbeat for a dead process — cannot keep it alive).
- **N-consecutive-miss probing** — direct data-plane probes (``healthz``)
  with a single attempt and no retry; ONE dropped probe never changes a
  verdict. Only ``miss_threshold`` consecutive misses produce DEAD.
- **Phi-style gray scoring** — a replica that answers but whose rolling
  median latency sits ≫ the fleet median of its peers for
  ``gray_windows`` consecutive polls is GRAY (limping: flaky NIC, swapping
  host, half-partitioned). Gray replicas are drained, not SIGKILLed.
- **Majority-of-peers witness rule** — a DEAD verdict is withheld when the
  observer cannot reach a majority of the *other* replicas in the same
  poll: the observer is then probably the partitioned party, and evicting
  the whole fleet from one isolated vantage point is the classic
  split-brain failure this rule exists to prevent.

The detector is deliberately passive — it produces verdicts; acting on
them (promotion, drain, resize) is ``persia_tpu/autopilot/heal.py``'s job
under the two-phase journal discipline.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional

from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.service.failure_detector")

LEASE_PREFIX = "lease/"

VERDICT_LIVE = "live"
VERDICT_SUSPECT = "suspect"
VERDICT_DEAD = "dead"
VERDICT_GRAY = "gray"


def lease_key(role: str, index: int) -> str:
    return f"{LEASE_PREFIX}{role}/{index}"


def _metrics():
    from persia_tpu.metrics import get_metrics

    return get_metrics()


def _record_event(kind: str, **attrs) -> None:
    try:
        from persia_tpu.tracing import record_event

        record_event(kind, **attrs)
    except Exception:  # pragma: no cover - tracing plane optional
        pass


class LeasePublisher:
    """Background thread publishing a monotone-seq lease for one process.

    Publish errors are swallowed (a flapping coordinator must not kill the
    PS it is supposed to watch) but always counted — an un-metered publish
    loop failing forever would silently demote this replica to lease-less.
    Each beat also feeds :mod:`persia_tpu.diagnostics` so the in-process
    stall detector sees the publisher itself.
    """

    def __init__(self, coord, role: str, index: int, addr: str,
                 interval_s: float = 0.5):
        self._coord = coord
        self.role = role
        self.index = int(index)
        self.addr = addr
        self.interval_s = float(interval_s)
        self.seq = 0
        self.publish_errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def publish_once(self) -> None:
        self.seq += 1
        payload = json.dumps({
            "seq": self.seq,
            "pid": os.getpid(),
            "addr": self.addr,
            "time_wall": time.time(),
        }).encode()
        self._coord.kv_put(lease_key(self.role, self.index), payload)

    def _run(self) -> None:
        from persia_tpu import diagnostics

        while not self._stop.wait(self.interval_s):
            try:
                self.publish_once()
                diagnostics.heartbeat(f"lease:{self.role}/{self.index}")
            except Exception as e:
                self.publish_errors += 1
                _metrics().counter(
                    "persia_tpu_lease_publish_errors",
                    "lease kv_put failures (coordinator unreachable)",
                ).inc(1.0, role=self.role)
                logger.debug("lease publish failed for %s/%d: %s",
                             self.role, self.index, e)

    def start(self) -> "LeasePublisher":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"persia-lease-{self.role}-{self.index}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


def maybe_start_lease_publisher(coord, role: str, index: int,
                                addr: str) -> Optional[LeasePublisher]:
    """Env-gated publisher start for fleet binaries (default ON when a
    coordinator is configured; ``PERSIA_LEASE=0`` opts out, e.g. the chaos
    suite's heartbeat-only-death injector wants manual control)."""
    if os.environ.get("PERSIA_LEASE", "1") not in ("1", "true"):
        return None
    interval = float(os.environ.get("PERSIA_LEASE_INTERVAL_S", "0.5"))
    return LeasePublisher(coord, role, index, addr,
                          interval_s=interval).start()


@dataclass
class DetectorConfig:
    # probes: one dropped probe NEVER evicts — only miss_threshold
    # consecutive misses produce DEAD
    miss_threshold: int = 3
    probe_timeout_s: float = 1.0
    # leases: control-plane staleness bound; a stale lease alone is only
    # ever SUSPECT (data plane authoritative)
    lease_ttl_s: float = 3.0
    # gray (limping) verdicts: replica rolling-median latency must exceed
    # max(gray_factor × fleet-median-of-peers, gray_min_latency_s) for
    # gray_windows CONSECUTIVE polls — a single latency spike is not gray
    gray_factor: float = 4.0
    gray_windows: int = 3
    gray_min_latency_s: float = 0.05
    window: int = 16
    # partition witness: withhold DEAD unless the observer reached at
    # least this fraction of the OTHER replicas in the same poll
    min_peer_witness_frac: float = 0.5


@dataclass
class ReplicaHealth:
    verdict: str = VERDICT_LIVE
    miss_streak: int = 0
    gray_streak: int = 0
    last_latency_s: Optional[float] = None
    median_latency_s: Optional[float] = None
    lease_seq: Optional[int] = None
    lease_fresh: Optional[bool] = None
    since: float = 0.0  # clock() of the last verdict transition
    latencies: Deque[float] = field(default_factory=lambda: deque(maxlen=16))


class FailureDetector:
    """Poll-driven verdict engine over a probe set + optional lease reader.

    ``probes`` maps replica index → zero-arg callable returning the probe
    latency in seconds (raising on failure). ``lease_reader`` (optional)
    returns ``{index: {"seq": int, ...}}`` from the coordinator kv.
    ``clock`` is injectable so tests drive lease aging deterministically.

    Verdict matrix per replica each :meth:`poll_once`:

    ==================  ===========  ==========================================
    probe               lease        verdict
    ==================  ===========  ==========================================
    ok                  fresh/none   LIVE (or GRAY after a sustained outlier)
    ok                  stale        SUSPECT — heartbeat-silent, never evicted
    miss < threshold    any          SUSPECT
    miss ≥ threshold    any          DEAD — unless the witness rule withholds
                                     (observer reached < majority of peers →
                                     SUSPECT: *I* am probably partitioned)
    ==================  ===========  ==========================================

    Note the heartbeat-only-death row is implicit: a FRESH lease does not
    rescue a replica whose data plane stopped answering — probes dominate.
    """

    def __init__(self, probes: Dict[int, Callable[[], float]],
                 cfg: Optional[DetectorConfig] = None,
                 lease_reader: Optional[Callable[[], Dict[int, dict]]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg or DetectorConfig()
        self.clock = clock
        self._probes = dict(probes)
        self._lease_reader = lease_reader
        self._health: Dict[int, ReplicaHealth] = {}
        # lease bookkeeping: idx -> (last_seq, clock at last advance)
        self._lease_seen: Dict[int, tuple] = {}
        self._lock = threading.Lock()
        self.polls = 0
        self.false_positive_guard = 0  # DEADs withheld by the witness rule
        for idx in self._probes:
            self._health[idx] = self._fresh_health()

    def _fresh_health(self) -> ReplicaHealth:
        h = ReplicaHealth(since=self.clock())
        h.latencies = deque(maxlen=self.cfg.window)
        return h

    # -- fleet membership (heal/resize paths) -------------------------------

    def add(self, idx: int, probe: Callable[[], float]) -> None:
        with self._lock:
            self._probes[idx] = probe
            self._health[idx] = self._fresh_health()
            self._lease_seen.pop(idx, None)

    def remove(self, idx: int) -> None:
        with self._lock:
            probe = self._probes.pop(idx, None)
            self._health.pop(idx, None)
            self._lease_seen.pop(idx, None)
        close = getattr(probe, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass

    def reset(self, idx: int, probe: Optional[Callable[[], float]] = None) -> None:
        """Forget a replica's history after a heal replaced the process
        behind it — the newcomer must not inherit the corpse's verdict."""
        with self._lock:
            if probe is not None:
                old = self._probes.get(idx)
                self._probes[idx] = probe
            else:
                old = None
            self._health[idx] = self._fresh_health()
            self._lease_seen.pop(idx, None)
        if old is not None and old is not probe:
            close = getattr(old, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    # -- the poll ------------------------------------------------------------

    def _read_leases(self) -> Dict[int, dict]:
        if self._lease_reader is None:
            return {}
        try:
            return self._lease_reader() or {}
        except Exception as e:
            _metrics().counter(
                "persia_tpu_detector_lease_read_errors",
                "lease scan failures (coordinator unreachable)",
            ).inc()
            logger.debug("lease scan failed: %s", e)
            return {}

    def poll_once(self) -> Dict[int, str]:
        """Probe every replica once and re-derive all verdicts. Returns
        ``{index: verdict}``. Thread-safe with add/remove/reset."""
        with self._lock:
            probes = dict(self._probes)
        now = self.clock()
        self.polls += 1
        leases = self._read_leases()

        probe_ok: Dict[int, bool] = {}
        latency: Dict[int, float] = {}
        for idx, probe in probes.items():
            try:
                latency[idx] = float(probe())
                probe_ok[idx] = True
            except Exception:
                probe_ok[idx] = False
                _metrics().counter(
                    "persia_tpu_detector_probe_misses",
                    "single probe failures (N of these make a DEAD verdict)",
                ).inc(1.0, replica=str(idx))

        with self._lock:
            # pass 1: streaks + lease freshness + rolling latency windows
            for idx in probes:
                h = self._health.get(idx)
                if h is None:
                    h = self._health[idx] = self._fresh_health()
                if probe_ok[idx]:
                    h.miss_streak = 0
                    h.last_latency_s = latency[idx]
                    h.latencies.append(latency[idx])
                    if h.latencies:
                        h.median_latency_s = statistics.median(h.latencies)
                else:
                    h.miss_streak += 1
                lease = leases.get(idx)
                if lease is not None and "seq" in lease:
                    seq = int(lease["seq"])
                    h.lease_seq = seq
                    prev = self._lease_seen.get(idx)
                    if prev is None or seq > prev[0]:
                        self._lease_seen[idx] = (seq, now)
                seen = self._lease_seen.get(idx)
                if seen is None:
                    h.lease_fresh = None  # never leased → lease plane mute
                else:
                    h.lease_fresh = (now - seen[1]) <= self.cfg.lease_ttl_s

            # pass 2: fleet latency baseline from the peers' medians
            medians = {i: h.median_latency_s for i, h in self._health.items()
                       if i in probes and h.median_latency_s is not None}

            # witness: what fraction of OTHER replicas did this poll reach
            verdicts: Dict[int, str] = {}
            for idx in probes:
                h = self._health[idx]
                peers = [i for i in probes if i != idx]
                if probe_ok[idx]:
                    verdicts[idx] = self._verdict_alive(idx, h, medians, peers)
                else:
                    verdicts[idx] = self._verdict_missing(
                        idx, h, probe_ok, peers)
                self._transition(idx, h, verdicts[idx], now)
            try:
                g = _metrics().gauge(
                    "persia_tpu_detector_verdicts",
                    "replicas per verdict class",
                )
                for v in (VERDICT_LIVE, VERDICT_SUSPECT, VERDICT_DEAD,
                          VERDICT_GRAY):
                    g.set(float(sum(1 for x in verdicts.values() if x == v)),
                          verdict=v)
            except Exception:  # pragma: no cover - metrics plane optional
                pass
            return verdicts

    def _verdict_alive(self, idx: int, h: ReplicaHealth,
                       medians: Dict[int, float], peers: List[int]) -> str:
        # heartbeat-silent: answers probes but the lease stopped advancing
        # — the control plane lost this replica, the data plane did not.
        # Surface, never evict.
        if h.lease_fresh is False:
            h.gray_streak = 0
            return VERDICT_SUSPECT
        peer_medians = [medians[i] for i in peers if i in medians]
        mine = h.median_latency_s
        if mine is not None and len(peer_medians) >= 2:
            fleet = statistics.median(peer_medians)
            bar = max(self.cfg.gray_factor * fleet, self.cfg.gray_min_latency_s)
            if mine > bar:
                h.gray_streak += 1
            else:
                h.gray_streak = 0
        else:
            h.gray_streak = 0
        if h.gray_streak >= self.cfg.gray_windows:
            return VERDICT_GRAY
        return VERDICT_LIVE

    def _verdict_missing(self, idx: int, h: ReplicaHealth,
                         probe_ok: Dict[int, bool], peers: List[int]) -> str:
        h.gray_streak = 0
        if h.miss_streak < self.cfg.miss_threshold:
            return VERDICT_SUSPECT
        # NOTE a fresh lease does NOT rescue: probes are the data plane and
        # the data plane is authoritative (heartbeat-only death).
        if peers:
            reached = sum(1 for i in peers if probe_ok.get(i))
            if reached < self.cfg.min_peer_witness_frac * len(peers):
                # the observer cannot see a majority of the fleet: *it* is
                # probably the partitioned party. Withhold DEAD — a lone
                # vantage point must not evict everyone else.
                self.false_positive_guard += 1
                return VERDICT_SUSPECT
        return VERDICT_DEAD

    def _transition(self, idx: int, h: ReplicaHealth, verdict: str,
                    now: float) -> None:
        if verdict == h.verdict:
            return
        prev, h.verdict, h.since = h.verdict, verdict, now
        logger.info("replica %d verdict %s -> %s (miss=%d gray=%d lease=%s)",
                    idx, prev, verdict, h.miss_streak, h.gray_streak,
                    h.lease_fresh)
        _record_event("detector.verdict", replica=idx, verdict=verdict,
                      prev=prev, miss_streak=h.miss_streak,
                      gray_streak=h.gray_streak)
        try:
            _metrics().counter(
                "persia_tpu_detector_transitions",
                "verdict transitions",
            ).inc(1.0, verdict=verdict)
        except Exception:  # pragma: no cover
            pass

    # -- introspection -------------------------------------------------------

    def health(self) -> Dict[int, ReplicaHealth]:
        with self._lock:
            return dict(self._health)

    def verdicts(self) -> Dict[int, str]:
        with self._lock:
            return {i: h.verdict for i, h in self._health.items()}

    def detected_at(self, idx: int) -> Optional[float]:
        """clock() timestamp of the replica's current verdict transition —
        the healer's MTTR measurement starts here."""
        with self._lock:
            h = self._health.get(idx)
            return None if h is None else h.since

    def close(self) -> None:
        with self._lock:
            probes = list(self._probes.values())
            self._probes.clear()
        for p in probes:
            close = getattr(p, "close", None)
            if close is not None:
                try:
                    close()
                except OSError:
                    pass


# -- wiring helpers ----------------------------------------------------------


def make_probe(addr: str, timeout_s: float = 1.0) -> Callable[[], float]:
    """One-attempt ``healthz`` probe against a PS/worker RPC endpoint.

    No retries and a breaker that never opens: the DETECTOR owns the
    miss-streak accounting — a retrying probe would hide exactly the
    misses the N-consecutive rule needs to count.
    """
    from persia_tpu.service import resilience
    from persia_tpu.service.rpc import RpcClient

    policy = resilience.ResiliencePolicy(
        retry=resilience.RetryPolicy(max_attempts=1, base_s=0.0, jitter=0.0),
        breaker_failure_threshold=1 << 30,
    )
    client = RpcClient(addr, timeout_s=timeout_s, policy=policy, pool_size=1)

    def _probe() -> float:
        t0 = time.perf_counter()
        client.call("healthz", idempotent=False)
        return time.perf_counter() - t0

    _probe.addr = addr  # type: ignore[attr-defined]
    _probe.close = client.close  # type: ignore[attr-defined]
    return _probe


def ps_fleet_probes(addrs: List[str],
                    timeout_s: float = 1.0) -> Dict[int, Callable[[], float]]:
    return {i: make_probe(a, timeout_s=timeout_s) for i, a in enumerate(addrs)}


def coordinator_lease_reader(coord, role: str = "ps"
                             ) -> Callable[[], Dict[int, dict]]:
    """Lease scan via the coordinator kv's prefix listing."""
    prefix = f"{LEASE_PREFIX}{role}/"

    def _read() -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for key in coord.kv_keys(prefix):
            raw = coord.kv_get(key)
            if not raw:
                continue
            try:
                out[int(key.rsplit("/", 1)[1])] = json.loads(raw.decode())
            except (ValueError, KeyError, IndexError):
                continue
        return out

    return _read

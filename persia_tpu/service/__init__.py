"""Multi-process service layer.

Replaces the reference's three communication planes (§2.6 of SURVEY.md):

- bulk tensors: custom HTTP/speedy/lz4 RPC (`rust/others/persia-rpc`) → here a
  length-prefixed binary TCP RPC (`persia_tpu/service/rpc.py`) carrying the
  framework's own wire formats;
- control/discovery: NATS request-reply (`rust/others/persia-nats-client`) →
  here a single lightweight coordinator service
  (`persia_tpu/service/discovery.py`) with registration + waiting + backoff;
- dense gradients: NCCL/DDP → XLA collectives over the TPU mesh (no service
  needed; see persia_tpu/parallel).
"""

from persia_tpu.service.rpc import RpcClient, RpcError, RpcServer  # noqa: F401
from persia_tpu.service.discovery import Coordinator, CoordinatorClient  # noqa: F401
from persia_tpu.service.clients import StoreClient, WorkerClient  # noqa: F401

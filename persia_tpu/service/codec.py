"""Wire compression for the RPC tier.

Parity target: the reference compresses large RPC bodies with lz4 FAST(3)
(`others/persia-rpc/src/lib.rs:68-145`). The round-1 zlib fallback is far
too slow for the per-batch lookup/gradient path, so the hot frames
effectively travelled uncompressed; ``native/codec.cpp`` provides an
LZ4-block-format codec fast enough to sit on the data plane. zlib remains
as the no-toolchain fallback (the frame flag records which codec was used,
so mixed deployments interoperate).
"""

from __future__ import annotations

import ctypes
import os
import zlib
from typing import Optional

from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.codec")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "codec.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "libpersia_codec.so")
_LIB: Optional[ctypes.CDLL] = None
_LOAD_FAILED = False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _LOAD_FAILED
    if _LIB is not None or _LOAD_FAILED:
        return _LIB
    try:
        from persia_tpu.embedding._native_build import build_so

        # CDLL the path build_so RETURNS (sanitizer-variant aware)
        so_path = build_so(
            _SRC, _SO,
            ["-O3", "-std=c++17", "-fPIC", "-shared", "-Wall"],
            logger,
        )
        lib = ctypes.CDLL(so_path)
        i64, u8p = ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8)
        lib.lz4_compress_bound.restype = i64
        lib.lz4_compress_bound.argtypes = [i64]
        lib.lz4_compress.restype = i64
        lib.lz4_compress.argtypes = [u8p, i64, u8p, i64]
        lib.lz4_decompress.restype = i64
        lib.lz4_decompress.argtypes = [u8p, i64, u8p, i64]
        _LIB = lib
    except Exception as e:  # noqa: BLE001 — toolchain-less host
        logger.warning("native codec unavailable (%r); falling back to zlib", e)
        _LOAD_FAILED = True
    return _LIB


def lz4_available() -> bool:
    return _load() is not None


def lz4_compress(data: bytes) -> bytes:
    lib = _load()
    if lib is None:
        raise RuntimeError("native lz4 codec unavailable")
    cap = lib.lz4_compress_bound(len(data))
    out = ctypes.create_string_buffer(cap)
    n = lib.lz4_compress(
        ctypes.cast(data, ctypes.POINTER(ctypes.c_uint8)), len(data),
        ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)), cap,
    )
    if n < 0:
        raise RuntimeError("lz4 compression failed")
    return out.raw[:n]


def lz4_decompress(data: bytes, orig_size: int) -> bytes:
    lib = _load()
    if lib is None:
        raise RuntimeError("native lz4 codec unavailable")
    out = ctypes.create_string_buffer(max(orig_size, 1))
    n = lib.lz4_decompress(
        ctypes.cast(data, ctypes.POINTER(ctypes.c_uint8)), len(data),
        ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)), orig_size,
    )
    if n != orig_size:
        raise ValueError(f"lz4 decompression produced {n} bytes, expected {orig_size}")
    return out.raw[:orig_size]


# ------------------------------------------------------- frame-level helpers
# Frame codec ids (the RPC frame's flag bits record the codec in use)
CODEC_NONE = 0
CODEC_ZLIB = 1
CODEC_LZ4 = 2


def compress_frame(payload: bytes, prefer_lz4: bool = True,
                   allow_zlib: bool = True):
    """(codec_id, body) — lz4 when available (body = u32 orig_size | blocks).
    ``allow_zlib=False`` returns CODEC_NONE instead of falling back: zlib on
    a hot frame costs more than it saves (the ~20x-slower codec this module
    exists to replace), so reply paths skip compression when lz4 is out."""
    if prefer_lz4 and lz4_available():
        import struct

        return CODEC_LZ4, struct.pack("<I", len(payload)) + lz4_compress(payload)
    if allow_zlib:
        return CODEC_ZLIB, zlib.compress(payload, level=1)
    return CODEC_NONE, payload


def decompress_frame(codec_id: int, body: bytes) -> bytes:
    if codec_id == CODEC_ZLIB:
        return zlib.decompress(body)
    if codec_id == CODEC_LZ4:
        import struct

        (orig,) = struct.unpack("<I", body[:4])
        return lz4_decompress(body[4:], orig)
    raise ValueError(f"unknown codec id {codec_id}")

"""Payload codecs for service RPC (the framework's speedy replacement).

Small tag-free formats per message family: ndarrays travel as
(dtype code, ndim, shape, raw bytes) like persia_tpu.data's wire helpers;
structured configs travel as JSON (control plane only — never on the hot
path)."""

from __future__ import annotations

import io
import json
import struct
from typing import Dict, List, Sequence, Tuple

import numpy as np

from persia_tpu.embedding.worker import RawEmbeddingBatch, SumEmbeddingBatch


def pack_ndarray(a: np.ndarray) -> bytes:
    a = np.ascontiguousarray(a)
    header = struct.pack("<10sB", a.dtype.str.encode().ljust(10), a.ndim)
    return header + struct.pack(f"<{a.ndim}q", *a.shape) + a.tobytes()


def unpack_ndarray(buf: io.BytesIO) -> np.ndarray:
    dtype_s, ndim = struct.unpack("<10sB", buf.read(11))
    shape = struct.unpack(f"<{ndim}q", buf.read(8 * ndim))
    dtype = np.dtype(dtype_s.rstrip(b"\x00").rstrip().decode())
    n = int(np.prod(shape)) if shape else 1
    return np.frombuffer(buf.read(n * dtype.itemsize), dtype=dtype).reshape(shape).copy()


def pack_ndarrays(arrays: Sequence[np.ndarray]) -> bytes:
    out = struct.pack("<H", len(arrays))
    return out + b"".join(pack_ndarray(a) for a in arrays)


def unpack_ndarrays(buf: io.BytesIO) -> List[np.ndarray]:
    (n,) = struct.unpack("<H", buf.read(2))
    return [unpack_ndarray(buf) for _ in range(n)]


def pack_json(obj) -> bytes:
    return json.dumps(obj).encode()


def unpack_json(raw: bytes):
    return json.loads(raw.decode())


# ---------------------------------------------------------- lookup/update


def pack_lookup_request(signs: np.ndarray, dim: int, train: bool) -> bytes:
    return struct.pack("<IB", dim, int(train)) + pack_ndarray(signs)


def unpack_lookup_request(raw: bytes) -> Tuple[np.ndarray, int, bool]:
    dim, train = struct.unpack("<IB", raw[:5])
    signs = unpack_ndarray(io.BytesIO(raw[5:]))
    return signs, dim, bool(train)


def pack_update_request(signs: np.ndarray, grads: np.ndarray, group: int) -> bytes:
    return struct.pack("<i", group) + pack_ndarrays([signs, grads])


def unpack_update_request(raw: bytes) -> Tuple[np.ndarray, np.ndarray, int]:
    (group,) = struct.unpack("<i", raw[:4])
    signs, grads = unpack_ndarrays(io.BytesIO(raw[4:]))
    return signs, grads, group


def pack_set_embedding(signs: np.ndarray, values: np.ndarray, dim: int) -> bytes:
    """Legacy v1 wire (4-byte header, no flags) — kept verbatim so old and
    new processes interoperate during rolling upgrades; the flagged variant
    rides a NEW method name (``set_embedding_v2``) instead of changing this
    format in place."""
    return struct.pack("<I", dim) + pack_ndarrays([signs, values])


def unpack_set_embedding(raw: bytes) -> Tuple[np.ndarray, np.ndarray, int]:
    (dim,) = struct.unpack("<I", raw[:4])
    signs, values = unpack_ndarrays(io.BytesIO(raw[4:]))
    return signs, values, dim


def pack_set_embedding_v2(
    signs: np.ndarray, values: np.ndarray, dim: int,
    commit_incremental: bool = False,
) -> bytes:
    # header = dim | flags (bit 0: commit to the incremental-update manager
    # — write-backs are training updates, checkpoint loads are not)
    return struct.pack("<IB", dim, 1 if commit_incremental else 0) + pack_ndarrays(
        [signs, values]
    )


def unpack_set_embedding_v2(raw: bytes) -> Tuple[np.ndarray, np.ndarray, int, bool]:
    dim, flags = struct.unpack("<IB", raw[:5])
    signs, values = unpack_ndarrays(io.BytesIO(raw[5:]))
    return signs, values, dim, bool(flags & 1)


# ------------------------------------------------- embedding batch results


def pack_emb_batches(batches: Sequence) -> bytes:
    out = [struct.pack("<H", len(batches))]
    for b in batches:
        name = b.name.encode()
        if isinstance(b, SumEmbeddingBatch):
            out.append(struct.pack("<BH", 0, len(name)) + name)
            out.append(pack_ndarray(b.pooled))
        elif isinstance(b, RawEmbeddingBatch):
            out.append(struct.pack("<BH", 1, len(name)) + name)
            out.append(pack_ndarrays([b.distinct, b.index, b.sample_id_num]))
        else:
            raise TypeError(type(b))
    return b"".join(out)


def unpack_emb_batches(raw: bytes) -> List:
    buf = io.BytesIO(raw)
    (n,) = struct.unpack("<H", buf.read(2))
    out: List = []
    for _ in range(n):
        kind, nlen = struct.unpack("<BH", buf.read(3))
        name = buf.read(nlen).decode()
        if kind == 0:
            out.append(SumEmbeddingBatch(name, unpack_ndarray(buf)))
        else:
            distinct, index, sample_id_num = unpack_ndarrays(buf)
            out.append(RawEmbeddingBatch(name, distinct, index, sample_id_num))
    return out


# --------------------------------------------------------- gradient batches


def pack_slot_grads(slot_grads: Dict[str, np.ndarray], scale_factor: float) -> bytes:
    out = [struct.pack("<fH", scale_factor, len(slot_grads))]
    for name, g in slot_grads.items():
        nb = name.encode()
        out.append(struct.pack("<H", len(nb)) + nb + pack_ndarray(g))
    return b"".join(out)


def unpack_slot_grads(raw: bytes) -> Tuple[Dict[str, np.ndarray], float]:
    buf = io.BytesIO(raw)
    scale, n = struct.unpack("<fH", buf.read(6))
    grads = {}
    for _ in range(n):
        (nlen,) = struct.unpack("<H", buf.read(2))
        name = buf.read(nlen).decode()
        grads[name] = unpack_ndarray(buf)
    return grads, scale

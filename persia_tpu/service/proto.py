"""Payload codecs for service RPC (the framework's speedy replacement).

Small tag-free formats per message family: ndarrays travel as
(dtype code, ndim, shape, raw bytes) like persia_tpu.data's wire helpers;
structured configs travel as JSON (control plane only — never on the hot
path)."""

from __future__ import annotations

import io
import json
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from persia_tpu.embedding.worker import (
    DevicePooledBatch,
    RawEmbeddingBatch,
    SumEmbeddingBatch,
)


def pack_ndarray(a: np.ndarray) -> bytes:
    a = np.ascontiguousarray(a)
    header = struct.pack("<10sB", a.dtype.str.encode().ljust(10), a.ndim)
    return header + struct.pack(f"<{a.ndim}q", *a.shape) + a.tobytes()


def unpack_ndarray(buf: io.BytesIO) -> np.ndarray:
    dtype_s, ndim = struct.unpack("<10sB", buf.read(11))
    shape = struct.unpack(f"<{ndim}q", buf.read(8 * ndim))
    dtype = np.dtype(dtype_s.rstrip(b"\x00").rstrip().decode())
    n = int(np.prod(shape)) if shape else 1
    return np.frombuffer(buf.read(n * dtype.itemsize), dtype=dtype).reshape(shape).copy()


def pack_ndarrays(arrays: Sequence[np.ndarray]) -> bytes:
    out = struct.pack("<H", len(arrays))
    return out + b"".join(pack_ndarray(a) for a in arrays)


def unpack_ndarrays(buf: io.BytesIO) -> List[np.ndarray]:
    (n,) = struct.unpack("<H", buf.read(2))
    return [unpack_ndarray(buf) for _ in range(n)]


def pack_json(obj) -> bytes:
    return json.dumps(obj).encode()


def unpack_json(raw: bytes):
    return json.loads(raw.decode())


# ------------------------------------------------- wire dtypes (f16 parity)

# The reference ships f16 embedding rows worker→NN and f16 gradients back
# (persia-common/src/lib.rs:157-180, ndarray_f32_to_f16 postprocess,
# embedding_worker_service/mod.rs:486-629); these codes put the same
# half-width option (plus bf16) on the batched lookup/update wire.
def wire_dtype_code(name: Optional[str]) -> int:
    if name in (None, "float32"):
        return 0
    if name == "float16":
        return 1
    if name == "bfloat16":
        return 2
    raise ValueError(f"wire dtype must be float32/float16/bfloat16, got {name!r}")


def _wire_np_dtype(code: int) -> np.dtype:
    if code == 0:
        return np.dtype(np.float32)
    if code == 1:
        return np.dtype(np.float16)
    if code == 2:
        from ml_dtypes import bfloat16  # registered numpy scalar (jax dep)

        return np.dtype(bfloat16)
    raise ValueError(f"unknown wire dtype code {code}")


# ---------------------------------------------------------- lookup/update


def pack_lookup_request(signs: np.ndarray, dim: int, train: bool) -> bytes:
    return struct.pack("<IB", dim, int(train)) + pack_ndarray(signs)


def unpack_lookup_request(raw: bytes) -> Tuple[np.ndarray, int, bool]:
    dim, train = struct.unpack("<IB", raw[:5])
    signs = unpack_ndarray(io.BytesIO(raw[5:]))
    return signs, dim, bool(train)


def pack_lookup_batched_request(
    signs: np.ndarray, key_ofs: np.ndarray, dims: np.ndarray, train: bool,
    reply_dtype: Optional[str] = None,
) -> List:
    """ONE multi-slot lookup frame per batch per replica (ref:
    lookup_batched_all_slots, embedding_worker_service/mod.rs:874-942).
    Returns a scatter-gather buffer list — the sign array ships as a
    memoryview, never joined host-side."""
    header = struct.pack(
        "<BBH", int(train), wire_dtype_code(reply_dtype), len(dims)
    )
    return [
        header,
        np.ascontiguousarray(dims, dtype=np.uint32).data,
        np.ascontiguousarray(key_ofs, dtype=np.int64).data,
        np.ascontiguousarray(signs, dtype=np.uint64).data,
    ]


def unpack_lookup_batched_request(raw: bytes):
    train, dtype_code, n = struct.unpack("<BBH", raw[:4])
    off = 4
    dims = np.frombuffer(raw, dtype=np.uint32, count=n, offset=off)
    off += 4 * n
    key_ofs = np.frombuffer(raw, dtype=np.int64, count=n + 1, offset=off)
    off += 8 * (n + 1)
    signs = np.frombuffer(raw, dtype=np.uint64, offset=off)
    return signs, key_ofs, dims, bool(train), dtype_code


def _export_view(a: np.ndarray):
    """Buffer-protocol view of any array — bfloat16 (an ml_dtypes scalar)
    can't export directly, so reinterpret as bytes."""
    return np.ascontiguousarray(a).view(np.uint8).data


def _import_array(raw, dtype: np.dtype, count: int = -1, offset: int = 0):
    n_bytes = (len(raw) - offset) if count < 0 else count * dtype.itemsize
    return np.frombuffer(
        raw, dtype=np.uint8, count=n_bytes, offset=offset
    ).view(dtype)


def pack_lookup_batched_reply(flat: np.ndarray, dtype_code: int) -> List:
    return [_export_view(flat.astype(_wire_np_dtype(dtype_code), copy=False))]


def unpack_lookup_batched_reply(raw: bytes, dtype_code: int) -> np.ndarray:
    flat = _import_array(raw, _wire_np_dtype(dtype_code))
    return flat.astype(np.float32) if dtype_code else flat.copy()


def pack_update_batched_request(
    signs: np.ndarray, key_ofs: np.ndarray, dims: np.ndarray,
    grads_flat: np.ndarray, opt_groups: np.ndarray,
    wire_dtype: Optional[str] = None,
) -> List:
    """ONE multi-slot gradient frame per batch per replica; gradients ship
    in the (optionally half-width) wire dtype like the reference's f16
    gradient return (persia-common/src/lib.rs:157-180)."""
    code = wire_dtype_code(wire_dtype)
    header = struct.pack("<BH", code, len(dims))
    return [
        header,
        np.ascontiguousarray(dims, dtype=np.uint32).data,
        np.ascontiguousarray(opt_groups, dtype=np.int32).data,
        np.ascontiguousarray(key_ofs, dtype=np.int64).data,
        np.ascontiguousarray(signs, dtype=np.uint64).data,
        _export_view(
            np.asarray(grads_flat).reshape(-1).astype(
                _wire_np_dtype(code), copy=False
            )
        ),
    ]


def unpack_update_batched_request(raw: bytes):
    code, n = struct.unpack("<BH", raw[:3])
    off = 3
    dims = np.frombuffer(raw, dtype=np.uint32, count=n, offset=off)
    off += 4 * n
    opt_groups = np.frombuffer(raw, dtype=np.int32, count=n, offset=off)
    off += 4 * n
    key_ofs = np.frombuffer(raw, dtype=np.int64, count=n + 1, offset=off)
    off += 8 * (n + 1)
    n_signs = int(key_ofs[-1]) if n else 0
    signs = np.frombuffer(raw, dtype=np.uint64, count=n_signs, offset=off)
    off += 8 * n_signs
    grads = _import_array(raw, _wire_np_dtype(code), offset=off).astype(
        np.float32, copy=False
    )
    return signs, key_ofs, dims, grads, opt_groups


def pack_update_journaled_request(
    journal_id: int, crc: int,
    signs: np.ndarray, key_ofs: np.ndarray, dims: np.ndarray,
    grads_flat: np.ndarray, opt_groups: np.ndarray,
    wire_dtype: Optional[str] = None,
) -> List:
    """Journaled multi-slot gradient frame: a 12-byte (u64 id, u32 crc)
    prefix on the plain ``update_batched`` wire. The id/crc pair is the PS
    apply-journal record (persia_tpu.jobstate) that makes the call
    retry-safe AND exactly-once across a trainer crash."""
    return [struct.pack("<QI", journal_id, crc & 0xFFFFFFFF)] + (
        pack_update_batched_request(
            signs, key_ofs, dims, grads_flat, opt_groups, wire_dtype=wire_dtype
        )
    )


def unpack_update_journaled_request(raw: bytes):
    journal_id, crc = struct.unpack_from("<QI", raw)
    return (journal_id, crc) + unpack_update_batched_request(raw[12:])


def pack_update_request(signs: np.ndarray, grads: np.ndarray, group: int) -> bytes:
    return struct.pack("<i", group) + pack_ndarrays([signs, grads])


def unpack_update_request(raw: bytes) -> Tuple[np.ndarray, np.ndarray, int]:
    (group,) = struct.unpack("<i", raw[:4])
    signs, grads = unpack_ndarrays(io.BytesIO(raw[4:]))
    return signs, grads, group


def pack_set_embedding(signs: np.ndarray, values: np.ndarray, dim: int) -> bytes:
    """Legacy v1 wire (4-byte header, no flags) — kept verbatim so old and
    new processes interoperate during rolling upgrades; the flagged variant
    rides a NEW method name (``set_embedding_v2``) instead of changing this
    format in place."""
    return struct.pack("<I", dim) + pack_ndarrays([signs, values])


def unpack_set_embedding(raw: bytes) -> Tuple[np.ndarray, np.ndarray, int]:
    (dim,) = struct.unpack("<I", raw[:4])
    signs, values = unpack_ndarrays(io.BytesIO(raw[4:]))
    return signs, values, dim


def pack_set_embedding_v2(
    signs: np.ndarray, values: np.ndarray, dim: int,
    commit_incremental: bool = False,
) -> bytes:
    # header = dim | flags (bit 0: commit to the incremental-update manager
    # — write-backs are training updates, checkpoint loads are not)
    return struct.pack("<IB", dim, 1 if commit_incremental else 0) + pack_ndarrays(
        [signs, values]
    )


def unpack_set_embedding_v2(raw: bytes) -> Tuple[np.ndarray, np.ndarray, int, bool]:
    dim, flags = struct.unpack("<IB", raw[:5])
    signs, values = unpack_ndarrays(io.BytesIO(raw[5:]))
    return signs, values, dim, bool(flags & 1)


# ------------------------------------------------- embedding batch results


def pack_emb_batches(batches: Sequence) -> bytes:
    out = [struct.pack("<H", len(batches))]
    for b in batches:
        name = b.name.encode()
        if isinstance(b, SumEmbeddingBatch):
            out.append(struct.pack("<BH", 0, len(name)) + name)
            out.append(pack_ndarray(b.pooled))
        elif isinstance(b, RawEmbeddingBatch):
            out.append(struct.pack("<BH", 1, len(name)) + name)
            out.append(pack_ndarrays([b.distinct, b.index, b.sample_id_num]))
        elif isinstance(b, DevicePooledBatch):
            out.append(struct.pack("<BH", 2, len(name)) + name)
            out.append(struct.pack("<B", int(b.sqrt_scaling)))
            out.append(pack_ndarrays([b.distinct, b.index, b.counts]))
        else:
            raise TypeError(type(b))
    return b"".join(out)


def unpack_emb_batches(raw: bytes) -> List:
    buf = io.BytesIO(raw)
    (n,) = struct.unpack("<H", buf.read(2))
    out: List = []
    for _ in range(n):
        kind, nlen = struct.unpack("<BH", buf.read(3))
        name = buf.read(nlen).decode()
        if kind == 0:
            out.append(SumEmbeddingBatch(name, unpack_ndarray(buf)))
        elif kind == 1:
            distinct, index, sample_id_num = unpack_ndarrays(buf)
            out.append(RawEmbeddingBatch(name, distinct, index, sample_id_num))
        elif kind == 2:
            (sqrt_scaling,) = struct.unpack("<B", buf.read(1))
            distinct, index, counts = unpack_ndarrays(buf)
            out.append(
                DevicePooledBatch(name, distinct, index, counts, bool(sqrt_scaling))
            )
        else:
            raise ValueError(f"unknown embedding batch kind {kind}")
    return out


# --------------------------------------------------------- gradient batches


def pack_slot_grads(slot_grads: Dict[str, np.ndarray], scale_factor: float) -> bytes:
    out = [struct.pack("<fH", scale_factor, len(slot_grads))]
    for name, g in slot_grads.items():
        nb = name.encode()
        out.append(struct.pack("<H", len(nb)) + nb + pack_ndarray(g))
    return b"".join(out)


def unpack_slot_grads(raw: bytes) -> Tuple[Dict[str, np.ndarray], float]:
    buf = io.BytesIO(raw)
    scale, n = struct.unpack("<fH", buf.read(6))
    grads = {}
    for _ in range(n):
        (nlen,) = struct.unpack("<H", buf.read(2))
        name = buf.read(nlen).decode()
        grads[name] = unpack_ndarray(buf)
    return grads, scale

"""Embedding-worker process.

Parity target: `rust/persia-embedding-server/src/bin/persia-embedding-worker.rs`
+ the worker RPC surface (`embedding_worker_service/mod.rs:1379-1561`):
forward_batched (buffer ids, return remote ref), can_forward_batched,
forward_batch_id, forward_directly, update_gradient_batched,
register_optimizer, configure, dump/load fan-out to all PSs, shutdown(_server).
"""

from __future__ import annotations

import argparse
import os
import struct
import threading
from typing import Optional

from persia_tpu.data import PersiaBatch
from persia_tpu.embedding.optim import OptimizerConfig
from persia_tpu.embedding.worker import EmbeddingWorker
from persia_tpu.config import HyperParameters
from persia_tpu.logger import get_default_logger
from persia_tpu.service import proto
from persia_tpu.service.clients import StoreClient
from persia_tpu.service.discovery import CoordinatorClient
from persia_tpu.service.rpc import RpcServer

logger = get_default_logger("persia_tpu.worker_server")


class EmbeddingWorkerService:
    def __init__(self, worker: EmbeddingWorker, port: int = 0):
        self.worker = worker
        self.server = RpcServer(port=port)
        s = self.server
        s.register("can_forward_batched", self._can_forward)
        s.register("forward_batched", self._forward_batched)
        s.register("forward_batch_id", self._forward_batch_id)
        s.register("forward_directly", self._forward_directly)
        s.register("update_gradient_batched", self._update_gradient)
        s.register("abort_gradient", self._abort_gradient)
        s.register("register_optimizer", self._register_optimizer)
        s.register("configure", self._configure)
        s.register("staleness", lambda p: struct.pack("<q", self.worker.staleness))
        s.register("ready_for_serving", self._ready_for_serving)
        s.register("dump", self._dump)
        s.register("load", self._load)
        s.register("model_manager_status", self._status)
        s.register("shutdown_servers", self._shutdown_servers)
        self.port = s.port

    def _ready_for_serving(self, payload: bytes) -> bytes:
        """b\"1\" only when every PS replica answers a probe (ref:
        ready_for_serving, embedding_worker_service/mod.rs:1379-1491)."""
        for r in self.worker.lookup_router.replicas:
            try:
                r.wait_ready(timeout_s=2.0)
            except Exception:  # noqa: BLE001
                return b"0"
        return b"1"

    def _can_forward(self, payload: bytes) -> bytes:
        return b"1" if self.worker.can_forward_batched() else b"0"

    def _forward_batched(self, payload: bytes) -> bytes:
        batch = PersiaBatch.from_bytes(payload)
        if not self.worker.can_forward_batched():
            raise RuntimeError("forward buffer full")  # backpressure to sender
        ref = self.worker.put_forward_ids(batch)
        return struct.pack("<q", ref)

    def _forward_batch_id(self, payload: bytes) -> bytes:
        ref, train = struct.unpack("<qB", payload)
        out = self.worker.forward_batch_id(ref, train=bool(train))
        return proto.pack_emb_batches(out)

    def _forward_directly(self, payload: bytes) -> bytes:
        train = bool(payload[0])
        batch = PersiaBatch.from_bytes(payload[1:])
        return proto.pack_emb_batches(self.worker.forward_directly(batch, train=train))

    def _update_gradient(self, payload: bytes) -> bytes:
        (ref,) = struct.unpack("<q", payload[:8])
        slot_grads, scale = proto.unpack_slot_grads(payload[8:])
        skipped = self.worker.update_gradient_batched(ref, slot_grads, scale_factor=scale)
        return proto.pack_json(skipped)

    def _abort_gradient(self, payload: bytes) -> bytes:
        (ref,) = struct.unpack("<q", payload)
        self.worker.abort_gradient(ref)
        return b"ok"

    def _register_optimizer(self, payload: bytes) -> bytes:
        cfg = OptimizerConfig.from_dict(proto.unpack_json(payload))
        self.worker.register_optimizer(cfg)
        return b"ok"

    def _configure(self, payload: bytes) -> bytes:
        self.worker.configure(HyperParameters.from_dict(proto.unpack_json(payload)))
        return b"ok"

    def _dump(self, payload: bytes) -> bytes:
        """Fan out to every PS (ref: emb_worker dump, mod.rs:1131-1148)."""
        req = proto.unpack_json(payload)
        self.worker.dump(req["path"], blocking=req.get("blocking", True))
        return b"ok"

    def _load(self, payload: bytes) -> bytes:
        return struct.pack("<q", self.worker.load(payload.decode()))

    def _status(self, payload: bytes) -> bytes:
        sts = [r.model_manager_status() for r in self.worker.lookup_router.replicas]
        return proto.pack_json(sts)

    def _shutdown_servers(self, payload: bytes) -> bytes:
        for r in self.worker.lookup_router.replicas:
            r.shutdown()
        return b"ok"

    def start(self) -> "EmbeddingWorkerService":
        self.server.start()
        return self


def main(argv: Optional[list] = None) -> None:
    ap = argparse.ArgumentParser("persia-tpu-embedding-worker")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--replica-index", type=int, default=None)
    ap.add_argument("--replica-size", type=int, default=None)
    ap.add_argument("--coordinator", type=str, required=True)
    ap.add_argument("--advertise-host", type=str,
                    default=os.environ.get("PERSIA_ADVERTISE_HOST", "127.0.0.1"))
    ap.add_argument("--num-parameter-servers", type=int, required=True)
    ap.add_argument("--embedding-config", type=str, default=None)
    ap.add_argument("--global-config", type=str, default=None)
    ap.add_argument("--num-threads", type=int, default=8)
    ap.add_argument("--ps-wire-dtype", type=str, default="float32",
                    choices=["float32", "float16", "bfloat16"],
                    help="batched lookup/update wire dtype toward the PS tier "
                         "(reference parity: f16 embedding/gradient wire)")
    ap.add_argument("--device-pooling", action="store_true",
                    help="ship sum slots unpooled (distinct rows + gather layout) so pooling runs on the trainer's device")
    args = ap.parse_args(argv)

    from persia_tpu import env
    from persia_tpu.config import EmbeddingConfig, load_embedding_config, load_global_config

    replica_index = (
        args.replica_index if args.replica_index is not None else env.get_replica_index()
    )
    replica_size = (
        args.replica_size if args.replica_size is not None else env.get_replica_size()
    )

    emb_cfg = (
        load_embedding_config(args.embedding_config)
        if args.embedding_config
        else EmbeddingConfig()
    )
    worker_kwargs = {}
    if args.global_config:
        g = load_global_config(args.global_config)
        worker_kwargs = dict(
            forward_buffer_size=g.embedding_worker.forward_buffer_size,
            buffered_data_expired_sec=g.embedding_worker.buffered_data_expired_sec,
        )

    coord = CoordinatorClient(args.coordinator)
    ps_addrs = coord.wait_for("parameter_server", args.num_parameter_servers)
    # env-configured resilience policy (service/resilience.py): setting
    # PERSIA_DEGRADE_AFTER_S arms degraded-mode lookups on this worker's
    # PS router — a dead shard then costs bounded quality, not liveness
    policy = None
    degrade_s = os.environ.get("PERSIA_DEGRADE_AFTER_S")
    if degrade_s:
        from persia_tpu.service.resilience import ResiliencePolicy

        policy = ResiliencePolicy(
            degrade_after_s=float(degrade_s),
            max_degraded_frac=float(
                os.environ.get("PERSIA_MAX_DEGRADED_FRAC", "1.0")
            ),
        )
    replicas = [
        StoreClient(a, wire_dtype=args.ps_wire_dtype, policy=policy)
        for a in ps_addrs
    ]
    for r in replicas:
        r.wait_ready()

    worker = EmbeddingWorker(
        emb_cfg, replicas, num_threads=args.num_threads,
        device_pooling=args.device_pooling, policy=policy, **worker_kwargs
    )
    svc = EmbeddingWorkerService(worker, port=args.port).start()
    logger.info(
        "embedding worker %d/%d on port %d (%d parameter servers)",
        replica_index, replica_size, svc.port, len(ps_addrs),
    )
    worker_addr = f"{args.advertise_host}:{svc.port}"
    coord.register("embedding_worker", replica_index, worker_addr)
    from persia_tpu.diagnostics import maybe_start_from_env
    from persia_tpu.service.failure_detector import maybe_start_lease_publisher

    maybe_start_from_env()  # opt-in deadlock/stall detector (ref: lib.rs:494)
    # heartbeat lease for the failure detector; each beat also feeds the
    # stall detector above (PERSIA_LEASE=0 opts out)
    lease = maybe_start_lease_publisher(
        coord, "embedding_worker", replica_index, worker_addr
    )
    svc.server._thread.join()
    if lease is not None:
        lease.stop()


if __name__ == "__main__":
    main()

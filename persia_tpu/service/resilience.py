"""Unified resilience policy for the service plane.

One engine owns every retry/backoff/deadline/breaker decision the process
makes against a remote peer, so the training-side clients (``StoreClient``
/ ``WorkerClient`` via ``RpcClient``), the DataLoader's lookup workers,
the HBM cache tier's PS probe path, and the serving gateway all share ONE
set of semantics instead of four hand-rolled loops (the pre-PR state:
``RpcClient.call`` had its own backoff, the gateway its own mark-down
logic, the loader its own retry counter, the cache tier nothing).

Pieces:

- :class:`RetryPolicy` — exponential backoff with deterministic,
  seed-driven jitter (chaos tests replay schedules bit-for-bit);
- :class:`Deadline` — a per-call time budget that PROPAGATES: each RPC
  attempt's socket timeout and each backoff sleep is capped by the
  remaining budget, so a call bounded to 2 s cannot spend 3 × 60 s in
  nested retries (the reference's NATS ops carry the same budget idea,
  core/nats.rs:162-180);
- :class:`CircuitBreaker` — per-endpoint consecutive-failure breaker with
  half-open probes: a dead PS shard costs ONE connect timeout per reset
  window instead of one per lookup, and the re-close after recovery is an
  observable event (``trips``/``state``) the chaos suite asserts on;
- :class:`ResiliencePolicy` — the shared container: breaker registry
  keyed by endpoint, the retry policy, and the degraded-lookup knobs
  (``degrade_after_s``, ``max_degraded_frac``) the embedding router uses
  to trade bounded quality for liveness when a shard stays down.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from persia_tpu.metrics import get_metrics


class ResilienceError(RuntimeError):
    pass


class DeadlineExceeded(ResilienceError, TimeoutError):
    """The call's time budget ran out (subclasses ``TimeoutError`` so the
    existing transport-error classification in ``rpc._is_transportish``
    and the retry loops treat it as a transport-class failure)."""


class CircuitOpenError(ResilienceError, ConnectionError):
    """The endpoint's breaker is open — fail fast, no socket was touched
    (subclasses ``ConnectionError`` for the same classification reason)."""


class Deadline:
    """Monotonic time budget. ``None`` deadlines are represented by the
    caller passing ``None`` — this class always has a bound."""

    __slots__ = ("t_end",)

    def __init__(self, budget_s: float):
        self.t_end = time.monotonic() + float(budget_s)

    @classmethod
    def after(cls, budget_s: float) -> "Deadline":
        return cls(budget_s)

    def remaining(self) -> float:
        return self.t_end - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, what: str = "call") -> None:
        if self.expired:
            raise DeadlineExceeded(f"deadline exceeded before {what}")

    def cap(self, timeout_s: Optional[float]) -> float:
        """Largest per-attempt timeout that still fits the budget (floored
        at 1 ms so sockets never get a non-positive timeout)."""
        rem = max(self.remaining(), 1e-3)
        return rem if timeout_s is None else min(float(timeout_s), rem)


@dataclass
class RetryPolicy:
    """Exponential backoff with deterministic jitter.

    ``jitter`` is the fraction of the nominal delay that is randomized
    away (0.5 → uniform in [0.5·d, d]); the RNG is seeded so two runs of
    the same schedule sleep the same sequence — chaos soak runs stay
    reproducible."""

    max_attempts: int = 3
    base_s: float = 0.05
    multiplier: float = 2.0
    max_s: float = 2.0
    jitter: float = 0.5
    seed: int = 0

    def __post_init__(self):
        self._rng = random.Random(self.seed)
        self._rng_lock = threading.Lock()

    def backoff(self, attempt: int) -> float:
        d = min(self.base_s * self.multiplier ** max(attempt, 0), self.max_s)
        if self.jitter <= 0.0 or d <= 0.0:
            return d
        with self._rng_lock:
            r = self._rng.random()
        return d * (1.0 - self.jitter * r)


_STATE_CLOSED = "closed"
_STATE_OPEN = "open"
_STATE_HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probes.

    closed → (``failure_threshold`` consecutive failures) → open →
    (``reset_timeout_s`` elapses) → half-open (ONE probe call allowed) →
    success closes / failure re-opens. ``allow()`` consumes the half-open
    probe slot; ``available()`` is the non-consuming routing check the
    gateway uses."""

    def __init__(
        self,
        endpoint: str,
        failure_threshold: int = 5,
        reset_timeout_s: float = 1.0,
    ):
        self.endpoint = endpoint
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout_s = float(reset_timeout_s)
        self._lock = threading.Lock()
        self._failures = 0
        self._state = _STATE_CLOSED
        self._open_until = 0.0
        self._probe_inflight = False
        self.trips = 0  # closed→open transitions (chaos suite asserts on it)
        m = get_metrics()
        self._m_state = m.gauge(
            "persia_tpu_breaker_open", "1 while the endpoint's breaker is open"
        )
        self._m_trips = m.counter(
            "persia_tpu_breaker_trips", "breaker closed->open transitions"
        )

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if self._state == _STATE_OPEN and time.monotonic() >= self._open_until:
            self._state = _STATE_HALF_OPEN
            self._probe_inflight = False

    def allow(self) -> bool:
        """May a call proceed now? Half-open grants exactly one in-flight
        probe per reset window."""
        with self._lock:
            self._maybe_half_open()
            if self._state == _STATE_CLOSED:
                return True
            if self._state == _STATE_HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                self._record("breaker.probe")
                return True
            return False

    def available(self) -> bool:
        """Non-consuming routing check (round-robin membership)."""
        with self._lock:
            self._maybe_half_open()
            return self._state != _STATE_OPEN

    def on_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != _STATE_CLOSED:
                prior = self._state
                self._state = _STATE_CLOSED
                self._m_state.set(0, endpoint=self.endpoint)
                self._record("breaker.close", prior_state=prior)

    def on_failure(self) -> None:
        with self._lock:
            self._maybe_half_open()
            self._failures += 1
            self._probe_inflight = False
            tripping = (
                self._state == _STATE_HALF_OPEN
                or (self._state == _STATE_CLOSED
                    and self._failures >= self.failure_threshold)
            )
            if tripping:
                if self._state != _STATE_OPEN:
                    self.trips += 1
                    self._m_trips.inc(endpoint=self.endpoint)
                    self._record_trip("failure")
                self._state = _STATE_OPEN
                self._open_until = time.monotonic() + self.reset_timeout_s
                self._m_state.set(1, endpoint=self.endpoint)

    def _record(self, kind: str, **attrs) -> None:
        """Every breaker state transition lands in the flight recorder —
        trips, half-open probe grants, and re-closes — stamped with the
        trace that drove it (if any)."""
        from persia_tpu import tracing

        tracing.record_event(kind, endpoint=self.endpoint,
                             trips=self.trips, **attrs)

    def _record_trip(self, cause: str) -> None:
        self._record("breaker.trip", cause=cause)

    def force_open(self) -> None:
        """Administrative open (the gateway's mark-down on a failed health
        probe maps here)."""
        with self._lock:
            if self._state != _STATE_OPEN:
                self.trips += 1
                self._m_trips.inc(endpoint=self.endpoint)
                self._record_trip("forced")
            self._state = _STATE_OPEN
            self._open_until = time.monotonic() + self.reset_timeout_s
            self._failures = self.failure_threshold
            self._m_state.set(1, endpoint=self.endpoint)

    def reset(self) -> None:
        """Administrative re-close: forget the failure history in place
        (object identity survives for callers holding a reference). The
        elastic tier's ``replace_replica`` maps here — a fresh process on a
        reused endpoint must not inherit its dead predecessor's OPEN state."""
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            self._open_until = 0.0
            if self._state != _STATE_CLOSED:
                self._state = _STATE_CLOSED
                self._m_state.set(0, endpoint=self.endpoint)


@dataclass
class ResiliencePolicy:
    """The shared policy container: one per process (``default_policy``)
    or one per test/bench scope.

    ``degrade_after_s``: how long the embedding router blocks-and-retries
    a dead shard before serving deterministic init-vector embeddings
    instead (``None`` = never degrade — fail like the pre-PR code).
    ``max_degraded_frac``: abort threshold — a lookup call (and the
    stream, per step) whose degraded fraction EXCEEDS this raises instead
    of silently training on mostly-synthetic embeddings."""

    retry: RetryPolicy = field(default_factory=RetryPolicy)
    breaker_failure_threshold: int = 5
    breaker_reset_s: float = 1.0
    degrade_after_s: Optional[float] = None
    max_degraded_frac: float = 1.0

    def __post_init__(self):
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()

    def breaker(self, endpoint: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(endpoint)
            if b is None:
                b = self._breakers[endpoint] = CircuitBreaker(
                    endpoint,
                    failure_threshold=self.breaker_failure_threshold,
                    reset_timeout_s=self.breaker_reset_s,
                )
            return b

    def reset_breaker(self, endpoint: str) -> None:
        """Forget the endpoint's breaker history — the elastic tier calls
        this when a FRESH process takes over an endpoint (standby promotion,
        restart on the original port): the predecessor's OPEN state would
        otherwise quarantine the healthy newcomer for a full reset window.
        A no-op when the endpoint has no breaker yet."""
        with self._lock:
            b = self._breakers.get(endpoint)
        if b is not None:
            prior = b.state
            b.reset()
            from persia_tpu import tracing

            tracing.record_event(
                "breaker.reset", endpoint=endpoint, prior_state=prior,
                trips=b.trips,
            )

    def breaker_states(self) -> Dict[str, str]:
        with self._lock:
            return {ep: b.state for ep, b in self._breakers.items()}

    def breaker_trips(self) -> Dict[str, int]:
        with self._lock:
            return {ep: b.trips for ep, b in self._breakers.items()}

    def backoff(self, attempt: int) -> float:
        return self.retry.backoff(attempt)

    def sleep_backoff(self, attempt: int, deadline: Optional[Deadline] = None) -> float:
        """THE sanctioned inter-attempt sleep: seeded-jitter backoff, capped
        by the remaining ``Deadline`` budget when one is in flight. Callers
        in ``service/``+``serving/`` must sleep through here (never a bare
        ``time.sleep``) so the RES lint rules can see every backoff and the
        chaos soak can replay it. Returns the seconds actually slept."""
        d = self.retry.backoff(attempt)
        if deadline is not None:
            d = min(d, max(deadline.remaining(), 0.0))
        if d > 0.0:
            time.sleep(d)
        return d


def poll_until(
    probe,
    timeout_s: float,
    policy: Optional[ResiliencePolicy] = None,
    what: str = "condition",
    swallow=(Exception,),
):
    """Policy-driven readiness poll — THE way to wait for a remote state.

    Calls ``probe()`` until it returns a truthy value (which is returned),
    swallowing ``swallow`` exceptions (pass ``()`` to fail fast on probe
    errors), sleeping the engine's seeded backoff between attempts with
    every sleep capped by the remaining :class:`Deadline` budget. Raises
    :class:`DeadlineExceeded` (a ``TimeoutError``) when the budget runs
    out. Replaces the hand-rolled ``while True: try/except/sleep`` loops
    persia-lint RES003/RES004 forbid outside this module."""
    pol = policy if policy is not None else default_policy()
    dl = Deadline(timeout_s)
    attempt = 0
    while True:
        try:
            val = probe()
            if val:
                return val
        except swallow:  # noqa: PERF203 — probe failures ARE the poll signal
            pass
        if dl.expired:
            raise DeadlineExceeded(
                f"timed out after {timeout_s:g}s waiting for {what}"
            )
        # cap backoff growth at attempt 8 (~policy max anyway) and by the
        # remaining budget so the final sleep never overshoots the deadline
        time.sleep(min(pol.backoff(min(attempt, 8)), max(dl.remaining(), 0.0)))
        attempt += 1


_DEFAULT: Optional[ResiliencePolicy] = None
_DEFAULT_LOCK = threading.Lock()


def default_policy() -> ResiliencePolicy:
    """Process-wide default policy (lazy). Clients constructed without an
    explicit policy share this one, so their breakers agree on endpoint
    health."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = ResiliencePolicy()
        return _DEFAULT

"""Minimal binary RPC over TCP.

Parity target: the reference's bespoke RPC crate (`others/persia-rpc/src/
lib.rs:68-145` — hyper HTTP POST + speedy bodies + optional lz4) and its
proc-macro-generated clients (`others/persia-rpc-macro`). Here: a
length-prefixed framed protocol over raw TCP with optional zlib compression,
a threaded server, and a reconnecting client. Python implementation is the
round-1 shell; the C++ data-plane equivalent slots under the same framing.

Frame:  u32 total_len | u8 flags | u16 method_len | method | payload
Reply:  u32 total_len | u8 status (0 ok, 1 app error) | payload
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
import zlib
from typing import Callable, Dict, Optional

from persia_tpu import diagnostics
from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.rpc")

_FLAG_COMPRESSED = 1
_SLOW_METHODS = frozenset({"dump", "load"})

_MAX_FRAME = 1 << 31  # 2 GiB sanity bound


class RpcError(RuntimeError):
    pass


def _is_transportish(e: BaseException) -> bool:
    """Transport failure, directly or relayed from a tier below."""
    if isinstance(e, RpcError):
        msg = str(e)
        return "remote error:" not in msg or "unavailable:" in msg
    return isinstance(e, (ConnectionError, TimeoutError, OSError))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        server: "RpcServer" = self.server.rpc_server  # type: ignore[attr-defined]
        try:
            while True:
                header = _recv_exact(sock, 4)
                (total,) = struct.unpack("<I", header)
                if total > _MAX_FRAME:
                    raise ConnectionError(f"oversized frame {total}")
                frame = _recv_exact(sock, total)
                flags = frame[0]
                (mlen,) = struct.unpack("<H", frame[1:3])
                method = frame[3 : 3 + mlen].decode()
                payload = frame[3 + mlen :]
                if flags & _FLAG_COMPRESSED:
                    payload = zlib.decompress(payload)
                fn = server.handlers.get(method)
                if fn is None:
                    reply, status = f"unknown method {method!r}".encode(), 1
                else:
                    try:
                        # stuck handlers show up in the stall detector's scan;
                        # checkpoint ops are legitimately slow (clients allow
                        # 3600s) so they get a matching threshold
                        slow = 3600.0 if method in _SLOW_METHODS else None
                        with diagnostics.inflight(f"rpc:{method}", stall_after_s=slow):
                            reply, status = fn(payload) or b"", 0
                    except Exception as e:  # noqa: BLE001 — app error crosses the wire
                        logger.exception("handler %s failed", method)
                        # a handler failing on a DOWNSTREAM transport error
                        # (this worker's PS died) is retryable for the
                        # caller — mark it so clients can classify, unlike
                        # genuine application errors which stay fatal
                        prefix = b"unavailable: " if _is_transportish(e) else b""
                        reply, status = prefix + repr(e).encode(), 1
                sock.sendall(struct.pack("<IB", len(reply) + 1, status) + reply)
                if method == "shutdown":
                    server.stop()
                    return
        except (ConnectionError, OSError):
            return


class _ThreadedTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class RpcServer:
    """Threaded RPC server: ``handlers[name] = fn(payload: bytes) -> bytes``.
    A built-in ``ping`` answers readiness probes; ``shutdown`` stops the
    server after replying (graceful shutdown, ref: hyper servers in
    bin/persia-embedding-worker.rs:70-78)."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        self.handlers: Dict[str, Callable[[bytes], bytes]] = {
            "ping": lambda p: b"pong",
            "shutdown": lambda p: b"ok",  # framing layer stops after replying
        }
        self._server = _ThreadedTCPServer((host, port), _Handler)
        self._server.rpc_server = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def register(self, name: str, fn: Callable[[bytes], bytes]) -> None:
        self.handlers[name] = fn

    def start(self) -> "RpcServer":
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        threading.Thread(target=self._server.shutdown, daemon=True).start()

    def serve_forever(self) -> None:
        self._server.serve_forever()


class RpcClient:
    """Pooled reconnecting client: up to ``pool_size`` concurrent in-flight
    calls per client, each on its own connection (the reference runs 8-10
    concurrent RPCs against each peer, forward.rs:640-779 — a single locked
    socket would serialize the worker's slot fan-out and the DataLoader's
    lookup workers into one in-flight request per server). Connections are
    created on demand, parked when idle, and dropped on transport errors;
    callers beyond ``pool_size`` wait for a free connection."""

    def __init__(
        self,
        addr: str,
        timeout_s: float = 60.0,
        compress_threshold: int = 1 << 20,
        retries: int = 3,
        pool_size: int = 8,
    ):
        host, port = addr.rsplit(":", 1)
        self.addr = (host, int(port))
        self.timeout_s = timeout_s
        self.compress_threshold = compress_threshold
        self.retries = retries
        self.pool_size = max(1, pool_size)
        self._idle: list = []
        self._total = 0
        self._gen = 0  # close() bumps: stale in-flight sockets die at checkin
        self._cond = threading.Condition()

    def _new_conn(self) -> socket.socket:
        s = socket.create_connection(self.addr, timeout=self.timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _checkout(self):
        with self._cond:
            while True:
                if self._idle:
                    return self._idle.pop(), self._gen
                if self._total < self.pool_size:
                    self._total += 1
                    gen = self._gen
                    break
                if not self._cond.wait(timeout=self.timeout_s):
                    raise RpcError(
                        f"no free connection to {self.addr} within {self.timeout_s}s"
                    )
        try:
            return self._new_conn(), gen
        except BaseException:
            with self._cond:
                self._total -= 1
                self._cond.notify()
            raise

    def _checkin(self, sock: socket.socket, gen: int, broken: bool = False) -> None:
        with self._cond:
            if broken or gen != self._gen:  # stale generation: close()d since
                self._total -= 1
                try:
                    sock.close()
                except OSError:
                    pass
            else:
                self._idle.append(sock)
            self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._gen += 1
            for s in self._idle:
                try:
                    s.close()
                except OSError:
                    pass
            self._total -= len(self._idle)
            self._idle.clear()
            self._cond.notify_all()

    def call(
        self,
        method: str,
        payload: bytes = b"",
        idempotent: bool = False,
        timeout_s: Optional[float] = None,
    ) -> bytes:
        """Invoke ``method``. Transport errors retry with exponential backoff
        ONLY for idempotent calls (ref concept: backoff-retry on NATS ops,
        core/nats.rs:162-180) — retrying a gradient update or dump after a
        dropped reply would double-apply it. ``timeout_s`` overrides the
        client default for long blocking operations (dump/load)."""
        last: Optional[Exception] = None
        attempts = self.retries if idempotent else 1
        for attempt in range(attempts):
            try:
                return self._call_once(method, payload, timeout_s)
            except (ConnectionError, OSError, socket.timeout) as e:
                last = e
                time.sleep(min(0.1 * 2**attempt, 2.0))
        raise RpcError(
            f"rpc {method} to {self.addr} failed"
            + (" after retries" if attempts > 1 else "")
        ) from last

    def _call_once(
        self, method: str, payload: bytes, timeout_s: Optional[float] = None
    ) -> bytes:
        flags = 0
        if len(payload) >= self.compress_threshold:
            payload = zlib.compress(payload, level=1)
            flags |= _FLAG_COMPRESSED
        m = method.encode()
        frame = struct.pack("<BH", flags, len(m)) + m + payload
        sock, gen = self._checkout()
        try:
            if timeout_s is not None:
                sock.settimeout(timeout_s)
            try:
                sock.sendall(struct.pack("<I", len(frame)) + frame)
                (total,) = struct.unpack("<I", _recv_exact(sock, 4))
                body = _recv_exact(sock, total)
            finally:
                if timeout_s is not None:
                    sock.settimeout(self.timeout_s)
        except BaseException:
            self._checkin(sock, gen, broken=True)
            raise
        self._checkin(sock, gen)
        status = body[0]
        reply = body[1:]
        if status != 0:
            raise RpcError(f"rpc {method}: remote error: {reply.decode(errors='replace')}")
        return reply

    def wait_ready(self, timeout_s: float = 60.0) -> None:
        deadline = time.time() + timeout_s
        while True:
            try:
                if self.call("ping") == b"pong":
                    return
            except RpcError:
                pass
            if time.time() > deadline:
                raise TimeoutError(f"service at {self.addr} not ready")
            time.sleep(0.2)

"""Minimal binary RPC over TCP.

Parity target: the reference's bespoke RPC crate (`others/persia-rpc/src/
lib.rs:68-145` — hyper HTTP POST + speedy bodies + optional lz4) and its
proc-macro-generated clients (`others/persia-rpc-macro`). Here: a
length-prefixed framed protocol over raw TCP with lz4-class compression
(``native/codec.cpp``; zlib fallback), scatter-gather sends (no payload
concatenation on the hot path), a threaded server, and a reconnecting
client.

Frame:  u32 total_len | u8 flags | u16 method_len | method | [trace] | payload
  flags bits 0-1: payload codec (0 none, 1 zlib, 2 lz4)
  flags bit 5:    trace-context header present (negotiated)
  flags bit 7:    client accepts compressed replies
  trace:          u8 len | "<trace_id>:<parent_span_id>" (ASCII)
Reply:  u32 total_len | u8 status | payload
  status low nibble: 0 ok, 1 app error; high nibble: payload codec
(Old peers only ever set/see bit 0 = zlib and a 0/1 status byte, so both
directions interoperate with round-1 processes. The trace header, like the
crc trailer, only goes on the wire to peers that advertise the capability.)
"""

from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
import time
import zlib
from typing import Callable, Dict, Optional, Sequence, Union

from persia_tpu import diagnostics, tracing
from persia_tpu.logger import get_default_logger
from persia_tpu.service import codec as _codec
from persia_tpu.service.resilience import (
    CircuitOpenError,
    Deadline,
    DeadlineExceeded,
    ResiliencePolicy,
    default_policy,
    poll_until,
)

logger = get_default_logger("persia_tpu.rpc")

_FLAG_CODEC_MASK = 0x03
_FLAG_TRACE = 0x20  # frame carries a trace-context header (negotiated)
_FLAG_CRC32 = 0x40  # payload carries a trailing crc32 (negotiated)
_FLAG_REPLY_COMPRESS_OK = 0x80
_STATUS_CRC = 0x08  # reply status bit: payload carries a trailing crc32
_SLOW_METHODS = frozenset({"dump", "load"})

_MAX_FRAME = 1 << 31  # 2 GiB sanity bound

Buffers = Union[bytes, Sequence]  # bytes | [bytes/memoryview, ...]


def _byte_views(bufs) -> list:
    """Byte-cast memoryviews (len() on a typed numpy ``.data`` view counts
    ELEMENTS, not bytes — every length computation below must see bytes)."""
    return [v for v in (memoryview(b).cast("B") for b in bufs) if len(v)]


def _send_buffers(sock: socket.socket, bufs) -> None:
    """Scatter-gather send: ship header + payload views without joining
    them into one bytes object first (the join doubles peak memory and
    copies multi-MB lookup replies once per call). ``bufs`` must already be
    byte views (``_byte_views``)."""
    bufs = list(bufs)
    while bufs:
        sent = sock.sendmsg(bufs)
        while bufs and sent >= len(bufs[0]):
            sent -= len(bufs[0])
            bufs.pop(0)
        if sent and bufs:
            bufs[0] = bufs[0][sent:]


def _flatten(payload: Buffers) -> bytes:
    if isinstance(payload, (bytes, bytearray, memoryview)):
        return bytes(payload)
    return b"".join(bytes(p) for p in payload)


def _caps_sum(caps: dict) -> str:
    """Self-checksum over the capability fields: the negotiation probe is
    the one exchange that CANNOT ride the negotiated integrity trailer
    (bootstrap), so the JSON carries its own crc — a damaged reply is
    re-probed instead of silently downgrading the connection."""
    import json

    canon = json.dumps(
        {k: caps[k] for k in sorted(caps) if k != "sum"}, sort_keys=True
    )
    return format(zlib.crc32(canon.encode()) & 0xFFFFFFFF, "08x")


def _capabilities_reply(_p: bytes = b"", crc: bool = False,
                        trace: bool = False) -> bytes:
    """Codec-negotiation probe: clients only send lz4 frames to peers that
    advertise it (round-1 peers answer 'unknown method' → zlib only), only
    send crc32-trailed frames to peers that advertise ``crc``, and only
    send trace-context headers to peers that advertise ``trace`` (the
    Python server parses both; the native C++ data plane parses neither,
    so it keeps the default codecs-only advertisement). Older clients
    ignore the extra fields and the ``sum`` field."""
    import json

    codecs = ["zlib"] + (["lz4"] if _codec.lz4_available() else [])
    caps = {"codecs": codecs}
    if crc:
        caps["integrity"] = ["crc32"]
    if trace:
        caps["trace"] = ["ctx1"]
    caps["sum"] = _caps_sum(caps)
    return json.dumps(caps).encode()


class RpcError(RuntimeError):
    pass


def _is_transportish(e: BaseException) -> bool:
    """Transport failure, directly or relayed from a tier below."""
    if isinstance(e, RpcError):
        msg = str(e)
        return "remote error:" not in msg or "unavailable:" in msg
    return isinstance(e, (ConnectionError, TimeoutError, OSError))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        server: "RpcServer" = self.server.rpc_server  # type: ignore[attr-defined]
        try:
            while True:
                header = _recv_exact(sock, 4)
                (total,) = struct.unpack("<I", header)
                if total > _MAX_FRAME:
                    raise ConnectionError(f"oversized frame {total}")
                frame = _recv_exact(sock, total)
                flags = frame[0]
                want_crc = bool(flags & _FLAG_CRC32)
                if want_crc:
                    # integrity trailer (negotiated via `capabilities`):
                    # covers the WHOLE frame after the length prefix
                    # (flags + method header + payload), verified BEFORE any
                    # parsing — a flipped method byte or length field is
                    # caught here, and the client sees a retryable
                    # "unavailable:" error instead of silent garbage
                    if (
                        len(frame) < 8
                        or zlib.crc32(frame[:-4])
                        != struct.unpack("<I", frame[-4:])[0]
                    ):
                        reply = b"unavailable: request frame crc mismatch"
                        sock.sendall(
                            struct.pack("<IB", len(reply) + 1, 1) + reply
                        )
                        continue
                    frame = frame[:-4]
                (mlen,) = struct.unpack("<H", frame[1:3])
                # errors="replace" keeps an (un-crc'd) corrupt method from
                # killing the handler thread — it resolves to unknown-method
                method = frame[3 : 3 + mlen].decode(errors="replace")
                off = 3 + mlen
                trace_blob = None
                if flags & _FLAG_TRACE and off < len(frame):
                    # negotiated trace-context header: "<trace_id>:<parent>"
                    tlen = frame[off]
                    trace_blob = frame[off + 1 : off + 1 + tlen].decode(
                        errors="replace"
                    )
                    off += 1 + tlen
                payload = frame[off:]
                codec_id = flags & _FLAG_CODEC_MASK
                if codec_id:
                    try:
                        payload = _codec.decompress_frame(codec_id, payload)
                    except Exception as e:  # noqa: BLE001 — e.g. no lz4 here
                        reply = f"unsupported codec {codec_id}: {e!r}".encode()
                        sock.sendall(
                            struct.pack("<IB", len(reply) + 1, 1) + reply
                        )
                        continue
                fn = server.handlers.get(method)
                if fn is None:
                    reply, status = f"unknown method {method!r}".encode(), 1
                else:
                    try:
                        # stuck handlers show up in the stall detector's scan;
                        # checkpoint ops are legitimately slow (clients allow
                        # 3600s) so they get a matching threshold
                        slow = 3600.0 if method in _SLOW_METHODS else None
                        with diagnostics.inflight(f"rpc:{method}", stall_after_s=slow):
                            if trace_blob is not None:
                                # adopt the caller's context for the handler's
                                # duration: spans it opens (and flight events
                                # it records) carry the caller's trace_id
                                tid, _, parent = trace_blob.partition(":")
                                with tracing.trace_context(tid, parent or None), \
                                        tracing.span(f"rpc.server.{method}"):
                                    reply, status = fn(payload) or b"", 0
                            else:
                                reply, status = fn(payload) or b"", 0
                    except Exception as e:  # noqa: BLE001 — app error crosses the wire
                        logger.exception("handler %s failed", method)
                        # a handler failing on a DOWNSTREAM transport error
                        # (this worker's PS died) is retryable for the
                        # caller — mark it so clients can classify, unlike
                        # genuine application errors which stay fatal
                        prefix = b"unavailable: " if _is_transportish(e) else b""
                        reply, status = prefix + repr(e).encode(), 1
                # handlers may return scatter-gather buffer lists (zero-copy
                # numpy views); compress large replies for peers that opted in
                rbufs = _byte_views(
                    [reply] if isinstance(reply, (bytes, bytearray, memoryview))
                    else reply
                )
                rlen = sum(len(b) for b in rbufs)
                if (
                    status == 0
                    and (flags & _FLAG_REPLY_COMPRESS_OK)
                    and rlen >= server.compress_threshold
                ):
                    # lz4-or-nothing: a zlib'd hot reply would cost more
                    # serving-thread time than the wire saves
                    cid, body = _codec.compress_frame(
                        _flatten(rbufs), allow_zlib=False
                    )
                    if cid and len(body) < rlen:  # incompressible stays raw
                        rbufs, rlen = [memoryview(body).cast("B")], len(body)
                        status |= cid << 4
                if want_crc:
                    # reply trailer covers status byte + payload
                    status |= _STATUS_CRC
                    crc = zlib.crc32(bytes([status]))
                    for b in rbufs:
                        crc = zlib.crc32(b, crc)
                    rbufs.append(memoryview(struct.pack("<I", crc)).cast("B"))
                    rlen += 4
                _send_buffers(
                    sock,
                    [memoryview(struct.pack("<IB", rlen + 1, status)).cast("B")]
                    + rbufs,
                )
                if method == "shutdown":
                    server.stop()
                    return
        except (ConnectionError, OSError):
            return


class _ThreadedTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class RpcServer:
    """Threaded RPC server: ``handlers[name] = fn(payload: bytes) -> bytes``.
    A built-in ``ping`` answers readiness probes; ``shutdown`` stops the
    server after replying (graceful shutdown, ref: hyper servers in
    bin/persia-embedding-worker.rs:70-78)."""

    def __init__(
        self, port: int = 0, host: str = "0.0.0.0",
        compress_threshold: int = 1 << 20,
    ):
        self.compress_threshold = compress_threshold
        self.handlers: Dict[str, Callable[[bytes], Buffers]] = {
            "ping": lambda p: b"pong",
            # codec + integrity + trace negotiation probe (this server
            # verifies crc and parses trace-context headers)
            "capabilities": lambda p: _capabilities_reply(p, crc=True, trace=True),
            "shutdown": lambda p: b"ok",  # framing layer stops after replying
        }
        self._server = _ThreadedTCPServer((host, port), _Handler)
        self._server.rpc_server = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def register(self, name: str, fn: Callable[[bytes], bytes]) -> None:
        self.handlers[name] = fn

    def start(self) -> "RpcServer":
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        threading.Thread(target=self._server.shutdown, daemon=True).start()

    def serve_forever(self) -> None:
        self._server.serve_forever()


class RpcClient:
    """Pooled reconnecting client: up to ``pool_size`` concurrent in-flight
    calls per client, each on its own connection (the reference runs 8-10
    concurrent RPCs against each peer, forward.rs:640-779 — a single locked
    socket would serialize the worker's slot fan-out and the DataLoader's
    lookup workers into one in-flight request per server). Connections are
    created on demand, parked when idle, and dropped on transport errors;
    callers beyond ``pool_size`` wait for a free connection."""

    def __init__(
        self,
        addr: str,
        timeout_s: float = 60.0,
        compress_threshold: int = 1 << 20,
        retries: int = 3,
        pool_size: int = 8,
        policy: Optional[ResiliencePolicy] = None,
        integrity: Optional[bool] = None,
    ):
        host, port = addr.rsplit(":", 1)
        self.addr = (host, int(port))
        self.endpoint = f"{host}:{int(port)}"
        self.timeout_s = timeout_s
        self.compress_threshold = compress_threshold
        self.retries = retries
        self.pool_size = max(1, pool_size)
        # resilience: backoff/jitter + the per-endpoint circuit breaker are
        # single-sourced in service/resilience.py (shared with the gateway
        # and the embedding router — no duplicated backoff logic)
        self.policy = policy if policy is not None else default_policy()
        # crc32 frame integrity (negotiated; env PERSIA_RPC_CRC=1 turns it
        # on process-wide — chaos runs flip it to catch corrupt frames)
        if integrity is None:
            integrity = os.environ.get("PERSIA_RPC_CRC", "0") == "1"
        self.integrity = bool(integrity)
        self._peer_lz4: Optional[bool] = None  # learned from `capabilities`
        self._peer_crc: Optional[bool] = None
        self._peer_trace: Optional[bool] = None
        self._idle: list = []
        self._total = 0
        self._gen = 0  # close() bumps: stale in-flight sockets die at checkin
        self._cond = threading.Condition()

    def _new_conn(self) -> socket.socket:
        s = socket.create_connection(self.addr, timeout=self.timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _checkout(self):
        with self._cond:
            while True:
                if self._idle:
                    return self._idle.pop(), self._gen
                if self._total < self.pool_size:
                    self._total += 1
                    gen = self._gen
                    break
                if not self._cond.wait(timeout=self.timeout_s):
                    raise RpcError(
                        f"no free connection to {self.addr} within {self.timeout_s}s"
                    )
        try:
            return self._new_conn(), gen
        except BaseException:
            with self._cond:
                self._total -= 1
                self._cond.notify()
            raise

    def _checkin(self, sock: socket.socket, gen: int, broken: bool = False) -> None:
        with self._cond:
            if broken or gen != self._gen:  # stale generation: close()d since
                self._total -= 1
                try:
                    sock.close()
                except OSError:
                    pass
            else:
                self._idle.append(sock)
            self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._gen += 1
            for s in self._idle:
                try:
                    s.close()
                except OSError:
                    pass
            self._total -= len(self._idle)
            self._idle.clear()
            self._cond.notify_all()

    def call(
        self,
        method: str,
        payload: Buffers = b"",
        idempotent: bool = False,
        timeout_s: Optional[float] = None,
        deadline: Optional[Deadline] = None,
    ) -> bytes:
        """Invoke ``method``. Transport errors retry with exponential backoff
        ONLY for idempotent calls (ref concept: backoff-retry on NATS ops,
        core/nats.rs:162-180) — retrying a gradient update or dump after a
        dropped reply would double-apply it. ``timeout_s`` overrides the
        client default for long blocking operations (dump/load).

        Resilience (service/resilience.py): backoff delays come from the
        shared :class:`RetryPolicy`; ``deadline`` caps every attempt's
        socket timeout AND every backoff sleep by the remaining budget;
        the endpoint's :class:`CircuitBreaker` fails calls fast while
        open (``ping`` is exempt — it IS the recovery probe, and its
        success re-closes the breaker)."""
        pol = self.policy
        breaker = pol.breaker(self.endpoint)
        probe = method == "ping"
        last: Optional[Exception] = None
        attempts = max(self.retries, 1) if idempotent else 1
        # the client-side hop span: no-op when tracing is disabled; when
        # enabled it opens (or extends) the ambient trace so _call_once can
        # ship the context to a trace-capable peer
        with tracing.span(f"rpc.client.{method}", endpoint=self.endpoint):
            return self._call_with_retries(
                method, payload, timeout_s, deadline,
                pol, breaker, probe, attempts, last,
            )

    def _call_with_retries(
        self, method, payload, timeout_s, deadline,
        pol, breaker, probe, attempts, last,
    ) -> bytes:
        for attempt in range(attempts):
            if deadline is not None:
                deadline.check(f"rpc {method}")
            if not probe and not breaker.allow():
                last = CircuitOpenError(
                    f"circuit open for {self.endpoint} (rpc {method})"
                )
            else:
                try:
                    reply = self._call_once(method, payload, timeout_s, deadline)
                    breaker.on_success()
                    return reply
                except DeadlineExceeded:
                    breaker.on_failure()
                    raise
                except (ConnectionError, OSError, socket.timeout) as e:
                    breaker.on_failure()
                    last = e
            if attempt + 1 < attempts:
                delay = pol.backoff(attempt)
                if deadline is not None:
                    delay = min(delay, max(deadline.remaining(), 0.0))
                time.sleep(delay)
        raise RpcError(
            f"rpc {method} to {self.addr} failed"
            + (" after retries" if attempts > 1 else "")
        ) from last

    def _call_once(
        self,
        method: str,
        payload: Buffers,
        timeout_s: Optional[float] = None,
        deadline: Optional[Deadline] = None,
    ) -> bytes:
        """``payload`` may be bytes or a list of buffers (scatter-gather:
        numpy views ship without a host-side join)."""
        # advertise compressed-reply support only when this process can
        # actually DECODE lz4 (replies are lz4-or-raw; see the server path)
        flags = _FLAG_REPLY_COMPRESS_OK if _codec.lz4_available() else 0
        bufs = _byte_views(
            [payload] if isinstance(payload, (bytes, bytearray, memoryview))
            else payload
        )
        plen = sum(len(b) for b in bufs)
        if method != "capabilities":
            if self.integrity and self._peer_crc is None:
                self._probe_peer_codecs()
                if self._peer_crc is None:
                    # the probe itself was damaged in transit: do NOT send
                    # an unprotected frame while the peer might support
                    # crc — surface a retryable transport error instead
                    raise ConnectionError(
                        "peer integrity capabilities unresolved"
                    )
            if plen >= self.compress_threshold:
                if self._peer_lz4 is None and _codec.lz4_available():
                    self._probe_peer_codecs()
                cid, body = _codec.compress_frame(
                    _flatten(bufs), prefer_lz4=bool(self._peer_lz4)
                )
                if len(body) < plen:  # incompressible payloads stay raw
                    bufs, plen = [memoryview(body).cast("B")], len(body)
                    flags |= cid
        want_crc = (
            self.integrity and self._peer_crc and method != "capabilities"
        )
        m = method.encode()
        trace_hdr = b""
        if method != "capabilities" and tracing.enabled():
            ctx = tracing.current_context()
            if ctx is not None:
                if self._peer_trace is None:
                    self._probe_peer_codecs()
                if self._peer_trace:
                    # negotiated trace-context header rides between the
                    # method name and the payload; best-effort (an
                    # undecided probe just skips it — unlike crc, a lost
                    # trace header costs visibility, not correctness)
                    blob = f"{ctx[0]}:{ctx[1] or ''}".encode()[:255]
                    trace_hdr = struct.pack("<B", len(blob)) + blob
                    flags |= _FLAG_TRACE
        if want_crc:
            # trailer covers the whole frame after the length prefix
            # (flags + method header + trace header + payload) so corruption
            # anywhere in the frame body is detectable server-side
            flags |= _FLAG_CRC32
            crc = zlib.crc32(struct.pack("<BH", flags, len(m)) + m + trace_hdr)
            for b in bufs:
                crc = zlib.crc32(b, crc)
            bufs = bufs + [memoryview(struct.pack("<I", crc)).cast("B")]
            plen += 4
        header = struct.pack(
            "<IBH", plen + 3 + len(m) + len(trace_hdr), flags, len(m)
        ) + m + trace_hdr
        eff_timeout = timeout_s
        if deadline is not None:
            eff_timeout = deadline.cap(
                timeout_s if timeout_s is not None else self.timeout_s
            )
        sock, gen = self._checkout()
        try:
            if eff_timeout is not None:
                sock.settimeout(eff_timeout)
            try:
                _send_buffers(sock, [memoryview(header).cast("B")] + bufs)
                (total,) = struct.unpack("<I", _recv_exact(sock, 4))
                body = _recv_exact(sock, total)
            finally:
                if eff_timeout is not None:
                    sock.settimeout(self.timeout_s)
        except BaseException:
            self._checkin(sock, gen, broken=True)
            raise
        self._checkin(sock, gen)
        status = body[0]
        reply = body[1:]
        codec_id = status >> 4
        status &= 0x0F
        if status & _STATUS_CRC:
            # reply integrity trailer covers status byte + payload
            if (
                len(body) < 5
                or zlib.crc32(body[:-4]) != struct.unpack("<I", body[-4:])[0]
            ):
                raise ConnectionError(
                    f"rpc {method}: reply frame crc mismatch"
                )
            reply = reply[:-4]
            status &= ~_STATUS_CRC
        if codec_id:
            reply = _codec.decompress_frame(codec_id, reply)
        if status != 0:
            if reply.startswith(b"unavailable: request frame crc"):
                # the server rejected a damaged frame: transport-class
                # failure — idempotent callers retry it like a reset
                raise ConnectionError(
                    f"rpc {method}: request frame corrupted in transit"
                )
            raise RpcError(f"rpc {method}: remote error: {reply.decode(errors='replace')}")
        return reply

    def _probe_peer_codecs(self) -> None:
        """One-shot `capabilities` probe before the first compressed (or
        crc-trailed) frame: lz4/crc32 go on the wire only to peers that
        advertise decoding them (round-1 peers answer 'unknown method' →
        zlib, no trailer; the native data plane advertises codecs only)."""
        import json

        try:
            caps = json.loads(self._call_once("capabilities", b""))
            if "sum" in caps and caps["sum"] != _caps_sum(caps):
                return  # damaged-in-transit caps: stay undecided, re-probe
            self._peer_lz4 = "lz4" in caps.get("codecs", [])
            self._peer_crc = "crc32" in caps.get("integrity", [])
            self._peer_trace = "ctx1" in caps.get("trace", [])
        except RpcError as e:
            # a legacy peer answers "unknown method 'capabilities'" — the
            # echoed method name is the tell. A CORRUPTED probe draws
            # "unknown method '<garbage>'" instead and must NOT latch the
            # legacy verdict (that would silently disable integrity off
            # one damaged frame).
            msg = str(e)
            if "unknown method 'capabilities'" in msg:
                self._peer_lz4 = False
                self._peer_crc = False
                self._peer_trace = False
        except Exception:  # noqa: BLE001 — transport/parse damage
            # the probe itself may have been corrupted or cut in transit:
            # leave the capabilities UNDECIDED so the next call re-probes,
            # instead of permanently disabling negotiation off one bad frame
            pass

    def wait_ready(self, timeout_s: float = 60.0) -> None:
        """Ping-poll on the shared engine (seeded backoff, Deadline-capped;
        pings are breaker-exempt so a half-open endpoint can re-close)."""
        poll_until(
            lambda: self.call("ping") == b"pong",
            timeout_s,
            policy=self.policy,
            what=f"service at {self.addr}",
            swallow=(RpcError,),
        )

"""Native (C++) RPC server binding — the parameter-server data plane.

Parity target: the reference's fully compiled remote path (hyper HTTP +
speedy bodies + lz4 over tokio, `others/persia-rpc/src/lib.rs:68-145`,
`persia-embedding-server/src/bin/*.rs`). ``NativeRpcServer`` owns the TCP
listener in C++ (`native/server.cpp`): the hot methods (``ping``,
``lookup_batched``, ``update_batched``) run frame-parse → dispatch → C++
store call → wire-dtype convert → writev reply entirely off the GIL;
every other registered method falls back to the Python handler table, so
the control plane (checkpoints, config, admin) is unchanged.

Drop-in for ``persia_tpu.service.rpc.RpcServer`` when the store is the
native ``NativeEmbeddingStore``; ``ParameterServerService`` picks it
automatically (opt out with ``PERSIA_NATIVE_SERVER=0``).
"""

from __future__ import annotations

import ctypes
import os
import threading
import zlib
from typing import Callable, Dict, Optional

from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.native_rpc")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRCS = [
    os.path.join(_REPO_ROOT, "native", "server.cpp"),
    os.path.join(_REPO_ROOT, "native", "codec.cpp"),
]
_SO = os.path.join(_REPO_ROOT, "native", "libpersia_net.so")
_PS_SO = os.path.join(_REPO_ROOT, "native", "libpersia_ps.so")
_PS_SO_PATH = _PS_SO  # resolved (variant-aware) by _load()

_FALLBACK_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ctypes.c_void_p,
)

_LIB: Optional[ctypes.CDLL] = None
_LOAD_FAILED = False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _LOAD_FAILED
    if _LIB is not None or _LOAD_FAILED:
        return _LIB
    try:
        from persia_tpu.embedding._native_build import build_so
        from persia_tpu.embedding.native_store import build_native as build_ps

        global _PS_SO_PATH
        # the server dlopens libpersia_ps.so for the store calls — under a
        # sanitizer that must be the matching VARIANT ps artifact (mixed
        # sanitized/unsanitized cores in one process would miss reports)
        _PS_SO_PATH = build_ps()
        # CDLL the path build_so RETURNS (sanitizer-variant aware)
        so_path = build_so(
            _SRCS, _SO,
            ["-O3", "-std=c++17", "-fPIC", "-shared", "-Wall", "-pthread", "-ldl"],
            logger,
        )
        lib = ctypes.CDLL(so_path)
        lib.net_server_start.restype = ctypes.c_void_p
        lib.net_server_start.argtypes = [
            ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p, _FALLBACK_CB,
            ctypes.c_int64,
        ]
        lib.net_server_port.restype = ctypes.c_int
        lib.net_server_port.argtypes = [ctypes.c_void_p]
        lib.net_server_stop.restype = None
        lib.net_server_stop.argtypes = [ctypes.c_void_p]
        lib.net_reply.restype = None
        lib.net_reply.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int64,
        ]
        _LIB = lib
    except Exception as e:  # noqa: BLE001 — toolchain-less host
        logger.warning("native rpc server unavailable (%r)", e)
        _LOAD_FAILED = True
    return _LIB


def native_server_available() -> bool:
    return _load() is not None


class NativeRpcServer:
    """RpcServer-shaped wrapper over the C++ listener. ``handlers`` serve
    the Python fallback path; the C++ side intercepts the hot methods and
    never consults them for lookup_batched/update_batched/ping."""

    def __init__(self, store, port: int = 0, compress_threshold: int = 1 << 20):
        lib = _load()
        if lib is None:
            raise RuntimeError("native rpc server unavailable")
        if not getattr(store, "_h", None):
            raise TypeError("NativeRpcServer requires a NativeEmbeddingStore")
        self._lib = lib
        from persia_tpu.service.rpc import _capabilities_reply

        self.handlers: Dict[str, Callable[[bytes], bytes]] = {
            "ping": lambda p: b"pong",
            "capabilities": _capabilities_reply,
            "shutdown": lambda p: b"ok",
        }
        self._stopped = threading.Event()

        # the ctypes callback object must outlive the server (C++ holds the
        # raw pointer)
        self._cb = _FALLBACK_CB(self._fallback)
        self._handle = lib.net_server_start(
            port, store._h, _PS_SO_PATH.encode(), self._cb, compress_threshold
        )
        if not self._handle:
            raise RuntimeError("net_server_start failed")
        self.port = lib.net_server_port(self._handle)
        self._thread: Optional[threading.Thread] = None

    # -------------------------------------------------------------- fallback

    def _fallback(self, method_b, payload_p, plen, reply_ctx) -> None:
        try:
            method = method_b.decode()
            payload = ctypes.string_at(payload_p, plen) if plen else b""
            if method.startswith("__zlib__:"):  # legacy zlib-compressed peer
                method = method[len("__zlib__:"):]
                payload = zlib.decompress(payload)
            fn = self.handlers.get(method)
            if fn is None:
                reply, status = f"unknown method {method!r}".encode(), 1
            else:
                try:
                    reply, status = fn(payload) or b"", 0
                except Exception as e:  # noqa: BLE001 — app error crosses the wire
                    logger.exception("handler %s failed", method)
                    from persia_tpu.service.rpc import _is_transportish

                    prefix = b"unavailable: " if _is_transportish(e) else b""
                    reply, status = prefix + repr(e).encode(), 1
            if not isinstance(reply, (bytes, bytearray)):
                # scatter-gather handler replies flatten here (control plane
                # only — the hot methods never reach Python)
                reply = b"".join(bytes(memoryview(b).cast("B")) for b in reply)
            self._lib.net_reply(reply_ctx, status, bytes(reply), len(reply))
            if method == "shutdown":
                self._stopped.set()
        except BaseException as e:  # noqa: BLE001 — never unwind into C++
            logger.exception("fallback dispatch failed")
            msg = repr(e).encode()
            self._lib.net_reply(reply_ctx, 1, msg, len(msg))

    # ------------------------------------------------------------- lifecycle

    def register(self, name: str, fn: Callable[[bytes], bytes]) -> None:
        self.handlers[name] = fn

    def start(self) -> "NativeRpcServer":
        # the C++ accept loop is already running; expose an RpcServer-shaped
        # joinable thread that parks until shutdown
        self._thread = threading.Thread(
            target=self._stopped.wait, daemon=True, name="native-rpc-park"
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._stopped.wait()

    def stop(self) -> None:
        self._stopped.set()
        h, self._handle = self._handle, None
        if h:
            self._lib.net_server_stop(h)

    def __del__(self):
        try:
            self.stop()
        except Exception:  # noqa: BLE001
            pass

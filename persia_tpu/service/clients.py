"""RPC client shims exposing the in-process store/worker surfaces.

``StoreClient`` quacks like an ``EmbeddingStore`` (used by an embedding
worker to reach remote parameter servers; ref: `EmbeddingParameterServiceClient`,
embedding_parameter_service/mod.rs:498-593). ``WorkerClient`` quacks like an
``EmbeddingWorker`` (used by TrainCtx/DataLoader on the NN worker; ref:
`EmbeddingWorkerClient`, embedding_worker_service/mod.rs:1379-1491)."""

from __future__ import annotations

import struct
from typing import Dict, Optional

import numpy as np

from persia_tpu.config import HyperParameters
from persia_tpu.data import PersiaBatch
from persia_tpu.embedding.optim import OptimizerConfig
from persia_tpu.service import proto
from persia_tpu.service import resilience
from persia_tpu.service.resilience import Deadline, ResiliencePolicy
from persia_tpu.service.rpc import RpcClient


class StoreClient:
    """Parameter-server RPC client with the EmbeddingStore surface.

    ``wire_dtype`` ("float16"/"bfloat16") halves the batched lookup/update
    wire exactly like the reference's f16 embedding/gradient wire
    (persia-common/src/lib.rs:157-180); default float32 keeps the
    determinism oracle bit-exact.

    ``policy`` is the shared :class:`ResiliencePolicy` (backoff, breaker,
    degraded knobs — service/resilience.py); ``deadline_s`` is an optional
    per-data-plane-call time budget propagated into ``RpcClient.call`` so
    a wedged shard bounds the caller's wait instead of stacking socket
    timeouts."""

    def __init__(
        self, addr: str, timeout_s: float = 120.0,
        wire_dtype: Optional[str] = None,
        policy: Optional[ResiliencePolicy] = None,
        deadline_s: Optional[float] = None,
    ):
        self.addr = addr
        self.wire_dtype = None if wire_dtype == "float32" else wire_dtype
        self.deadline_s = deadline_s
        self._rpc = RpcClient(addr, timeout_s=timeout_s, policy=policy)

    @property
    def policy(self) -> ResiliencePolicy:
        return self._rpc.policy

    @property
    def endpoint(self) -> str:
        return self._rpc.endpoint

    def _deadline(self) -> Optional[Deadline]:
        return None if self.deadline_s is None else Deadline.after(self.deadline_s)

    def wait_ready(self, timeout_s: float = 60.0) -> None:
        self._rpc.wait_ready(timeout_s)

    def lookup_batched(self, signs: np.ndarray, key_ofs: np.ndarray,
                       dims: np.ndarray, train: bool) -> np.ndarray:
        """Multi-slot lookup: ONE rpc per batch (the router's grouped
        fan-out lands here; ref lookup_batched_all_slots)."""
        raw = self._rpc.call(
            "lookup_batched",
            proto.pack_lookup_batched_request(
                signs, key_ofs, dims, train, reply_dtype=self.wire_dtype
            ),
            idempotent=True,  # same retry-safety argument as lookup
            deadline=self._deadline(),
        )
        return proto.unpack_lookup_batched_reply(
            raw, proto.wire_dtype_code(self.wire_dtype)
        )

    def update_batched(self, signs: np.ndarray, key_ofs: np.ndarray,
                       dims: np.ndarray, grads, opt_groups: np.ndarray) -> None:
        """Multi-slot gradient update: ONE rpc per gradient batch."""
        self._rpc.call(
            "update_batched",
            proto.pack_update_batched_request(
                signs, key_ofs, dims, grads, opt_groups,
                wire_dtype=self.wire_dtype,
            ),
        )

    def update_batched_journaled(
        self, journal_id: int, crc: int, signs: np.ndarray, key_ofs: np.ndarray,
        dims: np.ndarray, grads, opt_groups: np.ndarray,
    ) -> bool:
        """Exactly-once multi-slot gradient update: the PS's bounded
        apply-journal (persia_tpu.jobstate) dedupes on (id, crc), which
        ALSO makes the call idempotent-retryable — a dropped reply re-sent
        cannot double-apply. Returns True when applied, False on a
        duplicate (a resumed trainer replaying an already-applied step)."""
        raw = self._rpc.call(
            "update_journaled",
            proto.pack_update_journaled_request(
                journal_id, crc, signs, key_ofs, dims, grads, opt_groups,
                wire_dtype=self.wire_dtype,
            ),
            idempotent=True,
        )
        return raw == b"\x01"

    def journal_probe(self, journal_id: int, crc: int) -> int:
        raw = self._rpc.call(
            "journal_probe", struct.pack("<QI", journal_id, crc & 0xFFFFFFFF),
            idempotent=True,
        )
        return struct.unpack("<b", raw)[0]

    def journal_len(self) -> int:
        return struct.unpack(
            "<q", self._rpc.call("journal_len", idempotent=True)
        )[0]

    def journal_clear(self) -> None:
        self._rpc.call("journal_clear")

    def scan_nonfinite(self, cap: int = 65536):
        """Health scrub (persia_tpu/health): ask the PS to repair its
        NaN/Inf rows to the seeded init. NOT idempotent for retry
        purposes at the journal level — the journaled exactly-once wrapper
        (``health.scrub.scrub_store``) probes before calling — but the
        repair itself is convergent (a re-scan finds nothing), so the
        transport may retry it safely."""
        raw = self._rpc.call(
            "scan_nonfinite", struct.pack("<q", int(cap)), idempotent=True,
            timeout_s=120.0,
        )
        repaired = struct.unpack("<q", raw[:8])[0]
        signs = np.frombuffer(raw[8:], dtype=np.uint64).copy()
        return int(repaired), signs

    def lookup(self, signs: np.ndarray, dim: int, train: bool) -> np.ndarray:
        # train lookups mutate (LRU/admit) but are retry-safe: re-running a
        # lookup converges to the same entries, so idempotent for RPC purposes
        raw = self._rpc.call(
            "lookup", proto.pack_lookup_request(signs, dim, train),
            idempotent=True, deadline=self._deadline(),
        )
        return np.frombuffer(raw, dtype=np.float32).reshape(len(signs), dim).copy()

    def checkout_entries(self, signs: np.ndarray, dim: int) -> np.ndarray:
        """Full [emb | state] rows for the HBM cache tier. Misses are admitted
        with seeded init (retry-safe: re-running converges to the same rows)."""
        raw = self._rpc.call(
            "checkout_entries",
            proto.pack_lookup_request(signs, dim, True),
            idempotent=True, deadline=self._deadline(),
        )
        n = max(len(signs), 1)
        width = len(raw) // (4 * n) if len(signs) else dim
        return np.frombuffer(raw, dtype=np.float32).reshape(len(signs), width).copy()

    def probe_entries(self, signs: np.ndarray, dim: int):
        """Warm/cold split (no admission) for the HBM cache tier."""
        raw = self._rpc.call(
            "probe_entries",
            proto.pack_lookup_request(signs, dim, True),
            idempotent=True, deadline=self._deadline(),
        )
        n = len(signs)
        warm = np.frombuffer(raw[:n], dtype=np.uint8).astype(bool)
        vals = np.frombuffer(raw[n:], dtype=np.float32)
        width = vals.size // n if n else dim
        return warm, vals.reshape(n, width).copy()

    def update_gradients(self, signs: np.ndarray, grads: np.ndarray, group: int = 0) -> None:
        self._rpc.call("update_gradients", proto.pack_update_request(signs, grads, group))

    def advance_batch_state(self, group: int) -> None:
        self._rpc.call("advance_batch_state", struct.pack("<i", group))

    def register_optimizer(self, optimizer: OptimizerConfig) -> None:
        self._rpc.call("register_optimizer", proto.pack_json(optimizer.to_dict()))

    def get_optimizer(self) -> Optional[OptimizerConfig]:
        d = proto.unpack_json(self._rpc.call("get_optimizer", idempotent=True))
        return OptimizerConfig.from_dict(d) if d else None

    def configure(self, hyperparams: HyperParameters) -> None:
        self._rpc.call(
            "configure",
            proto.pack_json(hyperparams.to_dict()),
        )

    def set_embedding(
        self, signs: np.ndarray, values: np.ndarray, dim: Optional[int] = None,
        commit_incremental: bool = False,
    ) -> None:
        if dim is None:
            dim = values.shape[1]
        # a raw full-entry put is idempotent: replaying after a dropped
        # reply lands the same rows (a duplicate incremental commit is a
        # same-value upsert), so write-backs survive mid-frame resets
        if commit_incremental:
            self._rpc.call(
                "set_embedding_v2",
                proto.pack_set_embedding_v2(signs, values, dim, True),
                idempotent=True,
            )
        else:  # legacy wire: interoperates with older servers
            self._rpc.call(
                "set_embedding", proto.pack_set_embedding(signs, values, dim),
                idempotent=True,
            )

    def get_embedding_entry(self, sign: int) -> Optional[np.ndarray]:
        raw = self._rpc.call("get_entry", struct.pack("<Q", sign), idempotent=True)
        if not raw:
            return None
        return np.frombuffer(raw, dtype=np.float32).copy()

    def size(self) -> int:
        return struct.unpack("<q", self._rpc.call("size", idempotent=True))[0]

    def clear(self) -> None:
        self._rpc.call("clear")

    def dump_shard(self, shard_idx: int) -> bytes:
        return self._rpc.call(
            "dump_shard", struct.pack("<I", shard_idx), idempotent=True, timeout_s=600.0
        )

    def load_shard_bytes(self, raw: bytes) -> int:
        return struct.unpack("<q", self._rpc.call("load_shard", raw))[0]

    # elastic handoff --------------------------------------------------------

    def export_range(self, lo: int, hi: int) -> bytes:
        """Hash-range export [lo, hi) (hi == 0 = 2^64), sorted by sign —
        read-only and deterministic, so retries and resumed handoffs carry
        the same payload crc."""
        return self._rpc.call(
            "export_range", struct.pack("<QQ", lo, hi),
            idempotent=True, timeout_s=600.0,
        )

    def import_range_journaled(self, journal_id: int, crc: int, blob: bytes) -> bool:
        """Exactly-once range import on the destination PS; journal-deduped,
        so a dropped reply re-sent cannot double-import. True when applied."""
        raw = self._rpc.call(
            "import_range_journaled",
            struct.pack("<QI", journal_id, crc & 0xFFFFFFFF) + blob,
            idempotent=True, timeout_s=600.0,
        )
        return raw == b"\x01"

    def delete_range_journaled(self, journal_id: int, crc: int, lo: int, hi: int):
        """Exactly-once source-side range release. Returns (applied, removed)."""
        raw = self._rpc.call(
            "delete_range_journaled",
            struct.pack("<QIQQ", journal_id, crc & 0xFFFFFFFF, lo, hi),
            idempotent=True, timeout_s=600.0,
        )
        applied, removed = struct.unpack("<bq", raw)
        return bool(applied), int(removed)

    @property
    def num_internal_shards(self) -> int:
        return struct.unpack("<I", self._rpc.call("num_shards"))[0]

    def dump_to_dir(
        self, path: str, blocking: bool = True, session: Optional[str] = None
    ) -> None:
        self._rpc.call(
            "dump_to_dir",
            proto.pack_json({"path": path, "blocking": blocking, "session": session}),
            timeout_s=3600.0,
        )

    def load_from_dir(self, path: str) -> int:
        return struct.unpack(
            "<q", self._rpc.call("load_from_dir", path.encode(), timeout_s=3600.0)
        )[0]

    def model_manager_status(self) -> Dict:
        return proto.unpack_json(self._rpc.call("model_manager_status", idempotent=True))

    def replica_info(self) -> Dict:
        """Replica identity + the store backend actually serving it
        (``native`` / ``numpy``) — the one-native-data-path fleet probe."""
        return proto.unpack_json(self._rpc.call("replica_info", idempotent=True))

    def healthz(self) -> Dict:
        """Liveness + store-backend metadata (mirrors the serving-plane
        /healthz shape)."""
        return proto.unpack_json(self._rpc.call("healthz", idempotent=True))

    def shutdown(self) -> None:
        try:
            self._rpc.call("shutdown")
        except Exception:
            pass
        self._rpc.close()


class WorkerClient:
    """Embedding-worker RPC client with the EmbeddingWorker surface used by
    TrainCtx / DataLoader / DataCtx."""

    def __init__(
        self, addr: str, timeout_s: float = 120.0,
        policy: Optional[ResiliencePolicy] = None,
    ):
        self.addr = addr
        self._rpc = RpcClient(addr, timeout_s=timeout_s, policy=policy)

    @property
    def policy(self) -> ResiliencePolicy:
        return self._rpc.policy

    @property
    def endpoint(self) -> str:
        return self._rpc.endpoint

    def wait_ready(self, timeout_s: float = 60.0) -> None:
        self._rpc.wait_ready(timeout_s)

    def can_forward_batched(self) -> bool:
        return self._rpc.call("can_forward_batched", idempotent=True) == b"1"

    def wait_serving(self, timeout_s: float = 60.0) -> None:
        """Block until the worker reports its whole PS tier ready (ref:
        wait_for_serving polling, core/rpc.rs:118-241). Policy-driven poll:
        seeded backoff, Deadline-capped, shared breaker state."""
        resilience.poll_until(
            lambda: self._rpc.call("ready_for_serving", idempotent=True) == b"1",
            timeout_s,
            policy=self._rpc.policy,
            what="embedding worker's PS tier serving",
        )

    def put_forward_ids(self, batch: PersiaBatch) -> int:
        return struct.unpack("<q", self._rpc.call("forward_batched", batch.to_bytes()))[0]

    def forward_batch_id(self, ref: int, train: bool = True):
        raw = self._rpc.call("forward_batch_id", struct.pack("<qB", ref, int(train)))  # takes the buffer entry: NOT retryable
        return proto.unpack_emb_batches(raw)

    def forward_directly(self, batch: PersiaBatch, train: bool = False):
        raw = self._rpc.call(
            "forward_directly", struct.pack("<B", int(train)) + batch.to_bytes()
        )
        return proto.unpack_emb_batches(raw)

    def update_gradient_batched(
        self, ref: int, slot_grads: Dict[str, np.ndarray],
        scale_factor: float = 1.0, journal_id=None,
    ) -> Dict[str, int]:
        if journal_id is not None:
            # the remote worker tier does not carry the apply-journal wire
            # yet; failing loudly beats silently downgrading exactly-once
            # resume to at-least-once
            raise NotImplementedError(
                "journaled gradient returns require an in-process "
                "EmbeddingWorker (the worker-server RPC wire has no journal "
                "frame yet) — run the trainer direct-to-PS for exactly-once "
                "resume"
            )
        raw = self._rpc.call(
            "update_gradient_batched",
            struct.pack("<q", ref) + proto.pack_slot_grads(slot_grads, scale_factor),
        )
        return proto.unpack_json(raw)

    def abort_gradient(self, ref: int) -> None:
        self._rpc.call("abort_gradient", struct.pack("<q", ref))

    def register_optimizer(self, optimizer: OptimizerConfig) -> None:
        self._rpc.call("register_optimizer", proto.pack_json(optimizer.to_dict()))

    def configure(self, hyperparams: HyperParameters) -> None:
        self._rpc.call(
            "configure",
            proto.pack_json(hyperparams.to_dict()),
        )

    @property
    def staleness(self) -> int:
        return struct.unpack("<q", self._rpc.call("staleness", idempotent=True))[0]

    def dump(self, path: str, blocking: bool = True) -> None:
        self._rpc.call(
            "dump", proto.pack_json({"path": path, "blocking": blocking}),
            timeout_s=3600.0,
        )

    def load(self, path: str) -> int:
        return struct.unpack("<q", self._rpc.call("load", path.encode(), timeout_s=3600.0))[0]

    def shutdown(self, shutdown_servers: bool = False) -> None:
        try:
            if shutdown_servers:
                self._rpc.call("shutdown_servers")
            self._rpc.call("shutdown")
        except Exception:
            pass
        self._rpc.close()

"""Coordinator: the control plane replacing NATS.

The reference discovers services through NATS subjects with exponential
backoff (`rust/persia-core/src/nats.rs:156-216`, `others/persia-nats-client`)
and publishes the DDP master address through `MasterDiscoveryService`
(nats.rs:22-100). Here one tiny RPC service does registration, listing,
readiness barriers, and small key-value payloads (e.g. the optimizer config
pushed at context entry, replacing `publish_register_optimizer`)."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from persia_tpu.service import proto
from persia_tpu.service import resilience
from persia_tpu.service.rpc import RpcClient, RpcServer


class Coordinator:
    """In-process coordinator service (run it inside any long-lived process,
    typically the launcher or rank-0 trainer)."""

    def __init__(self, port: int = 0):
        self._registry: Dict[str, Dict[int, str]] = {}  # role -> index -> addr
        self._kv: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        self.server = RpcServer(port=port)
        self.server.register("register", self._register)
        self.server.register("deregister", self._deregister)
        self.server.register("list", self._list)
        self.server.register("kv_put", self._kv_put)
        self.server.register("kv_get", self._kv_get)
        self.server.register("kv_keys", self._kv_keys)
        self.port = self.server.port

    def start(self) -> "Coordinator":
        self.server.start()
        return self

    def stop(self) -> None:
        self.server.stop()

    def _register(self, payload: bytes) -> bytes:
        req = proto.unpack_json(payload)
        with self._lock:
            self._registry.setdefault(req["role"], {})[int(req["index"])] = req["addr"]
        return b"ok"

    def _deregister(self, payload: bytes) -> bytes:
        # the elastic tier's shrink path: a removed PS replica leaves the
        # registry so late joiners don't resolve a drained endpoint
        req = proto.unpack_json(payload)
        with self._lock:
            self._registry.get(req["role"], {}).pop(int(req["index"]), None)
        return b"ok"

    def _list(self, payload: bytes) -> bytes:
        role = payload.decode()
        with self._lock:
            members = self._registry.get(role, {})
            return proto.pack_json(
                [members[i] for i in sorted(members)]
            )

    def _kv_put(self, payload: bytes) -> bytes:
        req = proto.unpack_json(payload[: payload.index(b"\x00")])
        value = payload[payload.index(b"\x00") + 1 :]
        with self._lock:
            self._kv[req["key"]] = value
        return b"ok"

    def _kv_get(self, payload: bytes) -> bytes:
        with self._lock:
            return self._kv.get(payload.decode(), b"")

    def _kv_keys(self, payload: bytes) -> bytes:
        # prefix listing for the failure detector's lease scan: one RPC
        # returns every ``lease/...`` key instead of N point reads
        prefix = payload.decode()
        with self._lock:
            return proto.pack_json(
                sorted(k for k in self._kv if k.startswith(prefix))
            )


class CoordinatorClient:
    def __init__(self, addr: str, timeout_s: float = 30.0):
        self._client = RpcClient(addr, timeout_s=timeout_s)

    def register(self, role: str, index: int, addr: str) -> None:
        # registration is a keyed upsert → safe to retry
        self._client.call(
            "register",
            proto.pack_json({"role": role, "index": index, "addr": addr}),
            idempotent=True,
        )

    def deregister(self, role: str, index: int) -> None:
        # keyed delete → safe to retry (elastic shrink removes the replica)
        self._client.call(
            "deregister", proto.pack_json({"role": role, "index": index}),
            idempotent=True,
        )

    def list(self, role: str) -> List[str]:
        return proto.unpack_json(self._client.call("list", role.encode(), idempotent=True))

    def wait_for(self, role: str, count: int, timeout_s: float = 120.0) -> List[str]:
        """Readiness barrier on the shared engine (ref: nats.rs:162-216).
        Probe errors are NOT swallowed — a dead coordinator should fail
        fast, only a short registry is worth waiting out."""
        have: List[str] = []

        def _probe():
            have[:] = self.list(role)
            # boxed: poll_until succeeds on TRUTHY values, and a satisfied
            # count==0 barrier (worker-less topologies, e.g. the cached
            # tier's trainer-direct-to-PS chaos runs) yields an EMPTY list
            # — unboxed it would poll until the deadline and fail
            return [list(have)] if len(have) >= count else None

        try:
            return resilience.poll_until(
                _probe, timeout_s, what=f"{count} {role!r} registrations",
                swallow=(),
            )[0]
        except resilience.DeadlineExceeded:
            raise TimeoutError(
                f"waited {timeout_s}s for {count} {role!r}, have {len(have)}"
            ) from None

    def kv_put(self, key: str, value: bytes) -> None:
        self._client.call("kv_put", proto.pack_json({"key": key}) + b"\x00" + value)

    def kv_get(self, key: str) -> bytes:
        return self._client.call("kv_get", key.encode(), idempotent=True)

    def kv_keys(self, prefix: str) -> List[str]:
        return proto.unpack_json(
            self._client.call("kv_keys", prefix.encode(), idempotent=True)
        )

    def close(self):
        self._client.close()

"""Micro-batching engine: coalesce concurrent predict calls into one forward.

The single-replica server paid one jitted forward + one PS lookup round per
request. Under concurrent load almost all of that is per-dispatch overhead:
the same sparsity skew that makes PERSIA's LRU parameter servers work means
a coalesced batch shares lookups, and XLA's cost per row collapses once
rows share a program. The batcher turns N in-flight HTTP requests into one
``PersiaBatch`` forward and slices the scores back per request.

Admission control is explicit, not emergent:

- the queue is bounded (``queue_depth``); a full queue rejects immediately
  with :class:`QueueFullError` — the HTTP layer maps it to 429 so load
  sheds at the door instead of growing latency without bound;
- every request carries a deadline; requests that expire while queued are
  dropped (:class:`DeadlineExceededError` → 504) rather than wasting a
  forward on an answer nobody is waiting for;
- a forming batch closes at ``max_batch`` rows or ``max_wait_ms``,
  whichever first — the knob pair trades tail latency against coalescing.

Merged batches optionally pad to a power-of-two row bucket so jit sees a
bounded set of shapes instead of one program per concurrency level.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from persia_tpu import tracing
from persia_tpu.data import IDTypeFeature, NonIDTypeFeature, PersiaBatch
from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics
from persia_tpu.utils import round_up_pow2

logger = get_default_logger("persia_tpu.serving.batcher")


class QueueFullError(RuntimeError):
    """Admission queue saturated — shed load (HTTP 429)."""


class DeadlineExceededError(RuntimeError):
    """Request expired before a forward could answer it (HTTP 504)."""


def merge_batches(
    batches: Sequence[PersiaBatch], pad_to: int = 0
) -> Tuple[PersiaBatch, List[int]]:
    """Concatenate request batches into one forward batch.

    All batches must carry the same id-slot names (same model contract) and
    the same dense-feature count. Returns ``(merged, offsets)`` where
    ``offsets[i]:offsets[i+1]`` are request i's rows in the merged scores.
    ``pad_to`` > total rows appends empty-id / zero-dense samples (their
    scores are sliced off; pooled empty-id lookups contribute zero rows).
    """
    offsets = [0]
    for b in batches:
        offsets.append(offsets[-1] + b.batch_size)
    total = offsets[-1]
    pad = max(0, pad_to - total)
    if len(batches) == 1 and pad == 0:
        return batches[0], offsets

    first = batches[0]
    names = [f.name for f in first.id_type_features]
    merged_ids: List[IDTypeFeature] = []
    pad_counts = np.zeros(pad, dtype=np.int64)  # padded samples carry no ids
    for pos, name in enumerate(names):
        # merge in CSR form (flat ids + counts): IDTypeFeature's canonical
        # layout, so the merge is K concatenates instead of per-sample list
        # walks — this runs on the batcher's serial hot path
        flats: List[np.ndarray] = []
        counts: List[np.ndarray] = []
        for b in batches:
            f = b.id_type_features[pos]
            if f.name != name:
                raise ValueError(
                    f"cannot merge: slot order mismatch ({f.name!r} != {name!r})"
                )
            fl, ct = f.flat_counts()
            flats.append(fl)
            counts.append(ct)
        if pad:
            counts.append(pad_counts)
        merged_ids.append(IDTypeFeature.from_flat(
            name,
            np.concatenate(flats) if flats else np.empty(0, np.uint64),
            np.concatenate(counts),
        ))

    merged_dense: List[NonIDTypeFeature] = []
    for pos, nf in enumerate(first.non_id_type_features):
        arrs = [b.non_id_type_features[pos].data for b in batches]
        if pad:
            arrs.append(np.zeros((pad,) + arrs[0].shape[1:], dtype=arrs[0].dtype))
        merged_dense.append(NonIDTypeFeature(np.concatenate(arrs), name=nf.name))

    return (
        PersiaBatch(merged_ids, non_id_type_features=merged_dense,
                    requires_grad=False),
        offsets,
    )


class _Pending:
    __slots__ = ("batch", "deadline", "event", "result", "error", "ctx",
                 "t_submit")

    def __init__(self, batch: PersiaBatch, deadline: float):
        self.batch = batch
        self.deadline = deadline
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        # the submitter's trace context crosses to the forward thread with
        # the request (thread-locals don't): the coalesced forward adopts
        # the lead request's context so engine spans carry its trace_id
        self.ctx = tracing.current_context()
        self.t_submit = time.monotonic()


class MicroBatcher:
    """Bounded-queue request coalescer around a ``predict_fn(batch)``.

    ``predict_fn`` runs on the batcher's single forward thread — the jitted
    eval path is serialized by construction, so the engine never sees two
    concurrent forwards fighting over the dispatch path.
    """

    def __init__(
        self,
        predict_fn: Callable[[PersiaBatch], np.ndarray],
        max_batch: int = 256,
        max_wait_ms: float = 2.0,
        queue_depth: int = 256,
        default_deadline_s: float = 30.0,
        forward_grace_s: float = 10.0,
        pad_buckets: bool = True,
    ):
        self._predict = predict_fn
        self.max_batch = max(1, int(max_batch))
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self.queue_depth = max(1, int(queue_depth))
        self.default_deadline_s = default_deadline_s
        # a request popped just before its deadline still gets its forward's
        # answer: the submitter waits deadline + grace before giving up
        self.forward_grace_s = forward_grace_s
        self.pad_buckets = pad_buckets
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        m = get_metrics()
        self._m_batch_rows = m.histogram(
            "persia_tpu_serving_batch_rows", "rows per coalesced forward",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
        )
        self._m_requests = m.counter(
            "persia_tpu_serving_requests", "predict requests admitted"
        )
        self._m_shed = m.counter(
            "persia_tpu_serving_shed", "requests rejected on a full queue (429)"
        )
        self._m_expired = m.counter(
            "persia_tpu_serving_deadline_expired", "requests expired before answer (504)"
        )
        self._m_depth = m.gauge(
            "persia_tpu_serving_queue_depth", "admission queue depth"
        )
        self._m_queue_wait = m.histogram(
            "persia_tpu_serving_queue_wait_seconds",
            "per-request wait from submit to coalesced forward start "
            "(the replica-side queue hop of the latency attribution)",
        )

    # ------------------------------------------------------------ client side

    def submit(self, batch: PersiaBatch, deadline_s: Optional[float] = None) -> np.ndarray:
        """Blocking: enqueue, wait for the coalesced forward, return this
        request's score rows. Raises :class:`QueueFullError` /
        :class:`DeadlineExceededError` per the admission rules above."""
        budget = self.default_deadline_s if deadline_s is None else float(deadline_s)
        p = _Pending(batch, time.monotonic() + budget)
        with self._cond:
            if self._stop:
                raise RuntimeError("batcher is stopped")
            if len(self._q) >= self.queue_depth:
                self._m_shed.inc()
                raise QueueFullError(
                    f"admission queue full ({self.queue_depth} requests)"
                )
            self._q.append(p)
            self._m_depth.set(len(self._q))
            self._cond.notify()
        self._m_requests.inc()
        if not p.event.wait(budget + self.forward_grace_s):
            p.error = p.error or DeadlineExceededError(
                f"no answer within {budget + self.forward_grace_s:.3f}s"
            )
        if p.error is not None:
            if isinstance(p.error, DeadlineExceededError):
                self._m_expired.inc()
            raise p.error
        return p.result

    # ----------------------------------------------------------- worker side

    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="serving-batcher"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # fail anything still queued so no submitter hangs out its full grace
        with self._cond:
            leftovers, self._q = list(self._q), deque()
        for p in leftovers:
            self._finish_error(p, RuntimeError("batcher stopped"))

    def _finish_error(self, p: _Pending, e: BaseException) -> None:
        p.error = e
        p.event.set()

    def _collect_group(self, first: _Pending) -> List[_Pending]:
        """Gather more requests until max_batch rows or max_wait closes the
        window. Oversized requests never split; a request that would overflow
        the row budget closes the batch and stays queued. The queue drains in
        bulk under one lock acquire — per-request lock ping-pong with 32+
        submitter threads was measurable on the serial collection path."""
        group = [first]
        rows = first.batch.batch_size
        close = time.monotonic() + self.max_wait_s
        while rows < self.max_batch:
            with self._cond:
                if not self._q:
                    remaining = close - time.monotonic()
                    if remaining <= 0 or self._stop:
                        break
                    self._cond.wait(remaining)
                    if not self._q:
                        break
                while self._q:
                    nxt = self._q[0]
                    if rows + nxt.batch.batch_size > self.max_batch:
                        self._m_depth.set(len(self._q))
                        return group
                    self._q.popleft()
                    group.append(nxt)
                    rows += nxt.batch.batch_size
                self._m_depth.set(len(self._q))
        return group

    def _forward(self, live: List[_Pending], merged: PersiaBatch):
        """Run the coalesced forward under the lead request's trace context
        (if any): the engine's span — and anything beneath it — carries
        that request's trace_id, and the batch span lists every coalesced
        trace id so no request is unfindable in the merged timeline."""
        lead = next((p.ctx for p in live if p.ctx is not None), None)
        if lead is None or not tracing.enabled():
            return self._predict(merged)
        ids = ",".join(p.ctx[0] for p in live if p.ctx is not None)
        with tracing.trace_context(lead[0], lead[1]):
            with tracing.span("serving.batch_forward", coalesced=len(live),
                              trace_ids=ids[:512]):
                return self._predict(merged)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait(0.1)
                if not self._q and self._stop:
                    return
                first = self._q.popleft()
                self._m_depth.set(len(self._q))
            group = self._collect_group(first)
            now = time.monotonic()
            live = []
            for p in group:
                if p.deadline < now:
                    self._finish_error(
                        p, DeadlineExceededError("expired while queued")
                    )
                else:
                    live.append(p)
            if not live:
                continue
            for p in live:
                self._m_queue_wait.observe(now - p.t_submit)
            try:
                total = sum(p.batch.batch_size for p in live)
                pad_to = round_up_pow2(total) if self.pad_buckets else 0
                merged, offsets = merge_batches(
                    [p.batch for p in live], pad_to=pad_to
                )
                scores = np.asarray(self._forward(live, merged))
            except Exception as e:  # noqa: BLE001 — the error crosses to every caller
                logger.exception("coalesced forward failed (%d requests)", len(live))
                for p in live:
                    self._finish_error(p, e)
                continue
            self._m_batch_rows.observe(offsets[-1])
            for p, lo, hi in zip(live, offsets, offsets[1:]):
                p.result = scores[lo:hi]
                p.event.set()

"""Replica gateway: health-checked routing, retry/hedging, and
staleness-bounded quarantine.

One serving replica is a single point of failure and a single tail-latency
distribution. The gateway fronts a replica set — either a static address
list or a role discovered live from the coordinator
(persia_tpu/service/discovery.py, the control plane every other tier
already registers with) — and gives callers four properties:

- **health-checked routing**: a background probe loop marks replicas
  up/down from ``/healthz``; requests round-robin over the live set only;
- **retry with failover**: a transport failure trips the replica's
  circuit breaker and the request replays on the next live replica
  (predict is read-only → safe to retry, unlike the training RPC paths);
- **hedged requests**: if the primary has not answered within
  ``hedge_after_ms``, the same request fires at a second replica and the
  first answer wins — the classic tail-at-scale move; the straggler's
  answer is discarded. Hedge candidates and hedge failures ride the same
  per-replica breakers as primaries;
- **staleness quarantine**: each replica's ``/healthz`` reports its
  freshness lag against the trainer head (persia_tpu/incremental.py); a
  replica lagging past ``max_staleness_steps`` / ``max_staleness_s`` is
  *quarantined* — drained from the balance set but kept on health probes,
  auto-healed when resync catches it up. In-flight requests on a replica
  entering quarantine are never cancelled (quarantine only changes
  routing). When EVERY replica is stale the gateway degrades instead of
  failing: it serves from the least-stale replica and surfaces the
  replica's ``X-Staleness-Steps`` answer header to the caller — stale
  scores beat no scores, but only with an explicit label.

Every retry/backoff/breaker decision runs on the SHARED resilience engine
(``service/resilience.py``) — no hand-rolled sleeps, so the RES lint rules
and the chaos soak's replay both see all of it.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from persia_tpu import tracing
from persia_tpu.data import PersiaBatch
from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics
from persia_tpu.service.resilience import ResiliencePolicy, RetryPolicy
from persia_tpu.serving.client import InferenceClient

logger = get_default_logger("persia_tpu.serving.gateway")


class NoReplicaAvailableError(RuntimeError):
    """Every replica is down (or the request failed on all of them)."""


# The per-hop split of a served request: time queued behind the routing
# decision, time the replica reports holding the request (X-Server-Ms),
# wire + serialization overhead (attempt wall minus replica hold), and the
# replica-side micro-batcher queue wait.
_HOP_SERIES = (
    "persia_tpu_gateway_queue_wait_seconds",
    "persia_tpu_gateway_replica_server_seconds",
    "persia_tpu_gateway_wire_seconds",
    "persia_tpu_serving_queue_wait_seconds",
)


def hop_latency_summary() -> Dict[str, Dict[str, float]]:
    """Per-hop latency attribution from the split histograms, in artifact
    form (count / total seconds / mean ms per hop). Benches embed this so
    "where did the milliseconds go" is answerable from the JSON alone."""
    snap = get_metrics().snapshot()
    out: Dict[str, Dict[str, float]] = {}
    for name in _HOP_SERIES:
        count = sum(snap.get(f"{name}_count", {}).values())
        total = sum(snap.get(f"{name}_sum", {}).values())
        out[name] = {
            "count": int(count),
            "sum_s": round(total, 6),
            "mean_ms": round(total / count * 1e3, 4) if count else 0.0,
        }
    return out


class ReplicaGateway:
    """Route ``predict`` over a live replica set.

    ``replicas`` seeds a static set; ``coordinator`` (a
    ``CoordinatorClient``) + ``role`` refreshes the set each health tick so
    replicas added later join the rotation without a restart.

    Replica health and retry/backoff run on the SHARED resilience engine
    (``service/resilience.py`` — the same one the training-side RPC
    clients use): each replica gets a per-endpoint circuit breaker
    (threshold 1, reset = the health interval, so a failed replica leaves
    the rotation immediately and re-enters through a half-open probe),
    and inter-attempt backoff rides ``policy.sleep_backoff``.

    ``max_staleness_steps`` / ``max_staleness_s`` arm the freshness
    quarantine (None = replicas are never quarantined for lag; replicas
    that report no ``freshness`` block in /healthz are always exempt).
    The trainer head is estimated fleet-wide: the max head any replica
    reports, kept monotone — a black-holed replica cannot shrink the head
    by reporting its own frozen view.
    """

    def __init__(
        self,
        replicas: Optional[Sequence[str]] = None,
        coordinator=None,
        role: str = "inference",
        health_interval_s: float = 2.0,
        hedge_after_ms: float = 50.0,
        request_timeout_s: float = 30.0,
        max_attempts: int = 3,
        policy: Optional[ResiliencePolicy] = None,
        max_staleness_steps: Optional[int] = None,
        max_staleness_s: Optional[float] = None,
        head_source=None,
    ):
        self._clients: Dict[str, InferenceClient] = {}
        self._lock = threading.Lock()
        self._coordinator = coordinator
        self._role = role
        self.health_interval_s = health_interval_s
        self.hedge_after_s = max(0.0, hedge_after_ms) / 1e3
        self.request_timeout_s = request_timeout_s
        self.max_attempts = max(1, max_attempts)
        self.max_staleness_steps = max_staleness_steps
        self.max_staleness_s = max_staleness_s
        # optional durable head oracle: () -> (head_step, head_time_us),
        # e.g. incremental.read_head over the SOURCE delta dir. Without it
        # the head is the max any replica reports — enough unless a
        # partition freezes EVERY replica's view at once.
        self.head_source = head_source
        # serving failover wants immediate replica switches, so the backoff
        # base is tiny; the breaker re-close cadence tracks health probes
        self.policy = policy if policy is not None else ResiliencePolicy(
            retry=RetryPolicy(
                max_attempts=self.max_attempts, base_s=0.002, max_s=0.05
            ),
            breaker_failure_threshold=1,
            breaker_reset_s=health_interval_s,
        )
        self._rr = 0
        # QPS sensor window: (monotonic, request-counter reading) at the
        # previous request_rate() call — the autopilot polls it per tick
        self._rate_mark: Optional[Tuple[float, float]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # freshness bookkeeping (all guarded by _lock): last /healthz
        # freshness block per replica, the monotone fleet head estimate,
        # and the quarantine set + event log the bench records
        self._freshness: Dict[str, Dict] = {}
        self._quarantined: set = set()
        self._head_step = -1
        self._head_time_us = 0
        self.quarantine_log: List[Dict] = []
        # hedges need their own threads; 2x a small pool bounds the fan-out
        self._pool = ThreadPoolExecutor(max_workers=16, thread_name_prefix="gw-hedge")
        m = get_metrics()
        self._m_requests = m.counter(
            "persia_tpu_gateway_requests", "predict requests routed"
        )
        self._m_retries = m.counter(
            "persia_tpu_gateway_retries", "failover retries after a replica error"
        )
        self._m_hedges = m.counter(
            "persia_tpu_gateway_hedged", "hedged second requests fired"
        )
        self._m_live = m.gauge(
            "persia_tpu_gateway_live_replicas", "replicas currently passing health"
        )
        self._m_quarantined = m.gauge(
            "persia_tpu_gateway_quarantined_replicas",
            "replicas drained for freshness-lag violations",
        )
        self._m_quarantines = m.counter(
            "persia_tpu_gateway_quarantine_events", "replica quarantine entries"
        )
        self._m_heals = m.counter(
            "persia_tpu_gateway_heal_events", "replicas healed out of quarantine"
        )
        self._m_stale_served = m.counter(
            "persia_tpu_gateway_stale_served",
            "requests served by a quarantined replica (all replicas stale)",
        )
        self._m_probe_errors = m.counter(
            "persia_tpu_gateway_probe_errors", "health probe sweeps that failed"
        )
        # per-hop latency attribution (recorded per successful attempt):
        # dispatch queue wait in the hedge pool, the replica's self-reported
        # hold time (X-Server-Ms: its queue wait + coalesced forward), and
        # wire = gateway-observed wall minus the replica's hold
        self._m_queue_wait = m.histogram(
            "persia_tpu_gateway_queue_wait_seconds",
            "wait from routing decision to the attempt actually firing",
        )
        self._m_server_time = m.histogram(
            "persia_tpu_gateway_replica_server_seconds",
            "replica-reported request hold time (X-Server-Ms)",
        )
        self._m_wire = m.histogram(
            "persia_tpu_gateway_wire_seconds",
            "attempt wall time minus the replica's reported hold (wire + "
            "serialization overhead)",
        )
        for addr in replicas or []:
            self.add_replica(addr)

    # ------------------------------------------------------------- membership

    def add_replica(self, addr: str) -> None:
        with self._lock:
            if addr not in self._clients:
                self._clients[addr] = InferenceClient(
                    addr, timeout_s=self.request_timeout_s
                )

    def remove_replica(self, addr: str) -> bool:
        """Drain one replica out of the rotation (the autopilot's
        scale-down actuator): it leaves the balance set immediately — new
        requests never route to it, in-flight attempts on its client
        finish or fail through their own retry path — and its freshness /
        quarantine / breaker records are dropped so a later re-add starts
        with a clean slate (the :meth:`replace_replica`-style reset, not
        the swap-preserving one: the process behind the address is going
        away). Returns True when the address was a member.

        With a ``coordinator`` wired the caller must ALSO deregister the
        address there, or the next probe sweep re-adds it."""
        with self._lock:
            client = self._clients.pop(addr, None)
            self._freshness.pop(addr, None)
            self._quarantined.discard(addr)
        if client is None:
            return False
        self.policy.reset_breaker(addr)
        self._update_live_gauge()
        tracing.record_event("gateway.remove_replica", replica=addr)
        logger.info("replica %s removed from the rotation", addr)
        return True

    def live_replicas(self) -> List[str]:
        """The balance set: breaker-available AND not staleness-quarantined."""
        with self._lock:
            addrs = [a for a in self._clients if a not in self._quarantined]
        return [a for a in addrs if self.policy.breaker(a).available()]

    def quarantined_replicas(self) -> List[str]:
        with self._lock:
            return sorted(self._quarantined)

    def _mark_down(self, addr: str) -> None:
        self.policy.breaker(addr).force_open()
        self._update_live_gauge()

    def _update_live_gauge(self) -> None:
        with self._lock:
            total = len(self._clients)
        self._m_live.set(len(self.live_replicas()) if total else 0)
        self._m_quarantined.set(len(self._quarantined))

    # ------------------------------------------------------------- freshness

    def _lag_of(self, fresh: Dict) -> Tuple[int, float]:
        """A replica's lag against the FLEET head estimate (steps, seconds).
        Using the fleet head — not the replica's own — is what makes a
        black-holed replica (whose local head view is frozen along with its
        applied state) quarantinable at all."""
        applied = int(fresh.get("applied_step", -1))
        lag_steps = max(0, self._head_step - applied) if self._head_step >= 0 else 0
        applied_us = int(fresh.get("applied_time_us", 0))
        lag_s = 0.0
        if lag_steps > 0 and self._head_time_us > applied_us:
            lag_s = (self._head_time_us - applied_us) / 1e6
        return lag_steps, lag_s

    def _over_bound(self, lag_steps: int, lag_s: float) -> bool:
        if self.max_staleness_steps is not None and lag_steps > self.max_staleness_steps:
            return True
        if self.max_staleness_s is not None and lag_s > self.max_staleness_s:
            return True
        return False

    def _eval_quarantine(self, addr: str, fresh: Optional[Dict]) -> None:
        """Quarantine/heal one replica from its latest freshness report.
        Caller does NOT hold the lock."""
        with self._lock:
            if fresh is None:
                # no freshness contract → exempt (and heal a stale record:
                # a replica that dropped its delta channel stops being
                # judged on it)
                self._freshness.pop(addr, None)
                if addr in self._quarantined:
                    self._quarantined.discard(addr)
                    self._log_event("heal", addr, 0, 0.0)
                return
            self._freshness[addr] = fresh
            if int(fresh.get("head_step", -1)) > self._head_step:
                self._head_step = int(fresh["head_step"])
            if int(fresh.get("head_time_us", 0)) > self._head_time_us:
                self._head_time_us = int(fresh["head_time_us"])
            lag_steps, lag_s = self._lag_of(fresh)
            over = self._over_bound(lag_steps, lag_s)
            if over and addr not in self._quarantined:
                self._quarantined.add(addr)
                self._log_event("quarantine", addr, lag_steps, lag_s)
            elif not over and addr in self._quarantined:
                self._quarantined.discard(addr)
                self._log_event("heal", addr, lag_steps, lag_s)

    def _log_event(self, action: str, addr: str, lag_steps: int, lag_s: float) -> None:
        """Record + count a quarantine transition. Caller holds the lock."""
        self.quarantine_log.append({
            "action": action, "replica": addr, "lag_steps": lag_steps,
            "lag_seconds": round(lag_s, 3), "time": time.time(),
        })
        # the black box sees every quarantine transition, stamped with the
        # ambient trace_id (if a traced request triggered the evaluation)
        tracing.record_event(f"gateway.{action}", replica=addr,
                             lag_steps=lag_steps, lag_seconds=round(lag_s, 3))
        if action == "quarantine":
            self._m_quarantines.inc()
            logger.warning("replica %s quarantined (lag %d steps / %.2fs)",
                           addr, lag_steps, lag_s)
        else:
            self._m_heals.inc()
            logger.info("replica %s healed (lag %d steps)", addr, lag_steps)

    def staleness_of(self, addr: str) -> int:
        """Current lag estimate in steps for one replica (0 = fresh/unknown)."""
        with self._lock:
            fresh = self._freshness.get(addr)
            return self._lag_of(fresh)[0] if fresh else 0

    def freshness_view(self) -> Dict[str, Dict]:
        """Every replica's lag against the FLEET head (the gateway's honest
        view — a black-holed replica's self-report reads fresh because its
        head view froze along with its applied state)."""
        with self._lock:
            out = {}
            for addr, fresh in self._freshness.items():
                steps, secs = self._lag_of(fresh)
                out[addr] = {
                    "lag_steps": steps,
                    "lag_seconds": round(secs, 3),
                    "quarantined": addr in self._quarantined,
                }
            return out

    # ----------------------------------------------------------------- probes

    def _probe_all(self) -> None:
        if self.head_source is not None:
            try:
                hs, ht = self.head_source()
                with self._lock:
                    if int(hs) > self._head_step:
                        self._head_step = int(hs)
                    if int(ht) > self._head_time_us:
                        self._head_time_us = int(ht)
            except Exception as e:  # noqa: BLE001 — oracle outage ≠ gateway outage
                logger.warning("head_source read failed: %s", e)
        if self._coordinator is not None:
            try:
                for addr in self._coordinator.list(self._role):
                    self.add_replica(addr)
            except Exception as e:  # noqa: BLE001 — control plane hiccup
                logger.warning("coordinator list(%s) failed: %s", self._role, e)
        with self._lock:
            addrs = list(self._clients)
        for addr in addrs:
            fresh = None
            try:
                h = self._clients[addr].health()
                ok = h.get("status") == "ok"
                fresh = h.get("freshness")
            except Exception:  # noqa: BLE001 — any probe failure = down
                ok = False
            b = self.policy.breaker(addr)
            if ok:
                b.on_success()
                # quarantine is evaluated on every probe — including for
                # breaker-open replicas that just recovered — so healing
                # needs no request traffic, only probes
                self._eval_quarantine(addr, fresh)
            else:
                b.force_open()
        self._update_live_gauge()

    def start(self) -> "ReplicaGateway":
        self._probe_all()  # synchronous first probe: start() returns routable
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._health_loop, daemon=True, name="gateway-health"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._pool.shutdown(wait=False)

    def _health_loop(self) -> None:
        from persia_tpu import diagnostics

        while not self._stop.wait(self.health_interval_s):
            try:
                self._probe_all()
                # the prober is itself a liveness-critical component: beat
                # the stall detector so a wedged sweep surfaces as a
                # diagnostics.stall flight event instead of silent rot
                diagnostics.heartbeat("gateway-health")
            except Exception as e:  # noqa: BLE001 — prober must survive
                self._m_probe_errors.inc()
                logger.warning("health probe sweep failed: %s", e)

    # --------------------------------------------------------------- routing

    def _pick(self, exclude: set) -> Optional[str]:
        live = [a for a in self.live_replicas() if a not in exclude]
        if not live:
            return None
        with self._lock:
            self._rr += 1
            return live[self._rr % len(live)]

    def _pick_stale_fallback(self, exclude: set) -> Optional[str]:
        """All-replicas-stale degradation: the least-stale quarantined
        replica whose breaker still answers. Explicitly labelled service
        beats an outage — PR 3's degraded-lookup trade, at the gateway."""
        with self._lock:
            cands = [a for a in self._quarantined if a not in exclude]
            cands = sorted(
                cands,
                key=lambda a: self._lag_of(self._freshness[a])[0]
                if a in self._freshness else 0,
            )
        for a in cands:
            if self.policy.breaker(a).available():
                return a
        return None

    def predict(self, batch: PersiaBatch, deadline_ms: Optional[float] = None) -> np.ndarray:
        return self.predict_bytes(batch.to_bytes(), deadline_ms=deadline_ms)

    def predict_bytes(self, raw: bytes, deadline_ms: Optional[float] = None) -> np.ndarray:
        return self.predict_bytes_ex(raw, deadline_ms=deadline_ms)[0]

    def predict_bytes_ex(
        self, raw: bytes, deadline_ms: Optional[float] = None
    ) -> Tuple[np.ndarray, Dict]:
        """Route one request: round-robin primary, hedge after
        ``hedge_after_s``, fail over on error up to ``max_attempts``
        distinct replicas; when every fresh replica is gone, degrade onto
        the least-stale quarantined one. Returns ``(scores, info)`` where
        ``info`` carries ``staleness_steps`` (the serving replica's
        ``X-Staleness-Steps`` answer) and ``stale_fallback`` (plus
        ``trace_id`` when tracing is on)."""
        if tracing.enabled() and tracing.current_context() is None:
            # THE edge: a request arriving without a trace gets its id here,
            # and every hop below (gateway span, replica HTTP headers,
            # engine span) inherits it
            with tracing.trace_context():
                return self._predict_routed(raw, deadline_ms)
        return self._predict_routed(raw, deadline_ms)

    def _predict_routed(
        self, raw: bytes, deadline_ms: Optional[float]
    ) -> Tuple[np.ndarray, Dict]:
        self._m_requests.inc()
        tried: set = set()
        last: Optional[Exception] = None
        stale_fallback = False
        for attempt in range(self.max_attempts):
            addr = self._pick(tried)
            if addr is None:
                addr = self._pick_stale_fallback(tried)
                if addr is None:
                    break
                stale_fallback = True
            tried.add(addr)
            if attempt:
                self._m_retries.inc()
                # failover backoff rides the shared engine (tiny base:
                # serving wants an immediate replica switch, but repeated
                # failures should not hot-spin the fleet)
                self.policy.sleep_backoff(attempt - 1)
            try:
                with tracing.span("gateway.predict", replica=addr,
                                  attempt=attempt):
                    scores, headers = self._one_attempt(
                        addr, raw, tried, deadline_ms
                    )
            except Exception as e:  # noqa: BLE001 — classify then fail over
                last = e
                self.policy.breaker(addr).on_failure()
                self._update_live_gauge()
                logger.warning("replica %s failed (%s); failing over", addr, e)
                continue
            # the staleness answer is max(replica self-report, gateway fleet
            # view): a partitioned replica reads locally fresh — only the
            # gateway's head estimate exposes how far behind it really is
            info = {
                "replica": addr,
                "staleness_steps": max(
                    int(headers.get("x-staleness-steps", 0)),
                    self.staleness_of(addr),
                ),
                "stale_fallback": stale_fallback,
            }
            tid = tracing.current_trace_id()
            if tid:
                info["trace_id"] = tid
            if stale_fallback:
                self._m_stale_served.inc()
            return scores, info
        raise NoReplicaAvailableError(
            f"no live replica answered (tried {sorted(tried) or 'none'})"
        ) from last

    def _one_attempt(
        self, addr: str, raw: bytes, tried: set, deadline_ms: Optional[float]
    ) -> Tuple[np.ndarray, Dict]:
        """Primary request with a hedge: fire ``addr``, and if it has not
        answered within ``hedge_after_s`` fire one more replica; first
        success wins, the straggler is abandoned to its own timeout. Both
        the primary and the hedge settle their replica's breaker."""
        client = self._clients[addr]
        primary = self._submit_attempt(addr, client, raw, deadline_ms)
        futures = {primary: addr}
        done, _ = wait([primary], timeout=self.hedge_after_s,
                       return_when=FIRST_COMPLETED)
        if not done:
            hedge_addr = self._pick(tried | set(futures.values()))
            # the hedge consumes the target's breaker probe slot like any
            # real call: a half-open replica admits ONE probe, and a hedge
            # must not slip past that gate
            if hedge_addr is not None and self.policy.breaker(hedge_addr).allow():
                self._m_hedges.inc()
                futures[self._submit_attempt(
                    hedge_addr, self._clients[hedge_addr], raw, deadline_ms
                )] = hedge_addr
        pending = set(futures)
        first_error: Optional[Exception] = None
        return self._first_answer(addr, futures, pending, first_error)

    def _submit_attempt(self, addr: str, client: InferenceClient, raw: bytes,
                        deadline_ms: Optional[float]):
        """Dispatch one replica attempt on the hedge pool, carrying the
        routing thread's trace context across (thread-locals do not), and
        recording the per-hop latency attribution on success: pool queue
        wait, the replica's self-reported hold (``X-Server-Ms``), and
        wire = observed wall − replica hold."""
        ctx = tracing.current_context()
        t_sub = time.perf_counter()

        def run():
            self._m_queue_wait.observe(time.perf_counter() - t_sub)
            t0 = time.perf_counter()
            if ctx is not None:
                with tracing.trace_context(ctx[0], ctx[1]):
                    with tracing.span("gateway.attempt", replica=addr):
                        scores, headers = client.predict_bytes_ex(raw, deadline_ms)
            else:
                scores, headers = client.predict_bytes_ex(raw, deadline_ms)
            total = time.perf_counter() - t0
            try:
                server_s = float(headers.get("x-server-ms", 0.0)) / 1e3
            except ValueError:
                server_s = 0.0
            if server_s > 0.0:
                self._m_server_time.observe(server_s)
                self._m_wire.observe(max(0.0, total - server_s))
            return scores, headers

        return self._pool.submit(run)

    def _first_answer(self, addr, futures, pending, first_error):
        while pending:
            done, pending = wait(pending, timeout=self.request_timeout_s,
                                 return_when=FIRST_COMPLETED)
            if not done:
                break
            for f in done:
                try:
                    scores, headers = f.result()
                except Exception as e:  # noqa: BLE001 — maybe the hedge wins
                    first_error = first_error or e
                    self.policy.breaker(futures[f]).on_failure()
                else:
                    self.policy.breaker(futures[f]).on_success()
                    return scores, headers
        raise first_error or TimeoutError(f"no answer from {addr} within timeout")

    # ------------------------------------------------------------------ stats

    def request_rate(self) -> float:
        """Requests/second over the window since the previous call — the
        autopilot's serving-load sensor. The first call establishes the
        window and returns 0.0; subsequent calls measure the counter delta
        against the monotonic clock. A sub-millisecond window also returns
        0.0 rather than a spike artifact."""
        now = time.monotonic()
        count = float(self._m_requests.get())
        with self._lock:
            mark, self._rate_mark = self._rate_mark, (now, count)
        if mark is None or (now - mark[0]) < 1e-3:
            return 0.0
        return max(0.0, count - mark[1]) / (now - mark[0])

    def stats(self) -> Dict:
        with self._lock:
            quarantined = sorted(self._quarantined)
            head = self._head_step
        return {
            "replicas": sorted(self._clients),
            "live": self.live_replicas(),
            "quarantined": quarantined,
            "head_step": head,
            "requests": self._m_requests.get(),
            "retries": self._m_retries.get(),
            "hedges": self._m_hedges.get(),
            "quarantine_events": self._m_quarantines.get(),
            "heal_events": self._m_heals.get(),
            "stale_served": self._m_stale_served.get(),
            "breaker_states": self.policy.breaker_states(),
        }

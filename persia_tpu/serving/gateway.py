"""Replica gateway: health-checked routing, retry, and hedged requests.

One serving replica is a single point of failure and a single tail-latency
distribution. The gateway fronts a replica set — either a static address
list or a role discovered live from the coordinator
(persia_tpu/service/discovery.py, the control plane every other tier
already registers with) — and gives callers three properties:

- **health-checked routing**: a background probe loop marks replicas
  up/down from ``/healthz``; requests round-robin over the live set only;
- **retry with failover**: a transport failure marks the replica down and
  the request replays on the next live replica (predict is read-only →
  safe to retry, unlike the training RPC paths);
- **hedged requests**: if the primary has not answered within
  ``hedge_after_ms``, the same request fires at a second replica and the
  first answer wins — the classic tail-at-scale move; the straggler's
  answer is discarded.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Dict, List, Optional, Sequence

import numpy as np

from persia_tpu.data import PersiaBatch
from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics
from persia_tpu.service.resilience import ResiliencePolicy, RetryPolicy
from persia_tpu.serving.client import InferenceClient

logger = get_default_logger("persia_tpu.serving.gateway")


class NoReplicaAvailableError(RuntimeError):
    """Every replica is down (or the request failed on all of them)."""


class ReplicaGateway:
    """Route ``predict`` over a live replica set.

    ``replicas`` seeds a static set; ``coordinator`` (a
    ``CoordinatorClient``) + ``role`` refreshes the set each health tick so
    replicas added later join the rotation without a restart.

    Replica health and retry/backoff run on the SHARED resilience engine
    (``service/resilience.py`` — the same one the training-side RPC
    clients use): each replica gets a per-endpoint circuit breaker
    (threshold 1, reset = the health interval, so a failed replica leaves
    the rotation immediately and re-enters through a half-open probe),
    and inter-attempt backoff delays come from the policy's RetryPolicy
    instead of a hand-rolled loop.
    """

    def __init__(
        self,
        replicas: Optional[Sequence[str]] = None,
        coordinator=None,
        role: str = "inference",
        health_interval_s: float = 2.0,
        hedge_after_ms: float = 50.0,
        request_timeout_s: float = 30.0,
        max_attempts: int = 3,
        policy: Optional[ResiliencePolicy] = None,
    ):
        self._clients: Dict[str, InferenceClient] = {}
        self._lock = threading.Lock()
        self._coordinator = coordinator
        self._role = role
        self.health_interval_s = health_interval_s
        self.hedge_after_s = max(0.0, hedge_after_ms) / 1e3
        self.request_timeout_s = request_timeout_s
        self.max_attempts = max(1, max_attempts)
        # serving failover wants immediate replica switches, so the backoff
        # base is tiny; the breaker re-close cadence tracks health probes
        self.policy = policy if policy is not None else ResiliencePolicy(
            retry=RetryPolicy(
                max_attempts=self.max_attempts, base_s=0.002, max_s=0.05
            ),
            breaker_failure_threshold=1,
            breaker_reset_s=health_interval_s,
        )
        self._rr = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # hedges need their own threads; 2x a small pool bounds the fan-out
        self._pool = ThreadPoolExecutor(max_workers=16, thread_name_prefix="gw-hedge")
        m = get_metrics()
        self._m_requests = m.counter(
            "persia_tpu_gateway_requests", "predict requests routed"
        )
        self._m_retries = m.counter(
            "persia_tpu_gateway_retries", "failover retries after a replica error"
        )
        self._m_hedges = m.counter(
            "persia_tpu_gateway_hedged", "hedged second requests fired"
        )
        self._m_live = m.gauge(
            "persia_tpu_gateway_live_replicas", "replicas currently passing health"
        )
        for addr in replicas or []:
            self.add_replica(addr)

    # ------------------------------------------------------------- membership

    def add_replica(self, addr: str) -> None:
        with self._lock:
            if addr not in self._clients:
                self._clients[addr] = InferenceClient(
                    addr, timeout_s=self.request_timeout_s
                )

    def live_replicas(self) -> List[str]:
        with self._lock:
            addrs = list(self._clients)
        return [a for a in addrs if self.policy.breaker(a).available()]

    def _mark_down(self, addr: str) -> None:
        self.policy.breaker(addr).force_open()
        self._update_live_gauge()

    def _update_live_gauge(self) -> None:
        with self._lock:
            total = len(self._clients)
        self._m_live.set(len(self.live_replicas()) if total else 0)

    def _probe_all(self) -> None:
        if self._coordinator is not None:
            try:
                for addr in self._coordinator.list(self._role):
                    self.add_replica(addr)
            except Exception as e:  # noqa: BLE001 — control plane hiccup
                logger.warning("coordinator list(%s) failed: %s", self._role, e)
        with self._lock:
            addrs = list(self._clients)
        for addr in addrs:
            try:
                ok = self._clients[addr].health().get("status") == "ok"
            except Exception:  # noqa: BLE001 — any probe failure = down
                ok = False
            b = self.policy.breaker(addr)
            if ok:
                b.on_success()
            else:
                b.force_open()
        self._update_live_gauge()

    def start(self) -> "ReplicaGateway":
        self._probe_all()  # synchronous first probe: start() returns routable
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._health_loop, daemon=True, name="gateway-health"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._pool.shutdown(wait=False)

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            try:
                self._probe_all()
            except Exception as e:  # noqa: BLE001 — prober must survive
                logger.warning("health probe sweep failed: %s", e)

    # --------------------------------------------------------------- routing

    def _pick(self, exclude: set) -> Optional[str]:
        live = [a for a in self.live_replicas() if a not in exclude]
        if not live:
            return None
        with self._lock:
            self._rr += 1
            return live[self._rr % len(live)]

    def predict(self, batch: PersiaBatch, deadline_ms: Optional[float] = None) -> np.ndarray:
        return self.predict_bytes(batch.to_bytes(), deadline_ms=deadline_ms)

    def predict_bytes(self, raw: bytes, deadline_ms: Optional[float] = None) -> np.ndarray:
        """Route one request: round-robin primary, hedge after
        ``hedge_after_s``, fail over on error up to ``max_attempts``
        distinct replicas."""
        self._m_requests.inc()
        tried: set = set()
        last: Optional[Exception] = None
        for attempt in range(self.max_attempts):
            addr = self._pick(tried)
            if addr is None:
                break
            tried.add(addr)
            if attempt:
                self._m_retries.inc()
                # failover backoff rides the shared RetryPolicy (tiny base:
                # serving wants an immediate replica switch, but repeated
                # failures should not hot-spin the fleet)
                time.sleep(self.policy.backoff(attempt - 1))
            try:
                return self._one_attempt(addr, raw, tried, deadline_ms)
            except Exception as e:  # noqa: BLE001 — classify then fail over
                last = e
                self._mark_down(addr)
                logger.warning("replica %s failed (%s); failing over", addr, e)
        raise NoReplicaAvailableError(
            f"no live replica answered (tried {sorted(tried) or 'none'})"
        ) from last

    def _one_attempt(
        self, addr: str, raw: bytes, tried: set, deadline_ms: Optional[float]
    ) -> np.ndarray:
        """Primary request with a hedge: fire ``addr``, and if it has not
        answered within ``hedge_after_s`` fire one more replica; first
        success wins, the straggler is abandoned to its own timeout."""
        client = self._clients[addr]
        primary = self._pool.submit(client.predict_bytes, raw, deadline_ms)
        futures = {primary: addr}
        done, _ = wait([primary], timeout=self.hedge_after_s,
                       return_when=FIRST_COMPLETED)
        if not done:
            hedge_addr = self._pick(tried | set(futures.values()))
            if hedge_addr is not None:
                self._m_hedges.inc()
                futures[self._pool.submit(
                    self._clients[hedge_addr].predict_bytes, raw, deadline_ms
                )] = hedge_addr
        pending = set(futures)
        first_error: Optional[Exception] = None
        while pending:
            done, pending = wait(pending, timeout=self.request_timeout_s,
                                 return_when=FIRST_COMPLETED)
            if not done:
                break
            for f in done:
                try:
                    return f.result()
                except Exception as e:  # noqa: BLE001 — maybe the hedge wins
                    first_error = first_error or e
                    self._mark_down(futures[f])
        raise first_error or TimeoutError(f"no answer from {addr} within timeout")

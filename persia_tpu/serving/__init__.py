"""Production serving plane.

Replaces the single-replica HTTP wrapper (the old ``persia_tpu/serving.py``)
with a subsystem shaped for heavy traffic:

- :mod:`~persia_tpu.serving.batcher` — micro-batching engine: bounded
  admission queue, max-batch/max-wait coalescing, per-request deadlines,
  429 load-shedding;
- :mod:`~persia_tpu.serving.cache` — infer-side hot-embedding LRU keyed by
  sign, invalidated by incremental packets, epoch-cleared on rollover;
- :mod:`~persia_tpu.serving.gateway` — health-checked replica routing with
  retry, hedged requests, per-replica circuit breakers, and freshness-lag
  quarantine with staleness-labelled degraded serving;
- :mod:`~persia_tpu.serving.rollover` — atomic model-version rollover from
  checkpoint done-markers + live ``.inc`` delta consumption with
  crc-framed integrity + resync repair;
- :mod:`~persia_tpu.serving.server` — the HTTP replicas
  (:class:`InferenceServer` single-request, :class:`ServingServer` the
  full plane);
- :mod:`~persia_tpu.serving.client` — the matching urllib client.

The old import surface (``from persia_tpu.serving import InferenceServer,
InferenceClient``) is preserved.
"""

from persia_tpu.serving.batcher import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
    merge_batches,
)
from persia_tpu.serving.cache import (
    CachedLookupRouter,
    HotEmbeddingCache,
    attach_cache,
)
from persia_tpu.serving.client import InferenceClient
from persia_tpu.serving.engine import InferenceEngine, clone_infer_ctx
from persia_tpu.serving.gateway import NoReplicaAvailableError, ReplicaGateway
from persia_tpu.serving.rollover import ModelRollover
from persia_tpu.serving.server import InferenceServer, ServingServer

__all__ = [
    "CachedLookupRouter",
    "DeadlineExceededError",
    "HotEmbeddingCache",
    "InferenceClient",
    "InferenceEngine",
    "InferenceServer",
    "MicroBatcher",
    "ModelRollover",
    "NoReplicaAvailableError",
    "QueueFullError",
    "ReplicaGateway",
    "ServingServer",
    "attach_cache",
    "clone_infer_ctx",
    "merge_batches",
]

"""Atomic model-version rollover for a long-running serving replica.

Training publishes two kinds of updates a server must absorb without a
restart or a dropped request:

- **full checkpoints** (persia_tpu/checkpoint.py): a directory becomes
  valid only when its ``embedding_dump_done`` marker lands; the marker's
  ``session`` id is the version. The watcher polls the marker, and on a
  new session: deserializes the dense half into a FRESH ``TrainState``
  (off the request path), reloads the embedding tables in place on the
  shared worker (per-shard locks keep concurrent lookups valid), bumps
  the hot-cache epoch, and only then swaps the engine handle — in-flight
  requests finish on the old dense state, new requests see the new one;
- **incremental packets** (persia_tpu/incremental.py): applied live by an
  ``IncrementalLoader`` whose ``on_apply`` hook invalidates exactly the
  updated signs in the hot cache. Packets that predate the current
  checkpoint are skipped via ``skip_before_us`` (the marker records its
  ``time_us`` for exactly this).

The swap is wait-free for readers (one handle assignment, see
serving/engine.py); the expensive work — storage reads, flax
deserialization — happens on the watcher thread.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Optional, Union

from persia_tpu.checkpoint import DONE_MARKER
from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics
from persia_tpu.serving.engine import InferenceEngine, clone_infer_ctx
from persia_tpu.storage import StorageError, StoragePath, storage_path

logger = get_default_logger("persia_tpu.serving.rollover")


class ModelRollover:
    """Tie a serving engine to a checkpoint dir (full rollovers) and an
    incremental dir (live deltas)."""

    def __init__(
        self,
        engine: InferenceEngine,
        ckpt_dir: Union[str, StoragePath],
        inc_dir: Optional[Union[str, StoragePath]] = None,
        cache=None,
        poll_interval_s: float = 2.0,
        inc_scan_interval_s: Optional[float] = None,
    ):
        self.engine = engine
        self.root = storage_path(ckpt_dir)
        self.cache = cache
        self.poll_interval_s = poll_interval_s
        self._seen_session: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._inc_loader = None
        if inc_dir is not None:
            from persia_tpu.incremental import IncrementalLoader

            self._inc_loader = IncrementalLoader(
                engine.ctx.worker.lookup_router.replicas[0]
                if len(engine.ctx.worker.lookup_router.replicas) == 1
                else _RouterStore(engine.ctx.worker),
                inc_dir,
                scan_interval_sec=inc_scan_interval_s or poll_interval_s,
                on_apply=(cache.invalidate if cache is not None else None),
            )
        m = get_metrics()
        self._m_version_ts = m.gauge(
            "persia_tpu_serving_model_time_us", "time_us of the live checkpoint"
        )
        self._m_failed = m.counter(
            "persia_tpu_serving_rollover_failures", "rollovers that failed to apply"
        )

    # ----------------------------------------------------------------- state

    @property
    def version(self) -> str:
        return self.engine.version

    def _read_marker(self) -> Optional[Dict]:
        try:
            return json.loads(self.root.join(DONE_MARKER).read_text())
        except (OSError, ValueError, StorageError):
            return None

    # ------------------------------------------------------------------ poll

    def poll_once(self) -> bool:
        """One watcher tick: apply a new checkpoint if the done-marker moved,
        then drain unseen incremental packets. Returns True iff a full
        rollover was applied."""
        rolled = False
        info = self._read_marker()
        if info is not None:
            session = str(info.get("session", info.get("datetime", "")))
            if session and session != self._seen_session:
                self._apply_checkpoint(info, session)
                rolled = True
        if self._inc_loader is not None:
            self._inc_loader.poll_once()
        return rolled

    def _apply_checkpoint(self, info: Dict, session: str) -> None:
        import flax.serialization

        from persia_tpu.checkpoint import load_dense

        ctx = self.engine.ctx
        try:
            # dense half: deserialize into a fresh state off the request path
            new_state = ctx.state
            raw = load_dense(self.root, missing_ok=True)
            if raw is not None:
                new_state = flax.serialization.from_bytes(ctx.state, raw)
            # sparse half: in-place load on the shared store (entries re-route
            # by sign; concurrent lookups stay valid under the shard locks)
            ctx.worker.load(str(self.root))
        except Exception as e:  # noqa: BLE001 — a bad dump must not kill serving
            self._m_failed.inc()
            logger.exception("rollover to session %s failed: %s", session, e)
            self._seen_session = session  # don't retry a broken dump forever
            return
        if self.cache is not None:
            self.cache.bump_epoch()
        if self._inc_loader is not None:
            # packets older than this checkpoint must not regress its entries
            self._inc_loader.skip_before_us = int(info.get("time_us", 0))
        self._seen_session = session
        self._m_version_ts.set(float(info.get("time_us", 0)))
        self.engine.swap(clone_infer_ctx(ctx, new_state), version=session)

    # --------------------------------------------------------------- thread

    def start(self) -> "ModelRollover":
        # synchronous first poll: a server started against an existing
        # checkpoint dir is versioned before it takes traffic
        try:
            self.poll_once()
        except Exception as e:  # noqa: BLE001
            logger.warning("initial rollover poll failed: %s", e)
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="serving-rollover"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — watcher must survive
                logger.warning("rollover poll failed (will retry): %s", e)


class _RouterStore:
    """Adapter: incremental packets re-route by sign across a multi-replica
    router (the loader only needs ``load_shard_bytes``)."""

    def __init__(self, worker):
        self._worker = worker

    def load_shard_bytes(self, body: bytes) -> int:
        from persia_tpu.embedding.hashing import sign_to_shard
        import numpy as np

        from persia_tpu.incremental import packet_signs

        replicas = self._worker.lookup_router.replicas
        signs = packet_signs(body)
        if not len(signs):
            return 0
        owner = sign_to_shard(np.asarray(signs, dtype=np.uint64), len(replicas))
        # split the packet per owning replica, preserving the wire format
        import struct

        from persia_tpu.incremental import iter_packet_entries

        parts: Dict[int, list] = {}
        for (sign, blob), own in zip(iter_packet_entries(body), owner.tolist()):
            parts.setdefault(own, []).append(blob)
        n = 0
        for own, blobs in parts.items():
            payload = struct.pack("<I", len(blobs)) + b"".join(blobs)
            n += replicas[own].load_shard_bytes(payload)
        return n

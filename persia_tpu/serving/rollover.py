"""Atomic model-version rollover for a long-running serving replica.

Training publishes two kinds of updates a server must absorb without a
restart or a dropped request:

- **full checkpoints** (persia_tpu/checkpoint.py): a directory becomes
  valid only when its ``embedding_dump_done`` marker lands; the marker's
  ``session`` id is the version. The watcher polls the marker, and on a
  new session: deserializes the dense half into a FRESH ``TrainState``
  (off the request path), reloads the embedding tables in place on the
  shared worker (per-shard locks keep concurrent lookups valid), bumps
  the hot-cache epoch, and only then swaps the engine handle — in-flight
  requests finish on the old dense state, new requests see the new one;
- **incremental packets** (persia_tpu/incremental.py): applied live by an
  ``IncrementalLoader`` whose ``on_apply`` hook invalidates exactly the
  updated signs in the hot cache. Packets that predate the current
  checkpoint are skipped via ``skip_before_us`` (the marker records its
  ``time_us`` for exactly this).

Failure handling runs ON the shared resilience engine
(service/resilience.py): a checkpoint that fails to apply retries through
the policy's seeded backoff before being abandoned, every failure counts
into the ``persia_tpu_serving_rollover_failures`` counter, and a delta
channel that reports unrecoverable damage (``needs_resync``) triggers a
**resync**: re-apply the newest full checkpoint (when one exists), then
replay the retained packet tail from a clean high-water mark.

The swap is wait-free for readers (one handle assignment, see
serving/engine.py); the expensive work — storage reads, flax
deserialization — happens on the watcher thread.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Optional, Union

from persia_tpu.checkpoint import DONE_MARKER
from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics
from persia_tpu.service.resilience import ResiliencePolicy, RetryPolicy, poll_until
from persia_tpu.serving.engine import InferenceEngine, clone_infer_ctx
from persia_tpu.storage import StorageError, StoragePath, storage_path
from persia_tpu.tracing import record_event

logger = get_default_logger("persia_tpu.serving.rollover")


class ModelRollover:
    """Tie a serving engine to a checkpoint dir (full rollovers) and an
    incremental dir (live deltas). ``ckpt_dir=None`` runs a delta-only
    watcher (freshness + packet apply, resync from the retained tail)."""

    def __init__(
        self,
        engine: InferenceEngine,
        ckpt_dir: Optional[Union[str, StoragePath]] = None,
        inc_dir: Optional[Union[str, StoragePath]] = None,
        cache=None,
        poll_interval_s: float = 2.0,
        inc_scan_interval_s: Optional[float] = None,
        policy: Optional[ResiliencePolicy] = None,
        arbiter=None,
    ):
        self.engine = engine
        # when attached, the version swap routes through the control-plane
        # arbiter's topology lease as a ROLLOVER intent — the load half
        # (storage reads, deserialization) stays off-lease on this thread
        self.arbiter = arbiter
        self.root = storage_path(ckpt_dir) if ckpt_dir is not None else None
        self.cache = cache
        self.poll_interval_s = poll_interval_s
        # apply/initial-poll retries ride the shared engine; rollover wants
        # patient backoff (storage may be mid-publish), not serving-fast
        self.policy = policy if policy is not None else ResiliencePolicy(
            retry=RetryPolicy(max_attempts=3, base_s=0.05, max_s=1.0)
        )
        self._seen_session: Optional[str] = None
        self._new_state = None  # staged by _apply_session for the swap
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._inc_loader = None
        if inc_dir is not None:
            from persia_tpu.incremental import IncrementalLoader

            self._inc_loader = IncrementalLoader(
                engine.ctx.worker.lookup_router.replicas[0]
                if len(engine.ctx.worker.lookup_router.replicas) == 1
                else _RouterStore(engine.ctx.worker),
                inc_dir,
                scan_interval_sec=inc_scan_interval_s or poll_interval_s,
                on_apply=(cache.invalidate if cache is not None else None),
            )
        m = get_metrics()
        self._m_version_ts = m.gauge(
            "persia_tpu_serving_model_time_us", "time_us of the live checkpoint"
        )
        self._m_failed = m.counter(
            "persia_tpu_serving_rollover_failures", "rollovers that failed to apply"
        )
        self._m_resyncs = m.counter(
            "persia_tpu_serving_resyncs",
            "full resyncs after delta-channel damage",
        )

    # ----------------------------------------------------------------- state

    @property
    def version(self) -> str:
        return self.engine.version

    def freshness(self) -> Optional[Dict]:
        return self._inc_loader.freshness() if self._inc_loader else None

    def _read_marker(self) -> Optional[Dict]:
        if self.root is None:
            return None
        try:
            return json.loads(self.root.join(DONE_MARKER).read_text())
        except (OSError, ValueError, StorageError):
            return None

    # ------------------------------------------------------------------ poll

    def poll_once(self) -> bool:
        """One watcher tick: apply a new checkpoint if the done-marker moved,
        then drain unseen incremental packets, then repair any channel
        damage the drain reported. Returns True iff a full rollover was
        applied."""
        rolled = False
        info = self._read_marker()
        if info is not None:
            session = str(info.get("session", info.get("datetime", "")))
            if session and session != self._seen_session:
                self._apply_checkpoint(info, session)
                rolled = True
        if self._inc_loader is not None:
            self._inc_loader.poll_once()
            if self._inc_loader.needs_resync:
                self._resync(info)
        return rolled

    def _resync(self, info: Optional[Dict]) -> None:
        """Delta-channel damage repair: re-apply the newest checkpoint (the
        authoritative base — a gap's lost signs may exist nowhere else),
        then replay the retained packet tail from clean marks."""
        self._m_resyncs.inc()
        record_event(
            "serving.resync",
            session=self._seen_session or "",
            has_checkpoint=info is not None,
        )
        if info is not None and self._seen_session is not None:
            logger.warning(
                "delta channel damaged: resyncing from checkpoint %s",
                self._seen_session,
            )
            try:
                self._apply_session(info)
            except Exception as e:  # noqa: BLE001 — resync retries next tick
                self._m_failed.inc()
                logger.warning("resync checkpoint re-apply failed: %s", e)
                return
        else:
            logger.warning(
                "delta channel damaged: no checkpoint — replaying the "
                "retained packet tail"
            )
        if self.cache is not None:
            self.cache.bump_epoch()
        self._inc_loader.resync()

    def _apply_session(self, info: Dict) -> None:
        """The load half of a rollover: dense deserialize + in-place sparse
        load. Raises on failure (caller owns retry/abandon policy)."""
        import flax.serialization

        from persia_tpu.checkpoint import load_dense

        ctx = self.engine.ctx
        new_state = ctx.state
        raw = load_dense(self.root, missing_ok=True)
        if raw is not None:
            new_state = flax.serialization.from_bytes(ctx.state, raw)
        # sparse half: in-place load on the shared store (entries re-route
        # by sign; concurrent lookups stay valid under the shard locks)
        ctx.worker.load(str(self.root))
        self._new_state = new_state

    def _apply_checkpoint(self, info: Dict, session: str) -> None:
        attempts = max(1, self.policy.retry.max_attempts)
        for attempt in range(attempts):
            try:
                self._apply_session(info)
                break
            except Exception as e:  # noqa: BLE001 — a bad dump must not kill serving
                self._m_failed.inc()
                logger.exception(
                    "rollover to session %s failed (attempt %d/%d): %s",
                    session, attempt + 1, attempts, e,
                )
                if attempt + 1 >= attempts:
                    # storage answered but the dump is broken: a fresh dump
                    # gets a fresh session id, so don't retry this one forever
                    self._seen_session = session
                    return
                self.policy.sleep_backoff(attempt)
        if self.cache is not None:
            self.cache.bump_epoch()
        if self._inc_loader is not None:
            # packets older than this checkpoint must not regress its entries
            self._inc_loader.skip_before_us = int(info.get("time_us", 0))
            # the checkpoint IS an applied state: replicas resynced from it
            # report its step as their floor (trainer-annotated markers)
            step = int(info.get("train_step", -1))
            if step > self._inc_loader.applied_step:
                self._inc_loader.applied_step = step
                self._inc_loader.applied_time_us = max(
                    self._inc_loader.applied_time_us, int(info.get("time_us", 0))
                )
        self._seen_session = session
        self._m_version_ts.set(float(info.get("time_us", 0)))
        new_ctx = clone_infer_ctx(self.engine.ctx, self._new_state)
        if self.arbiter is not None:
            from persia_tpu.autopilot import arbiter as arbitration

            self.arbiter.run(arbitration.Intent(
                arbitration.INTENT_ROLLOVER, "rollover",
                # swap returns the PRIOR version string (truthy!) — wrap it,
                # the arbiter coerces the execute result to a dict
                lambda _abort_check: {
                    "prior": self.engine.swap(new_ctx, version=session),
                },
                label=f"session {session}",
            ))
        else:
            self.engine.swap(new_ctx, version=session)

    # --------------------------------------------------------------- thread

    def start(self) -> "ModelRollover":
        # synchronous first poll through the policy engine: a server started
        # against an existing checkpoint dir is versioned before it takes
        # traffic, and a storage hiccup retries on seeded backoff instead of
        # silently serving unversioned
        try:
            poll_until(
                lambda: (self.poll_once() or True),
                timeout_s=max(2 * self.poll_interval_s, 5.0),
                policy=self.policy,
                what="initial rollover poll",
            )
        except Exception as e:  # noqa: BLE001 — serve cold; the loop keeps trying
            self._m_failed.inc()
            logger.warning("initial rollover poll failed: %s", e)
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="serving-rollover"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — watcher must survive
                self._m_failed.inc()
                logger.warning("rollover poll failed (will retry): %s", e)


class _RouterStore:
    """Adapter: incremental packets re-route by sign across a multi-replica
    router (the loader only needs ``load_shard_bytes``)."""

    def __init__(self, worker):
        self._worker = worker

    def load_shard_bytes(self, body: bytes) -> int:
        from persia_tpu.embedding.hashing import sign_to_shard
        import numpy as np

        from persia_tpu.incremental import packet_signs

        replicas = self._worker.lookup_router.replicas
        signs = packet_signs(body)
        if not len(signs):
            return 0
        owner = sign_to_shard(np.asarray(signs, dtype=np.uint64), len(replicas))
        # split the packet per owning replica, preserving the wire format
        import struct

        from persia_tpu.incremental import iter_packet_entries

        parts: Dict[int, list] = {}
        for (sign, blob), own in zip(iter_packet_entries(body), owner.tolist()):
            parts.setdefault(own, []).append(blob)
        n = 0
        for own, blobs in parts.items():
            payload = struct.pack("<I", len(blobs)) + b"".join(blobs)
            n += replicas[own].load_shard_bytes(payload)
        return n

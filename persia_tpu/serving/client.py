"""Blocking HTTP client for the serving plane.

Matches both :class:`~persia_tpu.serving.server.InferenceServer` (the
single-request server) and :class:`~persia_tpu.serving.server.ServingServer`
(the batched gateway-fronted one).

The transport is a hand-rolled HTTP/1.1 over a persistent per-thread
socket (``threading.local``): ``http.client`` costs ~0.4ms of interpreter
time per call and ships headers/body as separate Nagle-delayed segments —
at serving QPS the client library would dominate the measurement. Here a
request is ONE ``sendall`` of pre-assembled bytes and a response is a
buffered readline loop; a stale connection (server restarted, idle
timeout) retries once on a fresh one — predict is a read, so the replay
is safe.

Per-request deadlines travel as the ``X-Deadline-Ms`` header so the
server's admission control can drop a request whose caller has already
given up. Non-200 responses raise :class:`urllib.error.HTTPError` (429 =
shed, 504 = deadline expired) so callers can branch on ``e.code``.
"""

from __future__ import annotations

import io
import json
import socket
import threading
import urllib.error
from typing import Optional

import numpy as np

from persia_tpu import tracing
from persia_tpu.data import PersiaBatch


class _Conn:
    """One persistent keep-alive connection."""

    __slots__ = ("sock", "rfile")

    def __init__(self, host: str, port: int, timeout_s: float):
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.rfile = self.sock.makefile("rb", buffering=65536)

    def close(self) -> None:
        try:
            self.rfile.close()
            self.sock.close()
        except OSError:
            pass


class InferenceClient:
    """Blocking HTTP client. ``addr`` is ``host:port`` or a full URL."""

    def __init__(self, addr: str, timeout_s: float = 30.0):
        addr = addr[7:] if addr.startswith("http://") else addr
        host, _, port = addr.partition(":")
        self.host = host
        self.port = int(port or 80)
        self.base = f"http://{host}:{self.port}"
        self.timeout_s = timeout_s
        self._local = threading.local()

    # ------------------------------------------------------------- transport

    def _conn(self) -> _Conn:
        c = getattr(self._local, "conn", None)
        if c is None:
            c = _Conn(self.host, self.port, self.timeout_s)
            self._local.conn = c
        return c

    def _drop_conn(self) -> None:
        c = getattr(self._local, "conn", None)
        if c is not None:
            c.close()
            self._local.conn = None

    def _request(self, method: str, path: str, body: bytes = b"",
                 extra_headers: str = "") -> bytes:
        return self._request_ex(method, path, body, extra_headers)[0]

    def _request_ex(self, method: str, path: str, body: bytes = b"",
                    extra_headers: str = ""):
        """One request over the thread's persistent connection; a dead
        connection retries once on a fresh one (GET/predict are reads).
        Returns ``(data, response_headers)`` — header names lowercased
        (the serving plane's staleness contract rides ``x-staleness-steps``)."""
        head = (
            f"{method} {path} HTTP/1.1\r\nHost: {self.host}\r\n"
            f"Content-Length: {len(body)}\r\n{extra_headers}\r\n"
        ).encode()
        for attempt in (0, 1):
            conn = self._conn()
            try:
                conn.sock.sendall(head + body)
                line = conn.rfile.readline(8192)
                if not line:
                    raise ConnectionError("server closed connection")
                status = int(line.split(None, 2)[1])
                clen = 0
                close_after = False
                headers = {}
                while True:
                    h = conn.rfile.readline(8192)
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.partition(b":")
                    k = k.strip().lower()
                    headers[k.decode()] = v.strip().decode()
                    if k == b"content-length":
                        clen = int(v.strip())
                    elif k == b"connection" and v.strip().lower() == b"close":
                        close_after = True
                data = conn.rfile.read(clen) if clen else b""
            except (ConnectionError, socket.timeout, OSError, ValueError,
                    IndexError):
                self._drop_conn()
                if attempt:
                    raise
                continue
            if close_after:
                self._drop_conn()
            if status != 200:
                # an HTTP status is an APP answer over a healthy connection —
                # keep it; 429/504 are the admission-control contract
                raise urllib.error.HTTPError(
                    f"{self.base}{path}", status,
                    data.decode(errors="replace"), headers, io.BytesIO(data),
                )
            return data, headers
        raise ConnectionError("unreachable")  # pragma: no cover

    # -------------------------------------------------------------- surface

    def predict(self, batch: PersiaBatch,
                deadline_ms: Optional[float] = None) -> np.ndarray:
        return self.predict_bytes(batch.to_bytes(), deadline_ms=deadline_ms)

    def predict_bytes(self, raw: bytes,
                      deadline_ms: Optional[float] = None) -> np.ndarray:
        return self.predict_bytes_ex(raw, deadline_ms=deadline_ms)[0]

    def predict_bytes_ex(self, raw: bytes,
                         deadline_ms: Optional[float] = None):
        """Like :meth:`predict_bytes` but also returns the response headers
        (lowercased) — the serving replica advertises its freshness lag as
        ``x-staleness-steps`` there."""
        extra = ""
        if deadline_ms is not None:
            extra = f"X-Deadline-Ms: {float(deadline_ms)}\r\n"
        if tracing.enabled():
            # ship the ambient trace context (X-Trace-Id / X-Parent-Span)
            # so the replica's spans join this caller's timeline
            extra += "".join(
                f"{k}: {v}\r\n" for k, v in tracing.wire_headers().items()
            )
        data, headers = self._request_ex("POST", "/predict", raw, extra)
        return np.load(io.BytesIO(data)), headers

    def health(self) -> dict:
        return json.loads(self._request("GET", "/healthz"))

    def version(self) -> str:
        return self._request("GET", "/version").decode()

    def metrics_text(self) -> str:
        return self._request("GET", "/metrics").decode()

    def close(self) -> None:
        self._drop_conn()

"""HTTP front of the serving plane.

Two servers share the handler plumbing:

- :class:`InferenceServer` — the original single-request wrapper (one
  forward per request, no queue). Kept as the simple embedding of an
  ``InferCtx`` and as the unbatched baseline the serving benchmark
  measures against.
- :class:`ServingServer` — the production-plane replica: requests flow
  through the micro-batching engine (serving/batcher.py), PS lookups
  short-circuit through the hot-embedding cache (serving/cache.py), and a
  rollover watcher (serving/rollover.py) upgrades the model live from
  checkpoint done-markers + incremental packets. Registers itself with
  the coordinator under the ``inference`` role so a
  :class:`~persia_tpu.serving.gateway.ReplicaGateway` can discover it.

HTTP contract (both servers): ``POST /predict`` takes
``PersiaBatch.to_bytes()`` and returns ``.npy`` scores; ``GET /healthz``
liveness + model/version metadata; ``GET /metrics`` Prometheus text.
ServingServer adds status mapping for admission control: 429 when the
queue sheds, 504 when a request's ``X-Deadline-Ms`` expires.
"""

from __future__ import annotations

import io
import json
import socket
import socketserver
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from persia_tpu import tracing
from persia_tpu.logger import get_default_logger
from persia_tpu.serving.batcher import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
)
from persia_tpu.serving.cache import attach_cache
from persia_tpu.serving.engine import InferenceEngine

logger = get_default_logger("persia_tpu.serving")


def _npy_bytes(scores: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(scores, dtype=np.float32))
    return buf.getvalue()


def _worker_store_backend(worker) -> str:
    """Backend of the worker's first lookup replica (``native`` / ``numpy``
    / ``remote``) — replicas in one router share a construction path, so
    the first one speaks for the replica set."""
    try:
        from persia_tpu.embedding.native_store import store_backend_name

        replicas = worker.lookup_router._topo[0]
        return store_backend_name(replicas[0]) if replicas else "none"
    except Exception:  # noqa: BLE001 — health metadata is best-effort
        return "unknown"


class _HTTPServer(ThreadingHTTPServer):
    # stdlib default backlog is 5: a client fleet opening one TCP connection
    # per request overflows it at load and sees connection resets — admission
    # control must come from the batcher's bounded queue (429), never from
    # the kernel silently dropping SYNs
    request_queue_size = 1024
    daemon_threads = True


_STATUS_TEXT = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    429: "Too Many Requests", 500: "Internal Server Error",
    504: "Gateway Timeout",
}


class _LeanHandler(socketserver.StreamRequestHandler):
    """Minimal keep-alive HTTP/1.1 handler for the batched serving front.

    ``BaseHTTPRequestHandler`` costs ~3.5ms of GIL-held Python per request
    (email-module header parsing, per-request date/log formatting) — at
    coalesced-forward cost of ~0.1ms/request that parser IS the serving
    plane's throughput ceiling. This handler does one buffered readline per
    line, a bytes split per header, and a single ``sendall`` per response:
    ~10x less interpreter work. Subclasses implement
    ``route(method, path, headers, body) -> (status, payload, ctype)``.
    """

    def handle(self):
        self.connection.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                line = self.rfile.readline(8192)
                if not line or line in (b"\r\n", b"\n"):
                    return  # client closed (or stray blank between requests)
                try:
                    method, path, _version = line.split(None, 2)
                except ValueError:
                    return
                headers = {}
                while True:
                    h = self.rfile.readline(8192)
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.partition(b":")
                    headers[k.strip().lower().decode()] = v.strip().decode()
                n = int(headers.get("content-length", 0))
                body = self.rfile.read(n) if n else b""
                extra = {}
                try:
                    routed = self.route(
                        method.decode(), path.decode(), headers, body
                    )
                    # 3-tuple or (status, payload, ctype, extra_headers) —
                    # the 4th slot carries per-response contract headers
                    # (X-Staleness-Steps on /predict)
                    if len(routed) == 4:
                        status, payload, ctype, extra = routed
                    else:
                        status, payload, ctype = routed
                except Exception:  # noqa: BLE001 — route() maps its own errors
                    logger.exception("unhandled route error")
                    status, payload, ctype = 500, b"internal error", "text/plain"
                extra_lines = "".join(f"{k}: {v}\r\n" for k, v in extra.items())
                head = (
                    f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, 'Status')}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"{extra_lines}"
                    f"Content-Length: {len(payload)}\r\n\r\n"
                ).encode()
                self.wfile.write(head + payload)
                if headers.get("connection", "").lower() == "close":
                    return
        except (ConnectionError, OSError, ValueError):
            return

    def route(self, method: str, path: str, headers: dict, body: bytes):
        raise NotImplementedError


class _LeanHTTPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    request_queue_size = 1024


class InferenceServer:
    """Serve an ``InferCtx`` over HTTP, one forward per request.
    ``port=0`` picks a free port."""

    def __init__(self, infer_ctx, port: int = 0, host: str = "0.0.0.0"):
        self.ctx = infer_ctx
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # keep-alive: clients reuse one TCP connection per thread; the
            # per-request handshake otherwise dominates small-payload QPS
            protocol_version = "HTTP/1.1"
            # headers and body flush as separate segments — without NODELAY
            # every response risks a ~40ms Nagle/delayed-ACK stall
            disable_nagle_algorithm = True

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    meta = {
                        "status": "ok",
                        "model": type(outer.ctx.model).__name__,
                        "requests": outer.request_count,
                    }
                    self._send(200, json.dumps(meta).encode(), "application/json")
                elif self.path == "/metrics":
                    from persia_tpu.metrics import get_metrics

                    self._send(200, get_metrics().render().encode(), "text/plain")
                else:
                    self._send(404, b"not found", "text/plain")

            def do_POST(self):
                if self.path != "/predict":
                    self._send(404, b"not found", "text/plain")
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    raw = self.rfile.read(n)
                    scores = outer.ctx.predict_from_bytes(raw)
                    outer.request_count += 1
                    self._send(200, _npy_bytes(scores), "application/octet-stream")
                except Exception as e:  # noqa: BLE001 — app error crosses the wire
                    logger.exception("predict failed")
                    self._send(400, repr(e).encode(), "text/plain")

            def log_message(self, *a):
                pass

        self.request_count = 0
        self._httpd = _HTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "InferenceServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="persia-infer-http")
        self._thread.start()
        logger.info("inference server on port %d", self.port)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


class ServingServer:
    """Production serving replica: batched forwards, hot-embedding cache,
    live model rollover, coordinator registration.

    Knobs mirror the admission-control story (serving/batcher.py):
    ``max_batch`` rows / ``max_wait_ms`` close a coalescing window;
    ``queue_depth`` bounds admission (full → 429); ``cache_rows`` > 0
    interposes the hot-embedding LRU on the worker's lookup router;
    ``ckpt_dir``/``inc_dir`` arm the rollover watcher.
    """

    def __init__(
        self,
        infer_ctx,
        port: int = 0,
        host: str = "0.0.0.0",
        max_batch: int = 256,
        max_wait_ms: float = 2.0,
        queue_depth: int = 256,
        cache_rows: int = 0,
        ckpt_dir: Optional[str] = None,
        inc_dir: Optional[str] = None,
        rollover_poll_s: float = 2.0,
        coordinator: Optional[str] = None,
        replica_index: int = 0,
        version: str = "v0",
    ):
        self.cache = (
            attach_cache(infer_ctx.worker, capacity=cache_rows)
            if cache_rows > 0 else None
        )
        # which store implementation backs this replica's embedding lookups
        # (native C++ core / numpy golden model / remote RPC proxy) — the
        # one-native-data-path health signal, surfaced on /healthz so a
        # soak can assert every replica rides the intended backend
        self.store_backend = _worker_store_backend(
            getattr(infer_ctx, "worker", None)
        )
        self.engine = InferenceEngine(infer_ctx, version=version)
        self.batcher = MicroBatcher(
            self.engine.predict,
            max_batch=max_batch,
            max_wait_ms=max_wait_ms,
            queue_depth=queue_depth,
        )
        if ckpt_dir is not None or inc_dir is not None:
            from persia_tpu.serving.rollover import ModelRollover

            # inc_dir alone is valid: a delta-only replica (no full
            # checkpoints) still consumes the live stream and reports
            # freshness; resync then replays the retained packet tail
            self.rollover = ModelRollover(
                self.engine, ckpt_dir, inc_dir=inc_dir, cache=self.cache,
                poll_interval_s=rollover_poll_s,
            )
        else:
            self.rollover = None
        self._coordinator_addr = coordinator
        self.replica_index = replica_index
        self._coordinator_client = None
        outer = self

        class Handler(_LeanHandler):
            def route(self, method: str, path: str, headers: dict, body: bytes):
                if method == "POST" and path == "/predict":
                    # trace contract: a request carrying X-Trace-Id has its
                    # context adopted for the handler's duration, so the
                    # replica-side spans (request, batch forward, engine)
                    # join the caller's timeline
                    tid = headers.get("x-trace-id")
                    if tid:
                        with tracing.trace_context(
                            tid, headers.get("x-parent-span")
                        ):
                            return outer._predict_route(headers, body)
                    return outer._predict_route(headers, body)
                if method == "GET" and path == "/healthz":
                    return (200, json.dumps(outer.health()).encode(),
                            "application/json")
                if method == "GET" and path == "/metrics":
                    from persia_tpu.metrics import get_metrics

                    return 200, get_metrics().render().encode(), "text/plain"
                if method == "GET" and path == "/version":
                    return 200, outer.engine.version.encode(), "text/plain"
                return 404, b"not found", "text/plain"

        self._httpd = _LeanHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _predict_route(self, headers: dict, body: bytes):
        """The /predict route body (runs under the adopted trace context,
        if the request carried one)."""
        t0 = time.perf_counter()
        try:
            deadline_hdr = headers.get("x-deadline-ms")
            deadline_s = (
                float(deadline_hdr) / 1e3 if deadline_hdr else None
            )
            from persia_tpu.data import PersiaBatch

            with tracing.span("serving.request",
                              replica=self.replica_index):
                scores = self.batcher.submit(
                    PersiaBatch.from_bytes(body), deadline_s=deadline_s
                )
        except QueueFullError as e:
            return 429, repr(e).encode(), "text/plain"
        except DeadlineExceededError as e:
            return 504, repr(e).encode(), "text/plain"
        except Exception as e:  # noqa: BLE001 — app error crosses the wire
            logger.exception("predict failed")
            return 400, repr(e).encode(), "text/plain"
        # staleness contract: every answer states how far behind
        # the trainer head it was computed, so a caller (or the
        # gateway's all-replicas-stale fallback) can judge it
        extra = {}
        f = self.freshness()
        if f is not None:
            extra["X-Staleness-Steps"] = str(int(f["lag_steps"]))
        # latency attribution: the time this replica held the request
        # (queue wait + coalesced forward) — the gateway subtracts it
        # from its own wall clock to attribute the wire hop
        extra["X-Server-Ms"] = f"{(time.perf_counter() - t0) * 1e3:.3f}"
        tid = tracing.current_trace_id()
        if tid:
            extra["X-Trace-Id"] = tid
        return (200, _npy_bytes(scores),
                "application/octet-stream", extra)

    def freshness(self):
        """Freshness snapshot from the armed incremental loader (None when
        the replica has no delta channel — such a replica is exempt from
        staleness quarantine: there is nothing to lag behind)."""
        if self.rollover is not None and self.rollover._inc_loader is not None:
            return self.rollover._inc_loader.freshness()
        return None

    def health(self) -> dict:
        h = {
            "status": "ok",
            "model": self.engine.model_name(),
            "version": self.engine.version,
            "queue_depth": len(self.batcher._q),
            "store_backend": self.store_backend,
        }
        if self.cache is not None:
            h["cache"] = self.cache.stats()
        f = self.freshness()
        if f is not None:
            h["freshness"] = f
        return h

    def start(self) -> "ServingServer":
        self.batcher.start()
        if self.rollover is not None:
            self.rollover.start()
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="persia-serving-http")
        self._thread.start()
        if self._coordinator_addr:
            try:
                from persia_tpu.service.discovery import CoordinatorClient
                from persia_tpu.service.failure_detector import (
                    maybe_start_lease_publisher,
                )

                self._coordinator_client = CoordinatorClient(self._coordinator_addr)
                self._coordinator_client.register(
                    "inference", self.replica_index, f"127.0.0.1:{self.port}"
                )
                # heartbeat lease for the failure detector / the gateway's
                # silent-replica diagnostics (PERSIA_LEASE=0 opts out)
                self._lease = maybe_start_lease_publisher(
                    self._coordinator_client, "inference",
                    self.replica_index, f"127.0.0.1:{self.port}",
                )
            except Exception as e:  # noqa: BLE001 — serve even if discovery is down
                logger.warning("coordinator registration failed: %s", e)
        logger.info("serving replica on port %d (version %s)",
                    self.port, self.engine.version)
        return self

    def stop(self) -> None:
        if getattr(self, "_lease", None) is not None:
            self._lease.stop()
        if self.rollover is not None:
            self.rollover.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        self.batcher.stop()
        if self._coordinator_client is not None:
            self._coordinator_client.close()
        if self._thread:
            self._thread.join(timeout=5)

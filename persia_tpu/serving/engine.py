"""Versioned inference engine: the swappable core of a serving replica.

A long-running server must upgrade its model without dropping requests.
The engine holds ONE immutable handle ``(infer_ctx, version)``; readers
(the batcher's forward thread, health endpoints) grab the handle with a
single attribute read — atomic under the GIL — so a concurrent
:meth:`swap` can never expose a half-updated pair. The rollover watcher
(persia_tpu/serving/rollover.py) builds the replacement ``InferCtx``
off-thread (dense state deserialized, eval step rebuilt) and swaps it in
only when it is fully ready; in-flight forwards finish on the handle they
started with.

The sparse half intentionally does NOT swap: embedding tables load in
place on the shared worker/store (the same live-apply semantics as
incremental packets), so a swap only needs to replace the dense state and
bump the hot-embedding cache epoch.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

import numpy as np

from persia_tpu import tracing
from persia_tpu.data import PersiaBatch
from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics

logger = get_default_logger("persia_tpu.serving.engine")


class InferenceEngine:
    """Thread-safe holder of the live ``InferCtx`` + model version."""

    def __init__(self, infer_ctx, version: str = "v0"):
        # ONE tuple attribute: handle reads are a single bytecode, so a
        # reader can never see ctx from one version paired with another's id
        self._handle: Tuple[object, str] = (infer_ctx, version)
        self._swap_lock = threading.Lock()
        m = get_metrics()
        self._m_rollovers = m.counter(
            "persia_tpu_serving_rollovers", "model version swaps applied"
        )
        self._m_forwards = m.counter(
            "persia_tpu_serving_forwards", "jitted eval forwards executed"
        )
        self._m_forward_time = m.histogram(
            "persia_tpu_serving_forward_seconds", "jitted eval forward latency"
        )

    @property
    def ctx(self):
        return self._handle[0]

    @property
    def version(self) -> str:
        return self._handle[1]

    def predict(self, batch: PersiaBatch) -> np.ndarray:
        ctx, version = self._handle
        # the engine hop of the distributed trace: inherits the request's
        # trace_id when the caller (batcher forward thread / request
        # thread) adopted one, so a client id is visible down to the
        # jitted forward
        with tracing.span("serving.engine_forward", version=version,
                          rows=batch.batch_size):
            with self._m_forward_time.time():
                out = ctx.predict(batch)
        self._m_forwards.inc()
        return np.asarray(out)

    def predict_from_bytes(self, raw: bytes) -> np.ndarray:
        return self.predict(PersiaBatch.from_bytes(raw))

    def model_name(self) -> str:
        return type(self.ctx.model).__name__

    def swap(self, new_ctx, version: str) -> str:
        """Atomically replace the live context. Returns the old version."""
        with self._swap_lock:
            old_ctx, old_version = self._handle
            self._handle = (new_ctx, version)
        self._m_rollovers.inc()
        logger.info("model rollover: %s -> %s", old_version, version)
        return old_version


def clone_infer_ctx(ctx, new_state=None):
    """Build a fresh ``InferCtx`` sharing the model/worker/config of ``ctx``
    but holding ``new_state`` (rollover: the dense half swaps, the sparse
    half is the shared in-place store)."""
    from persia_tpu.ctx import InferCtx

    return InferCtx(
        model=ctx.model,
        state=new_state if new_state is not None else ctx.state,
        worker=ctx.worker,
        embedding_config=ctx.embedding_config,
        mesh=ctx.mesh,
    )

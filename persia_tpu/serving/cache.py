"""Infer-side hot-embedding LRU cache.

PERSIA's sign-access distribution is heavily skewed — that skew is the
reason its LRU parameter servers hold the working set at all. The same
skew makes an infer-side cache pay: head signs answer from a local cache
and never touch the PS tier (in the remote-PS deployment that is a network
round-trip per batch). The cache interposes on the worker's lookup router
and only serves ``train=False`` lookups — the training path must always
see the authoritative store.

Layout is vectorized, not a per-sign dict walk: the serving hot path runs
a coalesced batch's worth of signs per call, and profiling the batched
forward put an OrderedDict-LRU at ~6µs/sign — most of the forward. Here a
hit costs one C-speed ``dict.get`` per sign for the slot index and then a
single fancy-index gather; recency is an int64 stamp per slot bumped once
per *call* (approximate LRU: eviction takes the oldest stamps via
``argpartition``, batched). Rows live in one ``(capacity, dim)`` float32
pool per distinct dim (``capacity`` is per dim).

Freshness has two tiers, mirroring the update paths that exist:

- **incremental packets** (persia_tpu/incremental.py) carry exactly the
  signs they update → :meth:`invalidate` drops those entries; the next
  lookup refetches and counts as ``stale``;
- **checkpoint rollover** reloads the whole table → :meth:`bump_epoch`
  clears everything at once (an epoch bump, not a per-sign walk).

Gauges exported: hit/miss/stale counters, resident-entry gauge, epoch
gauge — a flat hit rate on a skewed stream is a misconfiguration signal
(capacity too small or invalidation storm), so it is first-class.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Sequence

import numpy as np

from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics

logger = get_default_logger("persia_tpu.serving.cache")

_FREE_SENTINEL = np.int64(1 << 62)  # free slots can never be eviction victims


class _DimPool:
    """Fixed-capacity row pool for one embedding dim."""

    __slots__ = ("rows", "signs", "stamp", "index", "free")

    def __init__(self, capacity: int, dim: int):
        self.rows = np.zeros((capacity, dim), dtype=np.float32)
        self.signs = np.zeros(capacity, dtype=np.uint64)
        self.stamp = np.full(capacity, _FREE_SENTINEL, dtype=np.int64)
        self.index: Dict[int, int] = {}  # sign -> slot
        self.free: List[int] = list(range(capacity - 1, -1, -1))


class HotEmbeddingCache:
    """Sign-keyed approximate-LRU of embedding rows (inference values only —
    no optimizer state; the PS remains authoritative)."""

    def __init__(self, capacity: int = 100_000):
        self.capacity = max(1, int(capacity))
        self._pools: Dict[int, _DimPool] = {}
        self._lock = threading.Lock()
        self._tick = 0
        self._epoch = 0
        # instance-local tallies: the process metric registry dedups by name,
        # so the exported counters aggregate across caches while stats()
        # must describe THIS cache
        self._hits = 0
        self._misses = 0
        self._stale = 0
        m = get_metrics()
        self._m_hits = m.counter(
            "persia_tpu_serving_cache_hits", "infer lookups served from the hot cache"
        )
        self._m_misses = m.counter(
            "persia_tpu_serving_cache_misses", "infer lookups forwarded to the PS tier"
        )
        self._m_stale = m.counter(
            "persia_tpu_serving_cache_stale",
            "entries dropped by incremental-packet invalidation",
        )
        self._m_size = m.gauge(
            "persia_tpu_serving_cache_entries", "rows resident in the hot cache"
        )
        self._m_epoch = m.gauge(
            "persia_tpu_serving_cache_epoch", "cache epoch (bumped on rollover)"
        )

    # -------------------------------------------------------------- lookups

    def lookup_through(self, inner_lookup, keys: np.ndarray, dim: int) -> np.ndarray:
        """Serve ``keys`` from the cache; fetch misses through
        ``inner_lookup(miss_keys, dim)`` and admit them."""
        keys = np.asarray(keys, dtype=np.uint64)
        n = len(keys)
        out = np.empty((n, dim), dtype=np.float32)
        with self._lock:
            pool = self._pools.get(dim)
            if pool is None:
                pool = self._pools[dim] = _DimPool(self.capacity, dim)
            self._tick += 1
            tick = self._tick
            get = pool.index.get
            idx = np.fromiter(
                (get(s, -1) for s in keys.tolist()), dtype=np.int64, count=n
            )
            hit = idx >= 0
            hslots = idx[hit]
            out[hit] = pool.rows[hslots]
            pool.stamp[hslots] = tick
            miss_pos = np.nonzero(~hit)[0]
            nh = int(hit.sum())
        if nh:
            self._hits += nh
            self._m_hits.inc(nh)
        if not len(miss_pos):
            return out
        self._misses += len(miss_pos)
        self._m_misses.inc(len(miss_pos))
        miss_keys = keys[miss_pos]
        rows = np.asarray(inner_lookup(miss_keys, dim), dtype=np.float32)
        out[miss_pos] = rows
        with self._lock:
            self._admit(pool, miss_keys, rows, tick)
            self._m_size.set(sum(len(p.index) for p in self._pools.values()))
        return out

    def _admit(self, pool: _DimPool, signs: np.ndarray, rows: np.ndarray,
               tick: int) -> None:
        """Insert fetched rows, evicting the oldest stamps in one batched
        ``argpartition`` when the pool is full. Caller holds the lock."""
        todo = []
        for i, s in enumerate(signs.tolist()):
            slot = pool.index.get(s)
            if slot is not None:  # duplicate key within the miss set
                pool.rows[slot] = rows[i]
                pool.stamp[slot] = tick
            else:
                todo.append((s, i))
        if len(todo) > self.capacity:  # wider than the cache: keep the tail
            todo = todo[-self.capacity:]
        need = len(todo) - len(pool.free)
        if need > 0:
            victims = np.argpartition(pool.stamp, need - 1)[:need]
            for v in victims.tolist():
                pool.index.pop(int(pool.signs[v]), None)
                pool.stamp[v] = _FREE_SENTINEL
                pool.free.append(v)
        for s, i in todo:
            slot = pool.free.pop()
            pool.rows[slot] = rows[i]
            pool.signs[slot] = s
            pool.stamp[slot] = tick
            pool.index[s] = slot

    # ----------------------------------------------------------- freshness

    def invalidate(self, signs: Sequence[int]) -> int:
        """Drop specific signs (incremental packet applied). Returns how
        many were actually resident."""
        dropped = 0
        with self._lock:
            for s in np.asarray(signs, dtype=np.uint64).tolist():
                for pool in self._pools.values():
                    slot = pool.index.pop(s, None)
                    if slot is not None:
                        pool.stamp[slot] = _FREE_SENTINEL
                        pool.free.append(slot)
                        dropped += 1
            self._m_size.set(sum(len(p.index) for p in self._pools.values()))
        if dropped:
            self._stale += dropped
            self._m_stale.inc(dropped)
        return dropped

    def bump_epoch(self) -> int:
        """Clear everything (checkpoint rollover). Returns the new epoch."""
        with self._lock:
            self._pools.clear()
            self._epoch += 1
            self._m_size.set(0)
            self._m_epoch.set(self._epoch)
            return self._epoch

    @property
    def epoch(self) -> int:
        return self._epoch

    def __len__(self) -> int:
        with self._lock:
            return sum(len(p.index) for p in self._pools.values())

    def stats(self) -> Dict:
        hits, misses = self._hits, self._misses
        total = hits + misses
        return {
            "hits": hits,
            "misses": misses,
            "hit_rate": (hits / total) if total else 0.0,
            "stale_dropped": self._stale,
            "entries": len(self),
            "epoch": self._epoch,
            "capacity": self.capacity,
        }


class CachedLookupRouter:
    """Drop-in wrapper over a worker's lookup router (``ShardedLookup`` or a
    single-replica store client): ``train=False`` lookups flow through the
    hot cache; everything else — training lookups, gradient updates,
    checkpoint ops — passes through untouched via ``__getattr__``."""

    def __init__(self, inner, cache: HotEmbeddingCache):
        self.inner = inner
        self.cache = cache

    def lookup(self, keys: np.ndarray, dim: int, train: bool) -> np.ndarray:
        if train:
            return self.inner.lookup(keys, dim, True)
        return self.cache.lookup_through(
            lambda k, d: self.inner.lookup(k, d, False), keys, dim
        )

    def lookup_groups(self, groups, train: bool):
        if train:
            return self.inner.lookup_groups(groups, True)
        # per-group through the cache; misses of all groups could batch into
        # one inner call, but the hot path is the all-hit case where no
        # inner call happens at all
        return [
            self.cache.lookup_through(
                lambda k, d: self.inner.lookup(k, d, False), keys, int(dim)
            )
            for keys, dim in groups
        ]

    def __getattr__(self, name):
        return getattr(self.inner, name)


def attach_cache(worker, capacity: int = 100_000) -> HotEmbeddingCache:
    """Interpose a :class:`HotEmbeddingCache` on ``worker``'s lookup router.
    Returns the cache (wire ``IncrementalLoader(on_apply=cache.invalidate)``
    and rollover's ``bump_epoch`` to keep it fresh)."""
    cache = HotEmbeddingCache(capacity=capacity)
    worker.lookup_router = CachedLookupRouter(worker.lookup_router, cache)
    return cache

"""The pipelined host feeder: prefetch, bounded staleness, reorder, and the
background gradient-return engine.

Parity target: the reference's Forward engine
(`rust/persia-core/src/forward.rs`): an input channel, an optional reorder
worker (min-heap on batch_id for reproducibility, forward.rs:396-468), N
lookup workers gated by the ``embedding_staleness`` semaphore
(forward.rs:509-511,686-701), and a postprocess/staging worker; plus the
Backward engine (`backward.rs`): a 2-stage pipeline returning gradients and
releasing staleness permits (backward.rs:304-354).

TPU-first shape: workers are Python threads (the hot work — C++ PS calls and
numpy staging — releases the GIL); "copy to device" is ``device_put`` with
mesh shardings instead of pinned-pool cudaMemcpyAsync; the staleness
semaphore bounds how many batches may run ahead of their gradient return,
exactly the reference's bounded-async knob. The asynchrony argument
(README.md:56): embedding lookup for batch N+k overlaps the TPU step of
batch N.
"""

from __future__ import annotations

import heapq
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

import numpy as np

from persia_tpu import diagnostics
from persia_tpu.data import PersiaBatch
from persia_tpu.logger import get_default_logger
from persia_tpu.tracing import span

logger = get_default_logger("persia_tpu.data_loader")

_SENTINEL = object()


@dataclass
class PersiaTrainingBatch:
    """What the loader yields: a fully staged step input
    (ref: PersiaTrainingBatch, forward.rs:38-99 + embedding2tensor)."""

    ref: int
    batch: PersiaBatch
    emb_batches: List
    device_batch: Dict
    counts: List
    batch_id: Optional[int] = None


class _WorkerError:
    def __init__(self, exc: BaseException):
        self.exc = exc


class BackwardEngine:
    """Asynchronous gradient return (ref: backward.rs).

    ``push`` enqueues (ref, slot_grads); worker threads apply
    ``worker.update_gradient_batched`` and release the staleness permit.
    ``flush`` blocks until every pushed gradient has been applied (used at
    eval/checkpoint boundaries)."""

    def __init__(
        self,
        emb_worker,
        release_permit: Callable[[], None],
        num_workers: int = 2,
        queue_size: int = 32,
    ):
        self._worker = emb_worker
        self._release = release_permit
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._pending = 0
        self._lock = threading.Lock()
        self._done = threading.Condition(self._lock)
        self._error: Optional[BaseException] = None
        self._threads = [
            threading.Thread(target=self._run, daemon=True, name=f"backward-{i}")
            for i in range(num_workers)
        ]
        for t in self._threads:
            t.start()

    def push(self, ref: int, slot_grads, scale_factor: float = 1.0) -> None:
        """``slot_grads`` is either the per-slot gradient dict or a zero-arg
        callable producing it — the callable form defers the device→host
        gradient fetch into this engine's thread so it overlaps the next
        step."""
        with self._lock:
            if self._error is not None:
                raise RuntimeError("backward engine failed") from self._error
            self._pending += 1
        self._q.put((ref, slot_grads, scale_factor))

    def _run(self):
        while True:
            item = self._q.get()
            if item is _SENTINEL:
                return
            ref, slot_grads, scale = item
            try:
                if callable(slot_grads):
                    slot_grads = slot_grads()
                self._worker.update_gradient_batched(ref, slot_grads, scale_factor=scale)
            except BaseException as e:  # noqa: BLE001 — propagate to trainer
                self._worker.abort_gradient(ref)
                with self._lock:
                    self._error = e
            finally:
                self._release()
                with self._lock:
                    self._pending -= 1
                    self._done.notify_all()

    def flush(self, timeout: Optional[float] = None) -> None:
        with self._lock:
            ok = self._done.wait_for(lambda: self._pending == 0, timeout=timeout)
            if not ok:
                raise TimeoutError("backward engine flush timed out")
            if self._error is not None:
                err, self._error = self._error, None
                raise RuntimeError("backward engine failed") from err

    def shutdown(self):
        for _ in self._threads:
            self._q.put(_SENTINEL)
        for t in self._threads:
            t.join(timeout=5)


class DataLoader:
    """Pipelined iterator over a ``PersiaBatch`` source
    (ref: persia/data.py:228-271 DataLoader owning the Rust Forward engine).

    - ``staleness``: max batches allowed past lookup before their gradients
      return (Semaphore; ref forward.rs:509-511). The permit is released by
      the ``BackwardEngine`` after the update lands, or by ``mark_consumed``
      for requires_grad=False streams.
    - ``reproducible``: process + yield strictly in batch_id order
      (ref: PerisaDataOrderManager min-heap, forward.rs:396-468).
    - ``num_workers``: concurrent lookup workers (ref: forward_worker count).
    """

    def __init__(
        self,
        dataset: Iterable[PersiaBatch],
        ctx,
        num_workers: int = 3,
        staleness: int = 4,
        reproducible: bool = False,
        buffer_size: int = 8,
        timeout_s: float = 120.0,
    ):
        if staleness < 1:
            raise ValueError("staleness must be >= 1")
        self.dataset = dataset
        self.ctx = ctx
        self.num_workers = 1 if reproducible else max(1, num_workers)
        self.reproducible = reproducible
        self.buffer_size = buffer_size
        self.timeout_s = timeout_s
        self.staleness_sem = threading.Semaphore(staleness)
        self.backward_engine = BackwardEngine(
            ctx.worker, release_permit=self.staleness_sem.release
        )
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------- pipeline

    def _feed(self, in_q: "queue.Queue"):
        try:
            next_id = 0
            for batch in self.dataset:
                if batch.batch_id is None:
                    batch.batch_id = next_id
                next_id = batch.batch_id + 1
                in_q.put(batch)
        except BaseException as e:  # noqa: BLE001
            in_q.put(_WorkerError(e))
        finally:
            in_q.put(_SENTINEL)

    def _reorder(self, in_q: "queue.Queue", out_q: "queue.Queue"):
        """Strict batch_id-order emitter (ref: forward.rs:396-468)."""
        heap: List = []
        expect: Optional[int] = None
        seq = 0  # tiebreak: duplicate batch_ids must not compare PersiaBatch
        try:
            while True:
                item = in_q.get()
                if item is _SENTINEL or isinstance(item, _WorkerError):
                    for _, _, b in sorted(heap):
                        out_q.put(b)
                    out_q.put(item)
                    return
                heapq.heappush(heap, (item.batch_id, seq, item))
                seq += 1
                if expect is None:
                    expect = heap[0][0]
                while heap and heap[0][0] <= expect:
                    bid, _, b = heapq.heappop(heap)
                    out_q.put(b)
                    expect = bid + 1
        except BaseException as e:  # noqa: BLE001
            out_q.put(_WorkerError(e))

    def _lookup_worker(self, in_q: "queue.Queue", out_q: "queue.Queue"):
        beat_key = f"data_loader.lookup_worker.{threading.current_thread().name}"
        try:
            self._lookup_loop(in_q, out_q, beat_key)
        finally:
            diagnostics.unregister(beat_key)

    def _lookup_loop(self, in_q: "queue.Queue", out_q: "queue.Queue", beat_key: str):
        while True:
            # not registered while idle: waiting for input isn't a stall
            diagnostics.unregister(beat_key)
            item = in_q.get()
            if item is _SENTINEL or isinstance(item, _WorkerError):
                in_q.put(item)  # let sibling workers see the sentinel too
                out_q.put(item)
                return
            batch = item
            diagnostics.heartbeat(beat_key)
            self.staleness_sem.acquire()  # bounded async (forward.rs:686-690)
            diagnostics.heartbeat(beat_key)
            try:
                train = batch.requires_grad
                with span("lookup", batch_id=batch.batch_id):
                    ref = self.ctx.worker.put_forward_ids(batch)
                    emb_batches = self.ctx.worker.forward_batch_id(ref, train=train)
                with span("stage", batch_id=batch.batch_id):
                    device_batch, counts = self.ctx.prepare_features(batch, emb_batches)
                out_q.put(
                    PersiaTrainingBatch(
                        ref=ref,
                        batch=batch,
                        emb_batches=emb_batches,
                        device_batch=device_batch,
                        counts=counts,
                        batch_id=batch.batch_id,
                    )
                )
            except BaseException as e:  # noqa: BLE001
                self.staleness_sem.release()
                out_q.put(_WorkerError(e))
                return

    # ------------------------------------------------------------- consumer

    def __iter__(self) -> Iterator[PersiaTrainingBatch]:
        diagnostics.maybe_start_from_env()  # detector lives where beats are
        in_q: "queue.Queue" = queue.Queue(maxsize=self.buffer_size)
        staged_q: "queue.Queue" = queue.Queue(maxsize=self.buffer_size)
        self._threads = [threading.Thread(target=self._feed, args=(in_q,), daemon=True)]
        if self.reproducible:
            mid_q: "queue.Queue" = queue.Queue(maxsize=self.buffer_size)
            self._threads.append(
                threading.Thread(target=self._reorder, args=(in_q, mid_q), daemon=True)
            )
            lookup_in = mid_q
        else:
            lookup_in = in_q
        for _ in range(self.num_workers):
            self._threads.append(
                threading.Thread(
                    target=self._lookup_worker, args=(lookup_in, staged_q), daemon=True
                )
            )
        for t in self._threads:
            t.start()

        finished_workers = 0
        emit_heap: List = []
        expect: Optional[int] = None
        try:
            while True:
                try:
                    item = staged_q.get(timeout=self.timeout_s)
                except queue.Empty:
                    raise TimeoutError(
                        f"no staged batch within {self.timeout_s}s "
                        f"(staleness deadlock? forgot to call backward()/mark_consumed()?)"
                    ) from None
                if isinstance(item, _WorkerError):
                    raise RuntimeError("data pipeline worker failed") from item.exc
                if item is _SENTINEL:
                    finished_workers += 1
                    if finished_workers >= self.num_workers:
                        for _, _, tb in sorted(emit_heap):
                            yield tb
                        return
                    continue
                if self.reproducible:
                    heapq.heappush(emit_heap, (item.batch_id, item.ref, item))
                    if expect is None:
                        expect = emit_heap[0][0]
                    while emit_heap and emit_heap[0][0] == expect:
                        yield heapq.heappop(emit_heap)[2]
                        expect += 1
                else:
                    yield item
        finally:
            self.backward_engine.flush(timeout=self.timeout_s)

    # --------------------------------------------------------------- grads

    def backward(
        self, training_batch: PersiaTrainingBatch, emb_grads, scale_factor: float = 1.0
    ) -> None:
        """Queue this batch's embedding gradients for asynchronous return."""
        slot_grads = self.ctx.emb_grads_to_slot_grads(
            training_batch.emb_batches, emb_grads, training_batch.counts
        )
        self.backward_engine.push(training_batch.ref, slot_grads, scale_factor)

    def backward_packed(
        self, training_batch: PersiaTrainingBatch, gpacked, scale_factor: float = 1.0
    ) -> None:
        """Queue the step's still-on-device packed gradient buffer; the
        engine thread materializes it (np.asarray = the bulk device→host
        transfer) and splits it per slot, keeping the transfer off the
        training loop's critical path."""
        from persia_tpu.parallel.train_step import unpack_step_grads

        def _materialize():
            emb_grads = unpack_step_grads(
                np.asarray(gpacked), training_batch.device_batch
            )
            return self.ctx.emb_grads_to_slot_grads(
                training_batch.emb_batches, emb_grads, training_batch.counts
            )

        self.backward_engine.push(training_batch.ref, _materialize, scale_factor)

    def mark_consumed(self, training_batch: PersiaTrainingBatch) -> None:
        """Release the staleness permit for a no-gradient batch (eval)."""
        if training_batch.batch.requires_grad:
            self.ctx.worker.abort_gradient(training_batch.ref)
        self.staleness_sem.release()

    def flush(self):
        self.backward_engine.flush(timeout=self.timeout_s)

    def shutdown(self):
        self.backward_engine.shutdown()

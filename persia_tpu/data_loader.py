"""The pipelined host feeder: prefetch, bounded staleness, reorder, and the
background gradient-return engine.

Parity target: the reference's Forward engine
(`rust/persia-core/src/forward.rs`): an input channel, an optional reorder
worker (min-heap on batch_id for reproducibility, forward.rs:396-468), N
lookup workers gated by the ``embedding_staleness`` semaphore
(forward.rs:509-511,686-701), and a postprocess/staging worker; plus the
Backward engine (`backward.rs`): a 2-stage pipeline returning gradients and
releasing staleness permits (backward.rs:304-354).

TPU-first shape: workers are Python threads (the hot work — C++ PS calls and
numpy staging — releases the GIL); "copy to device" is ``device_put`` with
mesh shardings instead of pinned-pool cudaMemcpyAsync; the staleness
semaphore bounds how many batches may run ahead of their gradient return,
exactly the reference's bounded-async knob. The asynchrony argument
(README.md:56): embedding lookup for batch N+k overlaps the TPU step of
batch N.
"""

from __future__ import annotations

import contextlib
import heapq
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

import numpy as np

from persia_tpu import diagnostics, tracing
from persia_tpu.data import PersiaBatch
from persia_tpu.logger import get_default_logger
from persia_tpu.tracing import span

logger = get_default_logger("persia_tpu.data_loader")

_SENTINEL = object()


@dataclass
class PersiaTrainingBatch:
    """What the loader yields: a fully staged step input
    (ref: PersiaTrainingBatch, forward.rs:38-99 + embedding2tensor)."""

    ref: int
    batch: PersiaBatch
    emb_batches: List
    device_batch: Dict
    counts: List
    batch_id: Optional[int] = None
    worker_idx: int = 0  # which embedding worker holds the ref (dataflow)
    ticket: Optional[int] = None  # reorder emit sequence (reproducible mode)
    # the batch's trace frame (trace_id, parent_span), opened at the lookup
    # edge — the async gradient return adopts it so the journaled PS apply
    # carries the same trace_id as the lookup that produced the batch
    trace_ctx: Optional[tuple] = None


class _WorkerError:
    def __init__(self, exc: BaseException):
        self.exc = exc


def wait_for_serving(worker, timeout_s: float = 60.0) -> None:
    """Block until the embedding worker (and, for an in-process worker, its
    PS replicas) answer readiness probes again (ref: forward workers block
    on wait_for_serving after an RPC error, forward.rs:708-716,748-761)."""
    if hasattr(worker, "wait_serving"):  # remote worker: probes its PS tier
        worker.wait_serving(timeout_s=timeout_s)
        return
    targets = []
    if hasattr(worker, "wait_ready"):
        targets.append(worker)
    for r in getattr(getattr(worker, "lookup_router", None), "replicas", []):
        if hasattr(r, "wait_ready"):
            targets.append(r)
    for t in targets:
        t.wait_ready(timeout_s=timeout_s)


def _is_rpc_error(e: BaseException) -> bool:
    """TRANSPORT failures only — direct, or relayed by a server whose own
    downstream died (the "unavailable:" marker). An ``RpcError`` carrying a
    plain "remote error:" is an application error — retrying/dropping those
    would silently mask real bugs (they stay fatal; the typed
    ``ForwardIdNotFound`` has its own handling at the call sites)."""
    from persia_tpu.service.rpc import _is_transportish

    return _is_transportish(e)


class BackwardEngine:
    """Asynchronous gradient return (ref: backward.rs).

    ``push`` enqueues (ref, slot_grads); worker threads apply
    ``worker.update_gradient_batched`` and release the staleness permit.
    ``flush`` blocks until every pushed gradient has been applied (used at
    eval/checkpoint boundaries).

    Failure policy (ref: the reference's backward tasks log RPC errors and
    keep the pipeline alive — bounded-async tolerates a dropped gradient
    batch): transport errors wait for the servers to report ready, then
    retry ONCE; a ``ForwardIdNotFound`` reply on the retry means the first
    attempt actually applied (the buffer entry was consumed) and counts as
    success. Anything still failing drops the batch's sparse gradients with
    a warning + metric. Non-transport errors stay fatal."""

    def __init__(
        self,
        emb_worker,
        release_permit: Callable[[], None],
        num_workers: int = 2,
        queue_size: int = 32,
    ):
        from persia_tpu.metrics import get_metrics

        self._worker = emb_worker
        self._release = release_permit
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._pending = 0
        self._lock = threading.Lock()
        self._done = threading.Condition(self._lock)
        self._error: Optional[BaseException] = None
        self._m_dropped = get_metrics().counter(
            "persia_tpu_gradient_batches_dropped",
            "gradient batches dropped after RPC failure + failed retry",
        )
        self._threads = [
            threading.Thread(target=self._run, daemon=True, name=f"backward-{i}")
            for i in range(num_workers)
        ]
        for t in self._threads:
            t.start()

    def push(
        self, ref: int, slot_grads, scale_factor: float = 1.0, worker=None,
        journal_id=None, trace_ctx=None,
    ) -> None:
        """``slot_grads`` is either the per-slot gradient dict or a zero-arg
        callable producing it — the callable form defers the device→host
        gradient fetch into this engine's thread so it overlaps the next
        step. ``worker`` overrides the engine's default target (multi-worker
        dataflow routes each ref back to the worker that holds it);
        ``journal_id`` tags the apply for the PS apply-journal
        (exactly-once trainer resume, persia_tpu.jobstate);
        ``trace_ctx`` is the batch's (trace_id, parent_span) frame — the
        engine thread adopts it so the PS apply RPC carries the id the
        lookup opened."""
        with self._lock:
            if self._error is not None:
                raise RuntimeError("backward engine failed") from self._error
            self._pending += 1
        self._q.put((ref, slot_grads, scale_factor, worker, journal_id,
                     trace_ctx))

    @staticmethod
    def _do_update(worker, ref: int, slot_grads, scale: float, jid) -> None:
        if jid is not None:
            worker.update_gradient_batched(
                ref, slot_grads, scale_factor=scale, journal_id=jid
            )
        else:
            worker.update_gradient_batched(ref, slot_grads, scale_factor=scale)

    def _apply(self, worker, ref: int, slot_grads, scale: float, jid=None) -> None:
        try:
            self._do_update(worker, ref, slot_grads, scale, jid)
            return
        except BaseException as e:  # noqa: BLE001
            if not _is_rpc_error(e):
                raise
            logger.warning("gradient update for ref %d hit %r; waiting for serving", ref, e)
        wait_for_serving(worker)
        try:
            self._do_update(worker, ref, slot_grads, scale, jid)
        except BaseException as e:  # noqa: BLE001
            if "ForwardIdNotFound" in repr(e):
                return  # first attempt consumed the buffer entry → applied
            if not _is_rpc_error(e):
                raise
            logger.error("dropping gradient batch ref %d after retry: %r", ref, e)
            self._m_dropped.inc()
            try:
                worker.abort_gradient(ref)
            except Exception:  # noqa: BLE001 — best-effort staleness release
                pass

    def _run(self):
        while True:
            item = self._q.get()
            if item is _SENTINEL:
                return
            ref, slot_grads, scale, worker, jid, trace_ctx = item
            worker = worker if worker is not None else self._worker
            try:
                with contextlib.ExitStack() as tstack:
                    if trace_ctx is not None:
                        tstack.enter_context(
                            tracing.trace_context(trace_ctx[0], trace_ctx[1])
                        )
                        tstack.enter_context(span("grad.apply", ref=ref))
                    if callable(slot_grads):
                        slot_grads = slot_grads()
                    self._apply(worker, ref, slot_grads, scale, jid)
            except BaseException as e:  # noqa: BLE001 — propagate to trainer
                try:
                    worker.abort_gradient(ref)
                except Exception:  # noqa: BLE001
                    pass
                with self._lock:
                    self._error = e
            finally:
                self._release()
                with self._lock:
                    self._pending -= 1
                    self._done.notify_all()

    def flush(self, timeout: Optional[float] = None) -> None:
        with self._lock:
            ok = self._done.wait_for(lambda: self._pending == 0, timeout=timeout)
            if not ok:
                raise TimeoutError("backward engine flush timed out")
            if self._error is not None:
                err, self._error = self._error, None
                raise RuntimeError("backward engine failed") from err

    def shutdown(self):
        for _ in self._threads:
            self._q.put(_SENTINEL)
        for t in self._threads:
            t.join(timeout=5)


class BatchCursor:
    """The loader cursor a job-state manifest records: wraps a batch
    iterable, counts what it hands out, and fast-forwards past batches a
    crashed run already consumed (persia_tpu.jobstate).

    Skipping happens HERE — before preprocessing, lookup, or staging — so
    resume pays one cheap iterator drain instead of re-running the
    pipeline for steps the fence already covers. Deterministic sources
    (the only kind a bit-identical resume supports) yield the same batch
    at the same ordinal every run, which is the whole contract."""

    def __init__(self, batches: Iterable[PersiaBatch], skip: int = 0):
        self._batches = batches
        self.skip = int(skip)
        self.consumed = int(skip)  # absolute ordinal of the next yield

    def __iter__(self) -> Iterator[PersiaBatch]:
        it = iter(self._batches)
        for _ in range(self.skip):
            next(it, None)
        for b in it:
            yield b
            self.consumed += 1

    def state(self) -> Dict:
        return {"consumed_batches": self.consumed}


class _OrderedSemaphore:
    """Staleness semaphore whose acquires are granted in TICKET order.

    Reproducible mode keeps all N lookup workers (the round-1 build clamped
    to 1) but must make the PS see lookups in batch order — otherwise which
    worker wins the permit race decides which updates a lookup observes.
    With tickets, N workers still pipeline preprocessing/staging while the
    lookup sequence is bit-deterministic (ref: the reorder manager + permit
    discipline, forward.rs:396-468,686-701)."""

    def __init__(self, permits: int):
        self._cv = threading.Condition()
        self._permits = permits
        self._next = 0

    def acquire(self, ticket: int) -> None:
        with self._cv:
            while ticket != self._next or self._permits <= 0:
                self._cv.wait()
            self._permits -= 1
            self._next += 1
            self._cv.notify_all()

    def release(self) -> None:
        with self._cv:
            self._permits += 1
            self._cv.notify_all()


class DataLoader:
    """Pipelined iterator over a ``PersiaBatch`` source
    (ref: persia/data.py:228-271 DataLoader owning the Rust Forward engine).

    - ``staleness``: max batches allowed past lookup before their gradients
      return (Semaphore; ref forward.rs:509-511). The permit is released by
      the ``BackwardEngine`` after the update lands, or by ``mark_consumed``
      for requires_grad=False streams.
    - ``reproducible``: process + yield strictly in batch_id order with
      lookups granted in ticket order (ref: PerisaDataOrderManager min-heap,
      forward.rs:396-468); with ``staleness=1`` results are bit-identical
      for any ``num_workers``.
    - ``num_workers``: concurrent lookup workers (ref: forward_worker count).
    """

    def __init__(
        self,
        dataset: Iterable[PersiaBatch],
        ctx,
        num_workers: int = 3,
        staleness: int = 4,
        reproducible: bool = False,
        buffer_size: int = 8,
        timeout_s: float = 120.0,
        recovery_retries: int = 3,
        emb_workers: Optional[List] = None,
        validator=None,
    ):
        if staleness < 1:
            raise ValueError("staleness must be >= 1")
        self.dataset = dataset
        self.ctx = ctx
        # optional data-plane integrity gate (health.BatchValidator): a
        # rejected batch is quarantined at the feed stage and never enters
        # the lookup pipeline — batch_ids stay contiguous for the survivors
        self.validator = validator
        # embedding-worker handles addressable by a dataflow batch's
        # remote_ref worker index (defaults to the ctx's single worker)
        self.emb_workers = list(emb_workers) if emb_workers else [ctx.worker]
        self.num_workers = max(1, num_workers)
        self.reproducible = reproducible
        self.buffer_size = buffer_size
        self.timeout_s = timeout_s
        self.recovery_retries = recovery_retries
        # shared resilience policy: the ctx may carry one (TrainCtx's
        # resilience_policy), else the process default — backoff delays and
        # breaker state are then consistent with the RPC clients'
        from persia_tpu.service.resilience import default_policy

        self._policy = (
            getattr(ctx, "resilience_policy", None) or default_policy()
        )
        self.staleness_sem = (
            _OrderedSemaphore(staleness)
            if reproducible
            else threading.Semaphore(staleness)
        )
        self.backward_engine = BackwardEngine(
            ctx.worker, release_permit=self.staleness_sem.release
        )
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------- pipeline

    def _feed(self, in_q: "queue.Queue"):
        try:
            next_id = 0
            for step, batch in enumerate(self.dataset):
                if (self.validator is not None
                        and not self.validator.admit(batch, step=step)):
                    continue  # quarantined: never assigned an id
                if batch.batch_id is None:
                    batch.batch_id = next_id
                next_id = batch.batch_id + 1
                in_q.put(batch)
        except BaseException as e:  # noqa: BLE001
            in_q.put(_WorkerError(e))
        finally:
            in_q.put(_SENTINEL)

    def _reorder(self, in_q: "queue.Queue", out_q: "queue.Queue"):
        """Ascending-batch_id emitter (ref: forward.rs:396-468). Emits
        ``(ticket, batch)`` — the ticket sequences the ordered staleness
        gate AND the consumer's yield order, so N lookup workers acquire in
        emit order.

        Contiguous ids emit immediately; gapped ids (a multi-trainer
        dataflow delivers every world_size-th id) emit through a bounded
        look-ahead window of ``buffer_size`` batches — a deterministic
        function of the dataset's arrival order either way. Loader skew
        beyond the window is the only thing that can still reorder."""
        heap: List = []
        expect: Optional[int] = None
        seq = 0  # tiebreak: duplicate batch_ids must not compare PersiaBatch
        ticket = 0
        try:
            while True:
                item = in_q.get()
                if item is _SENTINEL or isinstance(item, _WorkerError):
                    for _, _, b in sorted(heap):
                        out_q.put((ticket, b))
                        ticket += 1
                    out_q.put(item)
                    return
                heapq.heappush(heap, (item.batch_id, seq, item))
                seq += 1
                if expect is None:
                    expect = heap[0][0]
                while heap and (
                    heap[0][0] <= expect or len(heap) > self.buffer_size
                ):
                    bid, _, b = heapq.heappop(heap)
                    out_q.put((ticket, b))
                    ticket += 1
                    expect = bid + 1
        except BaseException as e:  # noqa: BLE001
            out_q.put(_WorkerError(e))

    def _lookup_worker(self, in_q: "queue.Queue", out_q: "queue.Queue"):
        beat_key = f"data_loader.lookup_worker.{threading.current_thread().name}"
        try:
            self._lookup_loop(in_q, out_q, beat_key)
        finally:
            diagnostics.unregister(beat_key)

    def _lookup_loop(self, in_q: "queue.Queue", out_q: "queue.Queue", beat_key: str):
        while True:
            # not registered while idle: waiting for input isn't a stall
            diagnostics.unregister(beat_key)
            item = in_q.get()
            if item is _SENTINEL or isinstance(item, _WorkerError):
                in_q.put(item)  # let sibling workers see the sentinel too
                out_q.put(item)
                return
            if self.reproducible:
                ticket, batch = item
            else:
                ticket, batch = None, item
            diagnostics.heartbeat(beat_key)
            # bounded async (forward.rs:686-690); reproducible mode grants
            # permits in ticket order so the PS sees a deterministic
            # lookup sequence regardless of worker count. The try below
            # must IMMEDIATELY follow the acquire (persia-lint CONC002):
            # any statement in the gap — even a heartbeat — can raise and
            # leak the permit, wedging the staleness window forever.
            if self.reproducible:
                self.staleness_sem.acquire(ticket)
            else:
                self.staleness_sem.acquire()
            try:
                diagnostics.heartbeat(beat_key)
                train = batch.requires_grad
                with contextlib.ExitStack() as tstack:
                    # per-batch trace edge: the lookup/stage spans, the
                    # lookup RPCs, and (via trace_ctx on the staged batch)
                    # the eventual gradient apply all share one trace_id
                    frame = (tstack.enter_context(tracing.trace_context())
                             if tracing.enabled() else None)
                    with span("lookup", batch_id=batch.batch_id):
                        widx, ref, emb_batches = self._lookup_with_recovery(batch, train)
                    with span("stage", batch_id=batch.batch_id):
                        device_batch, counts = self.ctx.prepare_features(batch, emb_batches)
                out_q.put(
                    PersiaTrainingBatch(
                        ref=ref,
                        batch=batch,
                        emb_batches=emb_batches,
                        device_batch=device_batch,
                        counts=counts,
                        batch_id=batch.batch_id,
                        worker_idx=widx,
                        ticket=ticket,
                        trace_ctx=frame,
                    )
                )
            except BaseException as e:  # noqa: BLE001
                self.staleness_sem.release()
                out_q.put(_WorkerError(e))
                return

    def _lookup_with_recovery(self, batch, train: bool):
        """One batch's id-buffer + lookup round-trip with transient-failure
        recovery: on an RPC error, block until the worker/PS tier reports
        ready again and re-submit the whole batch (a consumed-but-failed
        ref cannot be replayed — the buffer entry is gone), bounded by
        ``recovery_retries`` (ref: forward.rs:708-716,748-761 catches lookup
        errors, waits for serving, and continues).

        A dataflow batch arrives with ``remote_ref`` — ids already buffered
        at embedding worker ``widx`` — so the first attempt skips the
        re-send; a lost ref (expired/worker restart) falls back to
        re-submitting the ids carried in the batch."""
        from persia_tpu.service.resilience import Deadline

        remote = getattr(batch, "remote_ref", None)
        widx = remote[0] if remote else 0
        if widx >= len(self.emb_workers):
            raise RuntimeError(
                f"dataflow batch references embedding worker {widx} but this "
                f"DataLoader only knows {len(self.emb_workers)} — pass "
                f"emb_workers= matching the DataflowSender's worker list"
            )
        worker = self.emb_workers[widx]
        # the whole batch's recovery (all attempts + serving waits + backoff
        # sleeps) runs under ONE deadline budget, so a wedged tier bounds
        # this worker's stall at timeout_s instead of retries x timeout_s
        deadline = Deadline.after(self.timeout_s)
        last: Optional[BaseException] = None
        for attempt in range(self.recovery_retries + 1):
            ref: Optional[int] = None
            try:
                if remote is not None:
                    ref = remote[1]
                    remote = None  # any retry re-submits the ids
                else:
                    ref = worker.put_forward_ids(batch)
                return widx, ref, worker.forward_batch_id(ref, train=train)
            except BaseException as e:  # noqa: BLE001
                lost_ref = "ForwardIdNotFound" in repr(e)
                if (not (_is_rpc_error(e) or lost_ref)
                        or attempt == self.recovery_retries
                        or deadline.expired):
                    raise
                if ref is not None and not lost_ref:
                    # a lost forward_batch_id REPLY may have succeeded
                    # server-side (entry stashed, staleness++) — abort the
                    # orphan ref so the retry's fresh ref cannot leak the
                    # post-forward buffer entry + staleness slot forever
                    try:
                        worker.abort_gradient(ref)
                    except Exception:  # noqa: BLE001 — best effort
                        pass
                last = e
                logger.warning(
                    "lookup for batch %s failed (%r); waiting for serving "
                    "(attempt %d/%d)", batch.batch_id, e, attempt + 1,
                    self.recovery_retries,
                )
                if not lost_ref:
                    wait_for_serving(
                        worker, timeout_s=max(deadline.remaining(), 0.1)
                    )
                # shared backoff policy (service/resilience.py): jittered
                # delay between recovery attempts, capped by the budget
                time.sleep(min(
                    self._policy.backoff(attempt),
                    max(deadline.remaining(), 0.0),
                ))
        raise RuntimeError("unreachable") from last

    # ------------------------------------------------------------- consumer

    def __iter__(self) -> Iterator[PersiaTrainingBatch]:
        diagnostics.maybe_start_from_env()  # detector lives where beats are
        in_q: "queue.Queue" = queue.Queue(maxsize=self.buffer_size)
        staged_q: "queue.Queue" = queue.Queue(maxsize=self.buffer_size)
        self._threads = [threading.Thread(target=self._feed, args=(in_q,), daemon=True)]
        if self.reproducible:
            mid_q: "queue.Queue" = queue.Queue(maxsize=self.buffer_size)
            self._threads.append(
                threading.Thread(target=self._reorder, args=(in_q, mid_q), daemon=True)
            )
            lookup_in = mid_q
        else:
            lookup_in = in_q
        for _ in range(self.num_workers):
            self._threads.append(
                threading.Thread(
                    target=self._lookup_worker, args=(lookup_in, staged_q), daemon=True
                )
            )
        for t in self._threads:
            t.start()

        finished_workers = 0
        emit_heap: List = []
        expect = 0  # next ticket to yield (reproducible mode)
        try:
            while True:
                try:
                    item = staged_q.get(timeout=self.timeout_s)
                except queue.Empty:
                    raise TimeoutError(
                        f"no staged batch within {self.timeout_s}s "
                        f"(staleness deadlock? forgot to call backward()/mark_consumed()?)"
                    ) from None
                if isinstance(item, _WorkerError):
                    raise RuntimeError("data pipeline worker failed") from item.exc
                if item is _SENTINEL:
                    finished_workers += 1
                    if finished_workers >= self.num_workers:
                        for _, _, tb in sorted(emit_heap):
                            yield tb
                        return
                    continue
                if self.reproducible:
                    # yield in TICKET order (the reorder thread's emit
                    # sequence — contiguous by construction, unlike
                    # batch_ids which a multi-trainer dataflow strides)
                    heapq.heappush(emit_heap, (item.ticket, item.ref, item))
                    while emit_heap and emit_heap[0][0] == expect:
                        yield heapq.heappop(emit_heap)[2]
                        expect += 1
                else:
                    yield item
        finally:
            self.backward_engine.flush(timeout=self.timeout_s)

    # --------------------------------------------------------------- grads

    def backward(
        self, training_batch: PersiaTrainingBatch, emb_grads,
        scale_factor: float = 1.0, journal_id=None,
    ) -> None:
        """Queue this batch's embedding gradients for asynchronous return."""
        slot_grads = self.ctx.emb_grads_to_slot_grads(
            training_batch.emb_batches, emb_grads, training_batch.counts
        )
        self.backward_engine.push(
            training_batch.ref, slot_grads, scale_factor,
            worker=self.emb_workers[training_batch.worker_idx],
            journal_id=journal_id, trace_ctx=training_batch.trace_ctx,
        )

    def backward_packed(
        self, training_batch: PersiaTrainingBatch, gpacked,
        scale_factor: float = 1.0, journal_id=None,
    ) -> None:
        """Queue the step's still-on-device packed gradient buffer; the
        engine thread materializes it (np.asarray = the bulk device→host
        transfer) and splits it per slot, keeping the transfer off the
        training loop's critical path."""
        from persia_tpu.parallel.train_step import unpack_step_grads

        def _materialize():
            packed = np.asarray(gpacked)
            if not np.isfinite(packed).all():
                # poisoned grad buffer reaching the PS wire: note it for
                # the health ladder (the on-device sentinel zeroes these
                # when armed; unarmed, detection must still not be silent)
                from persia_tpu.metrics import get_metrics
                from persia_tpu.tracing import record_event

                get_metrics().counter(
                    "persia_tpu_health_nonfinite_grads",
                    "non-finite packed gradient buffers at host decode",
                ).inc()
                record_event("health.anomaly", cause="nonfinite_grad_buffer")
            emb_grads = unpack_step_grads(packed, training_batch.device_batch)
            return self.ctx.emb_grads_to_slot_grads(
                training_batch.emb_batches, emb_grads, training_batch.counts
            )

        self.backward_engine.push(
            training_batch.ref, _materialize, scale_factor,
            worker=self.emb_workers[training_batch.worker_idx],
            journal_id=journal_id, trace_ctx=training_batch.trace_ctx,
        )

    def mark_consumed(self, training_batch: PersiaTrainingBatch) -> None:
        """Release the staleness permit for a no-gradient batch (eval)."""
        if training_batch.batch.requires_grad:
            self.emb_workers[training_batch.worker_idx].abort_gradient(
                training_batch.ref
            )
        self.staleness_sem.release()

    def flush(self):
        self.backward_engine.flush(timeout=self.timeout_s)

    def staleness_state(self) -> Dict:
        """Staleness-window occupancy for the job-state manifest: at a
        snapshot fence (post-``flush``) ``outstanding`` must be 0 — every
        permit returned, every gradient landed."""
        with self.backward_engine._lock:
            outstanding = self.backward_engine._pending
        return {
            "outstanding_gradient_batches": outstanding,
            "cursor": (
                self.dataset.state()
                if isinstance(self.dataset, BatchCursor) else None
            ),
        }

    def shutdown(self):
        self.backward_engine.shutdown()

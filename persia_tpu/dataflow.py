"""Multi-loader → multi-trainer dataflow routing.

Parity target: ``rust/persia-core/src/nats.rs:145-407``
(``PersiaDataFlowComponent``): each data-loader replica assigns GLOBAL batch
ids ``batch_id = local_idx * replica_size + replica_index`` so ids are
unique and interleave deterministically across loaders; id features
round-robin across embedding workers (with ``can_forward_batched``
backpressure + retry, nats.rs:250-312) and the dense half routes to trainer
``rank = batch_id % world_size`` (nats.rs:314-353).

TPU-first differences: the trainer-side receiver is a bounded
``MessageQueueServer`` on the framework's framed RPC layer (replacing the
NATS DataflowService channel); the wire batch carries BOTH the remote
forward ref AND the id features, so a trainer can recover from a lost ref
(worker restart) by re-submitting the ids — the reference would drop the
batch there.
"""

from __future__ import annotations

import struct
import time
from typing import Iterable, Iterator, List, Optional, Sequence

from persia_tpu.data import PersiaBatch
from persia_tpu.logger import get_default_logger
from persia_tpu.mq import MessageQueueClient, MessageQueueServer

logger = get_default_logger("persia_tpu.dataflow")

_REF_MAGIC = b"PREF"
_DONE = b"PDONE"


def _pack_meta(worker_idx: int, ref: int, user_meta: Optional[bytes]) -> bytes:
    return _REF_MAGIC + struct.pack("<iq", worker_idx, ref) + (user_meta or b"")


def _unpack_meta(meta: Optional[bytes]):
    """Returns ((worker_idx, ref) | None, user_meta)."""
    if meta is None or not meta.startswith(_REF_MAGIC):
        return None, meta
    worker_idx, ref = struct.unpack_from("<iq", meta, len(_REF_MAGIC))
    rest = meta[len(_REF_MAGIC) + 12:]
    return (worker_idx, ref), (rest or None)


class TrainerDataflow:
    """Trainer-side dense-batch receiver (ref: DataflowService,
    nats.rs:102-140): a bounded MQ the loaders push serialized batches into.

    ``dataset(num_loaders)`` yields ``PersiaBatch`` (with ``remote_ref`` and
    global ``batch_id`` restored) until every loader has sent its
    end-of-stream marker — feed it straight into ``DataLoader``
    (reproducible mode restores global batch order via its reorder heap).
    """

    def __init__(self, port: int = 0, capacity: int = 64):
        self._mq = MessageQueueServer(port=port, capacity=capacity).start()

    @property
    def port(self) -> int:
        return self._mq.port

    def stop(self) -> None:
        self._mq.stop()

    def dataset(
        self, num_loaders: int, timeout_s: float = 300.0
    ) -> Iterator[PersiaBatch]:
        client = MessageQueueClient(f"127.0.0.1:{self.port}")
        try:  # close on TimeoutError and on an abandoned generator too
            done = 0
            deadline = time.time() + timeout_s
            while done < num_loaders:
                raw = client.get(timeout_ms=2000)
                if raw is None:
                    if time.time() > deadline:
                        raise TimeoutError(
                            f"dataflow: only {done}/{num_loaders} loaders "
                            f"finished within {timeout_s}s"
                        )
                    continue
                deadline = time.time() + timeout_s
                if raw == _DONE:
                    done += 1
                    continue
                batch = PersiaBatch.from_bytes(raw)
                batch.remote_ref, batch.meta = _unpack_meta(batch.meta)
                yield batch
        finally:
            client.close()


class DataflowSender:
    """Data-loader side (ref: PersiaDataFlowComponent, nats.rs:145-407).

    ``workers``: embedding-worker handles (``WorkerClient`` or in-process
    ``EmbeddingWorker``); ``trainer_addrs``: every trainer's
    ``TrainerDataflow`` MQ address, indexed by rank.
    """

    def __init__(
        self,
        workers: Sequence,
        trainer_addrs: Sequence[str],
        replica_index: int = 0,
        replica_size: int = 1,
        backpressure_timeout_s: float = 120.0,
    ):
        if replica_size < 1 or not (0 <= replica_index < replica_size):
            raise ValueError("bad replica_index/replica_size")
        self.workers = list(workers)
        self.trainers = [MessageQueueClient(a) for a in trainer_addrs]
        self.replica_index = replica_index
        self.replica_size = replica_size
        self.backpressure_timeout_s = backpressure_timeout_s
        self._local = 0

    def send(self, batch: PersiaBatch) -> int:
        """Assign the global batch id, buffer ids at the owning embedding
        worker (backpressure-aware), and route the batch to its trainer.
        Returns the global batch id."""
        bid = self._local * self.replica_size + self.replica_index
        self._local += 1
        batch.batch_id = bid
        widx = bid % len(self.workers)
        worker = self.workers[widx]
        deadline = time.time() + self.backpressure_timeout_s
        while not worker.can_forward_batched():  # ref: nats.rs:250-312
            if time.time() > deadline:
                raise TimeoutError("embedding worker forward buffer full")
            time.sleep(0.05)
        ref = worker.put_forward_ids(batch)
        user_meta = batch.meta
        batch.meta = _pack_meta(widx, ref, user_meta)
        try:
            rank = bid % len(self.trainers)  # ref: nats.rs:314-353
            self.trainers[rank].put(batch.to_bytes())
        finally:
            batch.meta = user_meta
        return bid

    def send_all(self, batches: Iterable[PersiaBatch]) -> int:
        n = 0
        for b in batches:
            self.send(b)
            n += 1
        self.finish()
        return n

    def finish(self) -> None:
        """Signal end-of-stream to every trainer."""
        for t in self.trainers:
            t.put(_DONE)

    def close(self) -> None:
        for t in self.trainers:
            t.close()

"""Standalone persistent message queue over HTTP.

Parity target: `PersiaMessageQueueServer/Client`
(`rust/persia-core/src/utils.rs:9-79`) — a hyper HTTP queue utility where
PUT enqueues a byte payload and GET blocks until one is available.

Implemented on the framework's framed-TCP RPC layer
(`persia_tpu/service/rpc.py`) rather than raw HTTP: same wire stack as every
other service, optional compression for large payloads for free.
"""

from __future__ import annotations

import queue
import struct
import time
from typing import Optional

from persia_tpu.service.rpc import RpcClient, RpcServer


class MessageQueueServer:
    """Bounded byte-payload queue served over the RPC layer."""

    def __init__(self, port: int = 0, capacity: int = 1 << 14):
        self._q: "queue.Queue[bytes]" = queue.Queue(maxsize=capacity)
        self.server = RpcServer(port=port)
        self.server.register("mq_put", self._put)
        self.server.register("mq_get", self._get)
        self.server.register("mq_size", self._size)

    @property
    def port(self) -> int:
        return self.server.port

    def start(self) -> "MessageQueueServer":
        self.server.start()
        return self

    def stop(self) -> None:
        self.server.stop()

    # handlers (bytes in, bytes out). Server-side waits are bounded to
    # _MAX_WAIT_S so they always finish inside the RPC client's socket
    # timeout; "wait forever" is the client's long-poll loop.
    _MAX_WAIT_S = 10.0

    def _put(self, payload: bytes) -> bytes:
        (timeout_ms,) = struct.unpack("<I", payload[:4])
        wait = min(timeout_ms / 1e3, self._MAX_WAIT_S) if timeout_ms else self._MAX_WAIT_S
        try:
            self._q.put(payload[4:], timeout=wait)
            return b"\x01"
        except queue.Full:
            return b"\x00"

    def _get(self, payload: bytes) -> bytes:
        (timeout_ms,) = struct.unpack("<I", payload)
        wait = min(timeout_ms / 1e3, self._MAX_WAIT_S) if timeout_ms else self._MAX_WAIT_S
        try:
            return b"\x01" + self._q.get(timeout=wait)
        except queue.Empty:
            return b"\x00"

    def _size(self, payload: bytes) -> bytes:
        return struct.pack("<I", self._q.qsize())


class MessageQueueClient:
    def __init__(self, addr: str):
        self.client = RpcClient(addr)

    def put(self, payload: bytes, timeout_s: Optional[float] = None) -> None:
        """Enqueue; blocks (long-polling) while the queue is full."""
        deadline = None if timeout_s is None else time.time() + timeout_s
        while True:
            remaining_ms = 0
            if deadline is not None:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError("message queue full")
                remaining_ms = max(int(remaining * 1e3), 1)
            frame = struct.pack("<I", remaining_ms) + payload
            if self.client.call("mq_put", frame) == b"\x01":
                return
            if deadline is not None and time.time() >= deadline:
                raise TimeoutError("message queue full")

    def get(self, timeout_ms: int = 0) -> Optional[bytes]:
        """Dequeue; ``timeout_ms`` 0 = wait forever (client long-polls in
        bounded server-side waits); returns None on timeout."""
        deadline = None if timeout_ms == 0 else time.time() + timeout_ms / 1e3
        while True:
            remaining_ms = 0
            if deadline is not None:
                remaining = deadline - time.time()
                if remaining <= 0:
                    return None
                remaining_ms = max(int(remaining * 1e3), 1)
            resp = self.client.call("mq_get", struct.pack("<I", remaining_ms),
                                    idempotent=False)
            if resp[:1] == b"\x01":
                return resp[1:]
            if deadline is not None and time.time() >= deadline:
                return None

    def size(self) -> int:
        return struct.unpack("<I", self.client.call("mq_size", b""))[0]

    def close(self) -> None:
        self.client.close()

"""The HEAL actuator: verdicts in, exactly-once fleet repairs out.

Closes the sense→decide→heal loop over the
:class:`~persia_tpu.service.failure_detector.FailureDetector`'s verdicts
under the SAME discipline as every other autopilot actuator:

- **Guarded decisions** — DEAD heals fire immediately (the detector's
  N-consecutive-miss rule IS the debounce; MTTR is the product), but every
  fleet mutation is followed by a cooldown window of quiet polls so the
  detector re-baselines against the new topology before the next decision;
  GRAY drains additionally wait a min-dwell of stable verdicts (a replica
  that flaps between gray and live must not be drained), and fleet resizes
  ride the full hysteresis + dwell treatment. Held decisions count as
  suppressed flaps, exported like the PolicyEngine's.
- **Two-phase journal** — commit a ``planned`` manifest carrying the full
  decision (victim, batch re-advance counts, target size) + policy state,
  actuate, commit ``done``. The healer itself can be SIGKILLed mid-heal:
  :meth:`Healer.resume` re-drives the newest planned-without-done decision
  and converges exactly-once because every actuation is idempotent by
  construction (snapshot replay into a fresh standby is deterministic,
  coordinator registration is an upsert, ``reshard_ps`` resumes through
  the journal-deduped elastic engine).
- **MTTR is measured, not assumed** — each heal records
  detect→promoted→fresh durations (``mttr_s``) into the result manifest, a
  histogram metric, and :attr:`Healer.mttr_s` for the bench's percentiles.
"""

from __future__ import annotations

import threading
import time
from dataclasses import asdict, dataclass
from typing import Callable, Dict, List, Optional

from persia_tpu import elastic, jobstate
from persia_tpu.analysis.crashcheck import reach
from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics
from persia_tpu.tracing import record_event, span

from persia_tpu.autopilot import arbiter as arbitration
from persia_tpu.autopilot.policy import KIND_HEAL, Decision
from persia_tpu.service.failure_detector import (
    VERDICT_DEAD,
    VERDICT_GRAY,
)

logger = get_default_logger("persia_tpu.autopilot.heal")

ACTION_PROMOTE = "promote"
ACTION_DRAIN_GRAY = "drain_gray"
ACTION_RESIZE = "resize"


@dataclass
class HealConfig:
    # quiet polls after ANY fleet mutation: the detector must re-baseline
    # (fresh probes, empty latency windows) before the next decision
    heal_cooldown_polls: int = 2
    # a GRAY verdict must hold this many consecutive on_poll rounds
    # before the drain fires (on top of the detector's gray_windows)
    gray_min_dwell: int = 2
    # --- fleet resize (grow/shrink via reshard_ps) ---
    grow_lag_steps: float = 64.0  # freshness lag that demands capacity
    grow_quarantine_pressure: int = 2  # quarantined replicas ditto
    shrink_lag_frac: float = 0.25  # shrink only below this · grow_lag_steps
    size_min: int = 1
    size_max: int = 8
    resize_min_dwell: int = 2  # target must persist this many rounds

    def to_dict(self) -> Dict:
        return asdict(self)


class HealPolicy:
    """Pure decision layer over one verdict snapshot + resize sensors.

    At most ONE decision per round, priority DEAD > GRAY > resize: a dead
    shard is an availability hole, a gray one a latency hole, a resize an
    optimization — and healing the former usually changes the sensor
    picture the latter would act on."""

    def __init__(self, cfg: Optional[HealConfig] = None):
        self.cfg = cfg or HealConfig()
        self.suppressed = 0
        self._cooldown = 0
        self._gray_dwell: Dict[int, int] = {}
        self._resize_target: Optional[int] = None
        self._resize_dwell = 0

    def decide(self, verdicts: Dict[int, str],
               sensors: Optional[Dict] = None) -> Optional[Decision]:
        c = self.cfg
        dead = sorted(i for i, v in verdicts.items() if v == VERDICT_DEAD)
        gray = sorted(i for i, v in verdicts.items() if v == VERDICT_GRAY)
        # gray dwell clocks tick on verdicts, cooled down or not — a drain
        # must not also re-wait its dwell because a promote just ran
        for i in list(self._gray_dwell):
            if i not in gray:
                del self._gray_dwell[i]
        for i in gray:
            self._gray_dwell[i] = self._gray_dwell.get(i, 0) + 1
        if self._cooldown > 0:
            self._cooldown -= 1
            if dead or gray:
                self.suppressed += 1
            return None
        if dead:
            victim = dead[0]
            self._cooldown = c.heal_cooldown_polls
            return Decision(
                KIND_HEAL,
                f"replica {victim} DEAD (N-consecutive probe misses)",
                {"action": ACTION_PROMOTE, "victim": int(victim)},
            )
        ready = [i for i in gray if self._gray_dwell.get(i, 0) >= c.gray_min_dwell]
        if gray and not ready:
            self.suppressed += 1  # dwell held a clearing drain back
        if ready:
            victim = ready[0]
            self._cooldown = c.heal_cooldown_polls
            self._gray_dwell.pop(victim, None)
            return Decision(
                KIND_HEAL,
                f"replica {victim} GRAY for >= {c.gray_min_dwell} rounds",
                {"action": ACTION_DRAIN_GRAY, "victim": int(victim)},
            )
        return self._decide_resize(sensors)

    def _decide_resize(self, sensors: Optional[Dict]) -> Optional[Decision]:
        c = self.cfg
        if not sensors or "n_ps" not in sensors:
            return None
        n = int(sensors["n_ps"])
        lag = float(sensors.get("freshness_lag", 0.0))
        pressure = int(sensors.get("quarantine_pressure", 0))
        if lag > c.grow_lag_steps or pressure >= c.grow_quarantine_pressure:
            target = n + 1
        elif (lag < c.shrink_lag_frac * c.grow_lag_steps and pressure == 0
              and n > c.size_min):
            target = n - 1
        else:
            target = n
        target = min(max(target, c.size_min), c.size_max)
        if target == n:
            self._resize_target = None
            self._resize_dwell = 0
            return None
        if self._resize_target != target:
            # hysteresis dwell: a fresh target starts its clock; acting on
            # the first breach round would flap on sensor noise
            self._resize_target = target
            self._resize_dwell = 1
            self.suppressed += 1
            return None
        self._resize_dwell += 1
        if self._resize_dwell <= c.resize_min_dwell:
            self.suppressed += 1
            return None
        self._resize_dwell = 0
        self._resize_target = None
        self._cooldown = c.heal_cooldown_polls
        return Decision(
            KIND_HEAL,
            f"fleet {n} -> {target} (lag {lag:.1f} steps, "
            f"{pressure} quarantined)",
            {"action": ACTION_RESIZE, "n_new": int(target), "from": int(n),
             "freshness_lag": lag, "quarantine_pressure": pressure},
        )

    def export_state(self) -> Dict:
        return {
            "suppressed": int(self.suppressed),
            "cooldown": int(self._cooldown),
            "gray_dwell": {str(k): int(v) for k, v in self._gray_dwell.items()},
            "resize_target": self._resize_target,
            "resize_dwell": int(self._resize_dwell),
        }

    def load_state(self, state: Dict) -> None:
        self.suppressed = int(state.get("suppressed", 0))
        self._cooldown = int(state.get("cooldown", 0))
        self._gray_dwell = {int(k): int(v) for k, v in
                            (state.get("gray_dwell") or {}).items()}
        rt = state.get("resize_target")
        self._resize_target = None if rt is None else int(rt)
        self._resize_dwell = int(state.get("resize_dwell", 0))


class Healer:
    """Two-phase journaled executor of :class:`HealPolicy` decisions.

    Actuators are injected callables (same pattern as
    :class:`~persia_tpu.autopilot.controller.Autopilot`):

    - ``promote(victim, batch_advances) -> addr`` — fail a DEAD shard over
      onto a warm standby (``ServiceCtx.heal_promote``).
    - ``drain(victim, batch_advances) -> addr`` — live-replace a GRAY
      replica (``ServiceCtx.heal_drain_gray``).
    - ``resize(n_new) -> dict`` — grow/shrink the fleet
      (``ServiceCtx.reshard_ps`` at a drained fence).
    - ``sensors() -> dict`` — ``{"n_ps", "freshness_lag",
      "quarantine_pressure"}`` for the resize policy.
    - ``batch_advances() -> {group: count}`` — evaluated at PLAN time and
      recorded in the decision manifest, so a resumed heal re-advances the
      standby's optimizer clock from the SAME counts (bit-parity across
      the healer's own death).

    ``detector`` may be None for pure actuator tests; with one, every
    ``on_poll`` round polls it, and a completed promote/drain resets the
    victim's history with a fresh probe (``probe_factory(addr)``) so the
    newcomer does not inherit the corpse's verdict."""

    def __init__(
        self,
        state_dir,
        *,
        detector=None,
        policy: Optional[HealPolicy] = None,
        promote: Optional[Callable] = None,
        drain: Optional[Callable] = None,
        resize: Optional[Callable] = None,
        resume_resize: Optional[Callable] = None,
        sensors: Optional[Callable] = None,
        batch_advances: Optional[Callable] = None,
        probe_factory: Optional[Callable] = None,
        fault_hook: Optional[Callable] = None,
        clock: Callable[[], float] = time.monotonic,
        arbiter=None,
    ):
        self.mgr = jobstate.coerce_manager(state_dir)
        # when attached, heals route through the control-plane arbiter:
        # promote/drain outrank everything (and may preempt an in-flight
        # reshard), a RESIZE is itself a preemptable reshard intent
        self.arbiter = arbiter
        self.detector = detector
        self.policy = policy or HealPolicy()
        self._promote = promote
        self._drain = drain
        self._resize = resize
        self._resume_resize = resume_resize
        self._sensors = sensors
        self._batch_advances = batch_advances
        self._probe_factory = probe_factory
        self._fault_hook = fault_hook
        self.clock = clock
        self.rounds = 0
        self.heals = 0
        self.mttr_s: List[float] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        m = get_metrics()
        self._m_decisions = m.counter(
            "persia_tpu_heal_decisions", "heal decisions actuated, by action",
        )
        self._m_suppressed = m.counter(
            "persia_tpu_heal_suppressed",
            "heal decisions held by cooldown/dwell guards",
        )
        self._m_mttr = m.histogram(
            "persia_tpu_heal_mttr_seconds",
            "detect -> healed durations",
            buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
        )
        self._m_resumed = m.counter(
            "persia_tpu_heal_resumed",
            "planned heals re-driven after a healer crash",
        )

    # ----------------------------------------------------- two-phase drive

    def _commit(self, phase: str, decision: Decision, step: int,
                result: Optional[Dict] = None) -> None:
        w = self.mgr.begin_epoch()
        w.add_json("decision.json", decision.to_meta())
        w.commit({
            "healer": {
                "phase": phase,
                "step": int(step),
                "decision": decision.to_meta(),
                "policy_state": self.policy.export_state(),
                "result": result or {},
            },
        })

    def _actuate(self, decision: Decision,
                 abort_check: Optional[Callable] = None) -> Dict:
        p = decision.params
        action = p["action"]
        advances = {int(k): int(v) for k, v in
                    (p.get("batch_advances") or {}).items()}
        if action == ACTION_PROMOTE:
            if self._promote is None:
                raise RuntimeError("promote decision without an actuator")
            addr = self._promote(int(p["victim"]), advances)
            self._reprobe(int(p["victim"]), addr)
            return {"addr": addr}
        if action == ACTION_DRAIN_GRAY:
            if self._drain is None:
                raise RuntimeError("drain decision without an actuator")
            addr = self._drain(int(p["victim"]), advances)
            self._reprobe(int(p["victim"]), addr)
            return {"addr": addr}
        if action == ACTION_RESIZE:
            if self._resize is None:
                raise RuntimeError("resize decision without an actuator")
            kwargs = {}
            if abort_check is not None and arbitration.accepts_abort(self._resize):
                kwargs["abort_check"] = abort_check
            return dict(self._resize(int(p["n_new"]), **kwargs) or {})
        raise ValueError(f"unknown heal action {action!r}")

    def _reprobe(self, victim: int, addr) -> None:
        """A fresh process answers at ``addr`` now: wipe the victim slot's
        verdict history and point its probe at the newcomer."""
        if self.detector is None:
            return
        probe = None
        if self._probe_factory is not None and addr:
            probe = self._probe_factory(addr)
        self.detector.reset(victim, probe)

    def _drive(self, decision: Decision, step: int,
               detect_ts: Optional[float],
               abort_check: Optional[Callable] = None) -> Dict:
        record_event("heal.decide", step=step, action=decision.params["action"],
                     reason=decision.reason,
                     victim=decision.params.get("victim", -1))
        logger.info("healer: %s @ step %d — %s",
                    decision.params["action"], step, decision.reason)
        reach("heal.phase.planned")
        self._commit("planned", decision, step)
        if self._fault_hook is not None:
            self._fault_hook("planned")
        reach("heal.actuate")
        try:
            with span("heal.actuate", action=decision.params["action"],
                      step=step):
                result = self._actuate(decision, abort_check)
        except elastic.ReshardAborted as e:
            # a RESIZE preempted by a dead/gray heal: the elastic engine
            # already rolled the ring back; close this decision aborted so
            # resume() never re-drives it
            result = dict(e.stats)
            record_event("heal.aborted", step=step,
                         action=decision.params["action"])
            logger.info("healer: %s @ step %d preempted and rolled back",
                        decision.params["action"], step)
            reach("heal.phase.aborted")
            self._commit("aborted", decision, step, result)
            return result
        if detect_ts is not None:
            mttr = max(0.0, self.clock() - detect_ts)
            result["mttr_s"] = mttr
            self.mttr_s.append(mttr)
            self._m_mttr.observe(mttr)
        reach("heal.phase.done")
        self._commit("done", decision, step, result)
        self.heals += 1
        self._m_decisions.inc(action=decision.params["action"])
        return result

    # --------------------------------------------------------------- loops

    def on_poll(self, step: int = 0) -> Optional[Dict]:
        """One sense→decide→heal round. Safe to call from a timer thread
        or inline from a test; flap protection is the policy's
        cooldown/dwell guards, not the call cadence."""
        self.rounds += 1
        if self.detector is None:
            return None
        verdicts = self.detector.poll_once()
        sensors = self._sensors() if self._sensors is not None else None
        before = self.policy.suppressed
        decision = self.policy.decide(verdicts, sensors)
        held = self.policy.suppressed - before
        if held:
            self._m_suppressed.inc(held)
            record_event("heal.suppressed", step=step, held=held)
        if decision is None:
            return None
        p = decision.params
        if p["action"] in (ACTION_PROMOTE, ACTION_DRAIN_GRAY):
            if self._batch_advances is not None:
                p["batch_advances"] = {
                    str(k): int(v)
                    for k, v in (self._batch_advances() or {}).items()
                }
            detect_ts = self.detector.detected_at(int(p["victim"]))
        else:
            detect_ts = None
        return self._submit(decision, step, detect_ts)

    def _submit(self, decision: Decision, step: int,
                detect_ts: Optional[float]) -> Dict:
        """Route one heal through the arbiter's topology lease when
        attached, or drive it directly. Promote/drain intents sit at the
        top of the priority order and preempt an in-flight lower-priority
        protocol; a RESIZE is itself a preemptable reshard intent."""
        if self.arbiter is None:
            return self._drive(decision, step, detect_ts)
        action = decision.params["action"]
        if action == ACTION_PROMOTE:
            kind, key, direction, preemptable = (
                arbitration.INTENT_HEAL_DEAD, "", None, False)
        elif action == ACTION_DRAIN_GRAY:
            kind, key, direction, preemptable = (
                arbitration.INTENT_HEAL_GRAY, "", None, False)
        else:
            n_new = int(decision.params["n_new"])
            n_from = int(decision.params.get("from", n_new))
            kind, key, preemptable = (
                arbitration.INTENT_RESHARD, "ps_topology", True)
            direction = ("grow" if n_new > n_from
                         else "shrink" if n_new < n_from else None)
        result = self.arbiter.run(arbitration.Intent(
            kind, "healer",
            lambda abort_check: self._drive(
                decision, step, detect_ts, abort_check),
            key=key, direction=direction, preemptable=preemptable,
            label=decision.reason,
        ))
        if result.get("suppressed"):
            self.policy.suppressed += 1
            self._m_suppressed.inc()
        return result

    def start(self, interval_s: float = 0.5) -> "Healer":
        """Background poll loop — the autonomous mode the flagship chaos
        test runs in (no operator call). Decision flap protection lives in
        the policy's cooldown/dwell guards (see HealPolicy.decide)."""

        def run():
            step = 0
            while not self._stop.wait(interval_s):
                step += 1
                try:
                    self.on_poll(step)
                except Exception as e:
                    # the healer must outlive a failed heal attempt — the
                    # planned manifest keeps it resumable; count loudly
                    get_metrics().counter(
                        "persia_tpu_heal_errors",
                        "heal rounds that raised (resume token persists)",
                    ).inc()
                    logger.warning("heal round failed: %s", e)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="persia-healer")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)

    # -------------------------------------------------------------- resume

    def pending(self) -> Optional[Dict]:
        man = self.mgr.latest()
        if man is None:
            return None
        meta = man.meta.get("healer")
        if not meta or meta.get("phase") != "planned":
            return None
        return meta

    def resume(self) -> Optional[Dict]:
        """Re-drive a heal interrupted by SIGKILL, exactly-once: the
        planned manifest carries the victim and the recorded batch
        re-advance counts, and every actuation is idempotent (promote
        replays the same snapshot + advances into a standby and upserts
        the registration; resize resumes through the journal-deduped
        elastic engine). A clean log returns None; a second resume after
        completion is a no-op.

        An interrupted RESIZE re-enters through ``resume_resize``
        (:func:`~persia_tpu.elastic.resume_reshard` under the recorded
        phase manifest) — re-running a FRESH ``reshard_ps`` instead would
        re-plan against a half-moved ring. Only when the kill landed
        before the engine's first phase commit (resume_resize → None)
        does the recorded decision re-actuate from scratch — same plan,
        same journal ids, every op dedupes."""
        meta = self.pending()
        if meta is None:
            return None
        decision = Decision.from_meta(meta["decision"])
        step = int(meta.get("step", 0))
        self.policy.load_state(meta.get("policy_state", {}))
        record_event("heal.resume", step=step,
                     action=decision.params["action"])
        logger.info("healer: resuming planned %s from step %d",
                    decision.params["action"], step)
        with span("heal.resume", action=decision.params["action"], step=step):
            if (decision.params["action"] == ACTION_RESIZE
                    and self._resume_resize is not None):
                result = self._resume_resize()
                if result is None:  # killed before the engine's first phase
                    result = self._actuate(decision)
                result = dict(result)
            else:
                result = self._actuate(decision)
        if result.get("aborted"):
            # the kill landed mid-ABORT: the engine finished the rollback
            # on resume, so this heal closes aborted, not done
            reach("heal.phase.aborted")
            self._commit("aborted", decision, step, result)
            self._m_resumed.inc()
            return result
        self._commit("done", decision, step, result)
        self.heals += 1
        self._m_resumed.inc()
        self._m_decisions.inc(action=decision.params["action"])
        return result


# ------------------------------------------------------------------ wiring


def enable_self_heal(
    svc,
    state_dir: str,
    *,
    router=None,
    config: Optional[HealConfig] = None,
    detector=None,
    detector_config=None,
    sensors: Optional[Callable] = None,
    batch_advances: Optional[Callable] = None,
    reshard_state_dir=None,
    probe_timeout_s: float = 1.0,
    fault_hook: Optional[Callable] = None,
    arbiter=None,
) -> Healer:
    """Wire a Healer over a live ``ServiceCtx``: probes + leases feed a
    FailureDetector, decisions journal under ``state_dir/heal``, resizes
    run their elastic phase manifests under ``state_dir/reshard`` (or
    ``reshard_state_dir``). The caller starts the loop
    (``healer.start(interval_s)``) or drives ``on_poll`` from a fence."""
    import os

    from persia_tpu.service.failure_detector import (
        FailureDetector,
        make_probe,
    )

    if detector is None:
        detector = FailureDetector(
            svc.ps_probes(timeout_s=probe_timeout_s),
            detector_config,
            lease_reader=svc.ps_lease_reader(),
        )
    reshard_mgr = jobstate.coerce_manager(
        reshard_state_dir if reshard_state_dir is not None
        else os.path.join(str(state_dir), "reshard")
    )
    return Healer(
        os.path.join(str(state_dir), "heal"),
        detector=detector,
        policy=HealPolicy(config),
        promote=lambda victim, ba: svc.heal_promote(
            victim, router=router, batch_advances=ba, fault_hook=fault_hook,
        ),
        drain=lambda victim, ba: svc.heal_drain_gray(
            victim, router=router, batch_advances=ba, fault_hook=fault_hook,
        ),
        resize=lambda n_new, abort_check=None: svc.reshard_ps(
            n_new, reshard_mgr, router=router, abort_check=abort_check,
        ),
        resume_resize=lambda: svc.resume_reshard(reshard_mgr, router=router),
        sensors=sensors,
        batch_advances=batch_advances,
        probe_factory=lambda addr: make_probe(addr, timeout_s=probe_timeout_s),
        arbiter=arbiter,
    )

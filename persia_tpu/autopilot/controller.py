"""The Autopilot: sense → decide → two-phase actuate, at stream fences.

One control loop, three actuators behind one :class:`PolicyEngine`:

1. **PS reshard** — the access sketch's per-shard load model breaches the
   skew target → a load-weighted ring re-split runs at the next drained
   fence (``train_stream(fence_callback=...)`` parks the feeder, drains
   the write-back, then hands this controller the one window where
   topology may change).
2. **hot-sign read replication** — heavy hitters no split can spread get
   journaled copies on ring neighbours + a read fan-out map
   (:mod:`replicate`); single-writer gradients keep exactly-once.
3. **serving scale** — gateway QPS + quarantine pressure size the serving
   replica set through injected spawn/kill actuators (the quarantine/heal
   plumbing absorbs the membership churn).

**Exactly-once across SIGKILL.** Every actuation is two-phase against a
dedicated jobstate root: commit a ``planned`` manifest carrying the full
decision + policy state, actuate, commit ``done``. A controller killed at
ANY point and rebuilt over the same root (:meth:`Autopilot.resume`)
re-drives the newest planned-without-done decision idempotently — the
reshard resumes through :func:`persia_tpu.elastic.resume_reshard` (or
re-runs with the SAME recorded splits, every handoff op deduping on the PS
apply-journal), replication re-runs the same (epoch, step) round (journal
dedupe), and a scale re-drives toward the recorded target. The soft guard
state (dwell clocks) rides the manifests too; losing an uncommitted tick
of it can only DELAY the next decision, never double-apply one.

**Observable by construction.** Every round emits an ``autopilot.sense``
flight-recorder event (the sensor snapshot), every decision an
``autopilot.decide`` event, and every suppressed flap increments
``persia_tpu_autopilot_suppressed_flaps`` — a guard that silently holds is
indistinguishable from a dead sensor, so the holds are data.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

import numpy as np

from persia_tpu import elastic, jobstate
from persia_tpu.analysis.crashcheck import reach
from persia_tpu.embedding.tiering.profiler import publish_sketch_metrics
from persia_tpu.embedding.tiering.shard_planner import ShardPlanner
from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics
from persia_tpu.tracing import record_event, span

from persia_tpu.autopilot.policy import (
    KIND_REPLICATE,
    KIND_RESHARD,
    KIND_SCALE,
    Decision,
    PolicyConfig,
    PolicyEngine,
)
from persia_tpu.autopilot import arbiter as arbitration
from persia_tpu.autopilot.replicate import replicate_hot_signs

logger = get_default_logger("persia_tpu.autopilot")

AUTOPILOT_ENV = "PERSIA_AUTOPILOT"

# decision kind -> (intent kind, flap-suppression key, preemptable).
# Only the ring reshard is preemptable: it is the one actuation with a
# journaled ABORT arm (elastic.py); replication and scale are short and
# run to completion under the lease.
_INTENTS = {
    KIND_RESHARD: (arbitration.INTENT_RESHARD, "ps_topology", True),
    KIND_REPLICATE: (arbitration.INTENT_REPLICATE, "", False),
    KIND_SCALE: (arbitration.INTENT_SCALE, "serving_scale", False),
}

_accepts_abort = arbitration.accepts_abort


def autopilot_enabled() -> bool:
    """The launcher's ``--autopilot`` exports PERSIA_AUTOPILOT=1."""
    return os.environ.get(AUTOPILOT_ENV, "0") == "1"


class Autopilot:
    """Closed-loop fleet controller. Actuators are INJECTED callables so
    the same control loop runs over a live ``ServiceCtx`` topology, the
    in-process bench harness, or pure-stub tests:

    - ``reshard(n_shards, splits, step) -> dict`` — re-split the PS ring
      at the (already drained) fence; e.g.
      ``lambda n, sp, st: svc.reshard_ps(n, mgr, step=st, splits=sp,
      router=router)``.
    - ``resume_reshard() -> Optional[dict]`` — re-enter an interrupted
      reshard (None when none is pending).
    - ``scale_to(target) -> int`` — grow/shrink the serving replica set,
      returning the achieved count.
    - ``serving_sensors() -> dict`` — ``{"qps": .., "replicas": ..,
      "quarantined": ..}`` (see :func:`gateway_sensors`).

    ``state_dir`` is the controller's OWN jobstate root (decision
    manifests); keep it separate from the stream's snapshot root and pass
    the reshard actuator its own root too — three manifest streams, three
    directories, no cross-parsing.
    """

    def __init__(
        self,
        state_dir,
        *,
        policy: Optional[PolicyEngine] = None,
        profiler=None,
        router=None,
        reshard: Optional[Callable] = None,
        resume_reshard: Optional[Callable] = None,
        scale_to: Optional[Callable] = None,
        serving_sensors: Optional[Callable] = None,
        healer=None,
        arbiter=None,
    ):
        self.policy = policy or PolicyEngine(PolicyConfig())
        # when attached, every actuation routes through the control-plane
        # arbiter's topology lease (serialization + preemption + cross-loop
        # flap suppression); None keeps the direct-drive path for tests
        self.arbiter = arbiter
        # an attached Healer (autopilot.heal) rides this controller's
        # cadence: on_tick drives its sense->decide->heal round, resume()
        # re-drives its planned-without-done heal before our own
        self.healer = healer
        self.mgr = jobstate.coerce_manager(state_dir)
        self.profiler = profiler
        self.router = router
        self._reshard = reshard
        self._resume_reshard = resume_reshard
        self._scale_to = scale_to
        self._serving_sensors = serving_sensors
        self.rounds = 0
        m = get_metrics()
        self._m_decisions = m.counter(
            "persia_tpu_autopilot_decisions",
            "autopilot decisions actuated, by kind",
        )
        self._m_suppressed = m.counter(
            "persia_tpu_autopilot_suppressed_flaps",
            "decisions held back by hysteresis/dwell guards",
        )
        self._m_rounds = m.counter(
            "persia_tpu_autopilot_rounds", "control-loop rounds run",
        )
        self._m_skew = m.gauge(
            "persia_tpu_autopilot_modeled_skew",
            "sketch-modeled load skew of the current PS ring",
        )
        self._m_serving = m.gauge(
            "persia_tpu_autopilot_serving_replicas",
            "serving replica count the autopilot last observed",
        )
        self._m_resumed = m.counter(
            "persia_tpu_autopilot_resumed",
            "planned decisions re-driven after a controller crash",
        )
        self._m_aborted = m.counter(
            "persia_tpu_autopilot_aborted",
            "actuations preempted mid-flight and rolled back",
        )

    # --------------------------------------------------------------- sense

    def sense(self) -> Dict:
        """One sensor snapshot (also published: sketch load metrics via
        :func:`publish_sketch_metrics`, serving gauge). Recorded as an
        ``autopilot.sense`` flight event every round."""
        snap: Dict = {}
        if self.profiler is not None:
            splits = self.router.ring if self.router is not None else None
            snap.update(publish_sketch_metrics(self.profiler, splits=splits))
            self._m_skew.set(float(snap.get("skew", 1.0)))
        if self._serving_sensors is not None:
            sv = self._serving_sensors()
            snap.update({f"serving_{k}": v for k, v in sv.items()})
            self._m_serving.set(float(sv.get("replicas", 0)))
        return snap

    # ----------------------------------------------------- two-phase drive

    def _commit(self, phase: str, decision: Decision, step: int,
                result: Optional[Dict] = None) -> None:
        w = self.mgr.begin_epoch()
        w.add_json("decision.json", decision.to_meta())
        w.commit({
            "autopilot": {
                "phase": phase,
                "step": int(step),
                "decision": decision.to_meta(),
                "policy_state": self.policy.export_state(),
                "result": result or {},
            },
        })

    def _actuate(self, decision: Decision, step: int,
                 abort_check: Optional[Callable] = None) -> Dict:
        p = decision.params
        if decision.kind == KIND_RESHARD:
            if self._reshard is None:
                raise RuntimeError("reshard decision without an actuator")
            kwargs = {}
            if abort_check is not None and _accepts_abort(self._reshard):
                kwargs["abort_check"] = abort_check
            return dict(self._reshard(
                int(p["n_shards"]),
                np.asarray(p["splits"], dtype=np.uint64),
                int(step),
                **kwargs,
            ) or {})
        if decision.kind == KIND_REPLICATE:
            if self.router is None:
                raise RuntimeError("replicate decision without a router")
            return replicate_hot_signs(
                self.router, p["signs"],
                job_epoch=self.mgr.latest().meta["job_epoch"],
                step=int(step), fanout=int(p["fanout"]),
                salt=int(p.get("salt", 0)),
            )
        if decision.kind == KIND_SCALE:
            if self._scale_to is None:
                raise RuntimeError("scale decision without an actuator")
            return {"achieved": int(self._scale_to(int(p["target"])))}
        raise ValueError(f"unknown decision kind {decision.kind!r}")

    def _drive(self, decision: Decision, step: int,
               abort_check: Optional[Callable] = None) -> Dict:
        """planned → actuate → done (or → aborted, when a higher-priority
        intent preempted the actuation mid-flight and the engine rolled it
        back). A kill anywhere in between leaves the planned manifest as
        the resume token."""
        record_event("autopilot.decide", step=step, decision=decision.kind,
                     reason=decision.reason, **{
                         k: v for k, v in decision.params.items()
                         if not isinstance(v, (list, dict))
                     })
        logger.info("autopilot: %s @ step %d — %s",
                    decision.kind, step, decision.reason)
        reach("autopilot.phase.planned")
        self._commit("planned", decision, step)
        reach("autopilot.actuate")
        try:
            with span("autopilot.actuate", kind=decision.kind, step=step):
                result = self._actuate(decision, step, abort_check)
        except elastic.ReshardAborted as e:
            # the engine already released every imported range through the
            # journaled ABORT arm; the terminal "aborted" commit closes
            # this decision so resume() never re-drives it
            result = dict(e.stats)
            record_event("autopilot.aborted", step=step,
                         decision=decision.kind)
            logger.info("autopilot: %s @ step %d preempted and rolled back",
                        decision.kind, step)
            reach("autopilot.phase.aborted")
            self._commit("aborted", decision, step, result)
            self._m_aborted.inc()
            return result
        reach("autopilot.phase.done")
        self._commit("done", decision, step, result)
        self._m_decisions.inc(kind=decision.kind)
        return result

    def _submit(self, decision: Decision, step: int,
                direction: Optional[str] = None) -> Dict:
        """Route one decision through the arbiter's topology lease when
        attached, or drive it directly (stub/test wiring)."""
        if self.arbiter is None:
            return self._drive(decision, step)
        kind, key, preemptable = _INTENTS[decision.kind]
        result = self.arbiter.run(arbitration.Intent(
            kind, "autopilot",
            lambda abort_check: self._drive(decision, step, abort_check),
            key=key, direction=direction, preemptable=preemptable,
            label=decision.reason,
        ))
        if result.get("suppressed"):
            self._m_suppressed.inc()
        return result

    # --------------------------------------------------------------- loops

    def on_fence(self, gstep: int) -> Dict[str, Dict]:
        """The training-plane round — pass this method directly as
        ``train_stream(fence_callback=pilot.on_fence)``. The stream
        guarantees the fence invariants (feeder parked, write-back
        drained); everything here runs inside that window."""
        self.rounds += 1
        self._m_rounds.inc()
        snap = self.sense()
        record_event("autopilot.sense", step=gstep, **snap)
        applied: Dict[str, Dict] = {}
        before = self.policy.suppressed
        if self.profiler is not None and self._reshard is not None:
            n = len(self.router.replicas) if self.router is not None else 1
            splits = self.router.ring if self.router is not None else None
            d = self.policy.decide_reshard(self.profiler, n, splits)
            if d is not None:
                n_new = int(d.params["n_shards"])
                r = self._submit(d, gstep,
                                 direction="grow" if n_new > n
                                 else "shrink" if n_new < n else None)
                applied[KIND_RESHARD] = r
                if not r.get("suppressed") and not r.get("aborted"):
                    # the swap cleared the hot-read map — re-replicate now,
                    # onto the NEW owners' neighbours
                    self.policy.notify_topology_changed()
        if self.profiler is not None and self.router is not None:
            d = self.policy.decide_replicate(self.profiler)
            if d is not None:
                applied[KIND_REPLICATE] = self._submit(d, gstep)
        held = self.policy.suppressed - before
        if held:
            self._m_suppressed.inc(held)
            record_event("autopilot.suppressed", step=gstep, held=held)
        return applied

    def on_tick(self, step: int = 0) -> Dict[str, Dict]:
        """The serving-plane round — called on a timer (the launcher's
        ``--autopilot`` thread), independent of the training fence."""
        self.rounds += 1
        self._m_rounds.inc()
        applied_heal: Dict[str, Dict] = {}
        if self.healer is not None:
            healed = self.healer.on_poll(step)
            if healed is not None:
                applied_heal["heal"] = healed
        if self._serving_sensors is None or self._scale_to is None:
            return applied_heal
        sv = self._serving_sensors()
        self._m_serving.set(float(sv.get("replicas", 0)))
        record_event("autopilot.sense", step=step,
                     **{f"serving_{k}": v for k, v in sv.items()})
        before = self.policy.suppressed
        d = self.policy.decide_scale(
            float(sv.get("qps", 0.0)), int(sv.get("replicas", 0)),
            int(sv.get("quarantined", 0)),
        )
        applied: Dict[str, Dict] = applied_heal
        if d is not None:
            target = int(d.params["target"])
            have = int(sv.get("replicas", 0))
            applied[KIND_SCALE] = self._submit(
                d, step, direction="grow" if target > have
                else "shrink" if target < have else None,
            )
        held = self.policy.suppressed - before
        if held:
            self._m_suppressed.inc(held)
            record_event("autopilot.suppressed", step=step, held=held)
        return applied

    # -------------------------------------------------------------- resume

    def pending(self) -> Optional[Dict]:
        """The newest decision left ``planned`` without a ``done`` — the
        resume token, or None when the log is clean."""
        man = self.mgr.latest()
        if man is None:
            return None
        meta = man.meta.get("autopilot")
        if not meta or meta.get("phase") != "planned":
            return None
        return meta

    def resume(self) -> Optional[Dict]:
        """Re-drive a decision interrupted by SIGKILL, exactly-once:

        - **reshard**: if the elastic engine left its own phase manifest,
          :func:`~persia_tpu.elastic.resume_reshard` replays it (every op
          journal-deduped); if the kill landed BEFORE the engine's first
          commit, re-run with the SAME recorded splits — same plan, same
          journal ids, same outcome.
        - **replicate**: re-run the same (epoch, step) round; already-
          imported blobs dedupe.
        - **scale**: re-drive toward the recorded target (idempotent by
          construction — the actuator converges on a count).

        Restores the manifest's policy state first, then commits ``done``.
        Returns the actuation result, or None when nothing was pending."""
        if self.healer is not None:
            # an interrupted HEAL outranks an interrupted optimization: a
            # half-promoted standby is an availability hole
            self.healer.resume()
        meta = self.pending()
        if meta is None:
            return None
        decision = Decision.from_meta(meta["decision"])
        step = int(meta.get("step", 0))
        self.policy.load_state(meta.get("policy_state", {}))
        record_event("autopilot.resume", step=step, decision=decision.kind)
        logger.info("autopilot: resuming planned %s from step %d",
                    decision.kind, step)
        with span("autopilot.resume", kind=decision.kind, step=step):
            if decision.kind == KIND_RESHARD and self._resume_reshard is not None:
                result = self._resume_reshard()
                if result is None:  # killed before the engine's first phase
                    result = self._actuate(decision, step)
                result = dict(result)
            else:
                result = self._actuate(decision, step)
        if result.get("aborted"):
            # the kill landed mid-ABORT: the engine finished the rollback
            # on resume, so this decision closes aborted, not done
            reach("autopilot.phase.aborted")
            self._commit("aborted", decision, step, result)
            self._m_aborted.inc()
        else:
            self._commit("done", decision, step, result)
            self._m_decisions.inc(kind=decision.kind)
        self._m_resumed.inc()
        return result


# ------------------------------------------------------------------ wiring


def gateway_sensors(gateway) -> Callable[[], Dict]:
    """Serving sensor closure over a ReplicaGateway: windowed request rate
    + membership/quarantine pressure."""

    def sensors() -> Dict:
        st = gateway.stats()
        return {
            "qps": float(gateway.request_rate()),
            "replicas": len(st["replicas"]),
            "live": len(st["live"]),
            "quarantined": len(st["quarantined"]),
        }

    return sensors


def enable_autopilot(
    svc,
    state_dir: str,
    *,
    profiler,
    router=None,
    gateway=None,
    scale_to: Optional[Callable] = None,
    config: Optional[PolicyConfig] = None,
    arbiter=None,
) -> Autopilot:
    """Wire an Autopilot over a live ``ServiceCtx`` topology: decisions
    journal to ``state_dir/decisions``, reshards run their phase manifests
    in ``state_dir/reshard``. Pass the returned pilot's ``on_fence`` as
    ``train_stream(fence_callback=...)`` and (when a gateway is given)
    call ``on_tick`` from a timer for the serving plane."""
    reshard_mgr = jobstate.JobStateManager(
        os.path.join(str(state_dir), "reshard")
    )
    pilot = Autopilot(
        os.path.join(str(state_dir), "decisions"),
        policy=PolicyEngine(config or PolicyConfig()),
        profiler=profiler,
        router=router,
        reshard=lambda n, sp, st, abort_check=None: svc.reshard_ps(
            n, reshard_mgr, step=st, splits=sp, router=router,
            abort_check=abort_check,
        ),
        resume_reshard=lambda: svc.resume_reshard(
            reshard_mgr, router=router,
        ),
        scale_to=scale_to,
        serving_sensors=gateway_sensors(gateway) if gateway is not None
        else None,
        arbiter=arbiter,
    )
    return pilot

"""Control-plane arbiter: ONE lease over every fleet topology mutation.

Four independent loops drive the control plane — the Autopilot
(``autopilot/controller.py``), the Healer (``autopilot/heal.py``), the
AutoTierController (``embedding/tiering/controller.py``) and the serving
rollover (``serving/rollover.py``) — each firing at fences or on timers
with no mutual awareness. Until this module, a HEAL landing mid-reshard or
a tier move racing a ring re-split was only not-a-disaster by schedule
luck. The arbiter closes that hole: loops submit :class:`Intent`\\ s
instead of calling actuators directly, and the single topology-actuation
lease serializes them under a fixed priority order:

=============  ========  ====================================================
intent kind    priority  meaning
=============  ========  ====================================================
heal_dead      0         promote a standby over a DEAD replica
heal_gray      1         drain a gray (slow-but-answering) replica
scrub          2         integrity scrub of a quarantined range
reshard        3         ring re-split / resize (autopilot or healer RESIZE)
tier           4         HBM<->PS placement migration at a fence
replicate      5         hot-sign read replication
rollover       5         serving model version swap
scale          5         serving replica set resize
=============  ========  ====================================================

Three mechanisms ride the lease:

- **Serialization**: ``run(intent)`` blocks until the lease is free and no
  higher-priority intent is queued, executes, releases. At most one
  topology mutation is ever in flight — ``max_concurrent`` stays 1 by
  construction, and the soak (benchmarks/soak_bench.py) measures it
  independently rather than assuming it.
- **Journaled preemption**: a waiting intent of strictly higher priority
  sets the holder's preemption flag when the holder declared itself
  ``preemptable``. The holder's ``execute(abort_check)`` threads that flag
  into the two-phase engine (``elastic.execute_reshard(abort_check=...)``),
  which honors it at the next phase boundary by rolling back through the
  journaled ABORT arm (exactly-once; SIGKILL mid-abort resumes
  bit-identical — see persia_tpu/elastic.py).
- **Cross-loop flap suppression**: an intent that would UNDO another
  loop's actuation inside its dwell window (same ``key``, opposite
  ``direction``, different ``source``) is suppressed, counted, and
  exported — e.g. an autopilot ring shrink right after a healer resize
  grew the fleet.

Every grant/release/preempt/suppress is a flight-recorder event
(``arbiter.*``) and a metric, so the arbitration itself is observable.
"""

from __future__ import annotations

import heapq
import inspect
import itertools
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics
from persia_tpu.tracing import record_event, span

logger = get_default_logger("persia_tpu.autopilot.arbiter")

INTENT_HEAL_DEAD = "heal_dead"
INTENT_HEAL_GRAY = "heal_gray"
INTENT_SCRUB = "scrub"
INTENT_RESHARD = "reshard"
INTENT_TIER = "tier"
INTENT_REPLICATE = "replicate"
INTENT_ROLLOVER = "rollover"
INTENT_SCALE = "scale"

PRIORITY: Dict[str, int] = {
    INTENT_HEAL_DEAD: 0,
    INTENT_HEAL_GRAY: 1,
    INTENT_SCRUB: 2,
    INTENT_RESHARD: 3,
    INTENT_TIER: 4,
    INTENT_REPLICATE: 5,
    INTENT_ROLLOVER: 5,
    INTENT_SCALE: 5,
}

# the only direction pair that means "undo": a grow right after a shrink
# (or vice versa) is a flap; a resplit/rollover carries no direction and
# is never suppressed
_OPPOSITE = {("grow", "shrink"), ("shrink", "grow")}

# HEAL intents are never flap-suppressed: a dead replica outranks any
# dwell bookkeeping
_NEVER_SUPPRESSED = 1


@dataclass
class Intent:
    """One unit of control-plane work submitted to the arbiter.

    ``execute(abort_check)`` performs the actuation; ``abort_check`` is a
    zero-arg callable returning True once a higher-priority intent has
    requested preemption — thread it into the engine's phase boundaries
    (or ignore it for non-preemptable work). ``key``/``direction`` feed
    flap suppression (e.g. ``key="ps_topology"``, ``direction="grow"``);
    ``preemptable`` declares the execute body abortable at phase
    boundaries."""

    kind: str
    source: str
    execute: Callable[[Callable[[], bool]], Any]
    key: str = ""
    direction: Optional[str] = None
    preemptable: bool = False
    label: str = ""

    @property
    def priority(self) -> int:
        return PRIORITY[self.kind]


def accepts_abort(fn: Callable) -> bool:
    """Whether an injected actuator takes ``abort_check`` — legacy test
    actuators are plain positional lambdas and must keep working, so the
    loops only thread the preemption flag into actuators that declare the
    parameter (or take ``**kwargs``)."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return any(
        p.kind == inspect.Parameter.VAR_KEYWORD or p.name == "abort_check"
        for p in sig.parameters.values()
    )


@dataclass
class _Actuation:
    key: str
    direction: Optional[str]
    source: str
    ts: float


class Arbiter:
    """Holder of the single topology-actuation lease (see module doc).

    ``dwell_s`` is the flap-suppression window: an actuation's
    (key, direction, source) record stays live that long, and an intent
    from ANOTHER loop with the same key and the opposite direction inside
    the window is suppressed. ``clock`` is injectable for tests."""

    def __init__(self, *, dwell_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.dwell_s = float(dwell_s)
        self.clock = clock
        self._cv = threading.Condition()
        self._queue: List[Tuple[int, int, Intent]] = []
        self._seq = itertools.count()
        # (priority, seq, intent, preempt_event) of the lease holder
        self._holder: Optional[Tuple[int, int, Intent, threading.Event]] = None
        self._recent: List[_Actuation] = []
        self._active = 0
        self.max_concurrent = 0
        self.grants = 0
        self.preemptions = 0
        self.preempted_rollbacks = 0
        self.suppressed_flaps = 0
        m = get_metrics()
        self._m_grants = m.counter(
            "persia_tpu_arbiter_grants", "topology-lease grants, by kind",
        )
        self._m_preempts = m.counter(
            "persia_tpu_arbiter_preemptions",
            "preemption requests issued against a lower-priority holder",
        )
        self._m_suppressed = m.counter(
            "persia_tpu_arbiter_suppressed_flaps",
            "intents suppressed for undoing another loop inside its dwell",
        )
        self._m_queue = m.gauge(
            "persia_tpu_arbiter_queue_depth", "intents waiting on the lease",
        )

    # ----------------------------------------------------------- suppression

    def _suppressor(self, intent: Intent) -> Optional[_Actuation]:
        if not intent.key or intent.direction is None:
            return None
        if intent.priority <= _NEVER_SUPPRESSED:
            return None
        now = self.clock()
        self._recent = [a for a in self._recent
                        if now - a.ts < self.dwell_s]
        for a in reversed(self._recent):
            if (a.key == intent.key and a.source != intent.source
                    and (a.direction, intent.direction) in _OPPOSITE):
                return a
        return None

    # ----------------------------------------------------------------- lease

    def run(self, intent: Intent) -> Dict:
        """Submit ``intent`` and block until it executed (or was
        suppressed). Returns the execute result coerced to a dict, or
        ``{"suppressed": True, ...}`` when flap suppression held it.
        Exceptions from ``execute`` propagate after the lease releases —
        including ``elastic.ReshardAborted`` when the intent itself was
        preempted mid-flight (the loop commits its ``aborted`` phase)."""
        with self._cv:
            sup = self._suppressor(intent)
            if sup is not None:
                self.suppressed_flaps += 1
                self._m_suppressed.inc(kind=intent.kind)
                record_event(
                    "arbiter.suppress", intent=intent.kind, source=intent.source,
                    key=intent.key, direction=intent.direction,
                    undoes_source=sup.source, undoes_direction=sup.direction,
                )
                logger.info(
                    "arbiter: suppressed %s/%s (%s %s would undo %s's %s "
                    "inside dwell)", intent.source, intent.kind, intent.key,
                    intent.direction, sup.source, sup.direction,
                )
                return {"suppressed": True, "kind": intent.kind,
                        "undoes": sup.source}
            prio, seq = intent.priority, next(self._seq)
            heapq.heappush(self._queue, (prio, seq, intent))
            self._m_queue.set(float(len(self._queue)))
            preempt_asked = False
            while not (self._holder is None and self._queue[0][1] == seq):
                h = self._holder
                if (h is not None and not preempt_asked and prio < h[0]
                        and h[2].preemptable and not h[3].is_set()):
                    h[3].set()
                    preempt_asked = True
                    self.preemptions += 1
                    self._m_preempts.inc()
                    record_event(
                        "arbiter.preempt", holder_kind=h[2].kind,
                        holder_source=h[2].source, by_kind=intent.kind,
                        by_source=intent.source,
                    )
                    logger.info(
                        "arbiter: %s/%s preempting in-flight %s/%s",
                        intent.source, intent.kind, h[2].source, h[2].kind,
                    )
                self._cv.wait(0.05)
            heapq.heappop(self._queue)
            self._m_queue.set(float(len(self._queue)))
            ev = threading.Event()
            self._holder = (prio, seq, intent, ev)
            self.grants += 1
            self._m_grants.inc(kind=intent.kind)
            self._active += 1
            self.max_concurrent = max(self.max_concurrent, self._active)
        record_event("arbiter.grant", intent=intent.kind, source=intent.source,
                     label=intent.label)
        # "aborted" = the preemption was honored and rolled back — either
        # the engine's ReshardAborted escaped, or the loop swallowed it and
        # returned its aborted-phase stats. Either way the actuation did
        # NOT land, so it must not enter the flap ledger.
        aborted = False
        try:
            with span("arbiter.actuate", kind=intent.kind,
                      source=intent.source):
                result = intent.execute(ev.is_set)
            out = dict(result or {})
            aborted = bool(out.get("aborted"))
            return out
        except BaseException as e:  # noqa: BLE001 — release, then re-raise
            aborted = type(e).__name__ == "ReshardAborted"
            raise
        finally:
            if aborted:
                self.preempted_rollbacks += 1
            with self._cv:
                self._active -= 1
                self._holder = None
                if intent.key and not aborted:
                    self._recent.append(_Actuation(
                        intent.key, intent.direction, intent.source,
                        self.clock()))
                self._cv.notify_all()
            record_event("arbiter.release", intent=intent.kind,
                         source=intent.source, preempted=aborted)

    # ------------------------------------------------------------- observers

    def export_state(self) -> Dict:
        with self._cv:
            return {
                "grants": self.grants,
                "preemptions": self.preemptions,
                "preempted_rollbacks": self.preempted_rollbacks,
                "suppressed_flaps": self.suppressed_flaps,
                "max_concurrent": self.max_concurrent,
                "active": self._active,
                "queued": len(self._queue),
            }

"""Hot-sign read replication: journaled copies + the routing swap.

Heavy hitters concentrate READ traffic that no ring re-split can spread —
a single sign is atomic under range sharding (shard_planner places a
boundary just past it, never through it). The remaining lever is
replication: copy the hot sign's full entry (embedding + optimizer slots)
onto the ``fanout - 1`` ring neighbours after its owner, then tell the
router (``ShardedLookup.set_hot_read_replicas``) to fan READ lookups out
across the copies. Writes are untouched — gradients keep flowing to the
single owner under their journaled exactly-once ids, so there is exactly
one authoritative copy and the read replicas are *bounded-stale*, refreshed
every controller round (the same staleness contract asynchronous PS
training already grants the cache tier).

Exactly-once: each sign's copy is one ``export_range(h, h+1)`` blob (h =
splitmix64(sign), the routing hash — a colliding sign rides along and is
co-owned, which is harmless) imported under
``jobstate.replication_journal_id(epoch, step, i)``. A controller killed
mid-round and resumed re-runs the SAME (epoch, step) round: every blob
already imported dedupes on its journal id + crc, the rest apply — the
post-resume store state is bit-identical to an uninterrupted round.
"""

from __future__ import annotations

import zlib
from typing import Dict, Optional, Sequence

import numpy as np

from persia_tpu.embedding.hashing import (
    sign_to_range_shard,
    sign_to_shard,
    splitmix64,
)
from persia_tpu.jobstate import replication_journal_id
from persia_tpu.tracing import record_event, span

# the journal op-index field is 7 bits (handoff_journal_id); index 0..126
MAX_REPLICATED_SIGNS = 127


def replicate_hot_signs(
    router,
    signs: Sequence[int],
    *,
    job_epoch: int,
    step: int,
    fanout: int,
    salt: int = 0,
) -> Dict:
    """Copy each hot sign to its read replicas, then install the fan-out
    map on ``router``. Passing an empty ``signs`` clears the map (no
    copies). Idempotent for a fixed (job_epoch, step): replays dedupe on
    the replication journal. Returns stats (copies applied vs deduped)."""
    signs_u = np.unique(np.asarray(list(signs), dtype=np.uint64))
    if len(signs_u) > MAX_REPLICATED_SIGNS:
        raise ValueError(
            f"{len(signs_u)} hot signs exceed the replication journal's "
            f"op-index namespace ({MAX_REPLICATED_SIGNS})"
        )
    reps = router.replicas
    ring = router.ring
    n = len(reps)
    stats = {"signs": int(len(signs_u)), "fanout": int(fanout),
             "applied": 0, "deduped": 0}
    if len(signs_u) == 0 or fanout <= 1 or n <= 1:
        router.set_hot_read_replicas(
            np.empty(0, np.uint64), 0, salt=salt
        )
        return stats
    eff_fanout = min(int(fanout), n)
    owners = (sign_to_range_shard(signs_u, ring) if ring is not None
              else sign_to_shard(signs_u, n))
    pos = splitmix64(signs_u)
    with span("autopilot.replicate", signs=int(len(signs_u)),
              fanout=eff_fanout, step=step):
        for i in range(len(signs_u)):
            h = int(pos[i])
            hi = (h + 1) & 0xFFFFFFFFFFFFFFFF  # hi == 0 wraps to ring end
            blob = reps[int(owners[i])].export_range(h, hi)
            crc = zlib.crc32(blob) & 0xFFFFFFFF
            jid = replication_journal_id(job_epoch, step, i)
            for j in range(1, eff_fanout):
                dst = (int(owners[i]) + j) % n
                if reps[dst].import_range_journaled(jid, crc, blob):
                    stats["applied"] += 1
                else:
                    stats["deduped"] += 1
    router.set_hot_read_replicas(signs_u, eff_fanout, salt=salt)
    record_event("autopilot.replicated", step=step, **stats)
    return stats

"""Autopilot decision policy: sensors in, guarded decisions out.

The policy layer is deliberately PURE — it never touches a store, a
socket, or a thread. Each ``decide_*`` method maps one sensor snapshot to
at most one :class:`Decision`, and every path that could flap is gated by
the same two-token discipline the tiering planners use (persia-lint
CTRL001 enforces it repo-wide):

- **hysteresis margin** — a change is proposed only when the modeled
  improvement clears a multiplicative band, not on any epsilon delta;
- **min-dwell** — even a clearing change waits until the incumbent has
  been stable for ``min_dwell`` rounds, so two states cannot trade places
  every round. A clearing-but-dwelling round is counted as a *suppressed
  flap* (the controller exports it — a silent guard is indistinguishable
  from a dead sensor).

PS-reshard hysteresis/dwell live inside the reused
:class:`~persia_tpu.embedding.tiering.shard_planner.ShardPlanner`; the
serving-scale and hot-replication guards are implemented here with the
same shape.
"""

from __future__ import annotations

import math
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from persia_tpu.embedding.tiering.shard_planner import ShardPlanner

# decision kinds — also the jobstate manifest / metrics label vocabulary
KIND_RESHARD = "reshard"
KIND_REPLICATE = "replicate"
KIND_SCALE = "scale"
KIND_HEAL = "heal"  # decided by autopilot.heal.HealPolicy, not PolicyEngine


@dataclass
class PolicyConfig:
    """Knobs for all three actuators. Defaults are soak-tested by
    benchmarks/autopilot_bench.py; production tuning goes through the
    launcher env (see ``--autopilot``)."""

    # --- PS resharding (ring re-split at a drained fence) ---
    skew_target: float = 1.10  # act only when measured skew exceeds this
    reshard_hysteresis: float = 0.10
    reshard_min_dwell: int = 2
    # --- hot-sign read replication ---
    hot_fanout: int = 2  # owner + (fanout-1) read replicas per hot sign
    hot_max_signs: int = 32  # journal op-index namespace holds 127
    hot_mass_frac: float = 0.01  # sign must carry >= this of total mass
    hot_min_dwell: int = 1
    # --- serving replica scaling ---
    qps_per_replica: float = 200.0
    scale_min_replicas: int = 1
    scale_max_replicas: int = 8
    scale_hysteresis: float = 0.25
    scale_min_dwell: int = 2

    def to_dict(self) -> Dict:
        return asdict(self)


@dataclass
class Decision:
    """One actuation the controller should perform. ``params`` is
    JSON-serializable verbatim — it IS the planned-manifest payload, so a
    resumed controller re-drives from exactly these numbers."""

    kind: str
    reason: str
    params: Dict = field(default_factory=dict)

    def to_meta(self) -> Dict:
        return {"kind": self.kind, "reason": self.reason,
                "params": self.params}

    @classmethod
    def from_meta(cls, meta: Dict) -> "Decision":
        return cls(meta["kind"], meta.get("reason", ""),
                   dict(meta.get("params", {})))


class PolicyEngine:
    """Stateful guard counters + the pure decision functions.

    State here is SOFT: dwell counters and the last hot set reset on a
    controller restart, which can only DELAY the next actuation by
    ``min_dwell`` rounds — it can never double-apply one. Anything whose
    replay must be exactly-once rides the decision manifest instead
    (controller.py)."""

    def __init__(self, cfg: Optional[PolicyConfig] = None):
        self.cfg = cfg or PolicyConfig()
        c = self.cfg
        self.shard_planner = ShardPlanner(
            hysteresis=c.reshard_hysteresis, min_dwell=c.reshard_min_dwell,
        )
        self.suppressed = 0  # flaps suppressed across all decision kinds
        self._scale_dwell = 0
        self._scale_target: Optional[int] = None
        self._hot_dwell = 0
        self._hot_signs: Tuple[int, ...] = ()
        self._hot_salt = 0

    # ------------------------------------------------------------- reshard

    def decide_reshard(
        self, profiler, n_shards: int, current_splits,
    ) -> Optional[Decision]:
        """Propose a ring re-split when the sketch-modeled skew of the
        CURRENT ring exceeds ``skew_target`` and the reused ShardPlanner's
        hysteresis + dwell adopt the candidate. Returns None (and counts a
        suppressed flap when the margin cleared but dwell held) otherwise."""
        from persia_tpu.embedding.hashing import splitmix64, uniform_splits

        pos, w, residual = ShardPlanner.mass_from_profiler(profiler)
        if self._hot_signs and self.cfg.hot_fanout > 1 and len(w):
            # the installed read fan-out round-robins each hot sign's
            # reads over ``fanout`` replicas — model the owner's share as
            # 1/fanout so the ring balances the POST-replication load
            # (the neighbour smear is near-uniform and cancels in skew)
            hot_pos = splitmix64(
                np.asarray(self._hot_signs, dtype=np.uint64)
            )
            m = np.isin(pos, hot_pos)
            if m.any():
                w = np.asarray(w, dtype=np.float64).copy()
                w[m] /= float(min(self.cfg.hot_fanout, max(n_shards, 1)))
        if current_splits is None:
            # modulo routing has no ring; it is hash-uniform to first
            # order, so the uniform ring is the right skew model for it
            cur = (uniform_splits(n_shards) if n_shards > 1
                   else np.empty(0, np.uint64))
        else:
            cur = np.asarray(current_splits, dtype=np.uint64)
        cur_loads = ShardPlanner.shard_loads(cur, pos, w, residual)
        cur_skew = ShardPlanner.skew_of(cur_loads)
        if cur_skew <= self.cfg.skew_target:
            # balanced enough — keep the planner's dwell clock ticking so a
            # later breach does not ALSO have to wait out a stale counter
            self.shard_planner._current = cur
            self.shard_planner._dwell += 1
            return None
        self.shard_planner._current = cur
        before = self.shard_planner.suppressed
        plan = self.shard_planner.plan(n_shards, pos=pos, w=w,
                                       residual=residual)
        self.suppressed += self.shard_planner.suppressed - before
        if not plan.adopted:
            return None
        return Decision(
            KIND_RESHARD,
            f"skew {cur_skew:.3f} > target {self.cfg.skew_target:.3f}, "
            f"candidate {plan.skew:.3f}",
            {
                "n_shards": int(n_shards),
                "splits": [int(x) for x in plan.splits],
                "skew_before": float(cur_skew),
                "skew_after": float(plan.skew),
            },
        )

    def notify_topology_changed(self) -> None:
        """A ring swap cleared the router's hot-read map (the copies were
        placed relative to the OLD owner layout): forget the installed set
        so the next ``decide_replicate`` re-fires immediately and re-copies
        onto the new owners' neighbours."""
        self._hot_signs = ()
        self._hot_dwell = 0

    # ----------------------------------------------------------- replicate

    def decide_replicate(self, profiler) -> Optional[Decision]:
        """Propose a hot-sign read-replica refresh: the signs carrying at
        least ``hot_mass_frac`` of total sketch mass, capped at
        ``hot_max_signs``. A refresh is proposed when the set CHANGES (or
        to rotate the salt over an existing set); an unchanged set within
        dwell is suppressed."""
        c = self.cfg
        if c.hot_fanout <= 1 or c.hot_max_signs <= 0:
            return None
        total = sum(float(st.total) for st in profiler.stats().values())
        if total <= 0:
            return None
        cand: List[Tuple[float, int]] = []
        for name in profiler.stats():
            for sign, est in profiler.slot_tops(name):
                if float(est) >= c.hot_mass_frac * total:
                    cand.append((float(est), int(sign)))
        cand.sort(reverse=True)
        signs = tuple(sorted({s for _, s in cand[: c.hot_max_signs]}))
        if not signs and not self._hot_signs:
            return None
        changed = signs != self._hot_signs
        if not changed:
            self._hot_dwell += 1
            return None
        if self._hot_dwell < c.hot_min_dwell and self._hot_signs:
            # hysteresis dwell: the installed set keeps serving until the
            # new one has been the candidate long enough to trust
            self.suppressed += 1
            self._hot_dwell += 1
            return None
        self._hot_dwell = 0
        self._hot_signs = signs
        self._hot_salt += 1
        return Decision(
            KIND_REPLICATE,
            f"hot set changed: {len(signs)} signs >= "
            f"{c.hot_mass_frac:.3f} of mass",
            {"signs": list(signs), "fanout": int(c.hot_fanout),
             "salt": int(self._hot_salt)},
        )

    # --------------------------------------------------------------- scale

    def decide_scale(
        self, qps: float, n_replicas: int, quarantined: int = 0,
    ) -> Optional[Decision]:
        """Propose a serving fleet size from the gateway's request rate.
        Desired = ceil(qps / qps_per_replica) clamped to
        [min, max]; quarantined replicas are lag-drained capacity, so the
        live target grows by their count (the quarantine/heal plumbing
        already knows how to fold them back in). A change must hold for
        ``scale_min_dwell`` consecutive rounds (hysteresis band
        ``scale_hysteresis`` keeps a borderline qps from oscillating the
        desired count itself)."""
        c = self.cfg
        raw = qps / c.qps_per_replica if c.qps_per_replica > 0 else 0.0
        desired = max(1, math.ceil(raw))
        # hysteresis: within the band around the current size, keep it
        if n_replicas >= 1 and desired != n_replicas:
            lo = (n_replicas - 1) * c.qps_per_replica * (1 - c.scale_hysteresis)
            hi = n_replicas * c.qps_per_replica * (1 + c.scale_hysteresis)
            if lo <= qps <= hi:
                desired = n_replicas
        desired += max(int(quarantined), 0)
        desired = min(max(desired, c.scale_min_replicas), c.scale_max_replicas)
        if desired == n_replicas:
            self._scale_target = None
            self._scale_dwell = 0
            return None
        if self._scale_target != desired:
            # new target — start its dwell clock; acting now would flap
            self._scale_target = desired
            self._scale_dwell = 1
            self.suppressed += 1
            return None
        self._scale_dwell += 1
        if self._scale_dwell <= c.scale_min_dwell:
            self.suppressed += 1
            return None
        self._scale_dwell = 0
        self._scale_target = None
        return Decision(
            KIND_SCALE,
            f"qps {qps:.1f} wants {desired} replicas (have {n_replicas}, "
            f"{quarantined} quarantined)",
            {"target": int(desired), "from": int(n_replicas),
             "qps": float(qps), "quarantined": int(quarantined)},
        )

    # --------------------------------------------------------------- state

    def export_state(self) -> Dict:
        """Soft guard state — rides the decision manifests so a resumed
        controller restarts its dwell clocks close to where they were."""
        return {
            "suppressed": int(self.suppressed),
            "scale_dwell": int(self._scale_dwell),
            "scale_target": self._scale_target,
            "hot_dwell": int(self._hot_dwell),
            "hot_signs": [int(s) for s in self._hot_signs],
            "hot_salt": int(self._hot_salt),
            "reshard_dwell": int(self.shard_planner._dwell),
            "reshard_suppressed": int(self.shard_planner.suppressed),
        }

    def load_state(self, state: Dict) -> None:
        self.suppressed = int(state.get("suppressed", 0))
        self._scale_dwell = int(state.get("scale_dwell", 0))
        st = state.get("scale_target")
        self._scale_target = None if st is None else int(st)
        self._hot_dwell = int(state.get("hot_dwell", 0))
        self._hot_signs = tuple(int(s) for s in state.get("hot_signs", ()))
        self._hot_salt = int(state.get("hot_salt", 0))
        self.shard_planner._dwell = int(state.get("reshard_dwell", 0))
        self.shard_planner.suppressed = int(
            state.get("reshard_suppressed", 0)
        )

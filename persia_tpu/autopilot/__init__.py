"""Autopilot: a closed-loop fleet controller (ROADMAP direction 2).

Senses live telemetry the fleet already produces — the tiering access
sketch's per-shard load model, the serving gateway's request rate and
quarantine pressure — and reshapes the fleet through three actuators
behind one hysteresis/dwell-guarded policy engine:

- :mod:`policy` — pure decisions (PS ring re-split, hot-sign read
  replication, serving replica count) with flap suppression accounted;
- :mod:`replicate` — journaled exactly-once hot-sign copies + the
  ``ShardedLookup`` read fan-out swap;
- :mod:`controller` — the :class:`Autopilot` loop: fence-driven on the
  training plane (``train_stream(fence_callback=pilot.on_fence)``),
  timer-driven on the serving plane, every decision two-phase-journaled
  to jobstate so a SIGKILLed controller resumes its plan exactly-once;
- :mod:`heal` — the self-healing arc: the
  :class:`~persia_tpu.service.failure_detector.FailureDetector`'s
  lease/probe verdicts drive autonomous standby promotion for dead PS
  shards, gray-replica drains, and fleet grow/shrink, under the same
  two-phase journal (a SIGKILLed healer resumes its heal exactly-once).

Soak evidence: ``benchmarks/autopilot_bench.py`` → ``BENCH_AUTOPILOT.json``
and ``benchmarks/selfheal_bench.py`` → ``BENCH_SELFHEAL.json``.
"""

from persia_tpu.autopilot.controller import (  # noqa: F401
    AUTOPILOT_ENV,
    Autopilot,
    autopilot_enabled,
    enable_autopilot,
    gateway_sensors,
)
from persia_tpu.autopilot.heal import (  # noqa: F401
    ACTION_DRAIN_GRAY,
    ACTION_PROMOTE,
    ACTION_RESIZE,
    HealConfig,
    Healer,
    HealPolicy,
    enable_self_heal,
)
from persia_tpu.autopilot.policy import (  # noqa: F401
    KIND_HEAL,
    KIND_REPLICATE,
    KIND_RESHARD,
    KIND_SCALE,
    Decision,
    PolicyConfig,
    PolicyEngine,
)
from persia_tpu.autopilot.replicate import (  # noqa: F401
    MAX_REPLICATED_SIGNS,
    replicate_hot_signs,
)

"""Rank/replica environment parsing (ref: persia/env.py:25-132).

NN workers use ``RANK/LOCAL_RANK/WORLD_SIZE``; the other roles (data-loader,
embedding-worker, parameter-server) use ``REPLICA_INDEX/REPLICA_SIZE``.
"""

from __future__ import annotations

import os
from typing import Optional

def skip_check_data() -> bool:
    """When set, batch datatypes skip per-sample validation on the hot ingest
    path (ref: persia/env.py:13)."""
    return os.environ.get("PERSIA_SKIP_CHECK_DATA", "0") == "1"


def _get_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def get_rank() -> int:
    v = _get_int("RANK")
    if v is None:
        raise EnvironmentError("RANK is not set")
    return v


def get_local_rank() -> int:
    v = _get_int("LOCAL_RANK")
    if v is None:
        raise EnvironmentError("LOCAL_RANK is not set")
    return v


def get_world_size() -> int:
    v = _get_int("WORLD_SIZE")
    if v is None:
        raise EnvironmentError("WORLD_SIZE is not set")
    return v


def get_replica_index() -> int:
    v = _get_int("REPLICA_INDEX")
    if v is None:
        v = _get_int("RANK")
    if v is None:
        raise EnvironmentError("REPLICA_INDEX is not set")
    return v


def get_replica_size() -> int:
    v = _get_int("REPLICA_SIZE")
    if v is None:
        v = _get_int("WORLD_SIZE")
    if v is None:
        raise EnvironmentError("REPLICA_SIZE is not set")
    return v

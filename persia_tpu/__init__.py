"""persia_tpu — a TPU-native hybrid-parallel recommender training framework.

Capability parity target: openssl-sg-insights/PERSIA (100-trillion-parameter
hybrid-parallel recommendation training). The sparse half (huge embedding
tables keyed by u64 "signs") lives in sharded, LRU-evicting hash-map
parameter servers on CPU hosts (C++ core, `native/ps.cpp`) and is updated
asynchronously under a bounded-staleness semaphore; the dense half is a
JAX/flax model trained synchronously data-parallel on a TPU mesh with XLA
collectives (`persia_tpu/parallel`), fed by a pipelined host feeder
(`persia_tpu/data_loader.py`).

Layer map (TPU-first redesign of reference SURVEY.md §1):

  user API       persia_tpu.ctx / persia_tpu.data_loader / persia_tpu.embedding.optim
  dense engine   persia_tpu.parallel (mesh + pjit train step) + persia_tpu.models
  host feeder    persia_tpu.data_loader (prefetch pipeline, staleness, reorder)
  emb worker     persia_tpu.embedding.worker (dedup, routing, pooling, grad path)
  param server   persia_tpu.embedding.store (+ native C++ core)
  services       persia_tpu.service (RPC worker/PS processes, discovery)
  foundation     persia_tpu.config / persia_tpu.data / persia_tpu.storage / metrics
"""

from persia_tpu.version import __version__

__all__ = ["__version__"]

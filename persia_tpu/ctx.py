"""User-facing training/eval/inference contexts.

Parity target: ``persia/ctx.py`` — ``BaseCtx`` (common context wiring),
``DataCtx`` (data-loader side), ``EmbeddingCtx`` (feature prep + checkpoint),
``TrainCtx`` (training state machine), ``eval_ctx``/``InferCtx``.

TPU-first shape: instead of DLPack handoffs into torch autograd
(ref ctx.py:40-55), ``prepare_features`` stages numpy worker outputs into a
sharded device batch; the whole train step (forward, loss, backward, dense
update, embedding grads) is one jitted XLA program from
``persia_tpu.parallel.train_step``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
import optax

from persia_tpu.config import EmbeddingConfig, HyperParameters, JobType
from persia_tpu.data import PersiaBatch
from persia_tpu.embedding.optim import SGD as SparseSGD
from persia_tpu.embedding.worker import (
    DevicePooledBatch,
    EmbeddingWorker,
    FeatureEmbeddingBatch,
    RawEmbeddingBatch,
    SumEmbeddingBatch,
)
from persia_tpu.logger import get_default_logger
from persia_tpu.utils import round_up_pow2 as _round_up_pow2
from persia_tpu.parallel.train_step import (
    TrainState,
    build_eval_step,
    build_train_step,
    init_train_state,
    replicate_state,
    shard_device_batch,
    unpack_step_grads,
    unpack_step_header,
    unpack_step_header_dynamic,
    unpack_step_output,
)

logger = get_default_logger("persia_tpu.ctx")


def _pad_bucket(n: int) -> int:
    """Padded-distinct bucket: pow2 below 512, then 512-quantum — the
    gradient buffer rides the (slow) device→host wire, so past the small
    sizes pow2's up-to-2x padding waste costs real link time. Production
    zipf streams concentrate distinct counts tightly, so the quantum still
    yields a near-constant step signature."""
    if n <= 512:
        return _round_up_pow2(n)
    return -(-n // 512) * 512


def stage_embeddings(
    emb_batches: Sequence[FeatureEmbeddingBatch],
    dtype=None,
) -> Tuple[List[Dict], List[Optional[int]]]:
    """Convert worker outputs into the device batch's ``emb`` entries.

    Raw and device-pooled slots: distinct rows are padded to a bucketed
    size (static shapes for jit — a bounded set of compiled programs
    instead of one per distinct-count) with zero rows absorbing padded
    index entries. Device-pooled slots share ONE bucket (the max) so the
    step signature stays stable across batches; their index pad keeps
    pointing at row D (a zero row), and pad gradients land on rows the
    host slices off. Returns (emb_entries, true_distinct_counts) — counts
    are None for host-pooled slots and are used to slice padding off the
    returned gradients.
    """
    entries: List[Dict] = []
    counts: List[Optional[int]] = []
    shared_p = 0
    for eb in emb_batches:
        if isinstance(eb, DevicePooledBatch):
            shared_p = max(shared_p, eb.distinct.shape[0] + 1)
    if shared_p:
        shared_p = _pad_bucket(shared_p)
    for eb in emb_batches:
        if isinstance(eb, SumEmbeddingBatch):
            pooled = eb.pooled if dtype is None else eb.pooled.astype(dtype)
            entries.append({"pooled": pooled})
            counts.append(None)
        elif isinstance(eb, DevicePooledBatch):
            d, dim = eb.distinct.shape
            padded = np.zeros(
                (shared_p, dim),
                dtype=eb.distinct.dtype if dtype is None else dtype,
            )
            padded[:d] = eb.distinct
            # uint16 indexes when the padded table allows: the index matrix
            # rides host→device every batch (cast back on device, fused free)
            idx_dtype = np.uint16 if shared_p <= 0xFFFF else np.int32
            entry = {
                "distinct": padded,
                "pool_index": np.ascontiguousarray(eb.index, dtype=idx_dtype),
            }
            if eb.sqrt_scaling:
                # 2-D int column (packs with the index matrices on the mesh
                # staging path); rsqrt happens on device in f32
                entry["pool_counts"] = eb.counts.reshape(-1, 1).astype(np.int32)
            entries.append(entry)
            counts.append(d)
        else:
            d, dim = eb.distinct.shape
            p = _round_up_pow2(d + 1)
            padded = np.zeros((p, dim),
                              dtype=eb.distinct.dtype if dtype is None else dtype)
            padded[:d] = eb.distinct
            index = np.where(eb.index == d, p - 1, eb.index).astype(np.int32)
            mask = eb.index != d
            entries.append({"distinct": padded, "index": index, "mask": mask})
            counts.append(d)
    return entries, counts


class BaseCtx:
    """Common wiring (ref: persia/ctx.py:208-243). ``worker`` is the embedding
    -worker tier handle: in-process ``EmbeddingWorker`` or an RPC client with
    the same surface."""

    def __init__(self, worker: EmbeddingWorker, embedding_config: EmbeddingConfig):
        self.worker = worker
        self.embedding_config = embedding_config

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class EmbeddingCtx(BaseCtx):
    """Feature preparation + checkpoint plumbing (ref: persia/ctx.py:345-652)."""

    def __init__(
        self,
        worker: EmbeddingWorker,
        embedding_config: EmbeddingConfig,
        mesh=None,
        wire_dtype: Optional[str] = None,
    ):
        super().__init__(worker, embedding_config)
        self.mesh = mesh
        # host↔device embedding/gradient dtype; "bfloat16" halves transfer
        # bytes (ref capability: f16 wire format with f32 master weights,
        # common/lib.rs:157-180 + backward.rs EmbeddingGradientBatch)
        self.wire_dtype = None if wire_dtype in (None, "float32") else np.dtype(wire_dtype)

    def prepare_features(
        self, batch: PersiaBatch, emb_batches: Sequence[FeatureEmbeddingBatch]
    ) -> Tuple[Dict, List[Optional[int]]]:
        """Build the sharded device batch from a ``PersiaBatch`` + worker
        lookup results (ref: _prepare_feature, ctx.py:75-199)."""
        entries, counts = stage_embeddings(emb_batches, dtype=self.wire_dtype)
        device_batch = {
            "dense": [f.data.astype(np.float32) for f in batch.non_id_type_features],
            "labels": [l.data.astype(np.float32) for l in batch.labels],
            "emb": entries,
        }
        return shard_device_batch(device_batch, self.mesh), counts

    def emb_grads_to_slot_grads(
        self,
        emb_batches: Sequence[FeatureEmbeddingBatch],
        emb_grads: Sequence,
        counts: Sequence[Optional[int]],
    ) -> Dict[str, np.ndarray]:
        """Strip padding and key device gradients by slot name for the
        worker's gradient path."""
        out = {}
        for eb, g, d in zip(emb_batches, emb_grads, counts):
            g = np.asarray(g, dtype=np.float32)
            out[eb.name] = g if d is None else g[:d]
        return out

    def dump_checkpoint(self, dst: str, blocking: bool = True) -> None:
        """Dense state + sharded embedding checkpoint under ``dst``
        (ref: ctx.dump_checkpoint, persia/ctx.py:1007-1034)."""
        import flax.serialization

        from persia_tpu.checkpoint import dump_dense

        if getattr(self, "state", None) is not None:
            dump_dense(flax.serialization.to_bytes(self.state), dst)
        self.worker.dump(dst, blocking=blocking)

    def load_checkpoint(self, src: str) -> None:
        """Restore dense state (requires ``self.state`` initialized with the
        right shapes) + embedding tables (ref: ctx.load_checkpoint,
        persia/ctx.py:1036-1064)."""
        import flax.serialization

        from persia_tpu.checkpoint import load_dense

        if getattr(self, "state", None) is not None:
            raw = load_dense(src, missing_ok=True)
            if raw is not None:
                self.state = flax.serialization.from_bytes(self.state, raw)
        self.worker.load(src)


class DataCtx(BaseCtx):
    """Data-loader role: push batches into the dataflow
    (ref: persia/ctx.py:274-342). In-process mode forwards straight to the
    worker's id buffer; the service mode sends over RPC (persia_tpu.service)."""

    def send_data(self, batch: PersiaBatch) -> int:
        if not self.worker.can_forward_batched():
            raise RuntimeError("embedding worker forward buffer full")
        return self.worker.put_forward_ids(batch)


class TrainCtx(EmbeddingCtx):
    """Synchronous training context — the M1 slice (lookup-direct path,
    ref forward_directly, forward.rs:782-831). The pipelined/bounded-staleness
    path lives in ``persia_tpu.data_loader.DataLoader``.

    Responsibilities (ref: persia/ctx.py:655-1064): hold the jitted train
    step + TrainState, register the sparse optimizer on the PS tier, convert
    device grads into worker gradient updates.
    """

    def __init__(
        self,
        model,
        dense_optimizer: optax.GradientTransformation,
        embedding_optimizer,
        worker: EmbeddingWorker,
        embedding_config: EmbeddingConfig,
        mesh=None,
        grad_scale: float = 1.0,
        loss_fn=None,
        wire_dtype: Optional[str] = None,
        dynamic_loss_scale: bool = False,
        loss_scale_init: float = float(2 ** 15),
        loss_scale_growth_interval: int = 2000,
        loss_scale_max: float = float(2 ** 24),
        resilience_policy=None,
        dense_sync: Optional[str] = None,
        dense_sync_block_size: int = 256,
    ):
        super().__init__(worker, embedding_config, mesh=mesh, wire_dtype=wire_dtype)
        self.model = model
        self.dense_optimizer = dense_optimizer
        self.embedding_optimizer = embedding_optimizer
        self.grad_scale = grad_scale
        # shared service/resilience.py policy: the DataLoader picks it up
        # for its recovery backoff + per-batch deadline budget, so trainer-
        # side retry behavior is configured in ONE place
        self.resilience_policy = resilience_policy
        # (device header, batch) of the latest fetch_metrics=False prepared
        # step — materialized by last_prepared_metrics()
        self._deferred_header = None
        # crash-consistent job state (persia_tpu.jobstate): once resume()
        # has been called (even on a cold start) every gradient batch is
        # tagged with a (manifest epoch, global step) journal id so the PS
        # apply-journal can dedupe a post-crash replay; snapshot_job()
        # advances the epoch at each fence
        self._job_epoch: Optional[int] = None
        self._global_step: int = 0
        self._resume_state_bytes: Optional[bytes] = None
        self.last_resume_info: Optional[Dict] = None
        # dynamic mixed-precision loss scaling (ref: GradScaler management,
        # persia/ctx.py:926-1005): on-device finite check every step,
        # skip-step + scale backoff on overflow, periodic growth
        self.dynamic_loss_scale = dynamic_loss_scale
        self._loss_scale_init = loss_scale_init if dynamic_loss_scale else None
        kwargs = {} if loss_fn is None else {"loss_fn": loss_fn}
        self._train_step_jit = build_train_step(
            model, dense_optimizer,
            dynamic_loss_scale=dynamic_loss_scale,
            growth_interval=loss_scale_growth_interval,
            max_scale=loss_scale_max,
            **kwargs,
        )
        # explicit dense-plane sync mode (persia_tpu.parallel.grad_sync
        # DENSE_SYNC_MODES): None keeps the default implicit-psum path; a
        # mode string swaps the jitted step for build_sync_train_step's
        # explicit-collective step (quantized ring and/or ZeRO-style sharded
        # optimizer update). The bytegrad mode's error-feedback residual is
        # carried on the ctx (not in TrainState), so it does NOT survive a
        # jobstate resume — ring modes carry theirs inside opt_state and do.
        self.dense_sync = dense_sync
        self.dense_sync_block_size = int(dense_sync_block_size)
        self._sync_step = None
        self._sync_algorithm = None
        self._sync_sharded = False
        self._sync_wrapped = False
        self._sync_residual = None
        self._dense_wire_bytes_per_step = 0
        self._wire_counter = None
        if dense_sync is not None:
            if mesh is None:
                raise ValueError("dense_sync requires a device mesh")
            if dynamic_loss_scale:
                raise ValueError(
                    "dense_sync and dynamic_loss_scale are mutually "
                    "exclusive: the explicit-collective step has no "
                    "loss-scale path"
                )
            from persia_tpu.parallel.grad_sync import (
                BlockInt8Ring,
                build_sync_train_step,
                sync_mode_algorithm,
            )

            algo, sharded = sync_mode_algorithm(
                dense_sync, block_size=self.dense_sync_block_size
            )
            self._sync_algorithm = algo
            self._sync_sharded = sharded
            self._sync_wrapped = sharded or isinstance(algo, BlockInt8Ring)
            self._sync_step = build_sync_train_step(
                model, dense_optimizer, mesh, algo,
                sharded_update=sharded, **kwargs,
            )
        self._eval_step = build_eval_step(model)
        self.state: Optional[TrainState] = None

    @property
    def sync_mode(self) -> str:
        """The dense-plane sync mode label this ctx runs (and records):
        an explicit ``dense_sync`` mode, else "implicit-psum" on a real DP
        mesh, else "local"."""
        if self.dense_sync is not None:
            return self.dense_sync
        if self.mesh is not None and int(self.mesh.shape["data"]) > 1:
            return "implicit-psum"
        return "local"

    def dense_wire_bytes_per_step(self) -> int:
        """Modeled per-replica dense collective bytes per step for this
        ctx's sync mode (0 before state init — the param count prices it)."""
        return self._dense_wire_bytes_per_step

    def _note_dense_sync(self, state) -> None:
        """Price the per-step dense collective once (param count is known
        after state init) so the hot path only adds a python-int counter
        bump — no host syncs (persia-lint JAX001)."""
        from persia_tpu.metrics import get_metrics
        from persia_tpu.parallel.grad_sync import (
            dense_param_count,
            dense_sync_wire_bytes,
        )

        n = int(self.mesh.shape["data"]) if self.mesh is not None else 1
        self._dense_wire_bytes_per_step = dense_sync_wire_bytes(
            self.sync_mode, dense_param_count(state.params), n,
            block_size=self.dense_sync_block_size,
        )
        self._wire_counter = get_metrics().counter(
            "persia_tpu_dense_wire_bytes",
            "modeled dense-plane collective bytes dispatched, by sync mode",
        )

    def _run_dense_step(self, state, device_batch):
        """Dispatch one jitted dense step through the selected sync mode.

        Explicit modes get a sync-stage span on the dispatch edge; every
        mode (implicit-psum included) bumps the wire-bytes counter with the
        precomputed per-step cost. The default path stays exactly
        ``self._train_step_jit`` — zero new overhead when ``dense_sync`` is
        unset and the mesh is single-device."""
        if self._sync_step is not None:
            from persia_tpu import tracing

            with tracing.span(
                "train.dense_sync", mode=self.dense_sync,
                wire_bytes=self._dense_wire_bytes_per_step,
            ):
                if self._sync_residual is not None:
                    state, out, self._sync_residual = self._sync_step(
                        state, device_batch, self._sync_residual
                    )
                else:
                    state, out = self._sync_step(state, device_batch)
        else:
            state, out = self._train_step_jit(state, device_batch)
        if self._wire_counter is not None and self._dense_wire_bytes_per_step:
            self._wire_counter.inc(
                self._dense_wire_bytes_per_step, mode=self.sync_mode
            )
        return state, out

    def _train_step(self, state, device_batch):
        """Run the jitted step and unpack its single-transfer output into the
        (state, metrics, emb_grads) host view."""
        state, (header, gpacked) = self._run_dense_step(state, device_batch)
        if self.dynamic_loss_scale:
            loss, preds, scale, finite = unpack_step_header_dynamic(
                np.asarray(header), device_batch
            )
            emb_grads = unpack_step_grads(np.asarray(gpacked), device_batch)
            metrics = {"loss": loss, "preds": preds,
                       "loss_scale": scale, "grads_finite": finite}
        else:
            loss, preds, emb_grads = unpack_step_output(
                np.asarray(header), np.asarray(gpacked), device_batch
            )
            metrics = {"loss": loss, "preds": preds}
        return state, metrics, emb_grads

    def __enter__(self):
        # register the sparse optimizer on every PS replica
        # (ref: embedding_optimizer.apply(), persia/ctx.py:854-858)
        self.worker.register_optimizer(self.embedding_optimizer.config)
        return self

    def init_state(self, rng, sample_batch: Dict) -> TrainState:
        state = init_train_state(
            self.model, rng, sample_batch, self.dense_optimizer,
            loss_scale_init=self._loss_scale_init,
        )
        if self._sync_wrapped:
            # ring/sharded modes carry opt state in the init_sync_opt_state
            # wrapper (sharded moments + EF residual). Swap the template in
            # BEFORE the deferred overlay so a restored manifest's sharded
            # opt state lands on matching shapes.
            from persia_tpu.parallel.grad_sync import init_sync_opt_state

            state = state.replace(
                opt_state=init_sync_opt_state(
                    state.params, self.dense_optimizer, self.mesh,
                    self._sync_algorithm, self._sync_sharded,
                )
            )
        if self._resume_state_bytes is not None:
            # deferred resume: the manifest's dense/opt state overlays the
            # freshly initialized template (same model + optimizer shapes)
            import flax.serialization

            state = flax.serialization.from_bytes(
                state, self._resume_state_bytes
            )
            self._resume_state_bytes = None
        if self.mesh is not None:
            state = self._place_state(state)
        self.state = state
        if self._sync_residual is None and self.dense_sync == "bytegrad":
            from persia_tpu.parallel.grad_sync import init_residual

            self._sync_residual = init_residual(state.params)
        self._note_dense_sync(state)
        return state

    def _place_state(self, state: TrainState) -> TrainState:
        """Mesh placement for a (possibly host-resident) TrainState: the
        sync wrapper's lead-axis leaves shard over ``data``, everything else
        replicates."""
        if self._sync_wrapped:
            from persia_tpu.parallel.grad_sync import place_sync_state

            return place_sync_state(
                state, self.mesh, self._sync_algorithm, self._sync_sharded
            )
        return replicate_state(state, self.mesh)

    # -------------------------------------------------- crash-consistent jobs

    def _ps_replicas(self):
        router = getattr(self.worker, "lookup_router", None)
        if router is None:
            from persia_tpu.jobstate import ManifestError

            raise ManifestError(
                "job-state snapshots need direct PS replica handles (an "
                "in-process EmbeddingWorker over stores/StoreClients); a "
                "remote WorkerClient trainer should checkpoint via "
                "worker.dump instead"
            )
        return router.replicas

    def snapshot_job(self, job_state, loader=None, include_ps: bool = True,
                     extra_meta: Optional[Dict] = None, generators=None):
        """Step-fenced snapshot: drain the loader's in-flight gradients,
        then commit PS shards + dense/opt state + RNG streams as one
        manifest epoch (persia_tpu.jobstate). Returns the Manifest."""
        import flax.serialization

        from persia_tpu import jobstate

        mgr = jobstate.coerce_manager(job_state)
        if loader is not None:
            loader.flush()  # fence invariant: nothing in flight past here
        router = getattr(self.worker, "lookup_router", None)
        meta = {"kind": "train_ctx"}
        meta.update(extra_meta or {})
        manifest = jobstate.snapshot_job(
            mgr, self._global_step,
            state_bytes=(
                flax.serialization.to_bytes(self.state)
                if self.state is not None else None
            ),
            replicas=self._ps_replicas() if include_ps else None,
            batch_advances=(
                dict(getattr(router, "batch_advances", {})) if router else None
            ),
            components={
                "loader.json": {
                    "consumed_batches": self._global_step,
                    "staleness_outstanding": 0,  # fence = flushed
                },
            },
            meta=meta,
            generators=generators,
        )
        self._job_epoch = manifest.job_epoch
        return manifest

    def resume(self, job_state, restore_ps: bool = True, generators=None):
        """Rebuild the exact fence state from the newest good manifest (or
        arm journaling on a cold start). Returns the Manifest or None.

        ``restore_ps=True`` rewinds the PS to the fence — the replayed
        window re-applies and the run is bit-identical to a fault-free
        replay. ``restore_ps=False`` keeps the PS's post-crash state and
        relies on the apply-journal to skip already-applied batches
        (exactly-once, bounded staleness)."""
        from persia_tpu import jobstate

        mgr = jobstate.coerce_manager(job_state)
        router = getattr(self.worker, "lookup_router", None)
        manifest, info = jobstate.resume_job(
            mgr,
            replicas=(router.replicas if router is not None else None),
            rewind_ps=restore_ps,
            optimizer=self.embedding_optimizer.config,
            generators=generators,
        )
        self.last_resume_info = info
        if manifest is None:
            self._job_epoch = 0  # cold start: journal from step 0, epoch 0
            self._global_step = 0
            return None
        if manifest.has("dense.state"):
            self._resume_state_bytes = manifest.read_blob("dense.state")
            if self.state is not None:
                import flax.serialization

                self.state = flax.serialization.from_bytes(
                    self.state, self._resume_state_bytes
                )
                if self.mesh is not None:
                    self.state = self._place_state(self.state)
                self._resume_state_bytes = None
        router = getattr(self.worker, "lookup_router", None)
        if router is not None:
            # fences record CUMULATIVE advance counts; continue from them
            router.batch_advances = dict(info.get("batch_advances", {}))
        self._job_epoch = manifest.job_epoch
        self._global_step = manifest.step
        return manifest

    def _journal_id(self) -> Optional[int]:
        if self._job_epoch is None:
            return None
        from persia_tpu.jobstate import make_journal_id

        return make_journal_id(self._job_epoch, self._global_step)

    def train_step(self, batch: PersiaBatch) -> Dict:
        """One synchronous hybrid step: lookup → jitted step → gradient
        return. Returns host metrics {loss, preds}."""
        from persia_tpu import tracing

        # the step IS the trace edge on the synchronous path: the lookup
        # and gradient-update RPCs beneath inherit one trace_id, linking
        # this gradient batch to its journaled PS apply
        with tracing.span("train.step", step=self._global_step):
            return self._train_step_sync(batch)

    def _train_step_sync(self, batch: PersiaBatch) -> Dict:
        ref = self.worker.put_forward_ids(batch)
        emb_batches = self.worker.forward_batch_id(ref, train=True)
        try:
            device_batch, counts = self.prepare_features(batch, emb_batches)
            if self.state is None:
                self.init_state(jax.random.PRNGKey(0), device_batch)
            self.state, metrics, emb_grads = self._train_step(self.state, device_batch)
            slot_grads = self.emb_grads_to_slot_grads(emb_batches, emb_grads, counts)
        except Exception:
            # release the staleness slot + stashed layout (no silent buffer leak)
            self.worker.abort_gradient(ref)
            raise
        # emb grads ship scaled; the worker's scale_factor division unscales
        # (non-finite slots are NaN-skipped there, mod.rs:716-744). A static
        # grad_scale composes with the dynamic loss scale instead of being
        # silently discarded by it.
        scale = metrics.get("loss_scale", 1.0) * self.grad_scale
        jid = self._journal_id()
        if jid is not None:
            self.worker.update_gradient_batched(
                ref, slot_grads, scale_factor=scale, journal_id=jid
            )
        else:
            self.worker.update_gradient_batched(ref, slot_grads, scale_factor=scale)
        self._global_step += 1
        out = {
            "loss": float(metrics["loss"]),
            "preds": np.asarray(metrics["preds"]),
        }
        for k in ("loss_scale", "grads_finite"):
            if k in metrics:
                out[k] = metrics[k]
        return out

    def train_step_prepared(
        self, training_batch, loader, fetch_metrics: bool = True
    ) -> Optional[Dict]:
        """Pipelined step: consume a ``PersiaTrainingBatch`` from a
        ``DataLoader``; the embedding gradients return asynchronously through
        the loader's BackwardEngine (bounded staleness). The TPU step of batch
        N overlaps the lookup of batch N+k (ref: forward.rs pipeline +
        backward.rs).

        ``fetch_metrics=False`` (static loss scale only — the dynamic scale
        must be read every step) skips the per-step header fetch: on a
        remote-attached chip that device→host read costs tens of ms and
        permanently degrades dispatch latency, so metric-light loops fetch
        once at the end via :meth:`last_prepared_metrics`. Returns ``None``
        in that mode."""
        device_batch = training_batch.device_batch
        if self.state is None:
            self.init_state(jax.random.PRNGKey(0), device_batch)
        defer = not fetch_metrics and not self.dynamic_loss_scale
        if not defer:
            self._deferred_header = None  # this step's metrics are fresher
        try:
            self.state, (header, gpacked) = self._run_dense_step(self.state, device_batch)
            # start the bulk gradient download without blocking; the
            # BackwardEngine thread materializes it, so the device→host
            # transfer overlaps the next step instead of serializing with it
            try:
                gpacked.copy_to_host_async()
            except AttributeError:
                pass
            if defer:
                # stash only the labels SHAPE: keeping the device_batch
                # would pin the whole batch's device buffers until the
                # deferred fetch
                self._deferred_header = (
                    header, tuple(device_batch["labels"][0].shape)
                )
                dyn_scale, scale, finite = None, self.grad_scale, None
            elif self.dynamic_loss_scale:
                loss, preds, dyn_scale, finite = unpack_step_header_dynamic(
                    np.asarray(header), device_batch
                )
                # static grad_scale composes with the dynamic loss scale
                scale = dyn_scale * self.grad_scale
            else:
                loss, preds = unpack_step_header(np.asarray(header), device_batch)
                dyn_scale, scale, finite = None, self.grad_scale, None
        except Exception:
            loader.mark_consumed(training_batch)
            raise
        loader.backward_packed(
            training_batch, gpacked, scale_factor=scale,
            journal_id=self._journal_id(),
        )
        self._global_step += 1
        if defer:
            return None
        out = {"loss": loss, "preds": np.asarray(preds)}
        if finite is not None:
            out["loss_scale"] = dyn_scale
            out["grads_finite"] = finite
        return out

    def last_prepared_metrics(self) -> Optional[Dict]:
        """Materialize the most recent ``fetch_metrics=False`` step's
        header (ONE device→host fetch, after the loop it was deferred out
        of)."""
        if self._deferred_header is None:
            return None
        header, label_shape = self._deferred_header
        self._deferred_header = None
        h = np.asarray(header)
        return {"loss": float(h[0]), "preds": h[1:].reshape(label_shape)}

    def eval_batch(self, batch: PersiaBatch) -> np.ndarray:
        emb_batches = self.worker.forward_directly(batch, train=False)
        device_batch, _ = self.prepare_features(batch, emb_batches)
        return np.asarray(self._eval_step(self.state, device_batch))


class InferCtx(EmbeddingCtx):
    """Inference: lookup-direct, zeros-on-miss, no buffers
    (ref: persia/ctx.py:1077-1133)."""

    def __init__(self, model, state: TrainState, worker, embedding_config, mesh=None):
        super().__init__(worker, embedding_config, mesh=mesh)
        self.model = model
        self.state = state
        self._eval_step = build_eval_step(model)

    def predict(self, batch: PersiaBatch) -> np.ndarray:
        emb_batches = self.worker.forward_directly(batch, train=False)
        device_batch, _ = self.prepare_features(batch, emb_batches)
        return np.asarray(self._eval_step(self.state, device_batch))

    def predict_from_bytes(self, raw: bytes) -> np.ndarray:
        """(ref: get_embedding_from_bytes, persia/ctx.py:637-652)"""
        return self.predict(PersiaBatch.from_bytes(raw))

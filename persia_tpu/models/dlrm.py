"""DLRM — the flagship benchmark model (BASELINE.json: DLRM on Criteo).

Standard DLRM architecture (bottom MLP over dense features, pairwise dot
interactions between the bottom output and per-slot pooled embeddings, top
MLP over [bottom | interactions]), built TPU-first: bf16 compute on the MXU,
f32 params, the interaction computed as one batched matmul
(``jnp.einsum('bnd,bmd->bnm')``) instead of per-pair dots.
"""

from __future__ import annotations

from typing import Any, List, Sequence

import flax.linen as nn
import jax.numpy as jnp


def _mlp(x, sizes, dt, final_relu=True):
    for i, h in enumerate(sizes):
        x = nn.Dense(h, dtype=dt)(x)
        if final_relu or i < len(sizes) - 1:
            x = nn.relu(x)
    return x


class DLRM(nn.Module):
    embedding_dim: int = 16
    bottom_mlp: Sequence[int] = (64, 32, 16)  # last must equal embedding_dim
    top_mlp: Sequence[int] = (256, 128)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_features: List, embeddings: List, train: bool = True):
        dt = self.compute_dtype
        dense = jnp.concatenate([f.astype(dt) for f in non_id_features], axis=1)
        bottom = _mlp(dense, self.bottom_mlp, dt)  # (B, d)

        embs = []
        for emb in embeddings:
            if isinstance(emb, tuple):  # raw slot → mean-pool into one vector
                gathered, mask = emb
                m = mask[..., None].astype(gathered.dtype)
                denom = jnp.maximum(m.sum(axis=1), 1.0)
                embs.append(((gathered * m).sum(axis=1) / denom).astype(dt))
            else:
                embs.append(emb.astype(dt))

        # (B, n+1, d): bottom output joins the interaction like an embedding
        feats = jnp.stack([bottom] + embs, axis=1)
        inter = jnp.einsum("bnd,bmd->bnm", feats, feats)  # one MXU batched matmul
        n = feats.shape[1]
        iu, ju = jnp.triu_indices(n, k=1)
        inter_flat = inter[:, iu, ju]  # (B, n(n-1)/2)

        top_in = jnp.concatenate([bottom, inter_flat], axis=1)
        x = _mlp(top_in, self.top_mlp, dt)
        return nn.Dense(1, dtype=jnp.float32)(x)

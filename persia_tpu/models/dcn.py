"""DCN-v2 — deep & cross network v2 (BASELINE.json: DCN-v2 on Avazu).

Cross layers use the v2 formulation ``x_{l+1} = x0 ⊙ (W x_l + b) + x_l``
(optionally low-rank ``W = U Vᵀ``), run in parallel with a deep tower and
concatenated for the output head — each cross layer is one (or two, when
low-rank) MXU matmuls.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from persia_tpu.models.deepfm import field_matrix


class CrossLayerV2(nn.Module):
    """One DCN-v2 cross layer; ``rank`` enables the low-rank factorization."""

    rank: Optional[int] = None
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x0, xl):
        dt = self.compute_dtype
        if self.rank is None:
            wx = nn.Dense(x0.shape[-1], dtype=dt)(xl)
        else:
            wx = nn.Dense(self.rank, use_bias=False, dtype=dt)(xl)
            wx = nn.Dense(x0.shape[-1], dtype=dt)(wx)
        return x0 * wx + xl


class DCNv2(nn.Module):
    embedding_dim: int = 16
    num_cross_layers: int = 3
    cross_rank: Optional[int] = None  # None = full-rank W
    deep_mlp: Sequence[int] = (256, 128)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_features: List, embeddings: List, train: bool = True):
        dt = self.compute_dtype
        dense = jnp.concatenate([f.astype(dt) for f in non_id_features], axis=1)
        fields = field_matrix(embeddings, dt)  # (B, n, d)
        x0 = jnp.concatenate([dense, fields.reshape(fields.shape[0], -1)], axis=1)

        # cross tower
        xl = x0
        for _ in range(self.num_cross_layers):
            xl = CrossLayerV2(rank=self.cross_rank, compute_dtype=dt)(x0, xl)

        # deep tower (parallel structure)
        deep = x0
        for h in self.deep_mlp:
            deep = nn.relu(nn.Dense(h, dtype=dt)(deep))

        out = jnp.concatenate([xl, deep], axis=1)
        return nn.Dense(1, dtype=jnp.float32)(out)

"""Adult-income-style DNN: the e2e determinism-oracle model.

Capability parity with the reference example model
(`/root/reference/examples/src/adult-income/model.py:8-40`): a dense-feature
MLP + batch-norm, a sparse (concatenated pooled embeddings) MLP + batch-norm,
and a 3-layer head. Rebuilt in flax with bf16 compute / f32 params.
"""

from __future__ import annotations

from typing import Any, List, Sequence

import flax.linen as nn
import jax.numpy as jnp


class DNN(nn.Module):
    dense_mlp_size: int = 16
    sparse_mlp_size: int = 128
    hidden_sizes: Sequence[int] = (256, 128)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_features: List, embeddings: List, train: bool = True):
        dt = self.compute_dtype
        dense_x = jnp.concatenate([f.astype(dt) for f in non_id_features], axis=1)

        parts = []
        for emb in embeddings:
            if isinstance(emb, tuple):  # raw slot: (gathered (B,L,D), mask (B,L))
                gathered, mask = emb
                pooled = (gathered * mask[..., None].astype(gathered.dtype)).sum(axis=1)
                parts.append(pooled.astype(dt))
            else:
                parts.append(emb.astype(dt))
        sparse = jnp.concatenate(parts, axis=1)

        sparse = nn.Dense(self.sparse_mlp_size, dtype=dt)(sparse)
        sparse = nn.BatchNorm(use_running_average=not train, dtype=dt)(sparse)
        dense_x = nn.Dense(self.dense_mlp_size, dtype=dt)(dense_x)
        dense_x = nn.BatchNorm(use_running_average=not train, dtype=dt)(dense_x)

        x = jnp.concatenate([sparse, dense_x], axis=1)
        for h in self.hidden_sizes:
            x = nn.relu(nn.Dense(h, dtype=dt)(x))
        logits = nn.Dense(1, dtype=jnp.float32)(x)
        return logits

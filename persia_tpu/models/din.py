"""DIN — deep interest network (BASELINE.json: DIN on Taobao).

Raw (sequence) slots are the user's behavior history; instead of mean-pooling
them, DIN scores each history item against the candidate item with a small
"attention unit" MLP over ``[item, target, item − target, item · target]``
and pools with the resulting weights.

Batch convention: pooled slots are regular field embeddings; the FIRST pooled
slot is the candidate/target item (configurable via ``target_slot``); every
raw slot is attention-pooled against it. Padded history positions are masked
with −inf before the softmax, so autodiff sends them exactly zero gradient.

TPU-first: the attention unit runs over the whole (B, L, 4d) tensor in one
bf16 matmul batch; no per-position loops, static shapes throughout.
"""

from __future__ import annotations

from typing import Any, List, Sequence

import flax.linen as nn
import jax.numpy as jnp


class AttentionUnit(nn.Module):
    """DIN activation unit → one logit per history position."""

    hidden: Sequence[int] = (36,)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, items, target):
        # items (B, L, d), target (B, d)
        t = jnp.broadcast_to(target[:, None, :], items.shape)
        x = jnp.concatenate([items, t, items - t, items * t], axis=-1)
        for h in self.hidden:
            x = nn.Dense(h, dtype=self.compute_dtype)(x)
            # Dice in the paper; PReLU-family — sigmoid-gated works fine on MXU
            x = x * nn.sigmoid(x)
        return nn.Dense(1, dtype=jnp.float32)(x)[..., 0]  # (B, L)


class DIN(nn.Module):
    embedding_dim: int = 16
    attention_hidden: Sequence[int] = (36,)
    top_mlp: Sequence[int] = (200, 80)
    target_slot: int = 0  # index among the POOLED slots that is the candidate
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_features: List, embeddings: List, train: bool = True):
        dt = self.compute_dtype
        dense = jnp.concatenate([f.astype(dt) for f in non_id_features], axis=1)

        pooled = [e.astype(dt) for e in embeddings if not isinstance(e, tuple)]
        raws = [e for e in embeddings if isinstance(e, tuple)]
        if not pooled:
            raise ValueError("DIN needs at least one pooled slot as the target item")
        target = pooled[self.target_slot]

        interests = []
        for i, (hist, mask) in enumerate(raws):
            hist = hist.astype(dt)
            logits = AttentionUnit(
                hidden=self.attention_hidden, compute_dtype=dt, name=f"att_{i}"
            )(hist, target)
            logits = jnp.where(mask, logits, -jnp.inf)
            # all-padding rows would softmax to NaN; give them weight 0
            any_valid = mask.any(axis=1, keepdims=True)
            w = nn.softmax(jnp.where(any_valid, logits, 0.0), axis=1)
            w = jnp.where(mask, w, 0.0).astype(dt)
            interests.append(jnp.einsum("bl,bld->bd", w, hist))

        x = jnp.concatenate([dense] + pooled + interests, axis=1)
        for h in self.top_mlp:
            x = nn.Dense(h, dtype=dt)(x)
            x = x * nn.sigmoid(x)
        return nn.Dense(1, dtype=jnp.float32)(x)

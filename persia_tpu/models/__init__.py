"""Dense-model zoo (flax). Every model takes the framework's standard inputs:

    model.apply(variables, non_id_features, embeddings, train=...)

where ``non_id_features`` is a list of (B, F) arrays and ``embeddings`` is a
list aligned with the batch's slot order: pooled slots contribute a (B, dim)
array; raw (sequence) slots contribute a ``(gathered, mask)`` pair with
``gathered`` (B, L, dim) and boolean ``mask`` (B, L). Models return logits
(loss applies the sigmoid — unlike the reference models which bake
``nn.Sigmoid`` into ``forward``, e.g.
`/root/reference/examples/src/adult-income/model.py:40`).
"""

from persia_tpu.models.dnn import DNN  # noqa: F401
from persia_tpu.models.dlrm import DLRM  # noqa: F401
from persia_tpu.models.deepfm import DeepFM  # noqa: F401
from persia_tpu.models.dcn import DCNv2  # noqa: F401
from persia_tpu.models.din import DIN  # noqa: F401

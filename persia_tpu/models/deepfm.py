"""DeepFM — FM + deep tower benchmark model (BASELINE.json: DeepFM on Avazu).

The reference framework ships the dense half as a user-defined torch module
(`/root/reference/persia/ctx.py:447` just calls ``model(...)``); this is the
equivalent first-party model for the TPU engine's batch convention.

TPU-first: the FM second-order term uses the square-of-sum minus
sum-of-squares identity — two elementwise ops and a reduction, no pairwise
loop — and the deep tower runs bf16 on the MXU.
"""

from __future__ import annotations

from typing import Any, List, Sequence

import flax.linen as nn
import jax.numpy as jnp


def _pool_raw(emb, dt):
    """Mean-pool a raw (sequence) slot ``(gathered, mask)`` to (B, d)."""
    gathered, mask = emb
    m = mask[..., None].astype(gathered.dtype)
    denom = jnp.maximum(m.sum(axis=1), 1.0)
    return ((gathered * m).sum(axis=1) / denom).astype(dt)


def field_matrix(embeddings: List, dt) -> jnp.ndarray:
    """Stack per-slot embeddings into (B, n_fields, d); raw slots mean-pool."""
    fields = [
        _pool_raw(e, dt) if isinstance(e, tuple) else e.astype(dt) for e in embeddings
    ]
    return jnp.stack(fields, axis=1)


class DeepFM(nn.Module):
    embedding_dim: int = 16
    deep_mlp: Sequence[int] = (256, 128)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_features: List, embeddings: List, train: bool = True):
        dt = self.compute_dtype
        dense = jnp.concatenate([f.astype(dt) for f in non_id_features], axis=1)
        fields = field_matrix(embeddings, dt)  # (B, n, d)
        B = fields.shape[0]

        # first-order terms: a learned scalar per field + linear over dense
        first = nn.Dense(1, dtype=jnp.float32, name="dense_linear")(dense)
        field_w = self.param(
            "field_weight", nn.initializers.zeros, (fields.shape[1],), jnp.float32
        )
        first = first + (fields.astype(jnp.float32).sum(-1) * field_w).sum(
            axis=1, keepdims=True
        )

        # second-order FM: 0.5 * ((Σv)² − Σv²), summed over the dim axis
        sum_v = fields.sum(axis=1)
        fm = 0.5 * (sum_v * sum_v - (fields * fields).sum(axis=1)).sum(
            axis=1, keepdims=True
        ).astype(jnp.float32)

        # deep tower over [dense | flattened fields]
        deep = jnp.concatenate([dense, fields.reshape(B, -1)], axis=1)
        for h in self.deep_mlp:
            deep = nn.relu(nn.Dense(h, dtype=dt)(deep))
        deep = nn.Dense(1, dtype=jnp.float32, name="deep_out")(deep)

        return first + fm + deep

"""Multi-host distributed setup + mesh presets.

Parity target: `persia/distributed.py` (DDPOption/BaguaDistributedOption —
process-group init, master discovery, allreduce algorithm selection) and the
NATS master discovery (`rust/persia-core/src/nats.rs:22-100`).

On TPU none of that machinery survives translation: there is no NCCL process
group to configure and no master address to gossip — ``jax.distributed``
initializes from the coordinator env and XLA inserts the collectives that the
sharding layout implies. What remains worth abstracting:

- ``initialize_multihost()``: one call that reads the launcher/k8s envs
  (`JAX_COORDINATOR_ADDRESS` / `JAX_NUM_PROCESSES` / `JAX_PROCESS_ID`, the
  ones persia_tpu.k8s injects into trainer pods) and brings up the JAX
  runtime; a no-op single-process fallback keeps scripts portable.
- ``hybrid_mesh()``: the framework's named-axis convention — ``data`` (DP,
  dense gradients psum over ICI), ``ep`` (HBM-resident embedding shards),
  ``sp`` (sequence/context parallelism for ring attention) — so every module
  agrees on axis names the way the reference's roles agree on NATS subjects.
- ``DistributedOption``-style dataclasses for run-shape declarations, kept so
  user code ports 1:1 from the reference's option objects.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.distributed")


@dataclass
class DistributedOption:
    """Declares the parallel shape of a run (ref: DDPOption/BaguaOption,
    persia/distributed.py:74-411 — algorithm knobs collapse away because XLA
    owns the collectives; what remains is the mesh factorization)."""

    dp: int = 1          # data-parallel ways (dense half)
    ep: int = 1          # embedding-parallel ways (HBM-resident tables)
    sp: int = 1          # sequence-parallel ways (ring attention)

    def total(self) -> int:
        return self.dp * self.ep * self.sp


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Bring up the multi-host JAX runtime from args or the launcher envs
    (set by persia_tpu.k8s trainer pods). Returns True if distributed init
    ran, False for the single-process fallback."""
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    n = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "1"))
    pid = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "0"))
    if not addr or n <= 1:
        logger.info("single-process run (no coordinator configured)")
        return False
    jax.distributed.initialize(
        coordinator_address=addr, num_processes=n, process_id=pid
    )
    logger.info("jax.distributed up: process %d/%d via %s", pid, n, addr)
    return True


def hybrid_mesh(
    option: Optional[DistributedOption] = None,
    dp: Optional[int] = None,
    ep: int = 1,
    sp: int = 1,
) -> Mesh:
    """Build the framework's canonical mesh with axes ("data", "ep", "sp").

    ``dp=None`` absorbs all remaining devices into the data axis. Axes of
    size 1 still exist (named shardings stay valid whether or not an axis is
    actually parallel), so the same jitted step runs at any factorization.
    """
    if option is not None:
        dp, ep, sp = option.dp, option.ep, option.sp
    devices = jax.devices()
    n = len(devices)
    if dp is None:
        if n % (ep * sp):
            raise ValueError(f"{n} devices not divisible by ep*sp={ep * sp}")
        dp = n // (ep * sp)
    want = dp * ep * sp
    if want != n:
        # a subset mesh would leave devices (and in multi-host runs whole
        # processes) out of the collectives — hangs, not slowdowns
        raise ValueError(
            f"mesh dp*ep*sp={want} must use all {n} devices "
            f"(got dp={dp}, ep={ep}, sp={sp})"
        )
    arr = np.array(devices).reshape(dp, ep, sp)
    return Mesh(arr, axis_names=("data", "ep", "sp"))


def process_counts() -> Tuple[int, int]:
    """(process_index, process_count) — the launcher-facing rank view."""
    return jax.process_index(), jax.process_count()

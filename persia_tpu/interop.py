"""Zero-copy tensor interop with PyTorch via DLPack.

Parity target: the reference crosses embeddings into torch autograd through
DLPack capsules (`persia/ctx.py:40-55`, `rust/persia-core/src/tensor.rs:
314-335`, `dlpack.rs:81-96`). This framework's dense engine is JAX, so the
hot path never needs torch — but users migrating from the reference often
keep torch models for evaluation/export or feed persia-tpu embeddings into
torch pipelines. These helpers make that a zero-copy handoff where the
devices allow it (CPU↔CPU always; accelerator sharing depends on the
platforms' DLPack support).

Torch is an optional dependency: importing this module without torch raises
only when a conversion is attempted.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def _torch():
    try:
        import torch
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "persia_tpu.interop requires torch (pip install torch)"
        ) from e
    return torch


def jax_to_torch(x: jax.Array) -> "Any":
    """JAX array → torch tensor; zero-copy through DLPack when both sides
    share the device, else through host memory."""
    torch = _torch()
    try:
        return torch.from_dlpack(x)
    except Exception:
        # copy: np.asarray(x) aliases JAX's cached (immutable) host buffer —
        # sharing it would let torch mutations corrupt the JAX array
        arr = np.asarray(x)
        if arr.dtype.name == "bfloat16":  # torch.from_numpy can't take ml_dtypes
            return torch.from_numpy(arr.astype(np.float32)).to(torch.bfloat16)
        return torch.from_numpy(arr.copy())


def torch_to_jax(t: Any) -> jax.Array:
    """torch tensor → JAX array (zero-copy via DLPack when possible)."""
    try:
        return jnp.from_dlpack(t.detach())
    except Exception:
        t = t.detach().cpu()
        if t.dtype == _torch().bfloat16:  # .numpy() rejects BFloat16
            return jnp.asarray(t.float().numpy()).astype(jnp.bfloat16)
        return jnp.asarray(t.numpy())


def training_batch_to_torch(device_batch: dict) -> dict:
    """Convert a prepared device batch's leaves to torch tensors, preserving
    the {dense, labels, emb} structure (the reference's
    ``PersiaTrainingBatch``→torch handoff, ctx.py:75-199)."""
    conv = jax_to_torch
    out = {
        "dense": [conv(x) for x in device_batch["dense"]],
        "labels": [conv(x) for x in device_batch["labels"]],
        "emb": [],
    }
    for e in device_batch["emb"]:
        out["emb"].append({k: conv(v) for k, v in e.items()})
    return out

"""Kubernetes deployment layer.

Parity target: the reference's k8s operator crate (`k8s/src/crd.rs:42-64`
`PersiaJob` CRD; per-replica Pod generation with `REPLICA_INDEX`/
`REPLICA_SIZE` envs `k8s/src/crd.rs:67-172`; metrics-gateway Service
`k8s/src/crd.rs:100-169`; label selector `persia_job={name}` teardown
`k8s/src/lib.rs`; CRD dump `k8s/src/bin/gencrd.rs`).

TPU-first differences:

- The trainer role requests `google.com/tpu` resources with GKE TPU node
  selectors instead of `nvidia.com/gpu`, and gets JAX multi-host coordinator
  envs (`JAX_COORDINATOR_ADDRESS` / process count / id) instead of
  `torch.distributed` master discovery.
- The control plane is this framework's coordinator service (a Pod + Service
  here) rather than a NATS deployment.
- Manifests are generated as plain dicts → YAML; `apply`/`delete` shell out
  to kubectl. A `PersiaTpuJob` CRD + `job_from_custom_resource` keep the
  operator pattern available: any controller can reconcile the CR by calling
  ``generate_manifests``.
"""

from __future__ import annotations

import copy
import subprocess
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from persia_tpu.utils import dump_yaml_str, load_yaml_str

GROUP = "persia-tpu.dev"
VERSION = "v1"
PLURAL = "persiatpujobs"
KIND = "PersiaTpuJob"
JOB_LABEL = "persia-tpu-job"
ROLE_LABEL = "persia-tpu-role"

COORDINATOR_PORT = 7799
SERVICE_PORT = 8888
METRICS_PORT = 9091


@dataclass
class RoleSpec:
    """One process role (ref: PersiaJobSpec sub-specs, k8s/src/crd.rs:52-64)."""

    replicas: int = 1
    resources: Dict[str, Any] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    args: List[str] = field(default_factory=list)


@dataclass
class TpuSpec:
    """GKE TPU slice selection for the trainer role."""

    accelerator: str = "tpu-v5-lite-podslice"
    topology: str = "2x4"
    chips_per_host: int = 4
    num_hosts: int = 1


@dataclass
class JobSpec:
    name: str
    image: str
    parameter_server: RoleSpec = field(default_factory=RoleSpec)
    embedding_worker: RoleSpec = field(default_factory=RoleSpec)
    trainer: RoleSpec = field(default_factory=RoleSpec)
    data_loader: RoleSpec = field(default_factory=lambda: RoleSpec(replicas=0))
    tpu: TpuSpec = field(default_factory=TpuSpec)
    env: Dict[str, str] = field(default_factory=dict)
    volumes: List[Dict[str, Any]] = field(default_factory=list)
    volume_mounts: List[Dict[str, Any]] = field(default_factory=list)
    enable_metrics: bool = False
    global_config: Optional[str] = None
    embedding_config: Optional[str] = None
    namespace: str = "default"


def _svc_name(job: str, role: str) -> str:
    return f"{job}-{role}"


def coordinator_addr(spec: JobSpec) -> str:
    return f"{_svc_name(spec.name, 'coordinator')}.{spec.namespace}.svc:{COORDINATOR_PORT}"


def _base_env(spec: JobSpec, role: str, index: int, size: int) -> List[Dict[str, str]]:
    env = {
        "REPLICA_INDEX": str(index),
        "REPLICA_SIZE": str(size),
        "PERSIA_COORDINATOR_ADDR": coordinator_addr(spec),
        "LOG_LEVEL": "info",
    }
    if spec.global_config:
        env["PERSIA_GLOBAL_CONFIG"] = spec.global_config
    if spec.embedding_config:
        env["PERSIA_EMBEDDING_CONFIG"] = spec.embedding_config
    if spec.enable_metrics:
        env["PERSIA_METRICS_GATEWAY_ADDR"] = (
            f"{_svc_name(spec.name, 'metrics-gateway')}.{spec.namespace}.svc:{METRICS_PORT}"
        )
    env.update(spec.env)
    role_spec = getattr(spec, role.replace("-", "_"), None)
    if isinstance(role_spec, RoleSpec):
        env.update(role_spec.env)
    return [{"name": k, "value": v} for k, v in sorted(env.items())]


def _pod(
    spec: JobSpec,
    role: str,
    index: int,
    size: int,
    command: List[str],
    resources: Dict[str, Any],
    extra_env: Optional[List[Dict[str, str]]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    host_network: bool = False,
) -> Dict[str, Any]:
    name = f"{spec.name}-{role}-{index}"
    container = {
        "name": role,
        "image": spec.image,
        "command": command,
        "env": _base_env(spec, role, index, size) + (extra_env or []),
        "ports": [{"containerPort": SERVICE_PORT}],
    }
    if resources:
        container["resources"] = resources
    if spec.volume_mounts:
        container["volumeMounts"] = copy.deepcopy(spec.volume_mounts)
    pod_spec: Dict[str, Any] = {
        "restartPolicy": "OnFailure",
        "containers": [container],
    }
    if spec.volumes:
        pod_spec["volumes"] = copy.deepcopy(spec.volumes)
    if node_selector:
        pod_spec["nodeSelector"] = node_selector
    if host_network:
        pod_spec["hostNetwork"] = True
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": spec.namespace,
            "labels": {JOB_LABEL: spec.name, ROLE_LABEL: role,
                       "replica-index": str(index)},
        },
        "spec": pod_spec,
    }


def _service(spec: JobSpec, role: str, port: int, target_port: int) -> Dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": _svc_name(spec.name, role),
            "namespace": spec.namespace,
            "labels": {JOB_LABEL: spec.name},
        },
        "spec": {
            "clusterIP": "None",  # headless: per-pod DNS for replica discovery
            "selector": {JOB_LABEL: spec.name, ROLE_LABEL: role},
            "ports": [{"port": port, "targetPort": target_port}],
        },
    }


def generate_manifests(spec: JobSpec) -> List[Dict[str, Any]]:
    """All k8s objects for one job (ref: pod-per-replica generation,
    `k8s/src/crd.rs:67-172`)."""
    # `python -m` so pods work whether or not the console script is installed
    launcher = ["python", "-m", "persia_tpu.launcher"]
    out: List[Dict[str, Any]] = []

    out.append(_pod(spec, "coordinator", 0, 1,
                    launcher + ["coordinator", "--port", str(COORDINATOR_PORT)], {}))
    out.append(_service(spec, "coordinator", COORDINATOR_PORT, COORDINATOR_PORT))

    ps = spec.parameter_server
    for i in range(ps.replicas):
        out.append(_pod(
            spec, "parameter-server", i, ps.replicas,
            launcher + ["embedding-parameter-server", "--port", str(SERVICE_PORT),
                        "--replica-index", str(i), "--replica-size", str(ps.replicas)]
            + ps.args,
            ps.resources,
        ))
    out.append(_service(spec, "parameter-server", SERVICE_PORT, SERVICE_PORT))

    ew = spec.embedding_worker
    for i in range(ew.replicas):
        out.append(_pod(
            spec, "embedding-worker", i, ew.replicas,
            launcher + ["embedding-worker", "--port", str(SERVICE_PORT),
                        "--replica-index", str(i), "--replica-size", str(ew.replicas),
                        "--num-parameter-servers", str(ps.replicas)]
            + ew.args,
            ew.resources,
        ))
    out.append(_service(spec, "embedding-worker", SERVICE_PORT, SERVICE_PORT))

    dl = spec.data_loader
    for i in range(dl.replicas):
        out.append(_pod(
            spec, "data-loader", i, dl.replicas,
            launcher + ["data-loader", "--replica-index", str(i),
                        "--replica-size", str(dl.replicas)] + dl.args,
            dl.resources,
        ))

    tr = spec.trainer
    n_hosts = max(spec.tpu.num_hosts, 1)
    for i in range(tr.replicas):
        for host in range(n_hosts):
            proc_id = i * n_hosts + host
            jax_env = [
                {"name": "JAX_COORDINATOR_ADDRESS",
                 "value": f"{spec.name}-trainer-0-host-0.{_svc_name(spec.name, 'trainer')}"
                          f".{spec.namespace}.svc:8476"},
                {"name": "JAX_NUM_PROCESSES", "value": str(tr.replicas * n_hosts)},
                {"name": "JAX_PROCESS_ID", "value": str(proc_id)},
            ]
            resources = dict(tr.resources or {})
            resources.setdefault("limits", {})
            resources["limits"] = {**resources["limits"],
                                   "google.com/tpu": spec.tpu.chips_per_host}
            total = tr.replicas * n_hosts
            pod = _pod(
                spec, "trainer", proc_id, total,
                launcher + ["nn-worker"] + tr.args
                + ["--nnodes", str(total), "--node-rank", str(proc_id)],
                resources,
                extra_env=jax_env,
                node_selector={
                    "cloud.google.com/gke-tpu-accelerator": spec.tpu.accelerator,
                    "cloud.google.com/gke-tpu-topology": spec.tpu.topology,
                },
            )
            pod["metadata"]["name"] = f"{spec.name}-trainer-{i}-host-{host}"
            pod["metadata"]["labels"]["trainer-host"] = str(host)
            pod["spec"]["subdomain"] = _svc_name(spec.name, "trainer")
            pod["spec"]["hostname"] = f"{spec.name}-trainer-{i}-host-{host}"
            out.append(pod)
    out.append(_service(spec, "trainer", 8476, 8476))

    if spec.enable_metrics:
        out.append({
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {
                "name": _svc_name(spec.name, "metrics-gateway"),
                "namespace": spec.namespace,
                "labels": {JOB_LABEL: spec.name},
            },
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {JOB_LABEL: spec.name,
                                             ROLE_LABEL: "metrics-gateway"}},
                "template": {
                    "metadata": {"labels": {JOB_LABEL: spec.name,
                                            ROLE_LABEL: "metrics-gateway"}},
                    "spec": {"containers": [{
                        "name": "pushgateway",
                        "image": "prom/pushgateway:v1.6.2",
                        "ports": [{"containerPort": METRICS_PORT}],
                    }]},
                },
            },
        })
        out.append(_service(spec, "metrics-gateway", METRICS_PORT, METRICS_PORT))
    return out


def generate_crd() -> Dict[str, Any]:
    """The PersiaTpuJob CRD (ref: `k8s/src/bin/gencrd.rs`)."""
    role_props = {
        "replicas": {"type": "integer", "minimum": 0},
        "resources": {"type": "object", "x-kubernetes-preserve-unknown-fields": True},
        "env": {"type": "object", "additionalProperties": {"type": "string"}},
        "args": {"type": "array", "items": {"type": "string"}},
    }
    role_schema = {"type": "object", "properties": role_props}
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{PLURAL}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {"kind": KIND, "plural": PLURAL, "singular": "persiatpujob",
                      "shortNames": ["ptj"]},
            "scope": "Namespaced",
            "versions": [{
                "name": VERSION,
                "served": True,
                "storage": True,
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "properties": {"spec": {
                        "type": "object",
                        "required": ["image"],
                        "properties": {
                            "image": {"type": "string"},
                            "parameterServer": role_schema,
                            "embeddingWorker": role_schema,
                            "trainer": role_schema,
                            "dataLoader": role_schema,
                            "tpu": {"type": "object", "properties": {
                                "accelerator": {"type": "string"},
                                "topology": {"type": "string"},
                                "chipsPerHost": {"type": "integer"},
                                "numHosts": {"type": "integer"},
                            }},
                            "env": {"type": "object",
                                    "additionalProperties": {"type": "string"}},
                            "volumes": {"type": "array",
                                        "x-kubernetes-preserve-unknown-fields": True,
                                        "items": {"type": "object",
                                                  "x-kubernetes-preserve-unknown-fields": True}},
                            "volumeMounts": {"type": "array",
                                             "x-kubernetes-preserve-unknown-fields": True,
                                             "items": {"type": "object",
                                                       "x-kubernetes-preserve-unknown-fields": True}},
                            "enableMetrics": {"type": "boolean"},
                            "globalConfig": {"type": "string"},
                            "embeddingConfig": {"type": "string"},
                        },
                    }},
                }},
            }],
        },
    }


def _role_from_cr(d: Optional[Dict[str, Any]], default_replicas: int = 1) -> RoleSpec:
    d = d or {}
    replicas = d.get("replicas")
    if replicas is None:
        replicas = default_replicas
    return RoleSpec(
        replicas=int(replicas),
        resources=d.get("resources") or {},
        env={k: str(v) for k, v in (d.get("env") or {}).items()},
        args=[str(a) for a in (d.get("args") or [])],
    )


def job_from_custom_resource(cr: Dict[str, Any]) -> JobSpec:
    """PersiaTpuJob custom resource dict → JobSpec (operator reconcile hook)."""
    if cr.get("kind") != KIND:
        raise ValueError(f"expected kind {KIND}, got {cr.get('kind')!r}")
    meta, s = cr.get("metadata") or {}, cr.get("spec") or {}
    if "name" not in meta:
        raise ValueError("PersiaTpuJob metadata.name is required")
    if "image" not in s:
        raise ValueError("PersiaTpuJob spec.image is required")
    tpu = s.get("tpu") or {}
    return JobSpec(
        name=meta["name"],
        namespace=meta.get("namespace", "default"),
        image=s["image"],
        parameter_server=_role_from_cr(s.get("parameterServer")),
        embedding_worker=_role_from_cr(s.get("embeddingWorker")),
        trainer=_role_from_cr(s.get("trainer")),
        data_loader=_role_from_cr(s.get("dataLoader"), default_replicas=0),
        tpu=TpuSpec(
            accelerator=tpu.get("accelerator", TpuSpec.accelerator),
            topology=tpu.get("topology", TpuSpec.topology),
            chips_per_host=int(tpu.get("chipsPerHost", TpuSpec.chips_per_host)),
            num_hosts=int(tpu.get("numHosts", TpuSpec.num_hosts)),
        ),
        env={k: str(v) for k, v in (s.get("env") or {}).items()},
        volumes=s.get("volumes") or [],
        volume_mounts=s.get("volumeMounts") or [],
        enable_metrics=bool(s.get("enableMetrics", False)),
        global_config=s.get("globalConfig"),
        embedding_config=s.get("embeddingConfig"),
    )


def manifests_yaml(spec: JobSpec) -> str:
    return "\n---\n".join(dump_yaml_str(m) for m in generate_manifests(spec))


def _kubectl(args: List[str], stdin: Optional[str] = None) -> int:
    proc = subprocess.run(["kubectl"] + args, input=stdin, text=True)
    return proc.returncode


def apply(spec: JobSpec) -> int:
    """kubectl apply all manifests (ref: deploy by label,
    `k8s/src/lib.rs`)."""
    return _kubectl(["apply", "-f", "-"], stdin=manifests_yaml(spec))


def delete(name: str, namespace: str = "default") -> int:
    """Teardown by job label selector (ref: `k8s/src/lib.rs` delete path)."""
    rc = _kubectl(["delete", "pod,service,deployment", "-n", namespace,
                   "-l", f"{JOB_LABEL}={name}"])
    return rc


def load_job_yaml(text: str) -> JobSpec:
    """Parse either a PersiaTpuJob CR or a bare spec mapping."""
    d = load_yaml_str(text)
    if "kind" in d:
        return job_from_custom_resource(d)
    if "name" not in d:
        raise ValueError("job yaml needs a top-level 'name' (or be a PersiaTpuJob CR)")
    meta = {"name": d.pop("name"), "namespace": d.pop("namespace", "default")}
    return job_from_custom_resource({"kind": KIND, "metadata": meta, "spec": d})

"""Misc utilities (ref: persia/utils.py)."""

from __future__ import annotations

import random
import socket
import subprocess
from typing import Any, Dict, List

import numpy as np
import yaml


def setup_seed(seed: int) -> None:
    """Seed every RNG the framework touches (ref: persia/utils.py:13-32).

    JAX is functional: pass explicit ``jax.random.PRNGKey(seed)`` into model
    init; this seeds the host-side numpy/python RNGs used by data generation
    and admit-probability sampling.
    """
    random.seed(seed)
    np.random.seed(seed)


def round_up_pow2(n: int, floor: int = 8) -> int:
    """Smallest power of two >= n (>= floor) — the shared shape-bucketing
    primitive (static jit shapes from dynamic counts)."""
    p = floor
    while p < n:
        p <<= 1
    return p


def load_yaml(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return yaml.safe_load(f) or {}


def dump_yaml(content: Dict[str, Any], path: str) -> None:
    with open(path, "w") as f:
        yaml.safe_dump(content, f)


def load_yaml_str(text: str) -> Dict[str, Any]:
    return yaml.safe_load(text) or {}


def dump_yaml_str(content: Dict[str, Any]) -> str:
    return yaml.safe_dump(content, sort_keys=False)


def run_command(cmd: List[str], **kwargs) -> None:
    subprocess.check_call(cmd, **kwargs)


def find_free_port() -> int:
    """(ref: persia/utils.py:83-91)"""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("", 0))
        return s.getsockname()[1]

"""Metrics subsystem: Prometheus-compatible registry with push and pull.

Parity target: ``persia-metrics`` (`/root/reference/rust/persia-metrics/src/lib.rs`):
singleton ``PersiaMetricsManager`` with ``create_{counter,gauge,histogram}(_vec)``,
const labels ``{instance, ip_addr}``, and a scheduled push to a Prometheus
pushgateway (`lib.rs:169-201`).

TPU-first differences: pure stdlib (no prometheus client dep). Besides the
reference's push model we also expose a pull endpoint (``serve_http``) because
TPU-host jobs usually sit behind a scrape config rather than a gateway.
Everything is thread-safe; the hot-path cost of a counter bump is one dict
lookup + float add under a small lock.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

_DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Metric:
    def __init__(self, name: str, help_: str, const_labels: Dict[str, str]):
        self.name = name
        self.help = help_
        self.const_labels = const_labels
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, const_labels):
        super().__init__(name, help_, const_labels)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def get(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = list(self._values.items()) or [((), 0.0)]
        for key, v in items:
            labels = dict(self.const_labels)
            labels.update(dict(key))
            out.append(f"{self.name}{_fmt_labels(labels)} {v}")
        return out


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_, const_labels):
        super().__init__(name, help_, const_labels)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = float(value)

    def add(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def get(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = list(self._values.items()) or [((), 0.0)]
        for key, v in items:
            labels = dict(self.const_labels)
            labels.update(dict(key))
            out.append(f"{self.name}{_fmt_labels(labels)} {v}")
        return out


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, const_labels, buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help_, const_labels)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def time(self, **labels):
        """Context manager observing elapsed seconds."""
        return _Timer(self, labels)

    def get_count(self, **labels) -> int:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._totals.get(key, 0)

    def get_sum(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._sums.get(key, 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            keys = list(self._counts.keys())
            for key in keys:
                counts, total, s = self._counts[key], self._totals[key], self._sums[key]
                base = dict(self.const_labels)
                base.update(dict(key))
                for b, c in zip(self.buckets, counts):
                    lbl = dict(base, le=repr(float(b)))
                    out.append(f"{self.name}_bucket{_fmt_labels(lbl)} {c}")
                lbl = dict(base, le="+Inf")
                out.append(f"{self.name}_bucket{_fmt_labels(lbl)} {total}")
                out.append(f"{self.name}_sum{_fmt_labels(base)} {s}")
                out.append(f"{self.name}_count{_fmt_labels(base)} {total}")
        return out


class _Timer:
    def __init__(self, hist: Histogram, labels: Dict[str, str]):
        self._hist = hist
        self._labels = labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0, **self._labels)
        return False


def _local_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


class MetricsRegistry:
    """Per-process metric registry (ref: PersiaMetricsManager singleton,
    persia-metrics/src/lib.rs:108-167). ``job``/``instance`` become const
    labels on every series."""

    def __init__(self, job: str = "persia_tpu", instance: Optional[str] = None):
        self.job = job
        self.const_labels = {
            "instance": instance or f"rep_{os.environ.get('REPLICA_INDEX', '0')}",
            "ip_addr": _local_ip(),
        }
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._push_thread: Optional[threading.Thread] = None
        self._push_stop = threading.Event()
        self._server: Optional[ThreadingHTTPServer] = None

    def _get_or_create(self, cls, name: str, help_: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_, self.const_labels, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help_)

    def histogram(self, name: str, help_: str = "", buckets: Sequence[float] = _DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help_, buckets=buckets)

    def render(self) -> str:
        """Prometheus text exposition format."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def snapshot(self, prefix: str = "") -> Dict[str, Dict[str, float]]:
        """Programmatic counter/gauge values keyed by metric name then by a
        ``k=v,...`` label string (empty for unlabeled series). Chaos/bench
        runs embed this in their artifacts so resilience behavior (breaker
        trips, degraded counts, injected faults) is auditable from the
        JSON alone — no scraping, no reaching into private fields."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if prefix and not m.name.startswith(prefix):
                continue
            values = getattr(m, "_values", None)
            if values is None:  # histograms: expose count + sum
                series = {
                    "count": dict(getattr(m, "_totals", {})),
                    "sum": dict(getattr(m, "_sums", {})),
                }
                for suffix, vals in series.items():
                    for key, v in vals.items():
                        lbl = ",".join(f"{k}={val}" for k, val in key)
                        out.setdefault(f"{m.name}_{suffix}", {})[lbl] = float(v)
                continue
            with m._lock:
                items = list(values.items())
            for key, v in items:
                lbl = ",".join(f"{k}={val}" for k, val in key)
                out.setdefault(m.name, {})[lbl] = float(v)
        return out

    # ------------------------------------------------------------------ push

    def start_push(self, gateway_addr: Optional[str] = None, interval_sec: float = 10.0) -> bool:
        """Push to a Prometheus pushgateway every ``interval_sec``
        (ref: lib.rs:169-201 spawns the same loop against
        ``PERSIA_METRICS_GATEWAY_ADDR``). Returns False if no gateway is
        configured."""
        addr = gateway_addr or os.environ.get("PERSIA_TPU_METRICS_GATEWAY") or os.environ.get(
            "PERSIA_METRICS_GATEWAY_ADDR"
        )
        if not addr or self._push_thread is not None:
            return False
        host, _, port = addr.replace("http://", "").partition(":")

        def loop():
            import http.client

            while not self._push_stop.wait(interval_sec):
                try:
                    conn = http.client.HTTPConnection(host, int(port or 9091), timeout=5)
                    path = f"/metrics/job/{self.job}/instance/{self.const_labels['instance']}"
                    conn.request("PUT", path, body=self.render().encode(),
                                 headers={"Content-Type": "text/plain"})
                    conn.getresponse().read()
                    conn.close()
                except OSError:
                    pass  # gateway transiently unreachable; next tick retries

        self._push_thread = threading.Thread(target=loop, daemon=True, name="metrics-push")
        self._push_thread.start()
        return True

    def stop_push(self) -> None:
        if self._push_thread is not None:
            self._push_stop.set()
            self._push_thread.join(timeout=2)
            self._push_thread = None
            self._push_stop.clear()

    # ------------------------------------------------------------------ pull

    def serve_http(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Expose the role's telemetry endpoints; returns the bound port.

        - ``/metrics`` — Prometheus text exposition (scrape);
        - ``/spans`` — the tracing span ring as JSON plus a ``now_us`` clock
          sample for the fleet collector's offset handshake; ``?drain=1``
          drains the ring so repeated scrapes never double-count;
        - ``/flight`` — the flight-recorder event ring as JSON.

        Binds loopback by default; a fleet deployment that actually wants a
        cross-host scrape passes ``host="0.0.0.0"`` explicitly."""
        registry = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                path, _, query = self.path.partition("?")
                path = path.rstrip("/")
                if path in ("", "/metrics"):
                    body = registry.render().encode()
                    ctype = "text/plain; version=0.0.4"
                elif path == "/spans":
                    from persia_tpu import tracing
                    import json as _json

                    spans = (tracing.spans_drain() if "drain=1" in query
                             else tracing.spans_snapshot())
                    body = _json.dumps({
                        "now_us": time.time() * 1e6,
                        "pid": os.getpid(),
                        "role": tracing.get_role(),
                        "spans": spans,
                    }).encode()
                    ctype = "application/json"
                elif path == "/flight":
                    from persia_tpu import tracing
                    import json as _json

                    body = _json.dumps({
                        "now_us": time.time() * 1e6,
                        "pid": os.getpid(),
                        "role": tracing.get_role(),
                        "events": tracing.flight_snapshot(),
                    }).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True, name="metrics-http").start()
        return self._server.server_address[1]

    def shutdown(self) -> None:
        self.stop_push()
        if self._server is not None:
            self._server.shutdown()
            self._server = None


_REGISTRY: Optional[MetricsRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def get_metrics() -> MetricsRegistry:
    """Process-wide default registry (lazy)."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = MetricsRegistry()
        return _REGISTRY

"""Deterministic synthetic CTR dataset — the e2e oracle's data source.

The reference pins an exact AUC on the (downloaded) adult-income dataset as
its CI correctness oracle (`examples/src/adult-income/train.py:23-24,146-150`).
This environment has no network, so we generate an equivalent task: dense
features + categorical id slots with hidden ground-truth weights, labels from
a noisy logistic model. Fully seeded → every run sees identical data, so the
deterministic-mode AUC is exactly reproducible.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from persia_tpu.data import IDTypeFeature, Label, NonIDTypeFeature, PersiaBatch


def roc_auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Rank-based AUC (Mann-Whitney U), ties handled by average rank."""
    labels = np.asarray(labels).reshape(-1)
    scores = np.asarray(scores).reshape(-1)
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(scores) + 1)
    # average ranks over ties
    sorted_scores = scores[order]
    i = 0
    while i < len(sorted_scores):
        j = i
        while j + 1 < len(sorted_scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            ranks[order[i : j + 1]] = (i + 1 + j + 1) / 2.0
        i = j + 1
    n_pos = labels.sum()
    n_neg = len(labels) - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.5
    return float((ranks[labels > 0.5].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


class SyntheticClickDataset:
    """Adult-income-shaped task: ``num_dense`` dense features + categorical
    slots (single-id) + optional one sequence slot, labels from a hidden
    logistic model with noise."""

    def __init__(
        self,
        num_samples: int = 8192,
        num_dense: int = 5,
        vocab_sizes: Sequence[int] = (64, 32, 16, 100, 50, 8, 4, 300),
        seq_slot: Optional[Tuple[str, int, int]] = None,  # (name, vocab, max_len)
        noise: float = 1.0,
        seed: int = 42,
        task_seed: int = 1234,
    ):
        """``task_seed`` fixes the hidden ground-truth weights (shared between
        a train and a test split so generalization is measurable); ``seed``
        drives the sampling of features/labels."""
        task_rng = np.random.default_rng(task_seed)
        rng = np.random.default_rng(seed)
        self.num_dense = num_dense
        self.vocab_sizes = list(vocab_sizes)
        self.slot_names = [f"cat_{i}" for i in range(len(vocab_sizes))]
        self.seq_slot = seq_slot

        w_dense = task_rng.normal(size=num_dense)
        w_cats = [task_rng.normal(size=v) * 1.5 for v in self.vocab_sizes]
        w_seq = (
            task_rng.normal(size=seq_slot[1]) * 0.8 if seq_slot is not None else None
        )

        self.dense = rng.normal(size=(num_samples, num_dense)).astype(np.float32)
        logit = self.dense @ w_dense

        self.cat_ids = []
        for v, w_cat in zip(self.vocab_sizes, w_cats):
            ids = rng.integers(0, v, size=num_samples)
            logit = logit + w_cat[ids]
            self.cat_ids.append(ids.astype(np.uint64))

        if seq_slot is not None:
            _, vocab, max_len = seq_slot
            self.seq_ids: List[np.ndarray] = []
            for _ in range(num_samples):
                ln = rng.integers(0, max_len + 1)
                ids = rng.integers(0, vocab, size=ln)
                logit_add = w_seq[ids].sum() / max(np.sqrt(max(ln, 1)), 1.0)
                self.seq_ids.append(ids.astype(np.uint64))
                logit[len(self.seq_ids) - 1] += logit_add

        p = 1.0 / (1.0 + np.exp(-(logit / max(noise, 1e-6))))
        self.labels = (rng.random(num_samples) < p).astype(np.float32).reshape(-1, 1)
        self.num_samples = num_samples

    def batches(
        self, batch_size: int, requires_grad: bool = True, start_batch_id: int = 0
    ) -> Iterator[PersiaBatch]:
        bid = start_batch_id
        for lo in range(0, self.num_samples, batch_size):
            hi = min(lo + batch_size, self.num_samples)
            id_feats = [
                IDTypeFeature(
                    name, [self.cat_ids[k][i : i + 1] for i in range(lo, hi)]
                )
                for k, name in enumerate(self.slot_names)
            ]
            if self.seq_slot is not None:
                id_feats.append(
                    IDTypeFeature(self.seq_slot[0], self.seq_ids[lo:hi])
                )
            yield PersiaBatch(
                id_feats,
                non_id_type_features=[NonIDTypeFeature(self.dense[lo:hi])],
                labels=[Label(self.labels[lo:hi])],
                requires_grad=requires_grad,
                batch_id=bid,
            )
            bid += 1

"""Test/bench utilities: deterministic synthetic datasets + metrics."""

from persia_tpu.testing.synthetic import SyntheticClickDataset, roc_auc  # noqa: F401
from persia_tpu.testing.datasets import (  # noqa: F401
    AvazuSynthetic,
    CriteoSynthetic,
    Synthetic100T,
    TaobaoSynthetic,
    CRITEO_KAGGLE_VOCABS,
    CRITEO_1TB_VOCABS,
    AVAZU_VOCABS,
)

"""Test/bench utilities: deterministic synthetic datasets + metrics."""

from persia_tpu.testing.synthetic import SyntheticClickDataset, roc_auc  # noqa: F401

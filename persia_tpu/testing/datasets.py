"""Streaming synthetic datasets shaped like the BASELINE.json benchmark
configs (Criteo-Kaggle / Criteo-1TB DLRM, Avazu DeepFM/DCN-v2, Taobao DIN).

This environment has no network access, so real datasets cannot be
downloaded; these generators reproduce each dataset's *schema* (field count,
cardinalities, dense distributions, sequence structure) with a hidden,
seeded ground-truth model so AUC is learnable and exactly reproducible —
the same role the adult-income download plays for the reference's CI oracle
(`examples/src/adult-income/data.py`, `train.py:23-24`).

Unlike ``SyntheticClickDataset`` (which materializes every sample), these
stream: each batch is generated on demand from ``(seed, batch_index)``, so
a Criteo-1TB-scale epoch needs O(batch) memory. Per-id ground-truth weights
come from a splitmix64 hash of the sign (not a materialized table), so slots
with hundreds of millions of ids cost nothing to "store".
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from persia_tpu.data import (
    IDTypeFeature,
    IDTypeFeatureWithSingleID,
    Label,
    NonIDTypeFeature,
    PersiaBatch,
)
from persia_tpu.embedding.hashing import splitmix64



def hash_to_unit(ids: np.ndarray, salt: int) -> np.ndarray:
    """Deterministic per-id weight in [-1, 1) — a 2^64-entry virtual weight
    table that never gets materialized."""
    with np.errstate(over="ignore"):
        h = splitmix64(np.asarray(ids, np.uint64) ^ splitmix64(np.full(1, salt, np.uint64))[0])
    return (h >> np.uint64(11)).astype(np.float64) * (2.0 / (1 << 53)) - 1.0


def hash_to_vector(ids: np.ndarray, salt: int, dim: int) -> np.ndarray:
    """Deterministic per-id unit-ish vector (dim columns, independent salts)."""
    cols = [hash_to_unit(ids, salt * 1000003 + j) for j in range(dim)]
    v = np.stack(cols, axis=-1)
    return v / np.sqrt(dim)


class _StreamingBase:
    """Shared batching loop: subclasses implement ``_make(rng, n, batch_id)``
    returning a PersiaBatch-kwargs dict."""

    num_samples: int
    seed: int

    def batches(
        self, batch_size: int, requires_grad: bool = True, start_batch_id: int = 0
    ) -> Iterator[PersiaBatch]:
        bid = start_batch_id
        produced = 0
        while produced < self.num_samples:
            n = min(batch_size, self.num_samples - produced)
            rng = np.random.default_rng((self.seed, bid))
            kw = self._make(rng, n, bid)
            yield PersiaBatch(requires_grad=requires_grad, batch_id=bid, **kw)
            produced += n
            bid += 1

    def _make(self, rng, n, batch_id):  # pragma: no cover - abstract
        raise NotImplementedError


# Approximate public cardinalities of the 26 Criteo Kaggle categorical
# fields (exact values vary by preprocessing; the *shape* — a few huge
# slots, many small ones — is what matters for the benchmark).
CRITEO_KAGGLE_VOCABS: Sequence[int] = (
    1461, 584, 10_131_227, 2_202_608, 306, 24, 12_518, 634, 4, 93_146,
    5_684, 8_351_593, 3_195, 28, 14_993, 5_461_306, 11, 5_653, 2_174, 5,
    7_046_547, 19, 16, 286_181, 106, 142_572,
)

# Criteo-1TB (Terabyte) cardinalities are ~10-40x larger on the big slots;
# approximate shape used by public DLRM configs.
CRITEO_1TB_VOCABS: Sequence[int] = (
    45_833_188, 36_746, 17_245, 7_413, 20_243, 4, 7_114, 1_441, 63,
    29_275_261, 1_572_176, 345_138, 11, 2_209, 11_267, 128, 5, 975, 15,
    48_937_457, 17_246_239, 40_094_537, 452_104, 12_606, 105, 36,
)

CRITEO_NUM_DENSE = 13


class CriteoSynthetic(_StreamingBase):
    """Criteo-shaped click log: 13 integer-ish dense features (lognormal,
    log1p-normalized as in standard Criteo preprocessing) + 26 single-id
    categorical slots. Positive rate ~25% like the real dataset."""

    def __init__(
        self,
        num_samples: int = 65_536,
        vocab_sizes: Sequence[int] = CRITEO_KAGGLE_VOCABS,
        noise: float = 1.0,
        seed: int = 42,
        task_seed: int = 7,
    ):
        self.num_samples = num_samples
        self.vocab_sizes = list(vocab_sizes)
        self.slot_names = [f"cat_{i}" for i in range(len(vocab_sizes))]
        self.noise = noise
        self.seed = seed
        self.task_seed = task_seed
        task_rng = np.random.default_rng(task_seed)
        self._w_dense = task_rng.normal(size=CRITEO_NUM_DENSE) * 0.6
        self._bias = -1.4  # pushes base rate toward Criteo's ~25% positives

    def _make(self, rng, n, batch_id):
        raw = rng.lognormal(mean=1.0, sigma=1.5, size=(n, CRITEO_NUM_DENSE))
        dense = np.log1p(raw).astype(np.float32)
        logit = (dense - dense.mean()) @ self._w_dense + self._bias

        id_feats = []
        for k, (name, v) in enumerate(zip(self.slot_names, self.vocab_sizes)):
            # Zipf-ish skew: real Criteo ids are heavily head-concentrated
            u = rng.random(n)
            ids = np.minimum((u ** 3 * v).astype(np.uint64), np.uint64(v - 1))
            logit = logit + 1.5 * hash_to_unit(ids, self.task_seed * 131 + k)
            id_feats.append(IDTypeFeatureWithSingleID(name, ids))

        p = 1.0 / (1.0 + np.exp(-logit / max(self.noise, 1e-6)))
        labels = (rng.random(n) < p).astype(np.float32).reshape(-1, 1)
        return dict(
            id_type_features=id_feats,
            non_id_type_features=[NonIDTypeFeature(dense)],
            labels=[Label(labels)],
        )


# Avazu: 21 categorical fields (site/app/device/banner/C14-C21...) + hour.
AVAZU_VOCABS: Sequence[int] = (
    7, 7, 4_737, 7_745, 26, 8_552, 559, 36, 2_686_408, 6_729_486, 8_251,
    5, 4, 2_626, 8, 9, 435, 4, 68, 172, 60,
)


class AvazuSynthetic(_StreamingBase):
    """Avazu-shaped CTR log: 21 single-id categorical slots + the hour
    field encoded as 2 cyclical dense features."""

    def __init__(
        self,
        num_samples: int = 65_536,
        vocab_sizes: Sequence[int] = AVAZU_VOCABS,
        noise: float = 1.0,
        seed: int = 42,
        task_seed: int = 11,
    ):
        self.num_samples = num_samples
        self.vocab_sizes = list(vocab_sizes)
        self.slot_names = [f"field_{i}" for i in range(len(vocab_sizes))]
        self.noise = noise
        self.seed = seed
        self.task_seed = task_seed
        self._bias = -1.8  # Avazu positive rate ~17%

    def _make(self, rng, n, batch_id):
        hour = rng.integers(0, 24, size=n)
        dense = np.stack(
            [np.sin(2 * np.pi * hour / 24), np.cos(2 * np.pi * hour / 24)], axis=1
        ).astype(np.float32)
        logit = np.full(n, self._bias) + 0.3 * np.sin(2 * np.pi * hour / 24)

        id_feats = []
        for k, (name, v) in enumerate(zip(self.slot_names, self.vocab_sizes)):
            u = rng.random(n)
            ids = np.minimum((u ** 2.5 * v).astype(np.uint64), np.uint64(v - 1))
            logit = logit + 1.3 * hash_to_unit(ids, self.task_seed * 131 + k)
            id_feats.append(IDTypeFeatureWithSingleID(name, ids))

        p = 1.0 / (1.0 + np.exp(-logit / max(self.noise, 1e-6)))
        labels = (rng.random(n) < p).astype(np.float32).reshape(-1, 1)
        return dict(
            id_type_features=id_feats,
            non_id_type_features=[NonIDTypeFeature(dense)],
            labels=[Label(labels)],
        )


class TaobaoSynthetic(_StreamingBase):
    """Taobao-shaped user-behavior data for DIN: a candidate item + its
    category (pooled slots) and the user's behavior history (raw sequence
    slots ``hist_item``/``hist_cate``).

    Ground truth rewards history relevance: with probability ``repeat_p``
    the candidate is drawn from the user's own history (repeat-interest
    click signal the attention unit can discover); the label's logit adds a
    max-similarity term between hashed item vectors of candidate and
    history, so attention-pooling beats mean-pooling.
    """

    def __init__(
        self,
        num_samples: int = 65_536,
        item_vocab: int = 4_162_024,  # Taobao UserBehavior item count (approx)
        cate_vocab: int = 9_439,
        max_hist: int = 50,
        repeat_p: float = 0.35,
        noise: float = 0.8,
        seed: int = 42,
        task_seed: int = 13,
    ):
        self.num_samples = num_samples
        self.item_vocab = item_vocab
        self.cate_vocab = cate_vocab
        self.max_hist = max_hist
        self.repeat_p = repeat_p
        self.noise = noise
        self.seed = seed
        self.task_seed = task_seed

    def _cate_of(self, items: np.ndarray) -> np.ndarray:
        # category is a deterministic function of the item, like a catalog
        with np.errstate(over="ignore"):
            return splitmix64(items) % np.uint64(self.cate_vocab)

    def _make(self, rng, n, batch_id):
        L = self.max_hist
        hist_len = rng.integers(1, L + 1, size=n)
        # each user has an interest anchor; history items cluster around it
        anchors = rng.integers(0, self.item_vocab, size=n, dtype=np.uint64)
        hist_items: List[np.ndarray] = []
        for i in range(n):
            jitter = rng.integers(0, 1000, size=hist_len[i], dtype=np.uint64)
            with np.errstate(over="ignore"):
                items = (anchors[i] + jitter * jitter) % np.uint64(self.item_vocab)
            hist_items.append(items)

        cand = rng.integers(0, self.item_vocab, size=n, dtype=np.uint64)
        from_hist = rng.random(n) < self.repeat_p
        for i in np.nonzero(from_hist)[0]:
            cand[i] = hist_items[i][rng.integers(0, len(hist_items[i]))]

        d = 8
        v_cand = hash_to_vector(cand, self.task_seed, d)
        sim = np.empty(n)
        for i in range(n):
            v_h = hash_to_vector(hist_items[i], self.task_seed, d)
            sim[i] = (v_h @ v_cand[i]).max()
        logit = (
            3.0 * sim
            + 2.0 * from_hist.astype(np.float64)
            + 0.8 * hash_to_unit(cand, self.task_seed * 17)
            - 1.0
        )
        p = 1.0 / (1.0 + np.exp(-logit / max(self.noise, 1e-6)))
        labels = (rng.random(n) < p).astype(np.float32).reshape(-1, 1)

        hist_cates = [self._cate_of(h) for h in hist_items]
        recency = (np.minimum(hist_len, L) / L).astype(np.float32).reshape(-1, 1)
        return dict(
            id_type_features=[
                IDTypeFeatureWithSingleID("item", cand),
                IDTypeFeatureWithSingleID("cate", self._cate_of(cand)),
                IDTypeFeature("hist_item", hist_items),
                IDTypeFeature("hist_cate", hist_cates),
            ],
            non_id_type_features=[NonIDTypeFeature(recency)],
            labels=[Label(labels)],
        )


class Synthetic100T(_StreamingBase):
    """Uniform-random u64 signs over a 2^63 key space — the access
    pattern of the reference's 100-trillion-parameter regime
    (`/root/reference/README.md:29`): effectively infinite vocabulary, LRU
    working set, every batch mostly cold ids. No labels needed beyond a
    hash rule; this feeds the capacity/throughput harness."""

    def __init__(
        self,
        num_samples: int = 1 << 20,
        num_slots: int = 8,
        ids_per_sample: int = 4,
        seed: int = 42,
    ):
        self.num_samples = num_samples
        self.num_slots = num_slots
        self.ids_per_sample = ids_per_sample
        self.seed = seed

    def _make(self, rng, n, batch_id):
        id_feats = []
        logit = np.zeros(n)
        for k in range(self.num_slots):
            flat = rng.integers(0, 1 << 63, size=n * self.ids_per_sample, dtype=np.uint64)
            per = np.split(flat, n)
            logit += hash_to_unit(flat, k).reshape(n, -1).mean(axis=1)
            id_feats.append(IDTypeFeature(f"slot_{k}", per))
        dense = rng.normal(size=(n, 4)).astype(np.float32)
        labels = (logit > 0).astype(np.float32).reshape(-1, 1)
        return dict(
            id_type_features=id_feats,
            non_id_type_features=[NonIDTypeFeature(dense)],
            labels=[Label(labels)],
        )

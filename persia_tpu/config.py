"""Configuration layer.

Parity target: the reference's two-file YAML config system
(`/root/reference/rust/persia-embedding-config/src/lib.rs:461-650`):
``global_config.yml`` (job type, server capacities, pipeline knobs) and
``embedding_config.yml`` (per-slot embedding schema + feature groups).

TPU-first differences: no OnceCell singletons — configs are plain frozen
dataclasses passed explicitly; the dense-side options (mixed precision, mesh
shape) live here too because the dense engine is JAX, not torch.
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from persia_tpu.utils import load_yaml

MAX_BATCH_SIZE = 65535  # u16 sample indices on the wire (ref: persia/embedding/data.py:14)


class JobType(enum.Enum):
    """Job type selects pipeline behavior (ref: persia-embedding-config/src/lib.rs:171-177)."""

    TRAIN = "train"
    EVAL = "eval"
    INFER = "infer"


@dataclass(frozen=True)
class HashStackConfig:
    """Multi-round hashing vocabulary compression ("hash stack").

    Each id is hashed ``hash_stack_rounds`` times into ``[round * embedding_size,
    (round+1) * embedding_size)``; the resulting rows are summed. Compresses an
    unbounded vocabulary into ``rounds * size`` rows
    (ref: embedding_worker_service/mod.rs:348-400).
    """

    hash_stack_rounds: int = 0
    embedding_size: int = 0

    @property
    def enabled(self) -> bool:
        return self.hash_stack_rounds > 0 and self.embedding_size > 0


@dataclass(frozen=True)
class SlotConfig:
    """Per-feature-slot embedding schema (ref: persia-embedding-config/src/lib.rs:528-598).

    - ``embedding_summation``: True → sum-pool ids per sample into one (dim,)
      vector; False → "raw" slot returning distinct-id rows plus an index
      layout (sequence features).
    - ``sample_fixed_size``: raw slots pad/truncate each sample's id list to
      this length on the device side.
    - ``sqrt_scaling``: scale pooled output by 1/sqrt(n_ids) (and gradients
      symmetrically).
    - ``index_prefix``: per-slot prefix OR-ed into the top bits of every sign
      so one global key space is partitioned across slots.
    """

    dim: int
    name: str = ""
    embedding_summation: bool = True
    sqrt_scaling: bool = False
    sample_fixed_size: int = 10
    hash_stack_config: HashStackConfig = field(default_factory=HashStackConfig)
    index_prefix: int = 0


@dataclass(frozen=True)
class EmbeddingConfig:
    """Embedding schema: slot map + feature groups + prefix assignment
    (ref: persia-embedding-config/src/lib.rs:528-650).

    ``feature_groups`` partition slots; each group gets a distinct index
    prefix in the top ``feature_index_prefix_bit`` bits of the u64 sign, and
    optimizers may keep per-group state (Adam group beta powers). Slots not
    mentioned in any group form singleton groups, in slot order.
    """

    slots_config: Dict[str, SlotConfig] = field(default_factory=dict)
    feature_index_prefix_bit: int = 0
    feature_groups: Dict[str, List[str]] = field(default_factory=dict)

    def __post_init__(self):
        # Fill slot names and auto-assign group index prefixes.
        slots = {}
        for name, slot in self.slots_config.items():
            if slot.name != name:
                slot = dataclasses.replace(slot, name=name)
            slots[name] = slot

        groups = dict(self.feature_groups)
        grouped: set = set()
        for members in groups.values():
            for member in members:
                if member not in slots:
                    raise ValueError(f"feature group member {member!r} not a slot")
                if member in grouped:
                    raise ValueError(
                        f"slot {member!r} appears in multiple feature groups; "
                        f"groups must partition the slots"
                    )
                grouped.add(member)
        for name in slots:
            if name not in grouped:
                if name in groups:
                    raise ValueError(
                        f"slot {name!r} collides with a feature group of the same "
                        f"name but is not a member of it"
                    )
                groups[name] = [name]

        if self.feature_index_prefix_bit > 0:
            shift = 64 - self.feature_index_prefix_bit
            if len(groups) >= (1 << self.feature_index_prefix_bit):
                raise ValueError(
                    f"{len(groups)} feature groups do not fit in "
                    f"{self.feature_index_prefix_bit} prefix bits"
                )
            for group_idx, members in enumerate(groups.values()):
                prefix = (group_idx + 1) << shift
                for member in members:
                    if slots[member].index_prefix == 0:
                        slots[member] = dataclasses.replace(slots[member], index_prefix=prefix)

        object.__setattr__(self, "slots_config", slots)
        object.__setattr__(self, "feature_groups", groups)

    @property
    def slot_names(self) -> List[str]:
        return list(self.slots_config.keys())

    def slot(self, name: str) -> SlotConfig:
        return self.slots_config[name]

    def group_of(self, slot_name: str) -> int:
        for idx, members in enumerate(self.feature_groups.values()):
            if slot_name in members:
                return idx
        raise KeyError(slot_name)


INIT_UNIFORM = "uniform"
INIT_GAMMA = "gamma"
INIT_POISSON = "poisson"
INIT_NORMAL = "normal"
INIT_INVERSE_SQRT = "inverse_sqrt"

# numeric codes shared with native/ps.cpp ps_set_init_method
INIT_KIND_CODES = {
    INIT_UNIFORM: 0,
    INIT_GAMMA: 1,
    INIT_POISSON: 2,
    INIT_NORMAL: 3,
    INIT_INVERSE_SQRT: 4,
}


@dataclass(frozen=True)
class InitializationMethod:
    """Seeded-by-sign embedding init distribution
    (ref: InitializationMethod enum, persia-embedding-config/src/lib.rs:79-98;
    seeded entry init, persia-embedding-holder/src/emb_entry.rs:28-60).

    ``p0``/``p1`` per kind: uniform → (lower, upper); gamma → (shape, scale);
    poisson → (lambda, unused); normal → (mean, stddev); inverse_sqrt ignores
    both and draws uniform in ±1/sqrt(dim)."""

    kind: str = INIT_UNIFORM
    p0: float = -0.01
    p1: float = 0.01

    def __post_init__(self):
        if self.kind not in INIT_KIND_CODES:
            raise ValueError(f"unknown initialization kind: {self.kind!r}")

    @property
    def code(self) -> int:
        return INIT_KIND_CODES[self.kind]

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "p0": self.p0, "p1": self.p1}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "InitializationMethod":
        return InitializationMethod(
            kind=d["kind"], p0=float(d.get("p0", 0.0)), p1=float(d.get("p1", 0.0))
        )


@dataclass(frozen=True)
class HyperParameters:
    """Runtime-pushed embedding hyperparameters
    (ref: persia-embedding-config/src/lib.rs:99-105, persia/embedding/__init__.py:4-26)."""

    emb_initialization: Tuple[float, float] = (-0.01, 0.01)
    admit_probability: float = 1.0
    weight_bound: float = 10.0
    # None → BoundedUniform over emb_initialization (the reference's default)
    initialization_method: Optional[InitializationMethod] = None

    def resolved_init_method(self) -> InitializationMethod:
        if self.initialization_method is not None:
            return self.initialization_method
        lo, hi = self.emb_initialization
        return InitializationMethod(INIT_UNIFORM, lo, hi)

    def to_dict(self) -> Dict[str, Any]:
        m = self.initialization_method
        return {
            "emb_initialization": list(self.emb_initialization),
            "admit_probability": self.admit_probability,
            "weight_bound": self.weight_bound,
            "initialization_method": m.to_dict() if m is not None else None,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "HyperParameters":
        m = d.get("initialization_method")
        return HyperParameters(
            emb_initialization=tuple(d["emb_initialization"]),
            admit_probability=d["admit_probability"],
            weight_bound=d["weight_bound"],
            initialization_method=InitializationMethod.from_dict(m) if m else None,
        )


@dataclass(frozen=True)
class EmbeddingWorkerConfig:
    """(ref: PersiaEmbeddingWorkerConfig, persia-embedding-config/src/lib.rs:461-526)"""

    forward_buffer_size: int = 1000
    buffered_data_expired_sec: int = 3600


@dataclass(frozen=True)
class ParameterServerConfig:
    """(ref: PersiaEmbeddingParameterServerConfig)"""

    capacity: int = 1 << 20
    num_hashmap_internal_shards: int = 16
    enable_incremental_update: bool = False
    incremental_buffer_size: int = 1_000_000
    incremental_dir: str = "/tmp/persia_tpu_inc"
    full_amount_manager_buffer_size: int = 1000


@dataclass(frozen=True)
class CommonConfig:
    job_type: JobType = JobType.TRAIN
    checkpointing_config: Dict[str, Any] = field(default_factory=dict)
    metrics_config: Dict[str, Any] = field(default_factory=dict)


@dataclass(frozen=True)
class GlobalConfig:
    """The ``global_config.yml`` equivalent
    (ref: PersiaGlobalConfig, persia-embedding-config/src/lib.rs:461-526)."""

    common: CommonConfig = field(default_factory=CommonConfig)
    embedding_worker: EmbeddingWorkerConfig = field(default_factory=EmbeddingWorkerConfig)
    parameter_server: ParameterServerConfig = field(default_factory=ParameterServerConfig)


def _slot_from_dict(name: str, d: Dict[str, Any]) -> SlotConfig:
    hs = d.get("hash_stack_config") or {}
    return SlotConfig(
        name=name,
        dim=int(d["dim"]),
        embedding_summation=bool(d.get("embedding_summation", True)),
        sqrt_scaling=bool(d.get("sqrt_scaling", False)),
        sample_fixed_size=int(d.get("sample_fixed_size", 10)),
        hash_stack_config=HashStackConfig(
            hash_stack_rounds=int(hs.get("hash_stack_rounds", 0)),
            embedding_size=int(hs.get("embedding_size", 0)),
        ),
        index_prefix=int(d.get("index_prefix", 0)),
    )


def load_embedding_config(path: str) -> EmbeddingConfig:
    """Parse an ``embedding_config.yml`` (same schema family as the reference's
    `parse_embedding_config`, persia-embedding-config/src/lib.rs:600-650)."""
    raw = load_yaml(path)
    slots = {
        name: _slot_from_dict(name, d) for name, d in (raw.get("slots_config") or {}).items()
    }
    return EmbeddingConfig(
        slots_config=slots,
        feature_index_prefix_bit=int(raw.get("feature_index_prefix_bit", 0)),
        feature_groups={k: list(v) for k, v in (raw.get("feature_groups") or {}).items()},
    )


def load_global_config(path: str) -> GlobalConfig:
    raw = load_yaml(path)
    common = raw.get("common") or {}
    worker = raw.get("embedding_worker") or {}
    ps = raw.get("embedding_parameter_server") or raw.get("parameter_server") or {}
    return GlobalConfig(
        common=CommonConfig(
            job_type=JobType(str(common.get("job_type", "train")).lower()),
            checkpointing_config=common.get("checkpointing_config") or {},
            metrics_config=common.get("metrics_config") or {},
        ),
        embedding_worker=EmbeddingWorkerConfig(
            forward_buffer_size=int(worker.get("forward_buffer_size", 1000)),
            buffered_data_expired_sec=int(worker.get("buffered_data_expired_sec", 3600)),
        ),
        parameter_server=ParameterServerConfig(
            capacity=int(ps.get("capacity", 1 << 20)),
            num_hashmap_internal_shards=int(ps.get("num_hashmap_internal_shards", 16)),
            enable_incremental_update=bool(ps.get("enable_incremental_update", False)),
            incremental_buffer_size=int(ps.get("incremental_buffer_size", 1_000_000)),
            incremental_dir=str(ps.get("incremental_dir", "/tmp/persia_tpu_inc")),
            full_amount_manager_buffer_size=int(
                ps.get("full_amount_manager_buffer_size", 1000)
            ),
        ),
    )



"""Cluster e2e harness: apply a PersiaTpuJob, wait for trainers, tear down.

Parity target: `k8s/src/bin/e2e.rs` (the reference's CI system test — builds
a PersiaJob with 2 parameter servers / 2 embedding workers / 2 NN workers /
1 data loader, applies it to a live cluster, polls the nn-worker pods until
every one reports ``Succeeded`` within a 600 s deadline, then tears the job
down and verifies nothing labeled is left behind).

Differences by design: the reconcile loop can be driven INLINE (no separately
deployed operator needed for a smoke test), and the harness runs against any
``KubeApi`` — the in-memory fake in tests (`tests/test_k8s_e2e.py`) covers
the full pass/timeout/teardown logic without a cluster; pointing it at
``KubectlApi`` gives the reference's live-cluster behavior verbatim.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from persia_tpu.k8s import JOB_LABEL, KIND, ROLE_LABEL
from persia_tpu.k8s_operator import KubeApi, KubectlApi, Reconciler
from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.k8s_e2e")

API_VERSION = "persia-tpu.dev/v1"


def default_e2e_job(
    name: str = "persia-tpu-e2e", image: str = "persia-tpu:latest",
    namespace: str = "default",
) -> Dict[str, Any]:
    """The reference e2e topology (e2e.rs: 2 PS, 2 embedding workers, 2 NN
    workers, 1 data loader) as a PersiaTpuJob custom resource."""
    return {
        "apiVersion": API_VERSION,
        "kind": KIND,
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "image": image,
            "parameterServer": {"replicas": 2},
            "embeddingWorker": {"replicas": 2},
            "trainer": {"replicas": 2},
            "dataLoader": {"replicas": 1},
        },
    }


def _trainer_pods(api: KubeApi, namespace: str, job: str) -> List[Dict[str, Any]]:
    out = []
    # None = listing failed (API hiccup): treat as nothing-visible-yet and
    # let the poll loop retry next cycle
    for o in api.list_labeled(namespace) or []:
        meta = o.get("metadata", {})
        labels = meta.get("labels", {})
        if (
            o.get("kind") == "Pod"
            and labels.get(JOB_LABEL) == job
            and labels.get(ROLE_LABEL) == "trainer"
        ):
            out.append(o)
    return out


def run_e2e(
    api: KubeApi,
    cr: Optional[Dict[str, Any]] = None,
    namespace: str = "default",
    timeout_s: float = 600.0,
    poll_s: float = 2.0,
    drive_reconciler: bool = True,
    teardown: bool = True,
) -> Dict[str, Any]:
    """Apply ``cr``, wait for every trainer pod to reach ``Succeeded``
    (ref deadline: 600 s, e2e.rs), then tear down and verify cleanup.

    ``drive_reconciler=True`` runs the convergence loop inline each poll —
    the harness is then self-contained; with ``False`` it only observes
    (an operator deployment must be reconciling the cluster).

    Returns a report dict: ``ok``, ``phase`` ("succeeded" / "timeout" /
    "failed-cleanup"), ``elapsed_s``, ``pod_phases`` (last observation),
    ``expected_trainers``.
    """
    cr = cr or default_e2e_job(namespace=namespace)
    job_name = cr["metadata"]["name"]
    spec = cr.get("spec", {})
    n_trainers = int(spec.get("trainer", {}).get("replicas", 1)) * max(
        int(spec.get("tpu", {}).get("numHosts", 1)), 1
    )
    rec = Reconciler(api, namespace=namespace)
    api.create(cr)
    logger.info("e2e: applied %s %s (expecting %d trainer pods)",
                KIND, job_name, n_trainers)

    deadline = time.monotonic() + timeout_s
    t0 = time.monotonic()
    phases: Dict[str, str] = {}
    ok = False
    while time.monotonic() < deadline:
        if drive_reconciler:
            rec.reconcile_once()
        pods = _trainer_pods(api, namespace, job_name)
        phases = {p["metadata"]["name"]: api.pod_phase(p) for p in pods}
        if len(pods) >= n_trainers and all(
            ph == "Succeeded" for ph in phases.values()
        ):
            ok = True
            break
        time.sleep(poll_s)
    elapsed = time.monotonic() - t0
    phase = "succeeded" if ok else "timeout"
    if not ok:
        logger.error("e2e: trainers not Succeeded within %.0fs: %s",
                     timeout_s, phases)

    if teardown:
        api.delete(KIND, namespace, job_name)
        if drive_reconciler:
            # two-phase finalizer teardown needs TWO cycles: one sweeps the
            # children, the next observes them gone and releases the
            # finalizer (KubectlApi.delete is --wait=false, so the parked CR
            # does not block this thread)
            rec.reconcile_once()
            rec.reconcile_once()
        leftovers = [
            o["metadata"]["name"]
            for o in api.list_labeled(namespace) or []
            if o.get("metadata", {}).get("labels", {}).get(JOB_LABEL) == job_name
        ]
        if leftovers:
            logger.error("e2e: teardown left %s", leftovers)
            if ok:
                phase = "failed-cleanup"
            ok = False

    return {
        "ok": ok,
        "phase": phase,
        "elapsed_s": elapsed,
        "pod_phases": phases,
        "expected_trainers": n_trainers,
    }


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser("persia-tpu-k8s-e2e")
    ap.add_argument("--name", default="persia-tpu-e2e")
    ap.add_argument("--image", default="persia-tpu:latest")
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--timeout-s", type=float, default=600.0)
    ap.add_argument("--observe-only", action="store_true",
                    help="do not reconcile inline (an operator is deployed)")
    ap.add_argument("--keep", action="store_true", help="skip teardown")
    args = ap.parse_args(argv)
    report = run_e2e(
        KubectlApi(),
        default_e2e_job(args.name, args.image, args.namespace),
        namespace=args.namespace,
        timeout_s=args.timeout_s,
        drive_reconciler=not args.observe_only,
        teardown=not args.keep,
    )
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())

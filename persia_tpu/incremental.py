"""Incremental model updates: stream trained-embedding deltas to serving.

Parity target: ``persia-incremental-update-manager``
(`/root/reference/rust/persia-incremental-update-manager/src/lib.rs`):

- train side collects the signs touched by gradient updates into a dedup
  buffer; when it exceeds ``incremental_buffer_size`` it dumps a
  ``PerisaIncrementalPacket{content, timestamps}`` chunk as
  ``{replica}_{seq}.inc`` plus an ``inc_update_done`` marker (`lib.rs:178-312`)
- infer side scans ``incremental_dir`` every 10 s, loads packets it has not
  seen, and exports the ``inc_update_delay_sec`` gauge (`lib.rs:314-364`)

TPU-first differences: packets reuse the checkpoint shard wire format
(u32 count, then u64 sign / u32 dim / u32 len / f32 entry data) so the loader
is just ``store.load_shard_bytes`` — entries re-route by sign, which also
makes packets topology-independent. All IO goes through
:mod:`persia_tpu.storage` (disk / hdfs:// / gs://).

The **delta channel is chaos-hardened**: v2 packets are crc32-framed and
carry the publishing trainer's ``train_step`` plus a monotone ``seq``, so a
consuming replica detects torn/bit-flipped payloads (:class:`
PacketIntegrityError`), duplicate deliveries (seq high-water mark), and
sequence gaps (pruned or black-holed packets) — any unrecoverable damage
raises the loader's ``needs_resync`` flag, and :meth:`IncrementalLoader.
resync` (or the rollover watcher's checkpoint re-apply) repairs it. Every
replica exports its **freshness lag** — newest applied train step vs. the
trainer head, in steps and seconds — which is what the serving gateway's
staleness-bounded quarantine keys on (persia_tpu/serving/gateway.py).
"""

from __future__ import annotations

import json
import re
import struct
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Union

import numpy as np

from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics
from persia_tpu.storage import StorageError, StoragePath, storage_path

logger = get_default_logger("persia_tpu.incremental")

DONE_MARKER = "inc_update_done"
_PACKET_RE = re.compile(r"^(\d+)_(\d+)\.inc$")
_MARKER_RE = re.compile(rf"^{DONE_MARKER}\.(\d+)$")

_HEADER_V1 = struct.Struct("<4sIQ")  # magic, version, timestamp_us
# v2 adds the publisher's train step, the packet seq (also in the filename —
# the header copy survives a rename), and a crc32 over the body so payload
# damage is detected end-to-end, not just at the transport
_HEADER_V2 = struct.Struct("<4sIQQQI")  # magic, ver, ts_us, train_step, seq, body_crc
_MAGIC = b"PINC"


class PacketIntegrityError(ValueError):
    """The packet failed its crc32 / framing check (torn, bit-flipped, or
    truncated in the delta channel). Subclasses ``ValueError`` so existing
    bad-packet handling catches it."""


@dataclass
class PacketMeta:
    """Parsed packet header."""

    timestamp_us: int
    train_step: int
    seq: int
    version: int


def _pack_packet(entries: List[tuple], timestamp_us: int,
                 train_step: int = 0, seq: int = 0) -> bytes:
    """entries: [(sign, dim, entry_vec)] with entry_vec = [emb | opt state]."""
    parts = [struct.pack("<I", len(entries))]
    for sign, dim, vec in entries:
        parts.append(struct.pack("<QII", sign, dim, len(vec)))
        parts.append(vec.astype(np.float32).tobytes())
    body = b"".join(parts)
    head = _HEADER_V2.pack(
        _MAGIC, 2, timestamp_us, train_step, seq, zlib.crc32(body) & 0xFFFFFFFF
    )
    return head + body


def packet_meta(blob: bytes):
    """Parse + integrity-check a packet. Returns ``(PacketMeta, body)`` —
    the body is exactly the checkpoint shard wire format, ready for
    ``store.load_shard_bytes``. Raises :class:`PacketIntegrityError` when a
    v2 packet's crc32 does not cover its body (torn / corrupt)."""
    if len(blob) < _HEADER_V1.size:
        raise PacketIntegrityError("packet shorter than any header")
    magic, version = struct.unpack_from("<4sI", blob, 0)
    if magic != _MAGIC:
        raise ValueError("not an incremental packet")
    if version == 1:
        _, _, ts = _HEADER_V1.unpack_from(blob, 0)
        return PacketMeta(ts, 0, -1, 1), blob[_HEADER_V1.size:]
    if version != 2:
        raise ValueError(f"unsupported packet version {version}")
    if len(blob) < _HEADER_V2.size:
        raise PacketIntegrityError("torn v2 packet (header truncated)")
    _, _, ts, step, seq, crc = _HEADER_V2.unpack_from(blob, 0)
    body = blob[_HEADER_V2.size:]
    if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
        raise PacketIntegrityError(
            f"packet crc mismatch (seq {seq}): torn or corrupt body"
        )
    return PacketMeta(ts, step, seq, 2), body


def unpack_packet(blob: bytes):
    """Returns (timestamp_us, shard_format_blob) — compatibility surface
    over :func:`packet_meta` (v2 packets are crc-verified here too)."""
    meta, body = packet_meta(blob)
    return meta.timestamp_us, body


def iter_packet_entries(body: bytes):
    """Walk a packet body (shard wire format) without copying the entry
    data: yields ``(sign, entry_blob)`` where ``entry_blob`` is the entry's
    full header+data bytes (re-packable into a smaller packet)."""
    (n,) = struct.unpack_from("<I", body, 0)
    off = 4
    for _ in range(n):
        sign, _dim, ln = struct.unpack_from("<QII", body, off)
        end = off + 16 + 4 * ln
        yield sign, body[off:end]
        off = end


def packet_body_nonfinite(body: bytes) -> int:
    """Count entries in a packet body whose float payload carries a
    NaN/Inf — the same finite contract the fence-point PS scrubber
    enforces (persia_tpu/health). A crc-valid packet can still ship
    non-finite rows if the PUBLISHER was corrupted; a consumer that
    applies it would re-serve the damage."""
    (n,) = struct.unpack_from("<I", body, 0)
    off = 4
    bad = 0
    for _ in range(n):
        _sign, _dim, ln = struct.unpack_from("<QII", body, off)
        vals = np.frombuffer(body, dtype=np.float32, count=ln, offset=off + 16)
        if not np.isfinite(vals).all():
            bad += 1
        off += 16 + 4 * ln
    return bad


def packet_signs(body: bytes) -> np.ndarray:
    """Signs updated by a packet body — what an infer-side cache must
    invalidate when the packet applies (persia_tpu/serving/cache.py)."""
    (n,) = struct.unpack_from("<I", body, 0)
    signs = np.empty(n, dtype=np.uint64)
    off = 4
    for i in range(n):
        sign, _dim, ln = struct.unpack_from("<QII", body, off)
        signs[i] = sign
        off += 16 + 4 * ln
    return signs


class IncrementalUpdateManager:
    """Train-side: buffer touched signs, flush packets (ref: lib.rs:178-312).

    Attach with :func:`attach_incremental`; the store calls :meth:`commit`
    after each gradient batch. Flushing happens on a background thread when
    the dedup buffer crosses ``buffer_size`` (and at ``flush_interval_sec``
    heartbeats), never on the gradient hot path.

    The training loop calls :meth:`note_step` once per step so packets and
    the done-marker head beacon carry the trainer's committed train step —
    that beacon is what serving replicas measure freshness lag against. A
    restarted trainer (crash + jobstate auto-resume) RECOVERS its packet
    sequence from the directory listing, so replicas' high-water marks stay
    valid across trainer lives instead of silently ignoring a reset stream.
    """

    def __init__(
        self,
        store,
        inc_dir: Union[str, StoragePath],
        replica_index: int = 0,
        buffer_size: int = 1_000_000,
        flush_interval_sec: float = 10.0,
        retain_packets: int = 64,
        train_step: int = 0,
    ):
        self.store = store
        self.root = storage_path(inc_dir)
        self.replica_index = replica_index
        self.buffer_size = buffer_size
        self.flush_interval_sec = flush_interval_sec
        self.retain_packets = retain_packets
        self._pending: List[np.ndarray] = []
        self._pending_count = 0
        self._train_step = int(train_step)
        self._seq = self._recover_seq()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_flushed = get_metrics().counter(
            "persia_tpu_inc_entries_flushed", "embedding entries shipped incrementally"
        )

    def _recover_seq(self) -> int:
        """Continue the packet sequence after a trainer restart: a reset
        stream (seq back to 0) would be invisible to every consumer's
        high-water mark — their deltas would silently stop applying."""
        try:
            names = self.root.list() if self.root.exists() else []
        except StorageError:
            return 0
        top = -1
        for name in names:
            m = _PACKET_RE.match(name)
            if m and int(m.group(1)) == self.replica_index:
                top = max(top, int(m.group(2)))
        return top + 1

    # ------------------------------------------------------------- train side

    def note_step(self, step: int) -> None:
        """Record the trainer's committed step (monotone); stamped into the
        next packet + done marker as the freshness head."""
        with self._lock:
            if step > self._train_step:
                self._train_step = int(step)

    @property
    def train_step(self) -> int:
        return self._train_step

    def commit(self, signs: np.ndarray) -> None:
        """Record signs touched by a gradient batch (dedup happens at flush)."""
        with self._lock:
            self._pending.append(np.asarray(signs, dtype=np.uint64).copy())
            self._pending_count += len(signs)
            if self._pending_count >= self.buffer_size:
                self._wake.set()

    def start(self) -> "IncrementalUpdateManager":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="inc-update-flusher"
            )
            self._thread.start()
        return self

    def stop(self, final_flush: bool = True) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if final_flush:
            self.flush()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval_sec)
            self._wake.clear()
            if self._stop.is_set():
                break
            try:
                self.flush()
            except Exception as e:  # flusher must survive any transient error
                logger.warning("incremental flush failed (will retry): %s", e)

    def flush(self) -> int:
        """Dedup pending signs, snapshot their entries, write one packet.
        Returns entries written (0 = nothing pending)."""
        with self._lock:
            if not self._pending_count:
                return 0
            arrays, self._pending, self._pending_count = self._pending, [], 0
            step = self._train_step
        signs = np.unique(np.concatenate(arrays))
        entries = []
        for s in signs.tolist():
            rec = self.store.get_entry_record(s)  # atomic (dim, vec) snapshot
            if rec is None:
                continue  # evicted since the update — nothing to ship
            dim, vec = rec
            entries.append((s, dim, vec))
        if not entries:
            return 0
        ts = time.time_ns() // 1000
        with self._lock:
            seq = self._seq
            self._seq += 1
        try:
            self.root.makedirs()
            self.root.join(f"{self.replica_index}_{seq}.inc").write_bytes(
                _pack_packet(entries, ts, train_step=step, seq=seq)
            )
        except Exception:
            # requeue so the retry actually retries these signs (otherwise a
            # transient storage outage silently desyncs serving replicas)
            with self._lock:
                self._pending.append(signs)
                self._pending_count += len(signs)
                # the taken seq stays burned: reusing it could overwrite a
                # packet a concurrent flush shipped in the meantime
            raise
        # the head beacon: last shipped seq + flush time + committed train
        # step per replica (ref: inc_update_done, lib.rs:283-300). Consumers
        # discover packets by listing; they read THIS to learn the trainer
        # head their freshness lag is measured against.
        self.root.join(DONE_MARKER + f".{self.replica_index}").write_text(
            json.dumps({"replica": self.replica_index, "last_seq": seq,
                        "time_us": ts, "train_step": step})
        )
        # retention: a serving replica that boots from the latest full
        # checkpoint only needs recent deltas; prune the tail so the dir and
        # every scanner's listing stay bounded
        stale = seq - self.retain_packets
        if stale >= 0:
            try:
                self.root.join(f"{self.replica_index}_{stale}.inc").remove()
            except StorageError as e:
                logger.warning("could not prune old packet %d: %s", stale, e)
        self._m_flushed.inc(len(entries))
        logger.debug("incremental packet %d_%d.inc: %d entries (step %d)",
                     self.replica_index, seq, len(entries), step)
        return len(entries)


class IncrementalLoader:
    """Infer-side: scan the incremental dir, load unseen packets
    (ref: lib.rs:314-364). Entries re-route by sign on insert, so the serving
    topology is independent of the training topology.

    Damage handling (the delta channel is assumed hostile — see chaos.py's
    ``DeltaChannelChaos``):

    - **duplicate** deliveries are skipped by the per-publisher seq
      high-water mark (applying them would be idempotent anyway — packets
      carry full entry values — but the skip keeps ordering monotone);
    - **out-of-order** late deliveries (seq below the mark) are never
      applied — they would regress entries to stale values;
    - **torn / bit-flipped** packets fail the crc32 check; the loader holds
      position (strict per-publisher ordering) and retries once — a chaos
      relay may redeliver an intact copy — then gives up, skips past, and
      raises ``needs_resync``;
    - **gaps** (a seq jump: pruned retention or a black-holed channel) apply
      what arrived but raise ``needs_resync`` — the skipped packets' signs
      may never be re-covered by later packets.

    ``needs_resync`` is consumed by :meth:`resync` (clear marks, re-apply
    the retained tail — callers pairing with a chaos relay redeliver first)
    or by the rollover watcher, which re-applies the full checkpoint and
    then resyncs (persia_tpu/serving/rollover.py).
    """

    #: integrity-failed packets get this many reads before being skipped
    max_bad_retries = 2

    def __init__(
        self,
        store,
        inc_dir: Union[str, StoragePath],
        scan_interval_sec: float = 10.0,
        skip_before_us: int = 0,
        on_apply=None,
        reject_nonfinite: bool = True,
    ):
        self.store = store
        # data-plane health gate (persia_tpu/health): a crc-VALID packet
        # whose entry payload carries NaN/Inf is refused like a torn one
        # (hold position, retry for a clean redelivery, then skip +
        # needs_resync) — serving must never apply non-finite rows
        self.reject_nonfinite = reject_nonfinite
        self.root = storage_path(inc_dir)
        self.scan_interval_sec = scan_interval_sec
        # called with the applied packet's signs (np.uint64) AFTER each
        # load_shard_bytes — the serving hot cache invalidates exactly these
        # (persia_tpu/serving/cache.py); None = no listener
        self.on_apply = on_apply
        # packets older than this are marked seen but NOT applied — a serving
        # replica booting from a full checkpoint must not regress entries to
        # retained packets that predate it
        self.skip_before_us = skip_before_us
        # per-replica high-water seq: bounded state (a name set would grow
        # with every packet ever shipped) and makes restarts replay only the
        # retained tail
        self._hwm: Dict[int, int] = {}
        self._bad: Dict[str, int] = {}  # integrity-failure count per packet
        # per-publisher seq at the last resync: gaps at/below it are part
        # of the accepted (already-repaired) base, not new damage
        self._gap_accepted: Dict[int, int] = {}
        self.needs_resync = False
        # freshness state: newest applied (step, publish time) vs. the
        # trainer head read from the done-marker beacons
        self.applied_step = -1
        self.applied_time_us = 0
        self.head_step = -1
        self.head_time_us = 0
        self.stats: Dict[str, int] = {
            "applied_packets": 0, "corrupt_skipped": 0, "gaps": 0,
            "stale_dropped": 0, "resyncs": 0, "nonfinite_rejected": 0,
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        m = get_metrics()
        self._m_delay = m.gauge(
            "persia_tpu_inc_update_delay_sec",
            "age of the newest applied incremental packet at apply time",
        )
        self._m_loaded = m.counter(
            "persia_tpu_inc_entries_loaded", "embedding entries applied from packets"
        )
        self._m_corrupt = m.counter(
            "persia_tpu_inc_packets_corrupt",
            "incremental packets skipped on crc/framing failure",
        )
        self._m_gaps = m.counter(
            "persia_tpu_inc_packet_gaps", "seq gaps observed in the delta stream"
        )
        self._m_resyncs = m.counter(
            "persia_tpu_inc_resyncs", "loader resyncs after channel damage"
        )
        self._m_nonfinite = m.counter(
            "persia_tpu_health_delta_rejected",
            "delta packets refused because their payload failed the finite check",
        )
        self._m_lag_steps = m.gauge(
            "persia_tpu_inc_freshness_lag_steps",
            "train steps between the trainer head and the newest applied packet",
        )
        self._m_lag_sec = m.gauge(
            "persia_tpu_inc_freshness_lag_seconds",
            "seconds between the trainer head and the newest applied packet",
        )

    # ------------------------------------------------------------- freshness

    def _read_head(self, names: List[str]) -> None:
        for name in names:
            if not _MARKER_RE.match(name):
                continue
            try:
                info = json.loads(self.root.join(name).read_text())
            except (StorageError, ValueError):
                continue  # marker mid-write / damaged: next scan retries
            step = int(info.get("train_step", -1))
            ts = int(info.get("time_us", 0))
            if step > self.head_step:
                self.head_step = step
            if ts > self.head_time_us:
                self.head_time_us = ts

    def freshness(self) -> Dict:
        """Per-replica freshness snapshot: newest applied train step vs. the
        trainer head, in steps and seconds. The serving gateway's staleness
        quarantine keys on these numbers (via /healthz)."""
        head = max(self.head_step, self.applied_step)
        lag_steps = max(0, head - self.applied_step) if head >= 0 else 0
        lag_s = 0.0
        if lag_steps > 0 and self.head_time_us > self.applied_time_us:
            lag_s = (self.head_time_us - self.applied_time_us) / 1e6
        return {
            "applied_step": self.applied_step,
            "applied_time_us": self.applied_time_us,
            "head_step": head,
            "head_time_us": self.head_time_us,
            "lag_steps": lag_steps,
            "lag_seconds": round(lag_s, 3),
            "needs_resync": self.needs_resync,
        }

    def _export_freshness(self) -> None:
        f = self.freshness()
        self._m_lag_steps.set(float(f["lag_steps"]))
        self._m_lag_sec.set(float(f["lag_seconds"]))

    # ----------------------------------------------------------------- apply

    def poll_once(self) -> int:
        """Scan + apply all unseen packets in (replica, seq) order. Returns
        entries applied."""
        try:
            names = self.root.list() if self.root.exists() else []
        except StorageError:
            return 0
        self._read_head(names)
        per_replica: Dict[int, List] = {}
        for name in names:
            m = _PACKET_RE.match(name)
            if m:
                replica, seq = int(m.group(1)), int(m.group(2))
                if seq > self._hwm.get(replica, -1):
                    per_replica.setdefault(replica, []).append((seq, name))
        applied = 0
        for replica in sorted(per_replica):
            applied += self._apply_replica(replica, sorted(per_replica[replica]))
        if applied:
            self._m_loaded.inc(applied)
        self._export_freshness()
        return applied

    def _apply_replica(self, replica: int, todo: List) -> int:
        """Apply one publisher's pending packets in seq order. Stops at the
        first integrity failure (strict ordering: applying past damage would
        hide it) until the packet exhausts its retries."""
        applied = 0
        for seq, name in todo:
            if self._bad.get(name, 0) >= self.max_bad_retries:
                # damaged beyond the retry budget: skip past it so the
                # stream keeps flowing; resync owns the repair
                self._hwm[replica] = seq
                continue
            try:
                meta, body = packet_meta(self.root.join(name).read_bytes())
                if self.reject_nonfinite:
                    bad_rows = packet_body_nonfinite(body)
                    if bad_rows:
                        from persia_tpu.tracing import record_event

                        self.stats["nonfinite_rejected"] += 1
                        self._m_nonfinite.inc()
                        record_event(
                            "health.anomaly", cause="nonfinite_delta",
                            packet=name, seq=seq, rows=bad_rows,
                        )
                        raise PacketIntegrityError(
                            f"{bad_rows} non-finite entry row(s) in packet "
                            f"payload (seq {seq})"
                        )
            except (StorageError, ValueError, struct.error) as e:
                self._bad[name] = self._bad.get(name, 0) + 1
                self.stats["corrupt_skipped"] += 1
                self._m_corrupt.inc()
                self.needs_resync = True
                logger.warning(
                    "bad incremental packet %s (attempt %d/%d): %s", name,
                    self._bad[name], self.max_bad_retries, e,
                )
                break  # hold position: a redelivery may still repair seq
            prev = self._hwm.get(replica, -1)
            # a gap is a seq jump past an ESTABLISHED position — the first
            # packet ever seen never flags (the head of a retention-pruned
            # dir), and a post-resync replay never re-flags gaps at or
            # below the pre-resync mark (a permanently lost packet must
            # not re-trigger resync forever)
            if (prev >= 0 and seq > prev + 1
                    and seq > self._gap_accepted.get(replica, -1)):
                # seq jump: packets pruned (retention) or black-holed — what
                # they carried may never re-arrive; flag for resync
                self.stats["gaps"] += 1
                self._m_gaps.inc()
                self.needs_resync = True
                logger.warning(
                    "delta-stream gap for publisher %d: %d -> %d", replica,
                    prev, seq,
                )
            if meta.timestamp_us < self.skip_before_us:
                self._hwm[replica] = seq  # predates our boot checkpoint
                self.stats["stale_dropped"] += 1
                continue
            n = self.store.load_shard_bytes(body)
            self._hwm[replica] = seq
            applied += n
            self.stats["applied_packets"] += 1
            if meta.train_step > self.applied_step:
                self.applied_step = meta.train_step
            if meta.timestamp_us > self.applied_time_us:
                self.applied_time_us = meta.timestamp_us
            if self.on_apply is not None and n:
                try:
                    self.on_apply(packet_signs(body))
                except Exception as e:  # noqa: BLE001 — listener must not stop the scan
                    logger.warning("incremental on_apply hook failed: %s", e)
            self._m_delay.set(max(0.0, time.time() - meta.timestamp_us / 1e6))
        return applied

    def resync(self) -> int:
        """Recover from channel damage: clear the high-water marks and the
        bad-packet memory, then re-apply everything retained in order.
        Packets carry full entry values, so re-application is idempotent and
        converges to the newest value per sign. Callers whose channel is a
        chaos relay should ``redeliver`` first so damaged copies are
        replaced; callers with a checkpoint dir should re-apply the
        checkpoint first (rollover does both — serving/rollover.py).
        Returns entries applied."""
        for replica, hwm in self._hwm.items():
            if hwm > self._gap_accepted.get(replica, -1):
                self._gap_accepted[replica] = hwm
        self._hwm.clear()
        self._bad.clear()
        self.needs_resync = False
        self.stats["resyncs"] += 1
        self._m_resyncs.inc()
        return self.poll_once()

    def start(self) -> "IncrementalLoader":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="inc-update-loader"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.scan_interval_sec):
            try:
                self.poll_once()
            except Exception as e:  # scanner must survive transient errors
                logger.warning("incremental scan failed (will retry): %s", e)


def read_head(inc_dir: Union[str, StoragePath]):
    """Read the trainer head — ``(head_step, head_time_us)`` — straight
    from a delta directory's done-marker beacons. The serving gateway uses
    this as its ``head_source`` against the DURABLE source dir, so a
    partition that freezes every replica's local head view cannot also
    freeze the staleness measurement (the replicas would otherwise all
    report the same stale head and nobody would look behind)."""
    root = storage_path(inc_dir)
    head_step, head_time = -1, 0
    try:
        names = root.list() if root.exists() else []
    except StorageError:
        return head_step, head_time
    for name in names:
        if not _MARKER_RE.match(name):
            continue
        try:
            info = json.loads(root.join(name).read_text())
        except (StorageError, ValueError):
            continue
        head_step = max(head_step, int(info.get("train_step", -1)))
        head_time = max(head_time, int(info.get("time_us", 0)))
    return head_step, head_time


def attach_incremental(
    store,
    inc_dir: Union[str, StoragePath],
    replica_index: int = 0,
    buffer_size: int = 1_000_000,
    flush_interval_sec: float = 10.0,
) -> IncrementalUpdateManager:
    """Hook a manager onto a store's gradient path: every
    ``update_gradients`` commits its signs to the manager's buffer."""
    mgr = IncrementalUpdateManager(
        store, inc_dir, replica_index, buffer_size, flush_interval_sec
    ).start()
    store.inc_manager = mgr
    return mgr

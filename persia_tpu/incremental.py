"""Incremental model updates: stream trained-embedding deltas to serving.

Parity target: ``persia-incremental-update-manager``
(`/root/reference/rust/persia-incremental-update-manager/src/lib.rs`):

- train side collects the signs touched by gradient updates into a dedup
  buffer; when it exceeds ``incremental_buffer_size`` it dumps a
  ``PerisaIncrementalPacket{content, timestamps}`` chunk as
  ``{replica}_{seq}.inc`` plus an ``inc_update_done`` marker (`lib.rs:178-312`)
- infer side scans ``incremental_dir`` every 10 s, loads packets it has not
  seen, and exports the ``inc_update_delay_sec`` gauge (`lib.rs:314-364`)

TPU-first differences: packets reuse the checkpoint shard wire format
(u32 count, then u64 sign / u32 dim / u32 len / f32 entry data) so the loader
is just ``store.load_shard_bytes`` — entries re-route by sign, which also
makes packets topology-independent. All IO goes through
:mod:`persia_tpu.storage` (disk / hdfs:// / gs://).
"""

from __future__ import annotations

import json
import re
import struct
import threading
import time
from typing import Dict, List, Optional, Union

import numpy as np

from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import get_metrics
from persia_tpu.storage import StorageError, StoragePath, storage_path

logger = get_default_logger("persia_tpu.incremental")

DONE_MARKER = "inc_update_done"
_PACKET_RE = re.compile(r"^(\d+)_(\d+)\.inc$")

_HEADER = struct.Struct("<4sIQ")  # magic, version, timestamp_us
_MAGIC = b"PINC"


def _pack_packet(entries: List[tuple], timestamp_us: int) -> bytes:
    """entries: [(sign, dim, entry_vec)] with entry_vec = [emb | opt state]."""
    parts = [_HEADER.pack(_MAGIC, 1, timestamp_us), struct.pack("<I", len(entries))]
    for sign, dim, vec in entries:
        parts.append(struct.pack("<QII", sign, dim, len(vec)))
        parts.append(vec.astype(np.float32).tobytes())
    return b"".join(parts)


def unpack_packet(blob: bytes):
    """Returns (timestamp_us, shard_format_blob) — the body is exactly the
    checkpoint shard wire format, ready for ``store.load_shard_bytes``."""
    magic, version, ts = _HEADER.unpack_from(blob, 0)
    if magic != _MAGIC:
        raise ValueError("not an incremental packet")
    if version != 1:
        raise ValueError(f"unsupported packet version {version}")
    return ts, blob[_HEADER.size :]


def iter_packet_entries(body: bytes):
    """Walk a packet body (shard wire format) without copying the entry
    data: yields ``(sign, entry_blob)`` where ``entry_blob`` is the entry's
    full header+data bytes (re-packable into a smaller packet)."""
    (n,) = struct.unpack_from("<I", body, 0)
    off = 4
    for _ in range(n):
        sign, _dim, ln = struct.unpack_from("<QII", body, off)
        end = off + 16 + 4 * ln
        yield sign, body[off:end]
        off = end


def packet_signs(body: bytes) -> np.ndarray:
    """Signs updated by a packet body — what an infer-side cache must
    invalidate when the packet applies (persia_tpu/serving/cache.py)."""
    (n,) = struct.unpack_from("<I", body, 0)
    signs = np.empty(n, dtype=np.uint64)
    off = 4
    for i in range(n):
        sign, _dim, ln = struct.unpack_from("<QII", body, off)
        signs[i] = sign
        off += 16 + 4 * ln
    return signs


class IncrementalUpdateManager:
    """Train-side: buffer touched signs, flush packets (ref: lib.rs:178-312).

    Attach with :func:`attach_incremental`; the store calls :meth:`commit`
    after each gradient batch. Flushing happens on a background thread when
    the dedup buffer crosses ``buffer_size`` (and at ``flush_interval_sec``
    heartbeats), never on the gradient hot path.
    """

    def __init__(
        self,
        store,
        inc_dir: Union[str, StoragePath],
        replica_index: int = 0,
        buffer_size: int = 1_000_000,
        flush_interval_sec: float = 10.0,
        retain_packets: int = 64,
    ):
        self.store = store
        self.root = storage_path(inc_dir)
        self.replica_index = replica_index
        self.buffer_size = buffer_size
        self.flush_interval_sec = flush_interval_sec
        self.retain_packets = retain_packets
        self._pending: List[np.ndarray] = []
        self._pending_count = 0
        self._seq = 0
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_flushed = get_metrics().counter(
            "persia_tpu_inc_entries_flushed", "embedding entries shipped incrementally"
        )

    # ------------------------------------------------------------- train side

    def commit(self, signs: np.ndarray) -> None:
        """Record signs touched by a gradient batch (dedup happens at flush)."""
        with self._lock:
            self._pending.append(np.asarray(signs, dtype=np.uint64).copy())
            self._pending_count += len(signs)
            if self._pending_count >= self.buffer_size:
                self._wake.set()

    def start(self) -> "IncrementalUpdateManager":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="inc-update-flusher"
            )
            self._thread.start()
        return self

    def stop(self, final_flush: bool = True) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if final_flush:
            self.flush()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval_sec)
            self._wake.clear()
            if self._stop.is_set():
                break
            try:
                self.flush()
            except Exception as e:  # flusher must survive any transient error
                logger.warning("incremental flush failed (will retry): %s", e)

    def flush(self) -> int:
        """Dedup pending signs, snapshot their entries, write one packet.
        Returns entries written (0 = nothing pending)."""
        with self._lock:
            if not self._pending_count:
                return 0
            arrays, self._pending, self._pending_count = self._pending, [], 0
        signs = np.unique(np.concatenate(arrays))
        entries = []
        for s in signs.tolist():
            rec = self.store.get_entry_record(s)  # atomic (dim, vec) snapshot
            if rec is None:
                continue  # evicted since the update — nothing to ship
            dim, vec = rec
            entries.append((s, dim, vec))
        if not entries:
            return 0
        ts = time.time_ns() // 1000
        with self._lock:
            seq = self._seq
            self._seq += 1
        try:
            self.root.makedirs()
            self.root.join(f"{self.replica_index}_{seq}.inc").write_bytes(
                _pack_packet(entries, ts)
            )
        except Exception:
            # requeue so the retry actually retries these signs (otherwise a
            # transient storage outage silently desyncs serving replicas)
            with self._lock:
                self._pending.append(signs)
                self._pending_count += len(signs)
                # the taken seq stays burned: reusing it could overwrite a
                # packet a concurrent flush shipped in the meantime
            raise
        # informational marker for operators/external tooling: last shipped
        # seq + flush time per replica (ref: inc_update_done, lib.rs:283-300).
        # The loader itself discovers packets by listing, not via this marker.
        self.root.join(DONE_MARKER + f".{self.replica_index}").write_text(
            json.dumps({"replica": self.replica_index, "last_seq": seq, "time_us": ts})
        )
        # retention: a serving replica that boots from the latest full
        # checkpoint only needs recent deltas; prune the tail so the dir and
        # every scanner's listing stay bounded
        stale = seq - self.retain_packets
        if stale >= 0:
            try:
                self.root.join(f"{self.replica_index}_{stale}.inc").remove()
            except StorageError as e:
                logger.warning("could not prune old packet %d: %s", stale, e)
        self._m_flushed.inc(len(entries))
        logger.debug("incremental packet %d_%d.inc: %d entries", self.replica_index, seq, len(entries))
        return len(entries)


class IncrementalLoader:
    """Infer-side: scan the incremental dir, load unseen packets
    (ref: lib.rs:314-364). Entries re-route by sign on insert, so the serving
    topology is independent of the training topology."""

    def __init__(
        self,
        store,
        inc_dir: Union[str, StoragePath],
        scan_interval_sec: float = 10.0,
        skip_before_us: int = 0,
        on_apply=None,
    ):
        self.store = store
        self.root = storage_path(inc_dir)
        self.scan_interval_sec = scan_interval_sec
        # called with the applied packet's signs (np.uint64) AFTER each
        # load_shard_bytes — the serving hot cache invalidates exactly these
        # (persia_tpu/serving/cache.py); None = no listener
        self.on_apply = on_apply
        # packets older than this are marked seen but NOT applied — a serving
        # replica booting from a full checkpoint must not regress entries to
        # retained packets that predate it
        self.skip_before_us = skip_before_us
        # per-replica high-water seq: bounded state (a name set would grow
        # with every packet ever shipped) and makes restarts replay only the
        # retained tail
        self._hwm: Dict[int, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        m = get_metrics()
        self._m_delay = m.gauge(
            "persia_tpu_inc_update_delay_sec",
            "age of the newest applied incremental packet at apply time",
        )
        self._m_loaded = m.counter(
            "persia_tpu_inc_entries_loaded", "embedding entries applied from packets"
        )

    def poll_once(self) -> int:
        """Scan + apply all unseen packets in (replica, seq) order. Returns
        entries applied."""
        try:
            names = self.root.list() if self.root.exists() else []
        except StorageError:
            return 0
        todo = []
        for name in names:
            m = _PACKET_RE.match(name)
            if m:
                replica, seq = int(m.group(1)), int(m.group(2))
                if seq > self._hwm.get(replica, -1):
                    todo.append((replica, seq, name))
        todo.sort()
        applied = 0
        for replica, seq, name in todo:
            try:
                ts, body = unpack_packet(self.root.join(name).read_bytes())
            except (StorageError, ValueError, struct.error) as e:
                logger.warning("skipping bad incremental packet %s: %s", name, e)
                self._hwm[replica] = seq  # don't retry a corrupt packet forever
                continue
            if ts < self.skip_before_us:
                self._hwm[replica] = seq  # predates our boot checkpoint
                continue
            n = self.store.load_shard_bytes(body)
            self._hwm[replica] = seq
            applied += n
            if self.on_apply is not None and n:
                try:
                    self.on_apply(packet_signs(body))
                except Exception as e:  # noqa: BLE001 — listener must not stop the scan
                    logger.warning("incremental on_apply hook failed: %s", e)
            self._m_delay.set(max(0.0, time.time() - ts / 1e6))
        if applied:
            self._m_loaded.inc(applied)
        return applied

    def start(self) -> "IncrementalLoader":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="inc-update-loader"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.scan_interval_sec):
            try:
                self.poll_once()
            except Exception as e:  # scanner must survive transient errors
                logger.warning("incremental scan failed (will retry): %s", e)


def attach_incremental(
    store,
    inc_dir: Union[str, StoragePath],
    replica_index: int = 0,
    buffer_size: int = 1_000_000,
    flush_interval_sec: float = 10.0,
) -> IncrementalUpdateManager:
    """Hook a manager onto a store's gradient path: every
    ``update_gradients`` commits its signs to the manager's buffer."""
    mgr = IncrementalUpdateManager(
        store, inc_dir, replica_index, buffer_size, flush_interval_sec
    ).start()
    store.inc_manager = mgr
    return mgr

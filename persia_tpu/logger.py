"""Default logger (ref: persia/logger.py:55-93, without the colorlog dependency)."""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_FORMAT = "%(asctime)s [%(levelname)s] %(name)s: %(message)s"


def get_default_logger(name: str = "persia_tpu", level: Optional[str] = None) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(handler)
        logger.setLevel((level or os.environ.get("LOG_LEVEL", "INFO")).upper())
        logger.propagate = False
    elif level is not None:
        logger.setLevel(level.upper())
    return logger


def get_file_logger(name: str, path: str) -> logging.Logger:
    logger = get_default_logger(name)
    abspath = os.path.abspath(path)
    for h in logger.handlers:
        if isinstance(h, logging.FileHandler) and h.baseFilename == abspath:
            return logger
    handler = logging.FileHandler(path)
    handler.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(handler)
    return logger

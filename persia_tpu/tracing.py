"""Stage-latency tracing, distributed trace context, and the flight recorder.

Parity target: the reference's pervasive `tracing::debug!` stage timers
around every pipeline hop (`rust/persia-core/src/forward.rs:591-593,665-669`,
`embedding_worker_service/mod.rs:909-938`) with the `LOG_LEVEL` env filter
(`rust/persia-core/src/lib.rs:463-465`).

Adds what the reference lacks:

- an in-memory ring of completed spans exported as **chrome://tracing /
  Perfetto JSON**, so a training-run timeline (lookup → stage → device step
  → grad return) is viewable alongside JAX's own profiler traces;
- a **trace context** (``trace_id/span_id/parent_id``), thread-local and
  generated at the edge, that rides the RPC frame header (negotiated
  capability, see ``service/rpc.py``) and the serving path's
  ``X-Trace-Id``/``X-Parent-Span`` HTTP headers — one id links a client
  request to the replica's cache probe, and a gradient batch to its
  journaled PS apply;
- a **flight recorder**: a bounded ring of structured events (breaker
  trips, quarantine/heal, resyncs, fence commits, injected chaos faults),
  each stamped with the active trace_id, dumped atomically on
  SIGTERM/atexit/uncaught-fatal so every chaos failure has a black box.

Usage::

    from persia_tpu import tracing

    tracing.enable()          # or PERSIA_TRACE=1; off by default
    with tracing.span("lookup", slot="cat_0"):
        ...
    tracing.trace_export("/tmp/trace.json")

Spans nest via a thread-local context stack; duration is also pushed to the
metrics Histogram ``persia_stage_duration_seconds`` when metrics are enabled.
A span on a disabled tracer is a strict no-op — hot paths pay ~nothing by
default. The flight recorder is always on (its events are rare by
construction); only the dump path needs arming.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Deque, Dict, List, Optional, Tuple

from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.tracing")

_MAX_SPANS = int(os.environ.get("PERSIA_TRACE_BUFFER", "20000"))
_lock = threading.Lock()
_spans: Deque[Dict[str, Any]] = deque(maxlen=_MAX_SPANS)
_tls = threading.local()
# Opt-in, like the reference's LOG_LEVEL-gated stage timers: a span on a
# disabled tracer is a no-op, so hot paths pay ~nothing by default.
_enabled = os.environ.get("PERSIA_TRACE", "0") in ("1", "true")
_histogram = None
# Role tag stamped on exports/flight dumps so the fleet merger can name
# processes ("trainer0", "replica1", "gateway", ...). Set once per process.
_role = os.environ.get("PERSIA_ROLE", "")


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def set_role(role: str) -> None:
    """Tag this process's spans/flight dumps with a fleet role name."""
    global _role
    _role = role


def get_role() -> str:
    return _role or f"proc_{os.getpid()}"


def _get_histogram():
    global _histogram
    if _histogram is None:
        try:
            from persia_tpu.metrics import get_metrics

            _histogram = get_metrics().histogram(
                "persia_stage_duration_seconds", "per-stage latency"
            )
        except Exception:
            _histogram = False
    return _histogram


# --------------------------------------------------------------------- context
#
# The thread-local stack holds (trace_id, span_id) frames. ``span`` pushes a
# frame for its own id; ``trace_context`` pushes an adopted frame carrying a
# REMOTE parent (what arrived on the wire), so spans opened beneath it become
# children of the caller's span in the merged timeline. The stack works even
# when tracing is disabled — adoption is cheap and the flight recorder wants
# the ambient trace_id regardless — but ``span`` itself never touches it on
# the disabled path.

def _gen_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_context() -> Optional[Tuple[str, Optional[str]]]:
    """The ambient ``(trace_id, span_id)`` to propagate to a downstream hop,
    or ``None`` when no trace is active on this thread."""
    st = getattr(_tls, "stack", None)
    if st:
        return st[-1]
    return None


def current_trace_id() -> Optional[str]:
    ctx = current_context()
    return ctx[0] if ctx else None


@contextmanager
def trace_context(trace_id: Optional[str] = None,
                  parent_span: Optional[str] = None):
    """Open (edge) or adopt (wire) a trace scope on this thread.

    With no arguments a fresh ``trace_id`` is generated — this is the edge.
    With ids parsed off a frame/header, spans beneath become children of the
    remote caller's span. Yields the ``(trace_id, parent_span)`` frame."""
    st = _stack()
    frame = (trace_id or _gen_id(16), parent_span)
    st.append(frame)
    try:
        yield frame
    finally:
        st.pop()


def wire_headers() -> Dict[str, str]:
    """HTTP headers carrying the ambient context (empty when none active)."""
    ctx = current_context()
    if ctx is None:
        return {}
    h = {"X-Trace-Id": ctx[0]}
    if ctx[1]:
        h["X-Parent-Span"] = ctx[1]
    return h


@contextmanager
def span(name: str, **attrs):
    """Time a pipeline stage; logs at debug level, records for export."""
    if not _enabled:
        yield
        return
    st = _stack()
    if st:
        trace_id, parent = st[-1]
    else:
        trace_id, parent = _gen_id(16), None  # this span IS the edge
    span_id = _gen_id(8)
    st.append((trace_id, span_id))
    t0 = time.perf_counter()
    ts_us = time.time() * 1e6
    try:
        yield
    finally:
        st.pop()
        dur = time.perf_counter() - t0
        logger.debug("%s%s took %.3f ms %s", "  " * len(st), name, dur * 1e3,
                     attrs if attrs else "")
        args = {k: str(v) for k, v in attrs.items()}
        args["trace_id"] = trace_id
        args["span_id"] = span_id
        if parent:
            args["parent_id"] = parent
        with _lock:
            _spans.append({
                "name": name,
                "ph": "X",
                "ts": ts_us,
                "dur": dur * 1e6,
                "pid": os.getpid(),
                "tid": threading.get_ident() % 2**31,
                "args": args,
            })
        h = _get_histogram()
        if h:
            h.observe(dur, stage=name)


def record_span(name: str, dur_s: float, **attrs) -> None:
    """Record a span whose duration was measured EXTERNALLY (e.g. the
    native sharded-feed walker reports per-shard walk ns from inside the
    thread pool — wrapping the ctypes call in :func:`span` would time the
    whole dispatch, not the shard). The span ends "now"; its start is
    back-dated by the given duration. No-op when tracing is off."""
    if not _enabled:
        return
    st = _stack()
    if st:
        trace_id, parent = st[-1]
    else:
        trace_id, parent = _gen_id(16), None
    args = {k: str(v) for k, v in attrs.items()}
    args["trace_id"] = trace_id
    args["span_id"] = _gen_id(8)
    if parent:
        args["parent_id"] = parent
    with _lock:
        _spans.append({
            "name": name,
            "ph": "X",
            "ts": time.time() * 1e6 - dur_s * 1e6,
            "dur": dur_s * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident() % 2**31,
            "args": args,
        })
    h = _get_histogram()
    if h:
        h.observe(dur_s, stage=name)


@contextmanager
def stage_span(name: str, **attrs):
    """Pipeline-stage timer that ALWAYS feeds the live stage histogram
    (``persia_stage_duration_seconds{stage=...}``) and records a trace span
    only when tracing is enabled. The sanctioned replacement for hand-rolled
    ``t0 = time.time()`` stage timers in pipeline modules (persia-lint
    OBS002); the bench reads the same series the trace viewer shows."""
    if _enabled:
        with span(name, **attrs):
            yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        h = _get_histogram()
        if h:
            h.observe(time.perf_counter() - t0, stage=name)


def timed(name: Optional[str] = None):
    """Decorator flavor of :func:`span`."""

    def deco(fn):
        label = name or fn.__qualname__

        def wrapper(*a, **kw):
            with span(label):
                return fn(*a, **kw)

        wrapper.__name__ = fn.__name__
        wrapper.__qualname__ = fn.__qualname__
        return wrapper

    return deco


def spans_snapshot() -> list:
    with _lock:
        return list(_spans)


def spans_drain() -> list:
    """Snapshot AND clear the ring in one lock hold — the ``/spans``
    endpoint uses this so the fleet collector never double-counts."""
    with _lock:
        out = list(_spans)
        _spans.clear()
    return out


def clear() -> None:
    with _lock:
        _spans.clear()


def _atomic_write_json(path: str, doc: Dict[str, Any]) -> None:
    """temp + fsync + rename: the artifact never exists half-written (the
    same durable-write discipline persia-lint DUR001 polices elsewhere)."""
    data = json.dumps(doc).encode()
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def export_doc(events: Optional[List[Dict[str, Any]]] = None) -> Dict[str, Any]:
    """The per-role export document: trace events plus the clock/role
    metadata the fleet merger needs to align and name this process."""
    return {
        "traceEvents": spans_snapshot() if events is None else events,
        "displayTimeUnit": "ms",
        "metadata": {
            "role": get_role(),
            "pid": os.getpid(),
            "clock_unix_us": time.time() * 1e6,
        },
    }


def trace_export(path: str) -> int:
    """Write the span ring as chrome://tracing JSON; returns span count."""
    doc = export_doc()
    _atomic_write_json(path, doc)
    n = len(doc["traceEvents"])
    logger.info("exported %d trace events to %s", n, path)
    return n


# ------------------------------------------------------------ flight recorder
#
# A bounded ring of structured events — the black box. Unlike spans it is
# ALWAYS on: the events it records (breaker trips, quarantine/heal, resyncs,
# fence commits, injected chaos faults) are rare by construction, so the
# cost is one dict build + deque append per event. Each event is stamped
# with the ambient trace_id so a chaos fault can be correlated with the
# request/batch it hit. ``install_flight_recorder`` arms an atomic dump on
# SIGTERM, atexit, and uncaught fatal exceptions.

_FLIGHT_MAX = int(os.environ.get("PERSIA_FLIGHT_BUFFER", "4096"))
_flight_lock = threading.Lock()
_flight: Deque[Dict[str, Any]] = deque(maxlen=_FLIGHT_MAX)
_flight_seq = 0
_flight_path: Optional[str] = None
_flight_installed = False


def record_event(kind: str, **attrs) -> Dict[str, Any]:
    """Append a structured event to the flight ring (always on)."""
    global _flight_seq
    evt = {
        "kind": kind,
        "ts_us": time.time() * 1e6,
        "pid": os.getpid(),
        "tid": threading.get_ident() % 2**31,
        "trace_id": current_trace_id(),
        "attrs": {k: str(v) for k, v in attrs.items()},
    }
    with _flight_lock:
        evt["seq"] = _flight_seq
        _flight_seq += 1
        _flight.append(evt)
    return evt


def flight_snapshot() -> list:
    with _flight_lock:
        return list(_flight)


def flight_clear() -> None:
    global _flight_seq
    with _flight_lock:
        _flight.clear()
        _flight_seq = 0


def flight_dump(path: Optional[str] = None) -> Optional[str]:
    """Atomically write the flight ring (and its metadata) to ``path`` or
    the armed path; returns the path written, or None when unarmed."""
    target = path or _flight_path
    if not target:
        return None
    doc = {
        "role": get_role(),
        "pid": os.getpid(),
        "dumped_unix_us": time.time() * 1e6,
        "events": flight_snapshot(),
    }
    _atomic_write_json(target, doc)
    return target


def _dump_quietly() -> None:
    try:
        flight_dump()
    except Exception:  # noqa: BLE001 — a failing black box must not mask the crash
        pass
    if _export_path:
        try:
            # write directly (no logging): at interpreter exit the log
            # streams may already be closed, and logging then prints a
            # "--- Logging error ---" traceback over the real output
            _atomic_write_json(_export_path, export_doc())
        except Exception:  # noqa: BLE001
            pass


_export_path: Optional[str] = None
_export_armed = False


def arm_trace_export(path: str) -> None:
    """Arm a span-ring export to ``path`` at interpreter exit AND alongside
    any flight dump (SIGTERM / fatal excepthook) — a terminated role still
    leaves its timeline behind for the fleet merger's dead-role fallback."""
    global _export_path, _export_armed
    _export_path = path
    if not _export_armed:
        _export_armed = True
        atexit.register(_dump_quietly)


def install_flight_recorder(path: str) -> None:
    """Arm the flight recorder to dump to ``path`` on SIGTERM, interpreter
    exit, and uncaught fatal exceptions. Chains any handlers already
    installed (topology roles install their own SIGTERM shutdown first)."""
    global _flight_path, _flight_installed
    _flight_path = path
    if _flight_installed:
        return
    _flight_installed = True
    atexit.register(_dump_quietly)

    prev_hook = sys.excepthook

    def hook(exc_type, exc, tb):
        record_event("fatal", exc=f"{exc_type.__name__}: {exc}")
        _dump_quietly()
        prev_hook(exc_type, exc, tb)

    sys.excepthook = hook

    try:
        prev_term = signal.getsignal(signal.SIGTERM)

        def on_term(signum, frame):
            record_event("sigterm")
            _dump_quietly()
            if callable(prev_term):
                prev_term(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, on_term)
    except ValueError:
        pass  # not the main thread: atexit + excepthook still cover us

"""Stage-latency tracing.

Parity target: the reference's pervasive `tracing::debug!` stage timers
around every pipeline hop (`rust/persia-core/src/forward.rs:591-593,665-669`,
`embedding_worker_service/mod.rs:909-938`) with the `LOG_LEVEL` env filter
(`rust/persia-core/src/lib.rs:463-465`).

Adds what the reference lacks: an in-memory ring of completed spans that can
be exported as a **chrome://tracing / Perfetto JSON** file, so a training-run
timeline (lookup → stage → device step → grad return) is viewable alongside
JAX's own profiler traces.

Usage::

    from persia_tpu.tracing import span, trace_export

    tracing.enable()          # or PERSIA_TRACE=1; off by default
    with span("lookup", slot="cat_0"):
        ...
    trace_export("/tmp/trace.json")

Spans nest via a thread-local stack; duration is also pushed to the metrics
Histogram ``persia_stage_duration_seconds`` when metrics are enabled.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Deque, Dict, Optional

from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.tracing")

_MAX_SPANS = int(os.environ.get("PERSIA_TRACE_BUFFER", "20000"))
_lock = threading.Lock()
_spans: Deque[Dict[str, Any]] = deque(maxlen=_MAX_SPANS)
_tls = threading.local()
# Opt-in, like the reference's LOG_LEVEL-gated stage timers: a span on a
# disabled tracer is a no-op, so hot paths pay ~nothing by default.
_enabled = os.environ.get("PERSIA_TRACE", "0") in ("1", "true")
_histogram = None


def _depth() -> int:
    return getattr(_tls, "depth", 0)


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def _get_histogram():
    global _histogram
    if _histogram is None:
        try:
            from persia_tpu.metrics import get_metrics

            _histogram = get_metrics().histogram(
                "persia_stage_duration_seconds", "per-stage latency"
            )
        except Exception:
            _histogram = False
    return _histogram


@contextmanager
def span(name: str, **attrs):
    """Time a pipeline stage; logs at debug level, records for export."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    ts_us = time.time() * 1e6
    _tls.depth = _depth() + 1
    try:
        yield
    finally:
        _tls.depth -= 1
        dur = time.perf_counter() - t0
        logger.debug("%s%s took %.3f ms %s", "  " * _depth(), name, dur * 1e3,
                     attrs if attrs else "")
        with _lock:
            _spans.append({
                "name": name,
                "ph": "X",
                "ts": ts_us,
                "dur": dur * 1e6,
                "pid": os.getpid(),
                "tid": threading.get_ident() % 2**31,
                "args": {k: str(v) for k, v in attrs.items()},
            })
        h = _get_histogram()
        if h:
            h.observe(dur, stage=name)


def timed(name: Optional[str] = None):
    """Decorator flavor of :func:`span`."""

    def deco(fn):
        label = name or fn.__qualname__

        def wrapper(*a, **kw):
            with span(label):
                return fn(*a, **kw)

        wrapper.__name__ = fn.__name__
        wrapper.__qualname__ = fn.__qualname__
        return wrapper

    return deco


def spans_snapshot() -> list:
    with _lock:
        return list(_spans)


def clear() -> None:
    with _lock:
        _spans.clear()


def trace_export(path: str) -> int:
    """Write the span ring as chrome://tracing JSON; returns span count."""
    events = spans_snapshot()
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    logger.info("exported %d trace events to %s", len(events), path)
    return len(events)

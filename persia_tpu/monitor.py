"""Distinct-id monitoring via HyperLogLog.

Parity target: the reference's ``EmbeddingMonitorInner``
(`/root/reference/rust/persia-embedding-server/src/monitor.rs:29-114`): a
HyperLogLog++ estimator of distinct ids per feature slot, sampled by
background threads and exported as the ``estimated_distinct_id`` gauge.

TPU-first differences: the estimator is vectorized numpy (one
``np.maximum.at`` per batch instead of a per-id loop), and instead of a
channel + sampler thread the worker calls ``observe`` inline — the cost is
O(n_ids) bit math, negligible next to the lookup itself.
"""

from __future__ import annotations

import threading
from typing import Dict

import numpy as np

from persia_tpu.metrics import get_metrics


class HyperLogLog:
    """Classic HLL with the standard small/large-range corrections.

    ``precision`` p → 2^p one-byte registers; relative error ≈ 1.04/sqrt(2^p)
    (p=14 → ~0.8%).
    """

    def __init__(self, precision: int = 14):
        if not 4 <= precision <= 18:
            raise ValueError("precision must be in [4, 18]")
        self.p = precision
        self.m = 1 << precision
        self.registers = np.zeros(self.m, dtype=np.uint8)
        if self.m >= 128:
            self.alpha = 0.7213 / (1.0 + 1.079 / self.m)
        elif self.m == 64:
            self.alpha = 0.709
        elif self.m == 32:
            self.alpha = 0.697
        else:
            self.alpha = 0.673

    @staticmethod
    def idx_rank(signs: np.ndarray, p: int):
        """(register index, rank) arrays for a u64 sign batch — the
        hash-side half of ``add``, exposed so a multi-slot caller can do the
        bit math ONCE over concatenated slots (`observe_many`)."""
        # imported lazily: embedding.worker imports this module at package
        # init, so a top-level import of embedding.hashing would be circular
        from persia_tpu.embedding.hashing import splitmix64

        h = splitmix64(np.asarray(signs, dtype=np.uint64))
        idx = (h >> np.uint64(64 - p)).astype(np.int64)
        rest = h << np.uint64(p)  # top (64-p) hash bits, left-aligned
        # rank = leading zeros of `rest` + 1, capped at 64-p+1 (rest == 0)
        rank = np.full(len(h), 64 - p + 1, dtype=np.uint8)
        nz = rest != 0
        if nz.any():
            # leading zeros via float64 exponent trick is lossy; use bit scan
            r = rest[nz]
            lz = np.zeros(len(r), dtype=np.uint8)
            for shift in (32, 16, 8, 4, 2, 1):
                mask = r < (np.uint64(1) << np.uint64(64 - shift))
                lz[mask] += shift
                r[mask] = r[mask] << np.uint64(shift)
            rank[nz] = lz + 1
        return idx, rank

    def add(self, signs: np.ndarray) -> None:
        """Fold a u64 sign array into the registers (vectorized)."""
        if len(signs) == 0:
            return
        idx, rank = self.idx_rank(signs, self.p)
        np.maximum.at(self.registers, idx, rank)

    def estimate(self) -> float:
        regs = self.registers.astype(np.float64)
        est = self.alpha * self.m * self.m / np.sum(np.exp2(-regs))
        if est <= 2.5 * self.m:
            zeros = int(np.count_nonzero(self.registers == 0))
            if zeros:
                return self.m * np.log(self.m / zeros)  # linear counting
        return float(est)

    def merge(self, other: "HyperLogLog") -> None:
        if other.p != self.p:
            raise ValueError("precision mismatch")
        np.maximum(self.registers, other.registers, out=self.registers)

    def to_bytes(self) -> bytes:
        return bytes([self.p]) + self.registers.tobytes()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "HyperLogLog":
        hll = cls(precision=raw[0])
        hll.registers = np.frombuffer(raw[1:], dtype=np.uint8).copy()
        return hll


class EmbeddingMonitor:
    """Per-slot distinct-id estimation (ref: monitor.rs:29-114). The
    ``estimated_distinct_id`` gauge is labeled by slot name."""

    # estimate() sweeps all 2^p registers; refresh the gauge only every
    # N observes so the hot path stays O(batch ids) (the reference keeps the
    # estimate off the hot path with a sampler thread, monitor.rs:56-87)
    _GAUGE_REFRESH_EVERY = 64

    def __init__(self, precision: int = 14):
        self.precision = precision
        self._hlls: Dict[str, HyperLogLog] = {}
        self._observes: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._gauge = get_metrics().gauge(
            "persia_tpu_estimated_distinct_id",
            "HyperLogLog estimate of distinct ids seen per feature slot",
        )

    def observe(self, slot_name: str, signs: np.ndarray) -> None:
        with self._lock:
            hll = self._hlls.get(slot_name)
            if hll is None:
                hll = self._hlls[slot_name] = HyperLogLog(self.precision)
            hll.add(signs)
            self._bump_locked(slot_name, hll)

    def _bump_locked(self, slot_name: str, hll: HyperLogLog) -> None:
        n = self._observes.get(slot_name, 0)
        self._observes[slot_name] = n + 1
        if n % self._GAUGE_REFRESH_EVERY == 0:
            self._gauge.set(hll.estimate(), feature=slot_name)

    def observe_many(self, slot_signs) -> None:
        """Fold several slots' sign batches in one call: the splitmix + rank
        bit math runs ONCE over the concatenation (the per-slot numpy call
        overhead was a measurable share of the single-core feeder budget);
        only the final register max is per-slot. Estimates are identical to
        per-slot ``observe`` calls."""
        slot_signs = [(name, s) for name, s in slot_signs if len(s)]
        if not slot_signs:
            return
        if len(slot_signs) == 1:
            self.observe(*slot_signs[0])
            return
        concat = np.concatenate([s for _, s in slot_signs])
        idx, rank = HyperLogLog.idx_rank(concat, self.precision)
        with self._lock:
            off = 0
            for name, s in slot_signs:
                hll = self._hlls.get(name)
                if hll is None:
                    hll = self._hlls[name] = HyperLogLog(self.precision)
                np.maximum.at(hll.registers, idx[off:off + len(s)],
                              rank[off:off + len(s)])
                off += len(s)
                self._bump_locked(name, hll)

    def estimated_distinct_id(self, slot_name: str) -> float:
        with self._lock:
            hll = self._hlls.get(slot_name)
            if hll is None:
                return 0.0
            est = hll.estimate()
            self._gauge.set(est, feature=slot_name)
            return est

"""Durable job-state layer: step-fenced manifests + exactly-once resume.

PR 3's chaos plane proved recovery from *PS-side* faults while the trainer
stays alive; this module closes the other half: the trainer (or its TPU
host) dies with ``kill -9`` and the whole hybrid job must resume mid-epoch
with no re-trained and no double-applied gradients. Three pieces:

- **Epoch manifests** (:class:`JobStateManager` / :class:`EpochWriter`):
  every snapshot fence captures the job's components — PS shards, dense
  params + optimizer state, cache/ring occupancy, loader cursor, RNG
  streams — under one monotonic ``job_epoch`` directory. Every file is
  written temp + fsync + atomic rename; the ``MANIFEST.json`` (which
  records a crc32 per component) is written LAST, so a crash mid-capture
  leaves a manifest-less directory the scanner skips; a ``LAST_GOOD``
  pointer is published after the manifest and older epochs remain as
  fallbacks if the newest turns out torn.

- **Journal ids** (:func:`make_journal_id`): each gradient batch applied
  to a PS shard between fences is tagged ``(job_epoch, step, shard)`` plus
  a crc32 of its payload. The PS keeps a bounded apply-journal (see
  ``native/ps.cpp`` ``ps_journal_*`` and ``EmbeddingStore.journal_*``), so
  a resuming trainer replaying steps past the fence can detect and skip
  updates the crashed run already applied — the double-apply window
  between "gradient sent" and "manifest committed" closes.

- **PS capture/restore** (:func:`capture_ps` / :func:`restore_ps`): dump
  every replica's internal shards into the manifest; restore rewinds the
  PS (clear + replay + journal clear + batch-state re-advance) to the
  fence, which is what makes a resumed run BIT-IDENTICAL to a fault-free
  replay (journal-only resume — ``restore_ps=False`` — keeps the PS's
  post-fence updates and guarantees exactly-once application instead).

Everything here is local-disk-first (temp + fsync + rename needs POSIX
semantics); remote checkpoint directories keep flowing through
:mod:`persia_tpu.checkpoint` / :mod:`persia_tpu.storage`.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from persia_tpu.analysis.crashcheck import reach
from persia_tpu.logger import get_default_logger

logger = get_default_logger("persia_tpu.jobstate")

MANIFEST_NAME = "MANIFEST.json"
LAST_GOOD = "LAST_GOOD"
_EPOCH_RE = re.compile(r"^epoch_(\d{8})$")

# sampled once, same rationale as persia_tpu.storage
_UMASK = os.umask(0)
os.umask(_UMASK)


class ManifestError(RuntimeError):
    """Job-state manifest problem (missing, torn, or inconsistent)."""


class CorruptManifestError(ManifestError):
    """A manifest component failed its crc32 check."""


# ------------------------------------------------------------ durable writes


def fsync_write_bytes(path: str, data: bytes) -> None:
    """Crash-durable atomic publish on local disk: temp file in the target
    directory + ``fsync`` + atomic rename + directory ``fsync``. A reader
    can never observe a partial file, and a power cut after return cannot
    lose the rename (the directory entry is durable too)."""
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_" + os.path.basename(path))
    try:
        os.fchmod(fd, 0o666 & ~_UMASK)
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def _fsync_dir(d: str) -> None:
    try:
        dfd = os.open(d, os.O_RDONLY)
    except OSError:
        return  # non-POSIX-dir-fsync filesystem — rename atomicity still holds
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)


# --------------------------------------------------------------- journal ids


def make_journal_id(job_epoch: int, step: int) -> int:
    """u64 apply-journal id for one trainer gradient batch: the epoch of
    the last committed manifest (24 bits), the global step (32 bits), and
    a low byte left for the router to mix the PS replica index in — so a
    resumed replay of step ``s`` under the SAME manifest epoch produces
    the exact ids the crashed run recorded, per shard."""
    return ((job_epoch & 0xFFFFFF) << 40) | ((step & 0xFFFFFFFF) << 8)


def journal_shard_id(base_id: int, replica_index: int) -> int:
    """Mix the PS replica index into a :func:`make_journal_id` base.
    Replica indices must stay below 0x80 — the 0x80 low-byte half belongs
    to the handoff/replication/scrub namespaces (the namespace prover in
    ``analysis/protocol.py`` certifies the split)."""
    if not 0 <= replica_index < 0x80:
        raise ValueError(
            f"replica_index {replica_index} outside the gradient-id namespace "
            "[0, 0x80) — the high low-byte half is reserved for handoff ids"
        )
    return base_id | replica_index


def handoff_journal_id(base_id: int, op_index: int) -> int:
    """Journal id for one reshard-handoff op (range import or delete):
    the 0x80 low-byte namespace — real PS replica indices stay < 0x80
    (:func:`journal_shard_id`), so a handoff at the same fence step can
    never collide with a gradient batch's per-replica id. ``op_index``
    numbers the ops of one reshard plan (< 128)."""
    return base_id | 0x80 | (op_index & 0x7F)


def replication_journal_id(job_epoch: int, step: int, op_index: int) -> int:
    """Journal id for one hot-sign read-replication copy (owner range
    export → replica journaled import; persia_tpu/autopilot/replicate).
    The low byte reuses the handoff's 0x80 namespace, so the STEP field's
    top bit (bit 31 — fence steps never reach 2^31) separates the two: a
    replication refresh and a reshard handoff at the SAME fence step
    dedupe independently on a shared destination replica. ``op_index``
    numbers the hot signs of one refresh round (< 128)."""
    return handoff_journal_id(
        make_journal_id(job_epoch, (step & 0x3FFFFFFF) | 0x80000000), op_index
    )


def abort_journal_id(job_epoch: int, step: int, op_index: int) -> int:
    """Journal id for one reshard-ABORT rollback op (the journaled range
    delete that releases a partially imported arc when a higher-priority
    intent preempts an in-flight reshard; persia_tpu/elastic.py). Step
    bits 30-31 are the namespace subspace tags — handoff ``00``, scrub
    ``01``, replication ``10`` — and the abort family takes the last
    combination, ``11``: a rollback delete at the same fence step dedupes
    independently of the forward import it is undoing, which is what
    makes the abort arm exactly-once under SIGKILL+resume. ``op_index``
    numbers the rollback ops of one abort (< 128)."""
    return handoff_journal_id(
        make_journal_id(job_epoch, (step & 0x3FFFFFFF) | 0xC0000000), op_index
    )


def payload_crc(*arrays) -> int:
    """crc32 of a gradient batch's payload arrays — the ``crc`` member of
    the journal's (step, shard, crc) record. A replay that produces a
    DIFFERENT payload under the same id is a divergence bug, and the
    journal turns it into a loud error instead of silent corruption."""
    c = 0
    for a in arrays:
        c = zlib.crc32(np.ascontiguousarray(a).view(np.uint8).data, c)
    return c & 0xFFFFFFFF


# --------------------------------------------------------------- RNG streams


def capture_rng_streams(
    generators: Optional[Dict[str, np.random.Generator]] = None,
) -> Dict:
    """JSON-able snapshot of the process's RNG streams: the global numpy
    MT19937 state plus any named ``np.random.Generator`` the caller threads
    through (e.g. a dataset's ``.rng``)."""
    kind, keys, pos, has_gauss, cached = np.random.get_state()
    out: Dict = {
        "numpy_global": [kind, np.asarray(keys).tolist(), int(pos),
                         int(has_gauss), float(cached)],
    }
    for name, g in (generators or {}).items():
        out[f"gen:{name}"] = g.bit_generator.state
    return out


def restore_rng_streams(
    state: Dict, generators: Optional[Dict[str, np.random.Generator]] = None,
) -> None:
    g = state.get("numpy_global")
    if g:
        kind, keys, pos, has_gauss, cached = g
        np.random.set_state(
            (kind, np.asarray(keys, dtype=np.uint32), int(pos),
             int(has_gauss), float(cached))
        )
    for name, gen in (generators or {}).items():
        s = state.get(f"gen:{name}")
        if s is not None:
            gen.bit_generator.state = s


# ------------------------------------------------------------------ manifest


class Manifest:
    """Read view of one committed epoch. ``meta`` is the MANIFEST.json
    content; blobs re-verify their recorded crc32 on every read."""

    def __init__(self, epoch_dir: str, meta: Dict):
        self.dir = epoch_dir
        self.meta = meta

    @property
    def job_epoch(self) -> int:
        return int(self.meta["job_epoch"])

    @property
    def step(self) -> int:
        return int(self.meta.get("step", 0))

    @property
    def components(self) -> Dict[str, Dict]:
        return self.meta.get("components", {})

    def has(self, name: str) -> bool:
        return name in self.components

    def read_blob(self, name: str) -> bytes:
        comp = self.components.get(name)
        if comp is None:
            raise ManifestError(f"manifest {self.dir} has no component {name!r}")
        path = os.path.join(self.dir, name)
        with open(path, "rb") as f:
            data = f.read()
        if len(data) != int(comp["bytes"]) or (
            zlib.crc32(data) & 0xFFFFFFFF
        ) != int(comp["crc32"]):
            raise CorruptManifestError(
                f"component {name!r} of {self.dir} is torn or corrupt "
                f"({len(data)} bytes, crc mismatch vs manifest record)"
            )
        return data

    def read_json(self, name: str):
        return json.loads(self.read_blob(name).decode())


class EpochWriter:
    """Accumulates one epoch's components, then atomically commits the
    manifest (written LAST — until it exists, the epoch is invisible)."""

    def __init__(self, root: str, job_epoch: int):
        self.root = root
        self.job_epoch = job_epoch
        self.dir = os.path.join(root, f"epoch_{job_epoch:08d}")
        self._components: Dict[str, Dict] = {}
        self._committed = False
        os.makedirs(self.dir, exist_ok=True)

    def add_blob(self, name: str, data: bytes) -> None:
        if self._committed:
            raise ManifestError("epoch already committed")
        reach("jobstate.commit.component")
        fsync_write_bytes(os.path.join(self.dir, name), data)
        self._components[name] = {
            "bytes": len(data), "crc32": zlib.crc32(data) & 0xFFFFFFFF,
        }

    def add_json(self, name: str, obj) -> None:
        self.add_blob(name, json.dumps(obj).encode())

    def commit(self, meta: Optional[Dict] = None) -> Manifest:
        """Publish: MANIFEST.json (atomic), then the LAST_GOOD pointer.
        A crash before the manifest write leaves an invisible directory; a
        crash between manifest and pointer is covered by the scanner's
        newest-first fallback."""
        manifest = dict(meta or {})
        manifest["job_epoch"] = self.job_epoch
        manifest["components"] = self._components
        manifest.setdefault("datetime", time.strftime("%Y-%m-%dT%H:%M:%S"))
        reach("jobstate.commit.manifest")
        fsync_write_bytes(
            os.path.join(self.dir, MANIFEST_NAME), json.dumps(manifest).encode()
        )
        reach("jobstate.commit.pointer")
        fsync_write_bytes(
            os.path.join(self.root, LAST_GOOD),
            json.dumps(
                {"job_epoch": self.job_epoch, "dir": os.path.basename(self.dir)}
            ).encode(),
        )
        self._committed = True
        return Manifest(self.dir, manifest)


class JobStateManager:
    """Owns a job-state root directory of epoch manifests."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)

    # ------------------------------------------------------------- epochs

    def _epoch_dirs(self) -> List[Tuple[int, str]]:
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for n in names:
            m = _EPOCH_RE.match(n)
            if m:
                out.append((int(m.group(1)), os.path.join(self.root, n)))
        return sorted(out)

    def begin_epoch(self) -> EpochWriter:
        dirs = self._epoch_dirs()
        nxt = (dirs[-1][0] + 1) if dirs else 1
        return EpochWriter(self.root, nxt)

    def _load_manifest(self, epoch_dir: str) -> Optional[Manifest]:
        """Load + verify one epoch's manifest: the JSON must parse and every
        declared component file must exist with its recorded size (full crc
        verification happens per blob on read — size check here keeps the
        scan cheap while still rejecting torn captures)."""
        path = os.path.join(epoch_dir, MANIFEST_NAME)
        try:
            with open(path, "rb") as f:
                meta = json.loads(f.read().decode())
        except (OSError, ValueError):
            return None
        if "job_epoch" not in meta or "components" not in meta:
            return None
        for name, comp in meta["components"].items():
            fpath = os.path.join(epoch_dir, name)
            try:
                if os.path.getsize(fpath) != int(comp["bytes"]):
                    return None
            except OSError:
                return None
        return Manifest(epoch_dir, meta)

    def latest(self) -> Optional[Manifest]:
        """The newest loadable manifest: the LAST_GOOD pointer first, then a
        newest-first scan (covers a crash between manifest and pointer, and
        a pointer referencing a since-corrupted epoch)."""
        tried = set()
        ptr = self._read_pointer()
        if ptr is not None:
            d = os.path.join(self.root, ptr)
            tried.add(d)
            m = self._load_manifest(d)
            if m is not None:
                return m
            logger.warning(
                "jobstate: LAST_GOOD points at %s but its manifest does not "
                "verify — falling back to the newest good epoch", ptr,
            )
        for _e, d in reversed(self._epoch_dirs()):
            if d in tried:
                continue
            m = self._load_manifest(d)
            if m is not None:
                return m
        return None

    def _read_pointer(self) -> Optional[str]:
        try:
            with open(os.path.join(self.root, LAST_GOOD), "rb") as f:
                return json.loads(f.read().decode()).get("dir")
        except (OSError, ValueError):
            return None

    def prune(self, keep: int = 2) -> int:
        """Remove all but the newest ``keep`` GOOD epochs (and never the one
        LAST_GOOD points at). Returns directories removed."""
        import shutil

        ptr = self._read_pointer()
        good = [
            (e, d) for e, d in self._epoch_dirs()
            if self._load_manifest(d) is not None
        ]
        removed = 0
        for e, d in good[:-keep] if keep > 0 else good:
            if ptr is not None and os.path.basename(d) == ptr:
                continue
            shutil.rmtree(d, ignore_errors=True)
            removed += 1
        return removed


# --------------------------------------------------------- trainer snapshots


def coerce_manager(job_state: Union[str, "JobStateManager"]) -> "JobStateManager":
    return job_state if isinstance(job_state, JobStateManager) else JobStateManager(job_state)


def snapshot_job(
    mgr: "JobStateManager",
    step: int,
    *,
    state_bytes: Optional[bytes] = None,
    replicas: Optional[Sequence] = None,
    batch_advances: Optional[Dict[int, int]] = None,
    components: Optional[Dict[str, object]] = None,
    meta: Optional[Dict] = None,
    generators: Optional[Dict[str, np.random.Generator]] = None,
    prune_keep: int = 2,
) -> Manifest:
    """One step-fenced snapshot: PS shards + dense/opt state + extra JSON
    components + RNG streams under a fresh epoch, committed atomically.
    The caller guarantees the fence invariant — nothing in flight (stream
    drained / loader flushed) when this runs."""
    writer = mgr.begin_epoch()
    m: Dict = {"step": int(step)}
    if replicas is not None:
        m.update(capture_ps(writer, replicas))
        if batch_advances:
            m["ps_batch_advances"] = {
                str(k): int(v) for k, v in batch_advances.items()
            }
    if state_bytes is not None:
        writer.add_blob("dense.state", state_bytes)
    for name, obj in (components or {}).items():
        writer.add_json(name, obj)
    writer.add_json("rng.json", capture_rng_streams(generators))
    m.update(meta or {})
    manifest = writer.commit(m)
    mgr.prune(prune_keep)
    return manifest


def resume_job(
    mgr: "JobStateManager",
    *,
    replicas: Optional[Sequence] = None,
    rewind_ps: bool = True,
    optimizer=None,
    generators: Optional[Dict[str, np.random.Generator]] = None,
) -> Tuple[Optional[Manifest], Dict]:
    """Load the newest good manifest and rebuild the fence state. Returns
    ``(manifest_or_None, recovery_info)`` — the info dict is what
    ``bench.py --chaos`` records as recovery metrics.

    ``rewind_ps=True`` restores the PS shards to the fence (clear + replay
    + journal clear): the replayed window then re-applies its gradients
    and the run is BIT-IDENTICAL to a fault-free replay. ``rewind_ps=False``
    keeps the PS's post-fence state; the replayed window's applies dedupe
    against the apply-journal instead (exactly-once, bounded staleness)."""
    t0 = time.monotonic()
    manifest = mgr.latest()
    if manifest is None:
        return None, {"resumed": False, "step": 0, "job_epoch": 0}
    adv = {
        int(k): int(v)
        for k, v in manifest.meta.get("ps_batch_advances", {}).items()
    }
    restored = 0
    if rewind_ps and replicas is not None and manifest.meta.get("ps_replicas"):
        restored = restore_ps(
            manifest, replicas, optimizer=optimizer, batch_advances=adv
        )
    if manifest.has("rng.json"):
        restore_rng_streams(manifest.read_json("rng.json"), generators)
    info = {
        "resumed": True,
        "step": manifest.step,
        "job_epoch": manifest.job_epoch,
        "ps_rewound": bool(rewind_ps),
        "ps_entries_restored": restored,
        "time_to_resume_s": round(time.monotonic() - t0, 4),
        "batch_advances": adv,
    }
    return manifest, info


# -------------------------------------------------------- PS capture/restore


def _shard_blob_name(replica: int, shard: int) -> str:
    return os.path.join("ps", f"replica_{replica}_shard_{shard}.emb")


def capture_ps(writer: EpochWriter, replicas: Sequence) -> Dict:
    """Dump every PS replica's internal shards into the epoch (the trainer-
    side sibling of ``ServiceCtx.snapshot_ps`` — replicas are anything with
    the store surface: in-process stores or ``StoreClient`` handles).
    Returns the topology meta recorded in the manifest."""
    shards_per = []
    total = 0
    for ri, rep in enumerate(replicas):
        n = int(rep.num_internal_shards)
        shards_per.append(n)
        for si in range(n):
            blob = rep.dump_shard(si)
            writer.add_blob(_shard_blob_name(ri, si), blob)
            total += len(blob)
    return {
        "ps_replicas": len(replicas),
        "ps_internal_shards": shards_per,
        "ps_bytes": total,
    }


def restore_ps(
    manifest: Manifest, replicas: Sequence,
    optimizer=None, batch_advances: Optional[Dict[int, int]] = None,
) -> int:
    """Rewind the PS tier to the manifest's fence: per replica clear, replay
    shard blobs, CLEAR THE APPLY-JOURNAL (post-fence ids must re-apply after
    a rewind — a stale journal entry would wrongly skip them), re-register
    the optimizer, and re-advance Adam batch state to the fence's counts.
    Returns entries restored."""
    meta = manifest.meta
    n_reps = int(meta.get("ps_replicas", 0))
    if n_reps != len(replicas):
        raise ManifestError(
            f"manifest captured {n_reps} PS replicas but the resuming job "
            f"has {len(replicas)} — re-shard via checkpoint.load_store instead"
        )
    shards_per = meta.get("ps_internal_shards", [])
    restored = 0
    for ri, rep in enumerate(replicas):
        rep.clear()
        if hasattr(rep, "journal_clear"):
            rep.journal_clear()
        if optimizer is not None:
            rep.register_optimizer(optimizer)
        for si in range(int(shards_per[ri])):
            restored += rep.load_shard_bytes(
                manifest.read_blob(_shard_blob_name(ri, si))
            )
        for group, count in (batch_advances or {}).items():
            for _ in range(int(count)):
                rep.advance_batch_state(int(group))
    return restored

"""Serving-plane load generator: batched vs unbatched QPS, latency
percentiles, cache hit rate, batch-size histogram, and a live rollover
under load — the committed evidence is ``BENCH_SERVING.json``.

Method: one model + one embedding worker serve two HTTP fronts in turn —

1. **unbatched**: the single-request :class:`InferenceServer` (one jitted
   forward + one PS lookup round per request), hammered by N client
   threads — the old serving plane's ceiling;
2. **batched**: :class:`ServingServer` with the micro-batcher, the
   hot-embedding cache, and the rollover watcher armed. The same N client
   threads; mid-window the "trainer" dumps a new checkpoint and the bench
   asserts the version swapped with ZERO failed requests.

Requests draw zipf-skewed signs (the production shape — the skew is what
the hot cache exploits) from a pre-serialized payload pool so client-side
cost stays flat across modes.

Run:  JAX_PLATFORMS=cpu python benchmarks/serving_bench.py
Env:  BENCH_SERVING_SECONDS (per phase, default 6), BENCH_SERVING_CLIENTS
      (default 32), BENCH_SERVING_ROWS (rows/request, default 8).
"""

import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_SLOTS = 8
EMB_DIM = 16
VOCAB = 100_000


def _build_ctx():
    import optax

    from persia_tpu.config import EmbeddingConfig, SlotConfig
    from persia_tpu.ctx import TrainCtx
    from persia_tpu.embedding.optim import Adagrad
    from persia_tpu.embedding.native_store import create_store
    from persia_tpu.embedding.worker import EmbeddingWorker
    from persia_tpu.models import DNN

    cfg = EmbeddingConfig(
        slots_config={f"cat_{i}": SlotConfig(dim=EMB_DIM) for i in range(N_SLOTS)},
        feature_index_prefix_bit=8,
    )
    # fleet-default backend: auto rides the native C++ store whenever it
    # builds — serving lookups then never drop into numpy (ISSUE 17)
    store = create_store(
        os.environ.get("PERSIA_STORE_BACKEND", "auto"),
        capacity=1 << 18, num_internal_shards=4,
        optimizer=Adagrad(lr=0.1).config, seed=7)
    worker = EmbeddingWorker(cfg, [store])
    ctx = TrainCtx(
        model=DNN(dense_mlp_size=32, sparse_mlp_size=128, hidden_sizes=(128, 64)),
        dense_optimizer=optax.adam(3e-3),
        embedding_optimizer=Adagrad(lr=0.1),
        worker=worker,
        embedding_config=cfg,
    )
    return ctx, cfg


def _train_batch(rng, rows):
    from persia_tpu.data import (
        IDTypeFeatureWithSingleID,
        Label,
        NonIDTypeFeature,
        PersiaBatch,
    )

    ids = [
        IDTypeFeatureWithSingleID(
            f"cat_{i}",
            ((rng.zipf(1.2, rows).astype(np.uint64) + np.uint64(i * 1000)) % VOCAB),
        )
        for i in range(N_SLOTS)
    ]
    return PersiaBatch(
        ids,
        non_id_type_features=[NonIDTypeFeature(
            rng.normal(size=(rows, 8)).astype(np.float32))],
        labels=[Label(rng.integers(0, 2, (rows, 1)).astype(np.float32))],
        requires_grad=True,
    )


def _request_pool(rng, rows, n_payloads):
    """Pre-serialized zipf-skewed inference payloads (requires_grad=False)."""
    from persia_tpu.data import (
        IDTypeFeatureWithSingleID,
        NonIDTypeFeature,
        PersiaBatch,
    )

    pool = []
    for _ in range(n_payloads):
        ids = [
            IDTypeFeatureWithSingleID(
                f"cat_{i}",
                ((rng.zipf(1.2, rows).astype(np.uint64) + np.uint64(i * 1000)) % VOCAB),
            )
            for i in range(N_SLOTS)
        ]
        b = PersiaBatch(
            ids,
            non_id_type_features=[NonIDTypeFeature(
                rng.normal(size=(rows, 8)).astype(np.float32))],
            requires_grad=False,
        )
        pool.append(b.to_bytes())
    return pool


def _client_proc_main():
    """Load-generator subprocess: the client fleet must NOT share the
    server's GIL, or the measurement caps at the harness's own Python cost
    instead of the serving plane's. Each process runs N threads of
    keep-alive clients; payloads regenerate deterministically from the
    seed. Prints one JSON line."""
    from persia_tpu.serving import InferenceClient

    addr = os.environ["BENCH_SERVING_ADDR"]
    seconds = float(os.environ["BENCH_SERVING_WINDOW"])
    n_threads = int(os.environ["BENCH_SERVING_THREADS"])
    rows = int(os.environ["BENCH_SERVING_ROWS_PP"])
    seed = int(os.environ["BENCH_SERVING_SEED"])
    pool = _request_pool(np.random.default_rng(seed), rows, 64)

    # warm this process's connections + the server before the window
    warm = InferenceClient(addr, timeout_s=30.0)
    warm.predict_bytes(pool[0])

    stop = time.monotonic() + seconds
    lock = threading.Lock()
    latencies, failures, count = [], [], [0]

    def client(idx):
        cli = InferenceClient(addr, timeout_s=30.0)
        i = idx
        while time.monotonic() < stop:
            raw = pool[i % len(pool)]
            i += 1
            t0 = time.perf_counter()
            try:
                cli.predict_bytes(raw)
            except Exception as e:  # noqa: BLE001 — any failure is a data point
                with lock:
                    failures.append(repr(e))
                continue
            dt = (time.perf_counter() - t0) * 1e3
            with lock:
                latencies.append(round(dt, 3))
                count[0] += 1

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n_threads)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    print(json.dumps({"count": count[0], "failures": failures,
                      "latencies": latencies, "elapsed": elapsed}))


def _hammer(addr, n_procs, threads_per_proc, rows, seconds, extra_s=60.0):
    """Run the client fleet as subprocesses. Returns
    (completed, failures, latencies_ms, elapsed)."""
    import subprocess

    procs = []
    for i in range(n_procs):
        env = dict(
            os.environ,
            BENCH_SERVING_ROLE="client",
            BENCH_SERVING_ADDR=addr,
            BENCH_SERVING_WINDOW=str(seconds),
            BENCH_SERVING_THREADS=str(threads_per_proc),
            BENCH_SERVING_ROWS_PP=str(rows),
            BENCH_SERVING_SEED=str(100 + i),
        )
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    count, failures, latencies, elapsed = 0, [], [], 0.0
    for p in procs:
        out, err = p.communicate(timeout=seconds + extra_s)
        if p.returncode != 0:
            raise RuntimeError(f"client proc failed rc={p.returncode}:\n{err[-2000:]}")
        d = json.loads(out.strip().splitlines()[-1])
        count += d["count"]
        failures += d["failures"]
        latencies += d["latencies"]
        elapsed = max(elapsed, d["elapsed"])
    return count, failures, latencies, elapsed


def _store_lookup_ns(store, n=4096, iters=20):
    """Direct store ns/lookup (no HTTP, no batcher): the native-vs-numpy
    delta the BENCH_SERVING record commits alongside the backend name."""
    rng = np.random.default_rng(3)
    signs = rng.integers(0, VOCAB, size=n, dtype=np.uint64)
    store.lookup(signs, EMB_DIM, False)  # warm
    t0 = time.perf_counter_ns()
    for _ in range(iters):
        store.lookup(signs, EMB_DIM, False)
    return (time.perf_counter_ns() - t0) / (iters * n)


def _pcts(latencies):
    if not latencies:
        return {}
    a = np.asarray(latencies)
    return {
        "p50_ms": round(float(np.percentile(a, 50)), 2),
        "p99_ms": round(float(np.percentile(a, 99)), 2),
        "mean_ms": round(float(a.mean()), 2),
    }


def _batch_histogram(hist):
    """Per-bucket (non-cumulative) counts from the batcher's rows histogram."""
    with hist._lock:
        counts = hist._counts.get((), [0] * len(hist.buckets))
        total = hist._totals.get((), 0)
    out = {}
    prev = 0
    for b, c in zip(hist.buckets, counts):
        out[f"le_{int(b)}"] = c - prev
        prev = c
    out["le_inf"] = total - prev
    return out


def main():
    import jax

    from persia_tpu.ctx import InferCtx
    from persia_tpu.serving import InferenceClient, InferenceServer, ServingServer
    from persia_tpu.serving.gateway import hop_latency_summary

    seconds = float(os.environ.get("BENCH_SERVING_SECONDS", "6"))
    n_clients = int(os.environ.get("BENCH_SERVING_CLIENTS", "32"))
    rows = int(os.environ.get("BENCH_SERVING_ROWS", "8"))
    threads_per_proc = 8
    n_procs = max(1, n_clients // threads_per_proc)

    rng = np.random.default_rng(0)
    ctx, cfg = _build_ctx()
    ckpt_dir = tempfile.mkdtemp(prefix="serving_bench_ckpt_")
    with ctx:
        for _ in range(8):
            ctx.train_step(_train_batch(rng, 256))
    ctx.dump_checkpoint(ckpt_dir)

    infer = InferCtx(model=ctx.model, state=ctx.state, worker=ctx.worker,
                     embedding_config=cfg)

    # warm the jit caches so neither phase pays first-compile inside its window
    from persia_tpu.data import PersiaBatch

    warm_pool = _request_pool(np.random.default_rng(100), rows, 2)
    infer.predict(PersiaBatch.from_bytes(warm_pool[0]))

    # ---- phase 1: unbatched single-request server (the old plane)
    plain = InferenceServer(infer, port=0).start()
    u_count, u_failures, u_lat, u_elapsed = _hammer(
        f"127.0.0.1:{plain.port}", n_procs, threads_per_proc, rows, seconds
    )
    plain.stop()
    unbatched_qps = u_count / u_elapsed

    # ---- phase 2: the serving plane (batched + cache + rollover armed)
    # max_batch = the in-flight fleet's rows: the window then closes the
    # moment every outstanding request has arrived instead of idling out
    # max_wait; max_wait is the straggler bound, not the steady-state wait
    srv = ServingServer(
        infer, port=0,
        max_batch=int(os.environ.get("BENCH_SERVING_MAX_BATCH",
                                     str(rows * n_clients))),
        max_wait_ms=float(os.environ.get("BENCH_SERVING_MAX_WAIT_MS", "20.0")),
        queue_depth=4 * n_clients,
        cache_rows=1 << 17,
        ckpt_dir=ckpt_dir,
        rollover_poll_s=0.1,
    ).start()
    v1 = srv.engine.version

    # trainer keeps going and publishes v2 mid-window: wait for the load to
    # actually arrive (client procs pay ~seconds of import/startup), then
    # publish while requests are in flight
    rollover_info = {}

    def publish_v2():
        deadline = time.monotonic() + seconds + 60
        base = srv.batcher._m_requests.get()
        while (srv.batcher._m_requests.get() - base < 50
               and time.monotonic() < deadline):
            time.sleep(0.05)
        with ctx:
            for _ in range(2):
                ctx.train_step(_train_batch(rng, 256))
        ctx.dump_checkpoint(ckpt_dir)
        deadline = time.monotonic() + 30
        while srv.engine.version == v1 and time.monotonic() < deadline:
            time.sleep(0.05)
        rollover_info["applied"] = srv.engine.version != v1
        rollover_info["from"], rollover_info["to"] = v1, srv.engine.version

    pub = threading.Thread(target=publish_v2)
    pub.start()
    b_count, b_failures, b_lat, b_elapsed = _hammer(
        f"127.0.0.1:{srv.port}", n_procs, threads_per_proc, rows, seconds
    )
    pub.join(timeout=120)
    batched_qps = b_count / b_elapsed
    cache_stats = srv.cache.stats()
    hist = _batch_histogram(srv.batcher._m_batch_rows)
    health = InferenceClient(f"127.0.0.1:{srv.port}").health()
    store_backend = srv.store_backend
    srv.stop()
    from persia_tpu.embedding.native_store import store_backend_name

    replica0 = ctx.worker.lookup_router._topo[0][0]
    assert store_backend == store_backend_name(replica0)
    store_ns = _store_lookup_ns(replica0)

    speedup = batched_qps / max(unbatched_qps, 1e-9)
    out = {
        "metric": "serving_plane_qps",
        "rows_per_request": rows,
        "clients": n_clients,
        "window_seconds": seconds,
        "unbatched": {
            "qps": round(unbatched_qps, 1),
            "rows_per_sec": round(unbatched_qps * rows, 1),
            "failures": len(u_failures),
            **_pcts(u_lat),
        },
        "batched": {
            "qps": round(batched_qps, 1),
            "rows_per_sec": round(batched_qps * rows, 1),
            "failures": len(b_failures),
            **_pcts(b_lat),
        },
        "speedup_batched_vs_unbatched": round(speedup, 2),
        "cache": {
            "hit_rate": round(cache_stats["hit_rate"], 4),
            "hits": int(cache_stats["hits"]),
            "misses": int(cache_stats["misses"]),
            "entries": int(cache_stats["entries"]),
        },
        "batch_rows_histogram": hist,
        "store_backend": store_backend,
        "store_ns_per_lookup": round(store_ns, 1),
        "hop_latency": hop_latency_summary(),
        "rollover": {
            **rollover_info,
            "failed_requests_during_window": len(b_failures),
            "zero_failed_requests": len(b_failures) == 0,
        },
        "server_health": health,
        "platform": jax.default_backend(),
    }
    print(json.dumps(out, indent=1))
    assert rollover_info.get("applied"), "rollover did not apply during the window"
    assert not b_failures, f"requests failed during rollover window: {b_failures[:3]}"
    # The bar measures the gateway's win over the UNBATCHED per-request
    # baseline. 5x was calibrated when that baseline ran the numpy store;
    # the round-17 native default makes the unbatched path ~35% faster,
    # which shrinks the RELATIVE win without the gateway getting any
    # slower — so the native-backend bar is scaled to the same absolute
    # batched-throughput discipline over the faster baseline.
    bar = 3.0 if store_backend == "native" else 5.0
    assert speedup >= bar, (
        f"batched/unbatched speedup {speedup:.2f} < {bar}x acceptance bar"
        f" (store_backend={store_backend})"
    )
    dst = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "BENCH_SERVING.json")
    with open(dst, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {dst}")


if __name__ == "__main__":
    if os.environ.get("BENCH_SERVING_ROLE") == "client":
        _client_proc_main()
    else:
        main()

"""Feeder-thread hot-loop profile: the host-side prepare stage, before vs
after the fused native feed (``cache_feed_batch``).

The cached tier's saturated throughput is bounded by the single-core
feeder thread (prep) or the device (dispatch), whichever is slower; this
bench isolates the FEEDER half, which needs no accelerator — it runs the
exact bench.py cached configuration's ``tier.prepare_batch`` on the same
zipf stream, against a warm directory with in-flight eviction spans in the
hazard ledger, and attributes time across the admit / ledger / PS-probe /
warm-fill / cold-fill stages via PERSIA_TRACE spans.

Two paths over identically seeded fresh tiers:
  python-orchestrated  admit_positions + full-width ledger query + nonzero
                       + arange insert (the pre-fusion hot loop)
  fused-native         cache_feed_batch (admit+probe+LUT+ledger in ONE
                       ctypes call) + candidate revalidation + insert_range

Prints one JSON dict; PROFILE_FEEDER.md commits the measured numbers.
"""

import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import bench  # noqa: E402

STEPS = int(os.environ.get("PROFILE_STEPS", "60"))
WARM = int(os.environ.get("PROFILE_WARM", "20"))


def _python_orchestrated_prepare(tier, batch, pmap, ring_alloc):
    """The pre-fusion feeder orchestration, reproduced exactly: separate
    admit call, then the stream's old gate (full-width ledger query +
    host-side nonzero compaction) under _admit_aux."""

    def gate(gname, miss_signs):
        hits, _tokens, srcs = pmap.query(miss_signs)
        if not hits:
            return None
        pos = np.nonzero(srcs >= 0)[0]
        return [(None, srcs[pos], pos)]

    return tier.prepare_batch(batch, hazard_gate=gate, ring_alloc=ring_alloc)


def run_path(fused: bool):
    from persia_tpu import tracing
    from persia_tpu.embedding.hbm_cache.directory import PendingSignMap

    ctx = bench._cached_tier_ctx()
    tier = ctx.tier
    make_batch = bench._zipf_batch_maker()
    pmap = PendingSignMap()
    ring_pos = [0]

    def ring_alloc(gname, kp):  # unbounded stub ring: feeder cost only
        p = ring_pos[0]
        ring_pos[0] += kp
        return p

    token = [0]

    def feed(batch):
        if fused:
            item = tier.prepare_batch(
                batch, ring_alloc=ring_alloc, pending_map=pmap
            )
        else:
            item = _python_orchestrated_prepare(tier, batch, pmap, ring_alloc)
        # in-flight eviction spans enter the ledger exactly like the stream
        for gn, (ev, k, rp) in item[6].items():
            token[0] += 1
            if fused:
                pmap.insert_range(ev[:k], rp, token[0])
            else:
                pmap.insert(
                    ev[:k], rp + np.arange(k, dtype=np.int64), token[0]
                )
        return item

    # batches pre-generated OUTSIDE the timed loop (bench.py does the
    # same): the zipf draw is data-pipeline cost, not feeder cost
    batches = [make_batch() for _ in range(WARM + STEPS)]
    for b in batches[:WARM]:  # fill the directory + ledger to steady state
        feed(b)

    tracing.enable()
    tracing.clear()
    t0 = time.perf_counter()
    for b in batches[WARM:]:
        feed(b)
    wall = time.perf_counter() - t0
    tracing.enable(False)

    agg = defaultdict(lambda: [0, 0.0])
    for ev in tracing.spans_snapshot():
        agg[ev["name"]][0] += 1
        agg[ev["name"]][1] += ev["dur"] / 1e3
    out = {
        "path": "fused-native" if fused else "python-orchestrated",
        "prep_ms_per_step": round(wall / STEPS * 1e3, 3),
        "feeder_ceiling_samples_per_sec": round(
            STEPS * bench.BATCH_SIZE / wall, 1
        ),
        "ledger_entries": len(pmap),
    }
    for name in sorted(agg):
        cnt, ms = agg[name]
        out[name] = {
            "per_step": round(cnt / STEPS, 2),
            "busy_ms_per_step": round(ms / STEPS, 3),
        }
    return out


def main():
    results = [run_path(fused=False), run_path(fused=True)]
    before, after = results
    summary = {
        "config": {
            "batch_size": bench.BATCH_SIZE,
            "n_slots": bench.N_SLOTS,
            "positions_per_step": bench.BATCH_SIZE * bench.N_SLOTS,
            "cache_rows": int(os.environ.get("BENCH_CACHE_ROWS", str(1 << 21))),
            "steps": STEPS,
        },
        "before": before,
        "after": after,
        "prep_speedup": round(
            before["prep_ms_per_step"] / after["prep_ms_per_step"], 3
        ),
    }
    print(json.dumps(summary, indent=1))


if __name__ == "__main__":
    main()

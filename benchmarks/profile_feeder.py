"""Feeder-thread hot-loop profile: the host-side prepare stage, before vs
after the fused native feed (``cache_feed_batch``).

The cached tier's saturated throughput is bounded by the single-core
feeder thread (prep) or the device (dispatch), whichever is slower; this
bench isolates the FEEDER half, which needs no accelerator — it runs the
exact bench.py cached configuration's ``tier.prepare_batch`` on the same
zipf stream, against a warm directory with in-flight eviction spans in the
hazard ledger, and attributes time across the admit / ledger / PS-probe /
warm-fill / cold-fill stages via PERSIA_TRACE spans.

Two paths over identically seeded fresh tiers:
  python-orchestrated  admit_positions + full-width ledger query + nonzero
                       + arange insert (the pre-fusion hot loop)
  fused-native         cache_feed_batch (admit+probe+LUT+ledger in ONE
                       ctypes call) + candidate revalidation + insert_range

Round 14 adds the tiering-ON sweep (``"tiering"`` key): the same stream
with an access profiler attached, comparing the legacy shape (unsharded
directory + standalone ``cache.sketch_observe`` call per group) against
the sharded feeder (admit directory partitioned by the group salt, sketch
observe FUSED into the admit walk) at feed_threads ∈ {1, 2, 4}. Each
sharded run also prints the per-shard busy table (native-measured walk ns
accumulated over the timed steps) — a skewed column means the partition
salt is fighting the key distribution.

Prints one JSON dict; PROFILE_FEEDER.md commits the measured numbers.
"""

import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import bench  # noqa: E402

STEPS = int(os.environ.get("PROFILE_STEPS", "60"))
WARM = int(os.environ.get("PROFILE_WARM", "20"))


def _python_orchestrated_prepare(tier, batch, pmap, ring_alloc):
    """The pre-fusion feeder orchestration, reproduced exactly: separate
    admit call, then the stream's old gate (full-width ledger query +
    host-side nonzero compaction) under _admit_aux."""

    def gate(gname, miss_signs):
        hits, _tokens, srcs = pmap.query(miss_signs)
        if not hits:
            return None
        pos = np.nonzero(srcs >= 0)[0]
        return [(None, srcs[pos], pos)]

    return tier.prepare_batch(batch, hazard_gate=gate, ring_alloc=ring_alloc)


def run_path(fused: bool):
    from persia_tpu import tracing
    from persia_tpu.embedding.hbm_cache.directory import PendingSignMap

    ctx = bench._cached_tier_ctx()
    tier = ctx.tier
    make_batch = bench._zipf_batch_maker()
    pmap = PendingSignMap()
    ring_pos = [0]

    def ring_alloc(gname, kp):  # unbounded stub ring: feeder cost only
        p = ring_pos[0]
        ring_pos[0] += kp
        return p

    token = [0]

    def feed(batch):
        if fused:
            item = tier.prepare_batch(
                batch, ring_alloc=ring_alloc, pending_map=pmap
            )
        else:
            item = _python_orchestrated_prepare(tier, batch, pmap, ring_alloc)
        # in-flight eviction spans enter the ledger exactly like the stream
        for gn, (ev, k, rp) in item[6].items():
            token[0] += 1
            if fused:
                pmap.insert_range(ev[:k], rp, token[0])
            else:
                pmap.insert(
                    ev[:k], rp + np.arange(k, dtype=np.int64), token[0]
                )
        return item

    # batches pre-generated OUTSIDE the timed loop (bench.py does the
    # same): the zipf draw is data-pipeline cost, not feeder cost
    batches = [make_batch() for _ in range(WARM + STEPS)]
    for b in batches[:WARM]:  # fill the directory + ledger to steady state
        feed(b)

    tracing.enable()
    tracing.clear()
    t0 = time.perf_counter()
    for b in batches[WARM:]:
        feed(b)
    wall = time.perf_counter() - t0
    tracing.enable(False)

    agg = defaultdict(lambda: [0, 0.0])
    for ev in tracing.spans_snapshot():
        agg[ev["name"]][0] += 1
        agg[ev["name"]][1] += ev["dur"] / 1e3
    out = {
        "path": "fused-native" if fused else "python-orchestrated",
        "prep_ms_per_step": round(wall / STEPS * 1e3, 3),
        "feeder_ceiling_samples_per_sec": round(
            STEPS * bench.BATCH_SIZE / wall, 1
        ),
        "ledger_entries": len(pmap),
    }
    for name in sorted(agg):
        cnt, ms = agg[name]
        out[name] = {
            "per_step": round(cnt / STEPS, 2),
            "busy_ms_per_step": round(ms / STEPS, 3),
        }
    return out


def run_tier_path(shards, threads, probe=None):
    """Tiering-ON feeder cost: admit walk + sketch observe per step.

    ``shards=None`` — unsharded directory + classic single-sketch
    profiler; the observe is a SEPARATE native call per group (the
    pre-round-14 shape, visible as the ``cache.sketch_observe`` span).
    ``shards=S`` — directory partitioned into S shards, profiler family
    matched to it (one sub-sketch per shard, routed by the group salt),
    observe fused into the admit walk across ``threads`` walkers; the
    per-shard walk times surface as ``feed.shard`` spans.
    ``probe`` (round 17) — 0 pins the scalar slot walk, 1 the SIMD tag
    probe + wave passes, None keeps the library default; applied to every
    group directory after construction (bit-identical either way, so the
    A/B swaps ONLY the probe layout).
    """
    from persia_tpu import tracing
    from persia_tpu.embedding.hbm_cache.directory import PendingSignMap
    from persia_tpu.embedding.tiering import AccessProfiler

    saved = {
        k: os.environ.get(k)
        for k in ("PERSIA_FEED_SHARDS", "PERSIA_FEED_THREADS")
    }
    os.environ["PERSIA_FEED_SHARDS"] = "0" if shards is None else str(shards)
    os.environ["PERSIA_FEED_THREADS"] = str(threads)
    try:
        ctx = bench._cached_tier_ctx()  # tier reads the env at construction
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    tier = ctx.tier
    if probe is not None:
        for d in tier.dirs.values():
            d.set_probe_mode(probe)
    # slot_order follows the tier's group order so each group's slots map
    # to a CONTIGUOUS profiler index run — the fuse gate's precondition
    tier.profiler = AccessProfiler(
        [s for g in tier.groups for s in g.slots],
        shards=tier.feed_shards,
        slot_salts=tier.profiler_slot_salts() if tier.feed_shards else None,
    )
    make_batch = bench._zipf_batch_maker()
    pmap = PendingSignMap()
    ring_pos = [0]

    def ring_alloc(gname, kp):
        p = ring_pos[0]
        ring_pos[0] += kp
        return p

    token = [0]

    def feed(batch):
        item = tier.prepare_batch(batch, ring_alloc=ring_alloc, pending_map=pmap)
        for gn, (ev, k, rp) in item[6].items():
            token[0] += 1
            pmap.insert_range(ev[:k], rp, token[0])

    batches = [make_batch() for _ in range(WARM + STEPS)]
    for b in batches[:WARM]:
        feed(b)

    n_shards = tier.feed_shards or 0
    shard_busy = np.zeros(n_shards, dtype=np.float64)
    tracing.enable()
    tracing.clear()
    t0 = time.perf_counter()
    for b in batches[WARM:]:
        feed(b)
        if n_shards:  # busy_ns is per-feed: accumulate each timed step
            for st in tier.feeder_shard_stats().values():
                shard_busy += np.asarray(st["busy_ns"], dtype=np.float64)
    wall = time.perf_counter() - t0
    tracing.enable(False)

    agg = defaultdict(lambda: [0, 0.0])
    for ev in tracing.spans_snapshot():
        agg[ev["name"]][0] += 1
        agg[ev["name"]][1] += ev["dur"] / 1e3
    out = {
        "path": (
            "sharded-fused-observe" if n_shards else "unsharded+standalone-observe"
        ),
        "feed_shards": tier.feed_shards,
        "feed_threads": tier.feed_threads,
        "prep_ms_per_step": round(wall / STEPS * 1e3, 3),
        "feeder_ceiling_samples_per_sec": round(
            STEPS * bench.BATCH_SIZE / wall, 1
        ),
    }
    if n_shards:
        out["shard_busy_ms_per_step"] = [
            round(v / STEPS / 1e6, 3) for v in shard_busy.tolist()
        ]
        # native-measured admit-walk cost per position: the number the
        # round-17 probe A/B compares (prep_ms also carries python-side
        # staging, which the probe layout does not touch)
        out["walk_ns_per_sign"] = round(
            float(shard_busy.sum())
            / STEPS / (bench.BATCH_SIZE * bench.N_SLOTS), 2,
        )
    for name in sorted(agg):
        cnt, ms = agg[name]
        out[name] = {
            "per_step": round(cnt / STEPS, 2),
            "busy_ms_per_step": round(ms / STEPS, 3),
        }
    return out


def main():
    results = [run_path(fused=False), run_path(fused=True)]
    before, after = results
    summary = {
        "config": {
            "batch_size": bench.BATCH_SIZE,
            "n_slots": bench.N_SLOTS,
            "positions_per_step": bench.BATCH_SIZE * bench.N_SLOTS,
            "cache_rows": int(os.environ.get("BENCH_CACHE_ROWS", str(1 << 21))),
            "steps": STEPS,
        },
        "before": before,
        "after": after,
        "prep_speedup": round(
            before["prep_ms_per_step"] / after["prep_ms_per_step"], 3
        ),
    }
    shards = int(os.environ.get("PROFILE_FEED_SHARDS", "8"))
    legacy = run_tier_path(shards=None, threads=1)
    sweep = {
        f"t{t}": run_tier_path(shards=shards, threads=t) for t in (1, 2, 4)
    }
    summary["tiering"] = {
        "legacy": legacy,
        "sharded": sweep,
        "fused_t1_vs_legacy": round(
            legacy["prep_ms_per_step"] / sweep["t1"]["prep_ms_per_step"], 3
        ),
        "t4_vs_t1": round(
            sweep["t1"]["prep_ms_per_step"] / sweep["t4"]["prep_ms_per_step"],
            3,
        ),
    }
    # round 17: scalar vs SIMD probe layout at t=1 (same stream, same
    # directories, only the probe walk differs — outputs bit-identical).
    # The two paths run INTERLEAVED over several rounds and the headline
    # is the MEDIAN of each side: single-pass A/Bs on this 1-core host
    # swing +-20% with scheduler luck, and interleaving keeps a slow
    # machine moment from landing entirely on one side.
    rounds = int(os.environ.get("PROFILE_PROBE_ROUNDS", "3"))
    scalar_rs, simd_rs = [], []
    for _ in range(rounds):
        scalar_rs.append(run_tier_path(shards=shards, threads=1, probe=0))
        simd_rs.append(run_tier_path(shards=shards, threads=1, probe=1))
    scalar_rs.sort(key=lambda r: r["walk_ns_per_sign"])
    simd_rs.sort(key=lambda r: r["walk_ns_per_sign"])
    scalar = scalar_rs[len(scalar_rs) // 2]
    simd = simd_rs[len(simd_rs) // 2]
    summary["probe17"] = {
        "rounds": rounds,
        "scalar_t1": scalar,
        "simd_t1": simd,
        "scalar_walk_ns_rounds": [r["walk_ns_per_sign"] for r in scalar_rs],
        "simd_walk_ns_rounds": [r["walk_ns_per_sign"] for r in simd_rs],
        "admit_walk_speedup": round(
            scalar["walk_ns_per_sign"] / simd["walk_ns_per_sign"], 3
        ),
        "prep_speedup": round(
            scalar["prep_ms_per_step"] / simd["prep_ms_per_step"], 3
        ),
    }
    print(json.dumps(summary, indent=1))


if __name__ == "__main__":
    main()

"""File-borne Criteo AUC artifact: same held-out AUC across tiers.

BASELINE.md's north star is samples/sec AT matched model quality. The
synthetic-stream quality gate (bench.py BENCH_MODE=quality) prices the
tiers in-process; this script closes the remaining gap to real data by
driving the EXAMPLE CLI (`examples/criteo_dlrm/train.py`) end-to-end over
an on-disk Criteo-FORMAT file — the byte-identical schema of
Criteo-Kaggle's train.txt (label \t 13 ints \t 26 hex cats), through the
real `persia_tpu.datasets.CriteoTSV` ingestion path — for the fused,
cached, and hybrid tiers, and asserts they reach the same held-out AUC.

This environment has zero egress, so the slice is GENERATED (seeded,
documented below) from the CriteoSynthetic hidden-ground-truth model and
round-tripped through the TSV text format exactly as real data would be;
a user with the actual Criteo-Kaggle file gets the identical measurement
via `--data-path /path/to/train.txt` per tier. Writes
BENCH_CRITEO_REAL.json {file sha256, rows, per-tier auc + samples/sec}.

Run from the repo root: python benchmarks/criteo_file_auc.py
Knobs: CRITEO_FILE_STEPS (train batches, default 40), CRITEO_FILE_EVAL
(held-out batches, default 8), CRITEO_FILE_BS (default 4096).
"""

import gzip
import hashlib
import json
import os
import re
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STEPS = int(os.environ.get("CRITEO_FILE_STEPS", "40"))
EVAL = int(os.environ.get("CRITEO_FILE_EVAL", "8"))
BS = int(os.environ.get("CRITEO_FILE_BS", "4096"))
SEED = 42


def generate_slice(path: str) -> str:
    """Seeded Criteo-format TSV.gz; returns its sha256. Deterministic in
    (SEED, STEPS, EVAL, BS) — anyone can regenerate and verify the hash."""
    from persia_tpu.testing import CRITEO_KAGGLE_VOCABS, CriteoSynthetic

    ds = CriteoSynthetic(
        num_samples=(STEPS + EVAL) * BS, vocab_sizes=CRITEO_KAGGLE_VOCABS,
        seed=SEED,
    )
    h = hashlib.sha256()
    with gzip.open(path, "wt") as f:
        for b in ds.batches(batch_size=BS):
            dense = np.asarray(b.non_id_type_features[0].data)
            labels = np.asarray(b.labels[0].data).reshape(-1)
            # the parser applies log1p(int); the synthetic stream is already
            # log1p-space, so emit round(expm1(d)) to round-trip
            ints = np.rint(np.expm1(np.maximum(dense, 0.0))).astype(np.int64)
            cats = [np.asarray(fi.data) for fi in b.id_type_features]
            for r in range(len(labels)):
                row = [str(int(labels[r]))]
                row += [str(int(v)) for v in ints[r]]
                row += [format(int(c[r]), "x") for c in cats]
                line = "\t".join(row) + "\n"
                f.write(line)
                h.update(line.encode())
    return h.hexdigest()


def run_tier(tier: str, data_path: str) -> dict:
    """One tier through the example CLI in its own subprocess (a d2h in one
    tier must not degrade the next tier's dispatch latency on a
    remote-attached chip)."""
    cmd = [
        sys.executable, os.path.join(REPO, "examples", "criteo_dlrm", "train.py"),
        "--tier", tier, "--data-path", data_path,
        "--steps", str(STEPS), "--eval-steps", str(EVAL),
        "--batch-size", str(BS),
    ]
    if tier == "cached":
        cmd += ["--wire", "bfloat16"]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO, env=env)
    if out.returncode != 0:
        raise RuntimeError(
            f"tier {tier} failed (rc={out.returncode}):\n"
            + "\n".join(out.stderr.strip().splitlines()[-12:])
        )
    m = re.search(
        r"test_auc=([\d.]+) throughput=([\d,]+) samples/sec", out.stdout
    )
    if not m:
        raise RuntimeError(f"tier {tier}: no result line in:\n{out.stdout[-2000:]}")
    return {
        "auc": float(m.group(1)),
        "samples_per_sec": float(m.group(2).replace(",", "")),
    }


def main():
    data_path = os.environ.get(
        "CRITEO_FILE_PATH", "/tmp/criteo_slice_%d_%d_%d.tsv.gz" % (STEPS, EVAL, BS)
    )
    if not os.path.exists(data_path):
        print(f"generating {data_path} ...", flush=True)
        sha = generate_slice(data_path)
        rows = (STEPS + EVAL) * BS
    else:
        opener = gzip.open if data_path.endswith(".gz") else open
        h = hashlib.sha256()
        rows = 0
        with opener(data_path, "rt") as f:
            for line in f:
                h.update(line.encode())
                rows += 1
        sha = h.hexdigest()
    out = {
        "file": os.path.basename(data_path),
        "file_sha256": sha,
        "rows": rows,
        "train_steps": STEPS,
        "eval_steps": EVAL,
        "batch_size": BS,
        "format": "criteo-kaggle train.txt schema (label, 13 ints, 26 hex cats)",
        "source": "seeded CriteoSynthetic ground-truth model (zero-egress env); "
                  "swap --data-path for the real file to reproduce on Criteo",
    }
    for tier in ("fused", "cached", "hybrid"):
        print(f"running tier {tier} ...", flush=True)
        out[tier] = run_tier(tier, data_path)
        print(tier, out[tier], flush=True)
    import jax

    out["platform"] = jax.default_backend()
    aucs = [out[t]["auc"] for t in ("fused", "cached", "hybrid")]
    out["auc_spread"] = round(max(aucs) - min(aucs), 6)
    # Looser than BENCH_QUALITY's 0.02: that gate compares tiers on an
    # IDENTICAL seeded stream with shared embedding init; here the fused
    # tier's dense-table init is jax.random while the PS tiers seed by
    # sign, so short budgets legitimately land a few AUC points apart.
    # This artifact certifies the end-to-end FILE path trains every tier
    # to comparable quality; raise CRITEO_FILE_STEPS to tighten.
    gate = float(os.environ.get("CRITEO_FILE_SPREAD_GATE", "0.05"))
    assert out["auc_spread"] < gate, f"tier AUC spread too wide: {out}"
    with open(os.path.join(REPO, "BENCH_CRITEO_REAL.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()

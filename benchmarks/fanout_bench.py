"""Remote-PS fan-out scaling microbench.

Spawns N real PS subprocesses (ServiceCtx) and times sign-routed
``checkout_entries``/``probe_entries``/``update`` through ShardedLookup for
N = 1, 2, 4 replicas. With the concurrent fan-out the per-call wall time
should stay ROUGHLY FLAT as replicas grow (each replica handles 1/N of the
signs, all in flight at once) — the serial fan-out it replaces grew the
wall time toward N x single-replica RTT. Prints one JSON line per N.
"""

import json
import os
import sys
import tempfile
import textwrap
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from persia_tpu.config import load_embedding_config  # noqa: E402
from persia_tpu.embedding.optim import Adagrad  # noqa: E402
from persia_tpu.embedding.worker import EmbeddingWorker  # noqa: E402
from persia_tpu.helper import ServiceCtx  # noqa: E402

N_SIGNS = int(os.environ.get("FANOUT_SIGNS", "16384"))
DIM = 16
ROUNDS = int(os.environ.get("FANOUT_ROUNDS", "20"))


def main():
    with tempfile.TemporaryDirectory() as td:
        cfg_path = os.path.join(td, "embedding_config.yml")
        with open(cfg_path, "w") as f:
            f.write(textwrap.dedent(
                """
                feature_index_prefix_bit: 8
                slots_config:
                  cat_0: {dim: 16}
                """
            ))
        cfg = load_embedding_config(cfg_path)
        rng = np.random.default_rng(0)
        signs = rng.choice(1 << 30, N_SIGNS, replace=False).astype(np.uint64)
        grads = rng.normal(size=(N_SIGNS, DIM)).astype(np.float32)

        for n in (1, 2, 4):
            with ServiceCtx(
                num_parameter_servers=n, num_embedding_workers=0,
                embedding_config_path=cfg_path, backend="auto", seed=3,
            ) as svc:
                ps = svc.ps_clients()
                for c in ps:
                    c.wait_ready()
                worker = EmbeddingWorker(cfg, ps)
                worker.register_optimizer(Adagrad(lr=0.05).config)
                router = worker.lookup_router
                router.checkout_entries(signs, DIM)  # admit + warm

                t0 = time.perf_counter()
                for _ in range(ROUNDS):
                    router.checkout_entries(signs, DIM)
                t_checkout = (time.perf_counter() - t0) / ROUNDS * 1e3

                t0 = time.perf_counter()
                for _ in range(ROUNDS):
                    router.probe_entries(signs, DIM)
                t_probe = (time.perf_counter() - t0) / ROUNDS * 1e3

                t0 = time.perf_counter()
                for _ in range(ROUNDS):
                    router.update(signs, grads, 0)
                t_update = (time.perf_counter() - t0) / ROUNDS * 1e3

                print(json.dumps({
                    "replicas": n,
                    "signs": N_SIGNS,
                    "checkout_ms": round(t_checkout, 2),
                    "probe_ms": round(t_probe, 2),
                    "update_ms": round(t_update, 2),
                }), flush=True)


if __name__ == "__main__":
    main()

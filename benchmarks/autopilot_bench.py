"""Autopilot chaos soak: the closed-loop controller vs a drifting workload.

Emits ONE JSON record (committed as BENCH_AUTOPILOT.json) answering the
question the autopilot exists for: when the traffic shape drifts out from
under a fixed fleet — zipf skew ramping up, a QPS spike, the hot set
rotating — does the closed loop notice and reshape the fleet, without
dropping a request, and does a SIGKILL mid-decision resume to the exact
bytes an uninterrupted run produces?

Four legs over the SAME seeded :class:`~persia_tpu.chaos.LoadSchedule`
(zipf ramp + traffic spike + hot-set rotation):

1. **soak** — a 4-shard in-process PS tier behind a ``ShardedLookup``
   ring, a real ``AccessProfiler`` sketch, and an :class:`Autopilot`
   driving all three actuators: the skew ramp breaches the target and the
   ring re-splits through the REAL elastic handoff engine (journaled
   range moves over the live stores), the rotating hot set refreshes the
   journaled read-replica map, and the QPS spike scales a serving fleet
   up then back down. Every step serves a read batch through the router;
   a single failed request fails the bench.
2. **tail skew** — the final rotation window's reads routed by the
   soak's final topology (ring + hot fan-out): empirical per-replica
   read skew must be <= the policy's 1.10 target. The control leg's
   number shows what the same drift costs a fleet nobody reshapes.
3. **SIGKILL resume** — two identical fleets plan the same replication
   round; one is killed mid-actuation (planned manifest committed, a
   PREFIX of the journaled copies applied), rebuilt, and resumed. The
   resumed fleet's full store bytes must equal the uninterrupted one's,
   with the prefix ops visibly deduped and a second resume a no-op.
4. **control** — the soak traffic with no controller: uniform ring, no
   replication, fleet pinned at its initial size. Reports the read skew
   and overloaded-step count the autopilot avoided.
"""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_SHARDS = int(os.environ.get("AUTOPILOT_SHARDS", "4"))
N_SLOTS = 4
STEPS = int(os.environ.get("AUTOPILOT_STEPS", "72"))
BATCH = int(os.environ.get("AUTOPILOT_BATCH", "2048"))
READ_BATCH = 512
FENCE_EVERY = 8
# the tail probes the FINAL workload shape: steps 64..71 sit inside the
# last rotation window (rotate=24 → steps 48-71 are one hot set), after
# the controller has had three fences (48, 56, 64) to settle on it
TAIL_STEPS = 8
DIM = 16
SEED = 7
SKETCH_TOPK = 64  # per-slot tracked heavy hitters (model fidelity)
LOAD_SPEC = os.environ.get(
    "AUTOPILOT_LOAD",
    "seed=7,vocab=131072,a0=1.05,a1=1.45,ramp=8:32,"
    "qps=120,spike=5x36:52,rotate=24,stride=7919",
)
SKEW_TARGET = 1.10


def build_fleet(tmp, opt):
    from persia_tpu.embedding.hashing import uniform_splits
    from persia_tpu.embedding.store import EmbeddingStore
    from persia_tpu.embedding.worker import ShardedLookup

    stores = [
        EmbeddingStore(capacity=1 << 20, num_internal_shards=4,
                       optimizer=opt, seed=SEED)
        for _ in range(N_SHARDS)
    ]
    router = ShardedLookup(stores, ring=uniform_splits(N_SHARDS))
    return stores, router


def read_counts(router, batches):
    """Empirical per-replica READ routing counts (hot fan-out applied)."""
    counts = np.zeros(len(router.replicas), dtype=np.int64)
    for signs in batches:
        for r, idx in router._partition_positions(signs, read=True):
            counts[r] += len(idx)
    return counts


def skew_of(counts) -> float:
    return float(counts.max() / counts.mean())


def drive_soak(sched, stores, router, tmp):
    from persia_tpu.autopilot import Autopilot, PolicyConfig, PolicyEngine
    from persia_tpu import elastic, jobstate

    reshard_js = os.path.join(tmp, "reshard")
    events = {"reshard": [], "replicate": [], "scale": []}
    fleet = {"replicas": 1}
    cur = {"step": 0}

    def do_reshard(n, splits, step):
        old = router.ring
        plan = elastic.plan_reshard(
            n, n, None if old is None else [int(x) for x in old],
            [int(x) for x in splits], jobstate.make_journal_id(1, step),
        )
        stats = elastic.execute_reshard(plan, stores, stores, reshard_js)
        router.swap_topology(stores, ring=splits)
        events["reshard"].append({
            "step": int(step), "moves": len(plan.moves),
            "moved_bytes": stats["moved_bytes"],
            "imports_applied": stats["imports_applied"],
        })
        return stats

    def scale_to(target):
        events["scale"].append(
            {"step": cur["step"], "from": fleet["replicas"],
             "to": int(target)}
        )
        fleet["replicas"] = int(target)
        return fleet["replicas"]

    def sensors():
        return {"qps": sched.qps(cur["step"]),
                "replicas": fleet["replicas"], "quarantined": 0}

    pilot = Autopilot(
        os.path.join(tmp, "decisions"),
        policy=PolicyEngine(PolicyConfig(
            skew_target=SKEW_TARGET, reshard_hysteresis=0.05,
            reshard_min_dwell=1, hot_fanout=N_SHARDS, hot_max_signs=32,
            hot_mass_frac=0.005, hot_min_dwell=0, qps_per_replica=200.0,
            scale_hysteresis=0.2, scale_min_dwell=1, scale_max_replicas=8,
        )),
        profiler=None,  # installed below (import cycle keeps this lazy)
        router=router,
        reshard=do_reshard,
        resume_reshard=lambda: None,
        scale_to=scale_to,
        serving_sensors=sensors,
    )
    from persia_tpu.embedding.tiering import AccessProfiler

    prof = AccessProfiler([f"cat_{i}" for i in range(N_SLOTS)],
                          topk=SKETCH_TOPK)
    pilot.profiler = prof

    requests = {"ok": 0, "failed": 0}
    t0 = time.time()
    for step in range(STEPS):
        cur["step"] = step
        for s in range(N_SLOTS):
            signs = sched.signs(step, BATCH, slot=s)
            router.lookup(signs, DIM, train=True)
            prof.observe_slot(f"cat_{s}", signs)
        # the serving plane: one read batch per step MUST come back whole
        reads = sched.signs(step, READ_BATCH, slot=step % N_SLOTS)
        try:
            vals = router.lookup(reads, DIM, train=False)
            assert vals.shape == (len(reads), DIM)
            requests["ok"] += 1
        except Exception:  # noqa: BLE001 — any failure is the metric
            requests["failed"] += 1
        pilot.on_tick(step)  # serving plane ticks every step
        if step > 0 and step % FENCE_EVERY == 0:
            # decay the sketch so it tracks the CURRENT shape (the same
            # half-life discipline the tiering loop runs the sketch under)
            prof.decay(0.5)
            pilot.on_fence(step)  # the drained-fence window
    soak_s = time.time() - t0
    hot = router.hot_read_state()
    return {
        "pilot": pilot,
        "events": events,
        "requests": requests,
        "soak_s": round(soak_s, 3),
        "suppressed_flaps": int(pilot.policy.suppressed),
        "rounds": int(pilot.rounds),
        "hot_signs_installed": 0 if hot is None else int(len(hot[0])),
        "final_serving_replicas": fleet["replicas"],
    }


def sigkill_resume_leg(sched, opt, tmp):
    """Two identical fleets, same replication round; one dies mid-copy
    (prefix of the journaled ops applied) and resumes. Bytes must match."""
    from persia_tpu.autopilot import (
        Autopilot, PolicyConfig, PolicyEngine, replicate_hot_signs,
    )
    from persia_tpu.autopilot.policy import Decision, KIND_REPLICATE
    from persia_tpu.embedding.tiering import AccessProfiler

    def materialize():
        stores, router = build_fleet(tmp, opt)
        prof = AccessProfiler([f"cat_{i}" for i in range(N_SLOTS)], topk=16)
        for step in range(8):
            for s in range(N_SLOTS):
                signs = sched.signs(step, BATCH, slot=s)
                router.lookup(signs, DIM, train=True)
                prof.observe_slot(f"cat_{s}", signs)
        return stores, router, prof

    pol = PolicyConfig(hot_fanout=3, hot_max_signs=16, hot_mass_frac=0.005,
                       hot_min_dwell=0)

    # leg A: uninterrupted drive
    stores_a, router_a, prof_a = materialize()
    pilot_a = Autopilot(os.path.join(tmp, "ap_a"),
                        policy=PolicyEngine(pol), profiler=prof_a,
                        router=router_a)
    applied_a = pilot_a.on_fence(8)
    decision_a = applied_a.get(KIND_REPLICATE)
    assert decision_a is not None, "replication round never fired"

    # leg B: same decision planned, killed after a PREFIX of the copies
    stores_b, router_b, prof_b = materialize()
    pilot_b = Autopilot(os.path.join(tmp, "ap_b"),
                        policy=PolicyEngine(pol), profiler=prof_b,
                        router=router_b)
    d = pilot_b.policy.decide_replicate(prof_b)
    assert d is not None
    pilot_b._commit("planned", d, step=8)
    epoch = pilot_b.mgr.latest().meta["job_epoch"]
    prefix = len(d.params["signs"]) // 2
    partial = replicate_hot_signs(
        router_b, d.params["signs"][:prefix], job_epoch=epoch, step=8,
        fanout=d.params["fanout"], salt=d.params["salt"],
    )
    # ...SIGKILL here: pilot_b is gone; a fresh controller takes the root
    pilot_b2 = Autopilot(os.path.join(tmp, "ap_b"),
                         policy=PolicyEngine(pol), profiler=prof_b,
                         router=router_b)
    resumed = pilot_b2.resume()
    assert resumed is not None
    again = pilot_b2.resume()  # exactly-once: nothing left pending

    bit_identical = all(
        stores_a[i].export_range(0, 0) == stores_b[i].export_range(0, 0)
        for i in range(N_SHARDS)
    )
    hot_a, hot_b = router_a.hot_read_state(), router_b.hot_read_state()
    maps_match = (
        hot_a is not None and hot_b is not None
        and np.array_equal(hot_a[0], hot_b[0])
        and hot_a[1:] == hot_b[1:]
    )
    return {
        "signs": len(d.params["signs"]),
        "killed_after_ops": int(partial["applied"]),
        "resume_deduped": int(resumed.get("deduped", 0)),
        "resume_applied": int(resumed.get("applied", 0)),
        "second_resume_noop": again is None,
        "bit_identical": bool(bit_identical and maps_match),
    }


def control_leg(sched, opt, tmp):
    """No controller: the same drift over a fleet nobody reshapes."""
    stores, router = build_fleet(tmp, opt)
    qps_capacity = 200.0  # matches the soak policy's qps_per_replica
    overloaded = 0
    for step in range(STEPS):
        for s in range(N_SLOTS):
            router.lookup(sched.signs(step, BATCH, slot=s), DIM, train=True)
        if sched.qps(step) > qps_capacity:  # pinned single replica
            overloaded += 1
    tail = [
        sched.signs(STEPS - TAIL_STEPS + t, READ_BATCH, slot=s)
        for t in range(TAIL_STEPS) for s in range(N_SLOTS)
    ]
    counts = read_counts(router, tail)
    return {
        "tail_read_skew": round(skew_of(counts), 4),
        "tail_read_counts": counts.tolist(),
        "overloaded_steps": int(overloaded),
        "serving_replicas": 1,
    }


def main() -> int:
    from persia_tpu.chaos import LoadSchedule, parse_load_spec
    from persia_tpu.embedding.optim import Adagrad

    sched = LoadSchedule(parse_load_spec(LOAD_SPEC))
    opt = Adagrad(lr=0.05).config
    tmp = tempfile.mkdtemp(prefix="autopilot_bench_")

    stores, router = build_fleet(tmp, opt)
    soak = drive_soak(sched, stores, router, tmp)
    pilot = soak.pop("pilot")
    events = soak.pop("events")

    # tail: the final rotation window's reads, routed by the final
    # topology (ring + hot fan-out) — the load the soak's last decisions
    # actually balanced
    tail = [
        sched.signs(STEPS - TAIL_STEPS + t, READ_BATCH, slot=s)
        for t in range(TAIL_STEPS) for s in range(N_SLOTS)
    ]
    counts = read_counts(router, tail)
    tail_skew = skew_of(counts)

    resume = sigkill_resume_leg(sched, opt, tmp)
    control = control_leg(sched, opt, tmp)

    rec = {
        "bench": "autopilot",
        "workload": {"spec": LOAD_SPEC, "slots": N_SLOTS, "steps": STEPS,
                     "batch": BATCH, "read_batch": READ_BATCH,
                     "fence_every": FENCE_EVERY, "n_shards": N_SHARDS},
        "soak": {
            **soak,
            "reshard_events": events["reshard"],
            "scale_events": events["scale"],
            "tail_read_skew": round(tail_skew, 4),
            "tail_read_counts": counts.tolist(),
        },
        "resume": resume,
        "control": control,
    }
    ok = True

    def check(cond, msg):
        nonlocal ok
        if not cond:
            print(f"FAIL: {msg}", file=sys.stderr)
            ok = False

    check(len(events["reshard"]) >= 1, "no autonomous reshard fired")
    check(len(events["scale"]) >= 1, "no serving scale event fired")
    check(soak["requests"]["failed"] == 0,
          f"{soak['requests']['failed']} serving requests failed")
    check(tail_skew <= SKEW_TARGET,
          f"post-reshard read skew {tail_skew:.4f} > {SKEW_TARGET}")
    check(resume["bit_identical"] and resume["second_resume_noop"],
          "SIGKILL resume was not bit-identical exactly-once")
    rec["pass"] = ok

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_AUTOPILOT.json")
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

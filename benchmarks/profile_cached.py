"""Attribute the cached-tier stream time across pipeline stages, in situ.

Runs the exact bench.py BENCH_MODE=cached shape through ``train_stream``
with PERSIA_TRACE spans enabled and aggregates per-stage busy time per
step. Because the stream is pipelined across three threads, per-thread
busy-ms/step > wall-ms/step is possible; the WALL time is bounded below by
the busiest serial stage chain (feeder: prep; stager: stage; main:
dispatch; writeback: wb_flush + psgrad).

No device->host fetch happens inside the measured window (fetch_final=False)
— a single d2h permanently degrades dispatch latency ~200x on a
remote-attached chip and poisons everything measured after it.

Prints one JSON dict: wall ms/step, samples/sec, and per-span
{count/step, busy ms/step}.
"""

import json
import os
import sys
import time
from collections import defaultdict

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BATCH_SIZE = 4096
N_DENSE = 13
N_SLOTS = 26
EMB_DIM = 16
VOCAB = 1_000_000
STEPS = int(os.environ.get("PROFILE_STEPS", "100"))
WARM = int(os.environ.get("PROFILE_WARM", "16"))


def _zipf_ids(rng, n, vocab, offset, a=1.2):
    raw = rng.zipf(a, n).astype(np.uint64)
    return (raw + np.uint64(offset)) % vocab


def main():
    import optax

    from persia_tpu import tracing
    from persia_tpu.config import EmbeddingConfig, SlotConfig
    from persia_tpu.data import (
        IDTypeFeatureWithSingleID,
        Label,
        NonIDTypeFeature,
        PersiaBatch,
    )
    from persia_tpu.embedding.hbm_cache import CachedTrainCtx
    from persia_tpu.embedding.native_store import create_store
    from persia_tpu.embedding.optim import Adagrad
    from persia_tpu.embedding.worker import EmbeddingWorker
    from persia_tpu.models import DLRM

    cfg = EmbeddingConfig(
        slots_config={f"cat_{i}": SlotConfig(dim=EMB_DIM) for i in range(N_SLOTS)},
        feature_index_prefix_bit=8,
    )
    store = create_store(
        "auto", capacity=1 << 25, num_internal_shards=64,
        optimizer=Adagrad(lr=0.05).config, seed=1,
    )
    worker = EmbeddingWorker(cfg, [store], num_threads=16)
    model = DLRM(embedding_dim=EMB_DIM, bottom_mlp=(256, 64, EMB_DIM), top_mlp=(512, 256))
    ctx = CachedTrainCtx(
        model=model, dense_optimizer=optax.adam(1e-3),
        embedding_optimizer=Adagrad(lr=0.05), worker=worker,
        embedding_config=cfg, cache_rows=1 << 21,
        wb_wire_dtype="bfloat16",
        aux_wire_dtype=os.environ.get("BENCH_AUX_WIRE", "bfloat16"),
        admit_touches=int(os.environ.get("BENCH_ADMIT_TOUCHES", "2")),
    ).__enter__()

    rng = np.random.default_rng(0)
    slot_offsets = rng.integers(0, VOCAB, N_SLOTS, dtype=np.uint64)

    def make_batch():
        ids = [
            IDTypeFeatureWithSingleID(
                f"cat_{i}", _zipf_ids(rng, BATCH_SIZE, VOCAB, slot_offsets[i])
            )
            for i in range(N_SLOTS)
        ]
        return PersiaBatch(
            ids,
            non_id_type_features=[
                NonIDTypeFeature(rng.normal(size=(BATCH_SIZE, N_DENSE)).astype(np.float32))
            ],
            labels=[Label(rng.integers(0, 2, (BATCH_SIZE, 1)).astype(np.float32))],
            requires_grad=True,
        )

    batches = [make_batch() for _ in range(WARM + STEPS)]
    ctx.train_stream(batches[:WARM], fetch_final=False)  # warm cache + compile

    tracing.enable()
    tracing.clear()
    t0 = time.perf_counter()
    ctx.train_stream(batches[WARM:], fetch_final=False)
    wall = time.perf_counter() - t0
    tracing.enable(False)

    agg = defaultdict(lambda: [0, 0.0])
    for ev in tracing.spans_snapshot():
        agg[ev["name"]][0] += 1
        agg[ev["name"]][1] += ev["dur"] / 1e3  # us -> ms

    out = {
        "wall_ms_per_step": round(wall / STEPS * 1e3, 3),
        "samples_per_sec": round(STEPS * BATCH_SIZE / wall, 1),
    }
    for name in sorted(agg):
        cnt, ms = agg[name]
        out[name] = {
            "per_step": round(cnt / STEPS, 2),
            "busy_ms_per_step": round(ms / STEPS, 3),
        }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()

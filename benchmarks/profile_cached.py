"""Attribute the cached-tier stream time across pipeline stages, in situ.

Runs the exact bench.py BENCH_MODE=cached configuration (ctx + zipf batch
stream come from bench.py itself — no copy to drift) through
``train_stream`` with PERSIA_TRACE spans enabled and aggregates per-stage
busy time per step. Because the stream is pipelined across three threads,
per-thread busy-ms/step > wall-ms/step is possible; the WALL time is
bounded below by the busiest serial stage chain (feeder: prep; stager:
stage; main: dispatch; writeback: wb_flush + psgrad).

No device->host fetch happens inside the measured window (fetch_final=False)
— a single d2h permanently degrades dispatch latency ~200x on a
remote-attached chip and poisons everything measured after it.

Prints one JSON dict: wall ms/step, samples/sec, and per-span
{count/step, busy ms/step}.
"""

import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

STEPS = int(os.environ.get("PROFILE_STEPS", "100"))
WARM = int(os.environ.get("PROFILE_WARM", "16"))


def main():
    from persia_tpu import tracing

    ctx = bench._cached_tier_ctx()
    make_batch = bench._zipf_batch_maker()
    batches = [make_batch() for _ in range(WARM + STEPS)]
    ctx.train_stream(batches[:WARM], fetch_final=False)  # warm cache + compile

    tracing.enable()
    tracing.clear()
    t0 = time.perf_counter()
    ctx.train_stream(batches[WARM:], fetch_final=False)
    wall = time.perf_counter() - t0
    tracing.enable(False)

    agg = defaultdict(lambda: [0, 0.0])
    for ev in tracing.spans_snapshot():
        agg[ev["name"]][0] += 1
        agg[ev["name"]][1] += ev["dur"] / 1e3  # us -> ms

    out = {
        "wall_ms_per_step": round(wall / STEPS * 1e3, 3),
        "samples_per_sec": round(STEPS * bench.BATCH_SIZE / wall, 1),
    }
    for name in sorted(agg):
        cnt, ms = agg[name]
        out[name] = {
            "per_step": round(cnt / STEPS, 2),
            "busy_ms_per_step": round(ms / STEPS, 3),
        }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()

"""Dense-plane sync quality/memory bench → BENCH_r08.json.

Prices the ISSUE-13 dense plane end to end on whatever host runs it:

- quality: 20-step CriteoSynthetic runs (same hidden-ground-truth stream,
  seeds 5/7, as bench.py's quality-at-throughput gate) per dense sync mode,
  scored by held-out AUC — the block-scaled int8 ring must sit within 0.02
  AUC of the f32 allreduce or the byte saving is fiction.
- memory: measured per-replica optimizer-state bytes, replicated vs
  ZeRO-style sharded (``per_replica_opt_state_bytes`` over real
  addressable shards — not a model).
- dp-invariance: the SAME seeded global-batch stream trained under
  f32-sharded at n=8 (in-process) and n=32/64 (subprocess re-exec with
  ``XLA_FLAGS=--xla_force_host_platform_device_count=N``) must land the
  same dense params to a derived bound (adam |update| <= lr/step, so
  reduction-order noise across n is capped at steps*lr in the degenerate
  worst case; measured drift is recorded next to the bound).
- wire: the ``dense_sync_wire_bytes`` rows (single source of truth shared
  with bench.py records, WIRE_BENCH.json and the telemetry counter).

Usage: ``python benchmarks/dense_sync_bench.py [--write]`` (--write
publishes BENCH_r08.json at the repo root; default prints JSON to stdout).
The id slots feed the dense tower through a FIXED seeded hash-projection
table per slot (numpy host-side, not learnable) — identical for every
mode, so mode-vs-mode AUC deltas isolate the sync arithmetic; absolute
AUCs are lower than the full learnable-embedding tiers and are not
comparable to bench.py's quality numbers.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

# run as a script (python benchmarks/dense_sync_bench.py) sys.path[0] is
# benchmarks/ — the repo root must be importable for persia_tpu
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

N_DEV = int(os.environ.get("DENSE_SYNC_BENCH_DEVICES", "8"))
os.environ.setdefault(
    "XLA_FLAGS", f"--xla_force_host_platform_device_count={N_DEV}"
)

import numpy as np  # noqa: E402

BATCH = 64          # divisible by every n in (8, 32, 64)
STEPS = 20
EVAL_BATCHES = 16   # wide held-out tail: AUC estimation noise must sit far
                    # below the 0.02 mode-spread gate at this step budget
NOISE = 0.5         # CriteoSynthetic label noise; default 1.0 leaves a
                    # 20-step model near chance where AUC is all variance
DIM = 16
HASH_ROWS = 512
LR = 1e-2


def _hash_tables(n_slots):
    rng = np.random.default_rng(123)
    return [
        rng.normal(size=(HASH_ROWS, DIM)).astype(np.float32) * 0.1
        for _ in range(n_slots)
    ]


def _to_pooled(pb, tables):
    """PersiaBatch → the grad_sync host-batch form: each single-id slot's
    id indexes its fixed hash table (id % rows) → one (B, DIM) pooled
    feature per slot."""
    emb = []
    for f, tbl in zip(pb.id_type_features, tables):
        flat, _ = f.flat_counts()
        emb.append({"pooled": tbl[np.asarray(flat, np.uint64) % HASH_ROWS]})
    return {
        "dense": [np.asarray(d.data, np.float32) for d in pb.non_id_type_features],
        "labels": [np.asarray(l.data, np.float32) for l in pb.labels],
        "emb": emb,
    }


def _stream(steps, eval_batches):
    from persia_tpu.testing.datasets import CriteoSynthetic

    n_slots = 26
    ds = CriteoSynthetic(
        num_samples=(steps + eval_batches) * BATCH,
        vocab_sizes=[100_000] * n_slots,
        noise=NOISE, seed=5, task_seed=7,
    )
    tables = _hash_tables(n_slots)
    all_b = [_to_pooled(pb, tables) for pb in ds.batches(BATCH)]
    return all_b[:steps], all_b[steps:]


def _model():
    import jax.numpy as jnp

    from persia_tpu.models import DLRM

    return DLRM(
        embedding_dim=DIM, bottom_mlp=(64, DIM), top_mlp=(64,),
        compute_dtype=jnp.float32,
    )


def _build(mode, mesh, model, opt, sample):
    """(state, step) for a dense sync mode, placed for the mesh."""
    import jax

    from persia_tpu.parallel.grad_sync import (
        BlockInt8Ring,
        build_sync_train_step,
        init_sync_opt_state,
        place_sync_state,
        sync_mode_algorithm,
    )
    from persia_tpu.parallel.train_step import init_train_state, replicate_state

    algorithm, sharded = sync_mode_algorithm(mode)
    state = init_train_state(model, jax.random.PRNGKey(0), sample, opt)
    wrapped = sharded or isinstance(algorithm, BlockInt8Ring)
    if wrapped:
        state = state.replace(
            opt_state=init_sync_opt_state(state.params, opt, mesh, algorithm,
                                          sharded_update=sharded)
        )
        state = place_sync_state(state, mesh, algorithm, sharded_update=sharded)
    else:
        state = replicate_state(state, mesh)
    step = build_sync_train_step(model, opt, mesh, algorithm,
                                 sharded_update=sharded)
    return state, step


def _flat_params(state):
    import jax

    return np.concatenate(
        [np.asarray(p, np.float64).reshape(-1)
         for p in jax.tree.leaves(state.params)]
    )


def _train(mode, train_b, mesh, model, opt):
    from persia_tpu.parallel.train_step import (
        shard_device_batch,
        unpack_step_header,
    )

    from persia_tpu.parallel.grad_sync import init_residual

    state, step = _build(mode, mesh, model, opt, train_b[0])
    residual = init_residual(state.params) if mode == "bytegrad" else None
    losses = []
    for hb in train_b:
        if residual is not None:
            state, (header, _), residual = step(
                state, shard_device_batch(hb, mesh), residual
            )
        else:
            state, (header, _) = step(state, shard_device_batch(hb, mesh))
        loss, _ = unpack_step_header(np.asarray(header), hb)
        losses.append(float(loss))
    return state, losses


def _eval_auc(state, eval_b, model):
    import jax

    from persia_tpu.parallel.train_step import (
        _embedding_model_inputs,
        _split_emb,
    )
    from persia_tpu.testing.synthetic import roc_auc

    @jax.jit
    def fwd(params, dense, emb_diff):
        model_emb = _embedding_model_inputs(emb_diff, emb_static)
        return model.apply({"params": params}, dense, model_emb, train=False)

    preds, labels = [], []
    for hb in eval_b:
        emb_diff, emb_static = _split_emb(hb["emb"])
        logits = fwd(state.params, hb["dense"], emb_diff)
        preds.append(1.0 / (1.0 + np.exp(-np.asarray(logits).reshape(-1))))
        labels.append(np.concatenate([l.reshape(-1) for l in hb["labels"]]))
    return float(roc_auc(np.concatenate(labels), np.concatenate(preds)))


def bench_quality():
    """Held-out AUC per dense sync mode on the shared learnable stream.
    Gate: every quantized/sharded mode within 0.02 AUC of f32."""
    import optax

    from persia_tpu.parallel.mesh import data_parallel_mesh

    mesh = data_parallel_mesh()
    model = _model()
    train_b, eval_b = _stream(STEPS, EVAL_BATCHES)
    out = {}
    for mode in ("f32", "bytegrad", "block-int8-ring",
                 "f32-sharded", "block-int8-ring-sharded"):
        state, losses = _train(mode, train_b, mesh, model, optax.adam(LR))
        out[mode] = {
            "auc": round(_eval_auc(state, eval_b, model), 6),
            "loss_first5": round(float(np.mean(losses[:5])), 4),
            "loss_last5": round(float(np.mean(losses[-5:])), 4),
        }
        assert np.isfinite(losses).all(), (mode, losses)
        assert out[mode]["loss_last5"] < out[mode]["loss_first5"], (mode, losses)
    spread = max(
        abs(out[m]["auc"] - out["f32"]["auc"]) for m in out if m != "f32"
    )
    out["auc_spread_vs_f32"] = round(spread, 6)
    assert spread < 0.02, f"quality gate: AUC spread {spread} >= 0.02: {out}"
    return out


def bench_opt_memory():
    """Measured per-replica optimizer-state bytes, replicated vs sharded
    (real addressable-shard nbytes, adam moments on the bench model)."""
    import optax

    from persia_tpu.parallel.grad_sync import per_replica_opt_state_bytes
    from persia_tpu.parallel.mesh import data_parallel_mesh

    mesh = data_parallel_mesh()
    n = mesh.shape["data"]
    model = _model()
    train_b, _ = _stream(1, 0)
    opt = optax.adam(LR)
    rep, _ = _build("f32", mesh, model, opt, train_b[0])
    shd, _ = _build("f32-sharded", mesh, model, opt, train_b[0])
    rep_b = per_replica_opt_state_bytes(rep.opt_state)
    shd_b = per_replica_opt_state_bytes(shd.opt_state["opt"])
    out = {
        "n": n,
        "replicated_bytes_per_replica": rep_b,
        "sharded_bytes_per_replica": shd_b,
        "ratio": round(shd_b / rep_b, 4),
    }
    # chunk padding + optax's replicated scalar count keep the ratio a bit
    # above the ideal 1/n; 1.35/n is the honest measured envelope
    assert shd_b < rep_b * 1.35 / n, out
    return out


def _dp_child_params(n, path):
    """Re-exec this module under a forced n-device CPU topology; the child
    trains f32-sharded on the fixed stream and writes its flat params."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
    env["DENSE_SYNC_BENCH_DEVICES"] = str(n)
    env["JAX_PLATFORMS"] = "cpu"
    subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--dp-child", path],
        check=True, env=env, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    return np.load(path)


def _dp_run_here():
    import optax

    from persia_tpu.parallel.mesh import data_parallel_mesh

    train_b, _ = _stream(STEPS, 0)
    state, losses = _train(
        "f32-sharded", train_b, data_parallel_mesh(), _model(), optax.adam(LR)
    )
    return _flat_params(state), losses


def bench_dp_invariance():
    """f32-sharded final dense params at n=8 vs n=32 vs n=64 on the SAME
    seeded global-batch stream. Derived bound (__graft_entry__.py idiom):
    adam caps |update| at lr per step, so reduction-order divergence across
    n is <= STEPS*LR = 0.2 in the degenerate worst case; the gate is 1.5x
    the measured 8-virtual-device CPU drift envelope from the n=1-vs-n=8
    oracle (5.22e-3), far inside that bound."""
    p8, losses = _dp_run_here()
    out = {
        "steps": STEPS,
        "derived_worst_case_bound": STEPS * LR,
        "gate_atol": 1.5 * 5.22e-3,
        "loss_first5": round(float(np.mean(losses[:5])), 4),
        "loss_last5": round(float(np.mean(losses[-5:])), 4),
    }
    for n in (32, 64):
        with tempfile.NamedTemporaryFile(suffix=".npy", delete=False) as f:
            path = f.name
        try:
            pn = _dp_child_params(n, path)
        finally:
            os.unlink(path)
        drift = float(np.abs(p8 - pn).max())
        out[f"max_param_drift_n8_vs_n{n}"] = round(drift, 8)
        assert drift <= out["gate_atol"], (n, drift, out)
    return out


def bench_wire():
    import jax
    import optax

    from persia_tpu.parallel.grad_sync import (
        DENSE_SYNC_MODES,
        dense_param_count,
        dense_sync_wire_bytes,
    )
    from persia_tpu.parallel.train_step import init_train_state

    train_b, _ = _stream(1, 0)
    state = init_train_state(
        _model(), jax.random.PRNGKey(0), train_b[0], optax.sgd(0.1)
    )
    p = dense_param_count(state.params)
    n = N_DEV
    rows = {
        m: dense_sync_wire_bytes(m, p, n) for m in DENSE_SYNC_MODES
    }
    f32 = rows["f32"]
    assert f32 / rows["block-int8-ring"] >= 3.5, rows
    return {
        "dense_params": p, "n": n,
        "bytes_per_step_per_replica": rows,
        "block_int8_ring_vs_f32": round(f32 / rows["block-int8-ring"], 2),
    }


def main():
    if len(sys.argv) > 2 and sys.argv[1] == "--dp-child":
        p, _ = _dp_run_here()
        np.save(sys.argv[2], p)
        return

    import jax

    from bench import _link_class, bench_link

    link = bench_link()
    out = {
        "round": 8,
        "note": (
            "No TPU was attached to the round-8 build host (CPU, JAX cpu "
            "backend) — per the r06 precedent this artifact records the "
            "post-change bench run on that host with link evidence; "
            "CPU-host numbers are NOT chip numbers. This round lands the "
            "byte-optimal dense plane: block-scaled int8 ring allreduce "
            "(per-block scales + on-device error feedback inside each ring "
            "hop) and the ZeRO-style cross-replica sharded optimizer "
            "update. What a CPU host CAN prove is recorded here: the "
            "quality gate (held-out AUC per sync mode on the shared "
            "CriteoSynthetic stream, spread vs f32 < 0.02), the measured "
            "per-replica optimizer-state bytes (~1/n sharded, real "
            "addressable-shard sizes), dp-invariance of the sharded update "
            "at n=8/32/64 virtual devices, and the wire model "
            "(3.94x fewer dense-sync bytes/step for the int8 ring vs f32, "
            "the same dense_sync_wire_bytes pricing WIRE_BENCH.json and "
            "the persia_tpu_dense_wire_bytes counter use). What it CANNOT "
            "prove is the wall-clock win — on one CPU host all 'replicas' "
            "share the same memory bus, so no bytes cross a real wire; "
            "pricing the step-time claim needs a chip window: loop "
            "`python benchmarks/dense_sync_bench.py` until "
            "link_class=good on a TPU-attached host."
        ),
        "platform": jax.default_backend(),
        "link_class": _link_class(link),
        "link": link,
        "quality": bench_quality(),
        "opt_state_memory": bench_opt_memory(),
        "dp_invariance": bench_dp_invariance(),
        "wire": bench_wire(),
        "env": {
            "devices": N_DEV,
            "batch": BATCH,
            "steps": STEPS,
            "eval_batches": EVAL_BATCHES,
            "lr": LR,
            "jax": jax.__version__,
        },
    }
    text = json.dumps(out, indent=1)
    if "--write" in sys.argv:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, "BENCH_r08.json"), "w") as f:
            f.write(text + "\n")
    print(text)


if __name__ == "__main__":
    main()

"""Elastic PS tier: per-shard load skew, hash-uniform vs the sketch plan.

Emits ONE JSON record (committed as BENCH_ELASTIC.json) answering the
question the sparsity-aware :class:`ShardPlanner` exists for: under the
zipf traffic recommenders actually serve, how unbalanced are hash-uniform
ring shards, and how much of that skew does a plan driven by the tiering
access sketch (``AccessProfiler`` heavy hitters + decayed totals) recover?

Method: a deterministic zipf sign stream is observed into a real
``AccessProfiler`` (the native count-min/top-K sketch, the same artifact
the auto-tiering planner reads); ``ShardPlanner.plan`` inverts its load
CDF into ring splits. A held-out stream from the same distribution is
then routed by both rings (``sign_to_range_shard``) and the EMPIRICAL
per-shard access counts scored — skew = max/mean, 1.0 is perfect. The
modeled skews (what the planner believed) ride along so sketch error is
visible. Finally the plan is executed as a REAL 2->4 elastic reshard over
in-process stores holding the stream's working set, recording move
counts, bytes and wall time for the handoff engine itself.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_SHARDS = int(os.environ.get("ELASTIC_SHARDS", "4"))
N_SLOTS = 4
VOCAB = int(os.environ.get("ELASTIC_VOCAB", str(1 << 17)))
ZIPF_A = float(os.environ.get("ELASTIC_ZIPF_A", "1.5"))
STEPS = int(os.environ.get("ELASTIC_STEPS", "64"))
BATCH = int(os.environ.get("ELASTIC_BATCH", "4096"))
SEED = 7
DIM = 16


def zipf_batch(rng, slot: int) -> np.ndarray:
    ids = rng.zipf(ZIPF_A, BATCH).astype(np.uint64) % VOCAB
    return ids + np.uint64(slot * VOCAB + 1)


def empirical_skew(splits, streams) -> tuple:
    from persia_tpu.embedding.hashing import sign_to_range_shard

    counts = np.zeros(N_SHARDS, dtype=np.int64)
    for signs in streams:
        counts += np.bincount(
            sign_to_range_shard(signs, np.asarray(splits, np.uint64)),
            minlength=N_SHARDS,
        )
    return float(counts.max() / counts.mean()), counts.tolist()


def main() -> int:
    from persia_tpu import elastic, jobstate
    from persia_tpu.embedding.hashing import uniform_splits
    from persia_tpu.embedding.native_store import (
        create_store,
        store_backend_name,
    )
    from persia_tpu.embedding.optim import Adagrad
    from persia_tpu.embedding.tiering.profiler import AccessProfiler
    from persia_tpu.embedding.tiering.shard_planner import ShardPlanner

    slot_names = [f"cat_{i}" for i in range(N_SLOTS)]
    prof = AccessProfiler(slot_names, topk=16)
    rng = np.random.default_rng(SEED)
    t0 = time.time()
    for _ in range(STEPS):
        for s, name in enumerate(slot_names):
            prof.observe_slot(name, zipf_batch(rng, s))
    observe_s = time.time() - t0

    planner = ShardPlanner()
    plan = planner.plan(N_SHARDS, profiler=prof)
    uni = uniform_splits(N_SHARDS)
    pos, w, residual = ShardPlanner.mass_from_profiler(prof)
    modeled_uniform = ShardPlanner.skew_of(
        ShardPlanner.shard_loads(uni, pos, w, residual)
    )

    # held-out traffic from the same distribution scores both rings
    heldout = [zipf_batch(rng, s) for s in range(N_SLOTS) for _ in range(STEPS)]
    skew_uniform, counts_uniform = empirical_skew(uni, heldout)
    skew_planned, counts_planned = empirical_skew(plan.splits, heldout)

    # the plan as a real handoff: grow 2->4 over in-process stores holding
    # the stream's working set, landing on the sketch-driven ring
    opt = Adagrad(lr=0.05).config
    working_set = np.unique(np.concatenate(heldout))
    # fleet-default backend: auto resolves to the native C++ store, so the
    # handoff wire measured below is the native ps_export_range path
    backend = os.environ.get("PERSIA_STORE_BACKEND", "auto")
    srcs = [create_store(backend, capacity=1 << 20, num_internal_shards=4,
                         optimizer=opt, seed=SEED) for _ in range(2)]
    for r, st in enumerate(srcs):
        st.lookup(working_set[working_set % 2 == r], DIM, True)
    dests = list(srcs) + [
        create_store(backend, capacity=1 << 20, num_internal_shards=4,
                     optimizer=opt, seed=SEED)
        for _ in range(N_SHARDS - 2)
    ]
    rplan = elastic.plan_reshard(
        2, N_SHARDS, None, [int(x) for x in plan.splits],
        jobstate.make_journal_id(1, 0),
    )
    import tempfile

    t0 = time.time()
    stats = elastic.execute_reshard(
        rplan, srcs, dests, tempfile.mkdtemp(prefix="elastic_bench_js_")
    )
    reshard_s = time.time() - t0

    # direct store ns/lookup on the post-reshard fleet (warm rows): the
    # native-vs-numpy delta committed alongside the backend name
    probe_signs = working_set[: min(4096, len(working_set))]
    dests[0].lookup(probe_signs, DIM, False)
    t0 = time.perf_counter_ns()
    for _ in range(10):
        dests[0].lookup(probe_signs, DIM, False)
    store_ns = (time.perf_counter_ns() - t0) / (10 * max(len(probe_signs), 1))

    rec = {
        "bench": "elastic",
        "workload": {
            "slots": N_SLOTS, "vocab_per_slot": VOCAB, "zipf_a": ZIPF_A,
            "steps": STEPS, "batch": BATCH, "seed": SEED,
        },
        "n_shards": N_SHARDS,
        "skew_uniform": round(skew_uniform, 4),
        "skew_planned": round(skew_planned, 4),
        "counts_uniform": counts_uniform,
        "counts_planned": counts_planned,
        "modeled_skew_uniform": round(modeled_uniform, 4),
        "modeled_skew_planned": round(plan.skew, 4),
        "observe_s": round(observe_s, 3),
        "reshard": {
            "old_n": 2, "new_n": N_SHARDS,
            "store_backend": store_backend_name(srcs[0]),
            "store_ns_per_lookup": round(store_ns, 1),
            "entries": int(len(working_set)),
            "moves": len(rplan.moves),
            "imports_applied": stats["imports_applied"],
            "deletes_applied": stats["deletes_applied"],
            "moved_bytes": stats["moved_bytes"],
            "moved_bytes_per_s": round(stats["moved_bytes"]
                                       / max(reshard_s, 1e-9)),
            "entries_removed": stats["entries_removed"],
            "wall_s": round(reshard_s, 3),
        },
    }
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_ELASTIC.json")
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec))
    if skew_planned >= skew_uniform:
        print("FAIL: sketch-driven plan did not reduce empirical skew",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

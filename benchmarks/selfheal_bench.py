"""Self-healing fleet certification bench: MTTR under repeated PS death.

Emits ONE JSON record (committed as BENCH_SELFHEAL.json) answering the
questions the PR-18 tentpole exists for:

1. **MTTR** — how long from SIGKILL of a PS shard to a promoted warm
   standby serving again, fully autonomously (lease+probe
   ``FailureDetector`` -> ``Healer`` two-phase journal ->
   ``heal_promote``)?  K seeded kill/heal cycles, p50/p99 over the
   detect->promoted->fresh durations the healer records itself.
2. **Zero dropped requests** — a background lookup-load thread hammers
   the sharded router the whole time; every call must return live rows
   (the in-flight retry loop migrates to the promoted handle on
   ``replace_replica``).  ``failed_requests`` and the degraded-sign set
   must both end at zero.
3. **Gray drain** — wall time of ``heal_drain_gray`` (snapshot the
   still-answering replica, promote a fresh one, swap the router, then
   retire the gray process) on a live shard.
4. **No false positives** — a no-fault soak: N detector polls against a
   healthy fleet must end with every verdict LIVE and the witness-rule
   guard counter untouched.
"""

import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

KILL_CYCLES = int(os.environ.get("SELFHEAL_KILLS", "5"))
SOAK_POLLS = int(os.environ.get("SELFHEAL_SOAK_POLLS", "120"))
N_SIGNS = 512
DIM = 8
SEED = 7


def pct(xs, q):
    return float(np.percentile(np.asarray(xs, np.float64), q)) if xs else None


def main() -> int:
    import tempfile

    from persia_tpu.autopilot import enable_self_heal
    from persia_tpu.embedding.worker import ShardedLookup
    from persia_tpu.helper import ServiceCtx
    from persia_tpu.service.clients import StoreClient
    from persia_tpu.service.failure_detector import (
        VERDICT_LIVE,
        DetectorConfig,
        FailureDetector,
    )
    from persia_tpu.service.resilience import ResiliencePolicy, RetryPolicy

    rng = np.random.default_rng(SEED)
    signs = np.arange(1, N_SIGNS + 1, dtype=np.uint64)
    vals = rng.normal(size=(N_SIGNS, DIM)).astype(np.float32)

    policy = ResiliencePolicy(
        retry=RetryPolicy(max_attempts=4, base_s=0.02, max_s=0.3, seed=1),
        breaker_failure_threshold=3, breaker_reset_s=0.3,
        degrade_after_s=60.0,  # ride out every heal; degrading = failing
        max_degraded_frac=1.0,
    )

    rec = {
        "bench": "selfheal",
        "workload": {
            "n_ps": 2, "signs": N_SIGNS, "dim": DIM, "seed": SEED,
            "kill_cycles": KILL_CYCLES, "soak_polls": SOAK_POLLS,
        },
    }

    with ServiceCtx(num_parameter_servers=2, num_embedding_workers=0,
                    backend="numpy", seed=SEED) as svc, \
            tempfile.TemporaryDirectory() as state_dir:
        ps = [StoreClient(a, policy=policy, timeout_s=10.0)
              for a in svc.ps_addrs()]
        for c in ps:
            c.wait_ready()
        router = ShardedLookup(ps, policy=policy)
        router.set_embedding(signs, vals, dim=DIM)
        ref = router.lookup(signs, DIM, train=False)
        svc.snapshot_ps(0)
        svc.snapshot_ps(1)

        healer = enable_self_heal(
            svc, state_dir, router=router,
            detector_config=DetectorConfig(
                miss_threshold=3, probe_timeout_s=0.5),
            probe_timeout_s=0.5,
        )
        healer.start(interval_s=0.1)

        stats = {"lookups": 0, "failed": 0, "mismatched": 0}
        stop_load = threading.Event()

        def load():
            while not stop_load.is_set():
                try:
                    got = router.lookup(signs, DIM, train=False)
                except Exception:
                    stats["failed"] += 1
                else:
                    stats["lookups"] += 1
                    if not np.array_equal(got, ref):
                        stats["mismatched"] += 1
                time.sleep(0.01)

        loader = threading.Thread(target=load, daemon=True)
        loader.start()

        # ---- leg 1+2: K autonomous kill/heal cycles under live load ----
        t_bench = time.time()
        try:
            for cycle in range(KILL_CYCLES):
                svc.spawn_standby_ps()  # warm standby for this cycle
                n0 = len(healer.mttr_s)
                svc.kill_ps(1)
                deadline = time.monotonic() + 60.0
                while len(healer.mttr_s) <= n0:
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"cycle {cycle}: no heal within 60s")
                    time.sleep(0.02)
                time.sleep(0.5)  # let the fleet settle between cycles
            kill_wall_s = time.time() - t_bench

            # ---- leg 3: drain-and-replace a live (gray-verdict) shard ----
            t0 = time.monotonic()
            svc.heal_drain_gray(0, router=router)
            gray_drain_s = time.monotonic() - t0
            time.sleep(0.5)
        finally:
            stop_load.set()
            loader.join(timeout=10.0)
            healer.stop()
            healer.detector.close()

        final = router.lookup(signs, DIM, train=False)
        rec["mttr"] = {
            "samples_s": [round(x, 4) for x in healer.mttr_s],
            "p50_s": round(pct(healer.mttr_s, 50), 4),
            "p99_s": round(pct(healer.mttr_s, 99), 4),
            "heals": len(healer.mttr_s),
            "wall_s": round(kill_wall_s, 3),
        }
        rec["load"] = {
            "lookups": stats["lookups"],
            "failed_requests": stats["failed"],
            "value_mismatches": stats["mismatched"],
            "degraded_signs_final": len(router._degraded_signs),
            "final_rows_bitwise": bool(np.array_equal(final, ref)),
        }
        rec["gray_drain"] = {"mttr_s": round(gray_drain_s, 4)}
        rec["journal"] = {"pending_after": healer.pending() is not None}

        # ---- leg 4: no-fault soak — a fresh detector, healthy fleet ----
        det = FailureDetector(
            svc.ps_probes(timeout_s=0.5),
            DetectorConfig(miss_threshold=3, probe_timeout_s=0.5),
            lease_reader=svc.ps_lease_reader(),
        )
        try:
            soak_verdicts = []
            for _ in range(SOAK_POLLS):
                soak_verdicts.append(det.poll_once())
                time.sleep(0.01)
            non_live = sum(
                1 for vd in soak_verdicts for v in vd.values()
                if v != VERDICT_LIVE
            )
            rec["soak"] = {
                "polls": SOAK_POLLS,
                "non_live_verdicts": non_live,
                "false_positive_guard": det.false_positive_guard,
            }
        finally:
            det.close()

    ok = (
        rec["mttr"]["heals"] == KILL_CYCLES
        and rec["load"]["failed_requests"] == 0
        and rec["load"]["degraded_signs_final"] == 0
        and rec["load"]["final_rows_bitwise"]
        and not rec["journal"]["pending_after"]
        and rec["soak"]["false_positive_guard"] == 0
    )
    rec["ok"] = ok
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_SELFHEAL.json")
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec, indent=1))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

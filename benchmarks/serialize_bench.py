"""Wire-format microbenchmark: PersiaBatch serialize/deserialize throughput.

Parity target: the reference's criterion benches of the inference request
path (`rust/others/persia-common-benchmark/benches/serialize_inf_request.rs`
— speedy vs serde formats on an id-feature batch). Here the custom
little-endian wire format (persia_tpu/data.py to_bytes/from_bytes, shared
with the C++ services) is measured on the same two shapes the reference
uses: a single-id inference request and a multi-id (LIL) training batch.

Prints one JSON line per case:
  {"case": ..., "bytes": N, "encode_us": ..., "decode_us": ...,
   "encode_MBps": ..., "decode_MBps": ...}
"""

from __future__ import annotations

import json
import time

import numpy as np

from persia_tpu.data import IDTypeFeature, IDTypeFeatureWithSingleID, Label, NonIDTypeFeature, PersiaBatch


def _single_id_batch(batch_size=128, n_slots=16):
    rng = np.random.default_rng(0)
    return PersiaBatch(
        [
            IDTypeFeatureWithSingleID(
                f"slot_{i}", rng.integers(0, 1 << 40, batch_size, dtype=np.uint64)
            )
            for i in range(n_slots)
        ],
        non_id_type_features=[
            NonIDTypeFeature(rng.normal(size=(batch_size, 13)).astype(np.float32))
        ],
        labels=[Label(rng.integers(0, 2, (batch_size, 1)).astype(np.float32))],
        requires_grad=False,
    )


def _lil_batch(batch_size=128, n_slots=8, max_len=24):
    rng = np.random.default_rng(1)
    return PersiaBatch(
        [
            IDTypeFeature(
                f"slot_{i}",
                [
                    rng.integers(0, 1 << 40, rng.integers(0, max_len), dtype=np.uint64)
                    for _ in range(batch_size)
                ],
            )
            for i in range(n_slots)
        ],
        non_id_type_features=[
            NonIDTypeFeature(rng.normal(size=(batch_size, 13)).astype(np.float32))
        ],
        labels=[Label(rng.integers(0, 2, (batch_size, 1)).astype(np.float32))],
        requires_grad=True,
    )


def bench_case(name: str, batch: PersiaBatch, reps: int = 200) -> dict:
    wire = batch.to_bytes()
    nbytes = len(wire)
    # warm
    for _ in range(5):
        batch.to_bytes()
        PersiaBatch.from_bytes(wire)
    t0 = time.perf_counter()
    for _ in range(reps):
        batch.to_bytes()
    enc = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        PersiaBatch.from_bytes(wire)
    dec = (time.perf_counter() - t0) / reps
    return {
        "case": name,
        "bytes": nbytes,
        "encode_us": round(enc * 1e6, 1),
        "decode_us": round(dec * 1e6, 1),
        "encode_MBps": round(nbytes / enc / 1e6, 1),
        "decode_MBps": round(nbytes / dec / 1e6, 1),
    }


def bench_ps_wire(batch_size=4096, n_slots=26, dim=16, distinct_per_slot=1360,
                  reps=50) -> list:
    """Worker↔PS wire cost per training batch, BEFORE vs AFTER the batched
    RPC (ref gap the round-3 verdict names: one f32 per-slot request each
    way vs ONE multi-slot frame with an f16-class dtype + lz4-able ids).

    'before' = 26 × pack_lookup_request / pack_update_request f32 frames
    (the round-1 wire); 'after' = one pack_lookup_batched_request +
    pack_update_batched_request in each wire dtype. Bytes are the
    on-the-wire payload sizes; times are host pack+unpack cost."""
    from persia_tpu.service import proto

    rng = np.random.default_rng(3)
    keys = [
        rng.integers(0, 1 << 40, distinct_per_slot, dtype=np.uint64)
        for _ in range(n_slots)
    ]
    grads = [
        rng.normal(size=(distinct_per_slot, dim)).astype(np.float32)
        for _ in range(n_slots)
    ]
    key_ofs = np.zeros(n_slots + 1, np.int64)
    np.cumsum([len(k) for k in keys], out=key_ofs[1:])
    signs = np.concatenate(keys)
    dims = np.full(n_slots, dim, np.uint32)
    ogs = np.zeros(n_slots, np.int32)
    flat_rows = rng.normal(size=len(signs) * dim).astype(np.float32)
    flat_grads = np.concatenate([g.reshape(-1) for g in grads])

    out = []

    def run(tag, pack_req, pack_rep):
        for _ in range(3):
            pack_req(), pack_rep()
        t0 = time.perf_counter()
        for _ in range(reps):
            req = pack_req()
        t_req = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for _ in range(reps):
            rep = pack_rep()
        t_rep = (time.perf_counter() - t0) / reps
        nb = (
            sum(memoryview(b).nbytes for b in req)
            + sum(memoryview(b).nbytes for b in rep)
        )
        out.append({
            "case": f"ps_wire_{tag}",
            "wire_bytes_per_batch": nb,
            "host_pack_us": round((t_req + t_rep) * 1e6, 1),
        })

    # round-1 shape: one f32 frame per slot each way
    run(
        "before_per_slot_f32",
        lambda: [proto.pack_lookup_request(k, dim, True) for k in keys],
        lambda: (
            [flat_rows.tobytes()]
            + [proto.pack_update_request(k, g, 0) for k, g in zip(keys, grads)]
        ),
    )
    for wd in (None, "float16", "bfloat16"):
        tag = wd or "float32"
        run(
            f"after_batched_{tag}",
            lambda wd=wd: (
                proto.pack_lookup_batched_request(
                    signs, key_ofs, dims, True, reply_dtype=wd
                )
                + proto.pack_update_batched_request(
                    signs, key_ofs, dims, flat_grads, ogs, wire_dtype=wd
                )
            ),
            lambda wd=wd: proto.pack_lookup_batched_reply(
                flat_rows, proto.wire_dtype_code(wd)
            ),
        )
    return out


def bench_psgrad_wire(batch_size=4096, n_slots=26, dim=16,
                      distinct_per_slot=1360, reps=100) -> list:
    """The ps-stream DEVICE→HOST gradient-return wire per training batch —
    the physical ceiling of that regime (samples/sec ≤ d2h_BW /
    grad_bytes_per_sample). Bytes per batch for the three wire choices
    (f32 / bf16 / int8+per-slot-scales, hbm_cache/step.py ps_grad_wire)
    plus the host-side unpack cost each adds on the write-back thread.
    int8 rides bytegrad-style absmax quantization with a device-resident
    error-feedback residual, so the 4× byte cut is not paid in applied
    gradient fidelity (tests/test_hbm_cache.py int8-vs-f32 gate)."""
    import ml_dtypes

    rng = np.random.default_rng(9)
    n = n_slots * distinct_per_slot * dim
    g32 = rng.normal(size=n).astype(np.float32) * 1e-3
    gbf = g32.astype(ml_dtypes.bfloat16)
    scales = np.abs(g32.reshape(n_slots, -1)).max(axis=1).astype(np.float32)
    q8 = np.clip(
        np.round(
            g32.reshape(n_slots, -1) / scales[:, None] * 127.0
        ), -127, 127,
    ).astype(np.int8).reshape(-1)

    def timed(fn):
        for _ in range(3):
            fn()
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t0) / reps * 1e6

    out = []
    for tag, nbytes, unpack in (
        ("float32", g32.nbytes, lambda: g32.reshape(n_slots, -1)),
        ("bfloat16", gbf.nbytes, lambda: gbf.astype(np.float32)),
        (
            "int8_ef",
            q8.nbytes + scales.nbytes,
            lambda: q8.reshape(n_slots, -1).astype(np.float32)
            * (scales[:, None] / np.float32(127.0)),
        ),
    ):
        out.append({
            "case": f"psgrad_wire_{tag}",
            "d2h_bytes_per_batch": int(nbytes),
            "d2h_bytes_per_sample": round(nbytes / batch_size, 1),
            "host_unpack_us": round(timed(unpack), 1),
        })
    return out


def bench_allreduce_wire(n=8, block_size=256, reps=200) -> list:
    """Dense-plane allreduce wire cost per step per replica, priced by
    grad_sync.dense_sync_wire_bytes (the SAME model bench.py and the
    telemetry counters use) at the bench DLRM's dense shape — f32 /
    bytegrad / block-int8-ring and the ZeRO-style sharded variants.

    The honest line this table exists for: "bytegrad" quantizes at the
    endpoints but XLA's psum carries int8 summands AS INT32, so its wire is
    f32-width — only the explicit block-scaled ring actually moves ~1
    byte/elem. Host rows also time the per-chunk numpy block
    quantize/dequantize (the work each ring hop adds), priced on one
    chunk = P/n rounded to the block multiple."""
    import jax
    import optax

    from persia_tpu.models import DLRM
    from persia_tpu.parallel.grad_sync import (
        dense_param_count,
        dense_sync_wire_bytes,
    )
    from persia_tpu.parallel.train_step import init_train_state

    # the throughput bench's exact dense shape (bench.py bench_fused)
    rng = np.random.default_rng(11)
    batch = {
        "dense": [rng.normal(size=(32, 13)).astype(np.float32)],
        "labels": [rng.integers(0, 2, (32, 1)).astype(np.float32)],
        "emb": [
            {"pooled": rng.normal(size=(32, 16)).astype(np.float32)}
            for _ in range(26)
        ],
    }
    model = DLRM(embedding_dim=16, bottom_mlp=(256, 64, 16), top_mlp=(512, 256))
    state = init_train_state(model, jax.random.PRNGKey(0), batch, optax.sgd(0.1))
    p = dense_param_count(state.params)

    out = []
    f32 = dense_sync_wire_bytes("f32", p, n)
    for mode in (
        "f32", "bf16", "bytegrad", "block-int8-ring",
        "f32-sharded", "block-int8-ring-sharded",
    ):
        nb = dense_sync_wire_bytes(mode, p, n, block_size=block_size)
        out.append({
            "case": f"allreduce_wire_{mode}",
            "wire_bytes_per_step_per_replica": int(nb),
            "vs_f32": round(f32 / nb, 2) if nb else None,
            "dense_params": int(p),
            "n": n,
            "block_size": block_size,
        })

    # per-hop host-side cost proxy: block quantize + dequantize of one
    # ring chunk (on TPU this runs fused on-device; the numpy timing bounds
    # the arithmetic the wire saving buys back)
    chunk = (-(-p // n) + block_size - 1) // block_size * block_size
    v = rng.normal(size=chunk).astype(np.float32)

    def qdq():
        b = v.reshape(-1, block_size)
        s = np.maximum(np.abs(b).max(axis=1), 1e-30)
        q = np.clip(np.round(b / s[:, None] * 127.0), -127, 127).astype(np.int8)
        return q.astype(np.float32) * (s[:, None] / np.float32(127.0))

    for _ in range(5):
        qdq()
    t0 = time.perf_counter()
    for _ in range(reps):
        qdq()
    out.append({
        "case": "allreduce_block_int8_chunk_qdq",
        "chunk_elems": int(chunk),
        "host_qdq_us": round((time.perf_counter() - t0) / reps * 1e6, 1),
    })
    return out


def main() -> None:
    for name, batch in (
        ("infer_single_id_128x16", _single_id_batch()),
        ("train_lil_128x8", _lil_batch()),
        ("infer_single_id_4096x26", _single_id_batch(4096, 26)),
    ):
        print(json.dumps(bench_case(name, batch)))
    for row in bench_ps_wire():
        print(json.dumps(row))
    for row in bench_psgrad_wire():
        print(json.dumps(row))
    for row in bench_allreduce_wire():
        print(json.dumps(row))


if __name__ == "__main__":
    main()

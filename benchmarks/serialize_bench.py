"""Wire-format microbenchmark: PersiaBatch serialize/deserialize throughput.

Parity target: the reference's criterion benches of the inference request
path (`rust/others/persia-common-benchmark/benches/serialize_inf_request.rs`
— speedy vs serde formats on an id-feature batch). Here the custom
little-endian wire format (persia_tpu/data.py to_bytes/from_bytes, shared
with the C++ services) is measured on the same two shapes the reference
uses: a single-id inference request and a multi-id (LIL) training batch.

Prints one JSON line per case:
  {"case": ..., "bytes": N, "encode_us": ..., "decode_us": ...,
   "encode_MBps": ..., "decode_MBps": ...}
"""

from __future__ import annotations

import json
import time

import numpy as np

from persia_tpu.data import IDTypeFeature, IDTypeFeatureWithSingleID, Label, NonIDTypeFeature, PersiaBatch


def _single_id_batch(batch_size=128, n_slots=16):
    rng = np.random.default_rng(0)
    return PersiaBatch(
        [
            IDTypeFeatureWithSingleID(
                f"slot_{i}", rng.integers(0, 1 << 40, batch_size, dtype=np.uint64)
            )
            for i in range(n_slots)
        ],
        non_id_type_features=[
            NonIDTypeFeature(rng.normal(size=(batch_size, 13)).astype(np.float32))
        ],
        labels=[Label(rng.integers(0, 2, (batch_size, 1)).astype(np.float32))],
        requires_grad=False,
    )


def _lil_batch(batch_size=128, n_slots=8, max_len=24):
    rng = np.random.default_rng(1)
    return PersiaBatch(
        [
            IDTypeFeature(
                f"slot_{i}",
                [
                    rng.integers(0, 1 << 40, rng.integers(0, max_len), dtype=np.uint64)
                    for _ in range(batch_size)
                ],
            )
            for i in range(n_slots)
        ],
        non_id_type_features=[
            NonIDTypeFeature(rng.normal(size=(batch_size, 13)).astype(np.float32))
        ],
        labels=[Label(rng.integers(0, 2, (batch_size, 1)).astype(np.float32))],
        requires_grad=True,
    )


def bench_case(name: str, batch: PersiaBatch, reps: int = 200) -> dict:
    wire = batch.to_bytes()
    nbytes = len(wire)
    # warm
    for _ in range(5):
        batch.to_bytes()
        PersiaBatch.from_bytes(wire)
    t0 = time.perf_counter()
    for _ in range(reps):
        batch.to_bytes()
    enc = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        PersiaBatch.from_bytes(wire)
    dec = (time.perf_counter() - t0) / reps
    return {
        "case": name,
        "bytes": nbytes,
        "encode_us": round(enc * 1e6, 1),
        "decode_us": round(dec * 1e6, 1),
        "encode_MBps": round(nbytes / enc / 1e6, 1),
        "decode_MBps": round(nbytes / dec / 1e6, 1),
    }


def main() -> None:
    for name, batch in (
        ("infer_single_id_128x16", _single_id_batch()),
        ("train_lil_128x8", _lil_batch()),
        ("infer_single_id_4096x26", _single_id_batch(4096, 26)),
    ):
        print(json.dumps(bench_case(name, batch)))


if __name__ == "__main__":
    main()

"""Wire-format microbenchmark: PersiaBatch serialize/deserialize throughput.

Parity target: the reference's criterion benches of the inference request
path (`rust/others/persia-common-benchmark/benches/serialize_inf_request.rs`
— speedy vs serde formats on an id-feature batch). Here the custom
little-endian wire format (persia_tpu/data.py to_bytes/from_bytes, shared
with the C++ services) is measured on the same two shapes the reference
uses: a single-id inference request and a multi-id (LIL) training batch.

Prints one JSON line per case:
  {"case": ..., "bytes": N, "encode_us": ..., "decode_us": ...,
   "encode_MBps": ..., "decode_MBps": ...}
"""

from __future__ import annotations

import json
import time

import numpy as np

from persia_tpu.data import IDTypeFeature, IDTypeFeatureWithSingleID, Label, NonIDTypeFeature, PersiaBatch


def _single_id_batch(batch_size=128, n_slots=16):
    rng = np.random.default_rng(0)
    return PersiaBatch(
        [
            IDTypeFeatureWithSingleID(
                f"slot_{i}", rng.integers(0, 1 << 40, batch_size, dtype=np.uint64)
            )
            for i in range(n_slots)
        ],
        non_id_type_features=[
            NonIDTypeFeature(rng.normal(size=(batch_size, 13)).astype(np.float32))
        ],
        labels=[Label(rng.integers(0, 2, (batch_size, 1)).astype(np.float32))],
        requires_grad=False,
    )


def _lil_batch(batch_size=128, n_slots=8, max_len=24):
    rng = np.random.default_rng(1)
    return PersiaBatch(
        [
            IDTypeFeature(
                f"slot_{i}",
                [
                    rng.integers(0, 1 << 40, rng.integers(0, max_len), dtype=np.uint64)
                    for _ in range(batch_size)
                ],
            )
            for i in range(n_slots)
        ],
        non_id_type_features=[
            NonIDTypeFeature(rng.normal(size=(batch_size, 13)).astype(np.float32))
        ],
        labels=[Label(rng.integers(0, 2, (batch_size, 1)).astype(np.float32))],
        requires_grad=True,
    )


def bench_case(name: str, batch: PersiaBatch, reps: int = 200) -> dict:
    wire = batch.to_bytes()
    nbytes = len(wire)
    # warm
    for _ in range(5):
        batch.to_bytes()
        PersiaBatch.from_bytes(wire)
    t0 = time.perf_counter()
    for _ in range(reps):
        batch.to_bytes()
    enc = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        PersiaBatch.from_bytes(wire)
    dec = (time.perf_counter() - t0) / reps
    return {
        "case": name,
        "bytes": nbytes,
        "encode_us": round(enc * 1e6, 1),
        "decode_us": round(dec * 1e6, 1),
        "encode_MBps": round(nbytes / enc / 1e6, 1),
        "decode_MBps": round(nbytes / dec / 1e6, 1),
    }


def bench_ps_wire(batch_size=4096, n_slots=26, dim=16, distinct_per_slot=1360,
                  reps=50) -> list:
    """Worker↔PS wire cost per training batch, BEFORE vs AFTER the batched
    RPC (ref gap the round-3 verdict names: one f32 per-slot request each
    way vs ONE multi-slot frame with an f16-class dtype + lz4-able ids).

    'before' = 26 × pack_lookup_request / pack_update_request f32 frames
    (the round-1 wire); 'after' = one pack_lookup_batched_request +
    pack_update_batched_request in each wire dtype. Bytes are the
    on-the-wire payload sizes; times are host pack+unpack cost."""
    from persia_tpu.service import proto

    rng = np.random.default_rng(3)
    keys = [
        rng.integers(0, 1 << 40, distinct_per_slot, dtype=np.uint64)
        for _ in range(n_slots)
    ]
    grads = [
        rng.normal(size=(distinct_per_slot, dim)).astype(np.float32)
        for _ in range(n_slots)
    ]
    key_ofs = np.zeros(n_slots + 1, np.int64)
    np.cumsum([len(k) for k in keys], out=key_ofs[1:])
    signs = np.concatenate(keys)
    dims = np.full(n_slots, dim, np.uint32)
    ogs = np.zeros(n_slots, np.int32)
    flat_rows = rng.normal(size=len(signs) * dim).astype(np.float32)
    flat_grads = np.concatenate([g.reshape(-1) for g in grads])

    out = []

    def run(tag, pack_req, pack_rep):
        for _ in range(3):
            pack_req(), pack_rep()
        t0 = time.perf_counter()
        for _ in range(reps):
            req = pack_req()
        t_req = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for _ in range(reps):
            rep = pack_rep()
        t_rep = (time.perf_counter() - t0) / reps
        nb = (
            sum(memoryview(b).nbytes for b in req)
            + sum(memoryview(b).nbytes for b in rep)
        )
        out.append({
            "case": f"ps_wire_{tag}",
            "wire_bytes_per_batch": nb,
            "host_pack_us": round((t_req + t_rep) * 1e6, 1),
        })

    # round-1 shape: one f32 frame per slot each way
    run(
        "before_per_slot_f32",
        lambda: [proto.pack_lookup_request(k, dim, True) for k in keys],
        lambda: (
            [flat_rows.tobytes()]
            + [proto.pack_update_request(k, g, 0) for k, g in zip(keys, grads)]
        ),
    )
    for wd in (None, "float16", "bfloat16"):
        tag = wd or "float32"
        run(
            f"after_batched_{tag}",
            lambda wd=wd: (
                proto.pack_lookup_batched_request(
                    signs, key_ofs, dims, True, reply_dtype=wd
                )
                + proto.pack_update_batched_request(
                    signs, key_ofs, dims, flat_grads, ogs, wire_dtype=wd
                )
            ),
            lambda wd=wd: proto.pack_lookup_batched_reply(
                flat_rows, proto.wire_dtype_code(wd)
            ),
        )
    return out


def main() -> None:
    for name, batch in (
        ("infer_single_id_128x16", _single_id_batch()),
        ("train_lil_128x8", _lil_batch()),
        ("infer_single_id_4096x26", _single_id_batch(4096, 26)),
    ):
        print(json.dumps(bench_case(name, batch)))
    for row in bench_ps_wire():
        print(json.dumps(row))


if __name__ == "__main__":
    main()
